"""InferenceEngine: a loaded model + private Scope + bucketed dispatch.

Load path: a `save_inference_model` directory (native versioned JSON
desc) or a reference-era `save_inference_model` directory (era-wire
ProgramDesc protobuf, via `io.load_reference_model`) — auto-detected.
The program goes through the full `paddle_tpu/analysis` pass pipeline AT
LOAD: a malformed model is rejected with structured `Diagnostic`s before
it can take traffic, instead of surfacing as an opaque trace/XLA error
inside some unlucky request's batch.

Shape discipline (the TVM fixed-shape-artifact idea applied to serving):
every dispatch — coalesced batch or single request — runs at a shape from
a small configured lattice of (batch bucket, seq bucket) pairs, pre-traced
at startup (`warmup()`) so steady state never compiles. Bucketing is also
what makes the correctness invariant testable: at a FIXED compiled shape,
XLA row results depend only on that row's values, so a request's rows are
bit-identical whether it was dispatched alone (`run_direct` at the same
bucket) or coalesced with strangers. Across DIFFERENT shapes XLA may
vectorize reductions differently — which is exactly why the engine never
dispatches at ad-hoc shapes.

Sequence feeds ride the `core/lod.py` machinery: each request's LoDTensor
pads to the batch's seq bucket (`to_padded(max_len=seq_bucket)`) and the
`@SEQLEN` companion carries true lengths; pad rows get length 1 over zero
data so length-normalizing ops can't manufacture NaN/Inf in rows nobody
reads.
"""
import os
import threading
import time

import numpy as np

from ..core.executor import Executor, Scope, scope_guard
from ..core.framework import convert_dtype
from ..core.lod import LoDTensor
from ..core.utils import find_var
from ..observability import trace as _trace
from .batcher import Batcher, DecodeBatcher, ServingError
from .metrics import ServingMetrics

__all__ = ["InferenceEngine", "ResultSlice", "InvalidRequestError",
           "DecodeEngine"]

SEQLEN_SUFFIX = "@SEQLEN"


class InvalidRequestError(ServingError):
    """The request's feeds don't match the model contract (missing feed,
    wrong feature dims, sequence longer than the largest bucket, ...)."""


def _default_batch_buckets(max_batch_size):
    buckets, b = [], 1
    while b < max_batch_size:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch_size)
    return buckets


def _covering_bucket(buckets, n, what):
    for b in buckets:
        if b >= n:
            return b
    raise InvalidRequestError(
        "%s %d exceeds the largest configured bucket %d"
        % (what, n, buckets[-1]))


class ResultSlice(object):
    """One request's share of a dispatched batch: lazy FetchHandles plus
    this request's row range. The dispatch has been enqueued on device;
    `numpy()` pays the device->host copy for THESE rows only (the row
    slice happens device-side before the transfer on a real
    accelerator; on the CPU backend np.asarray is already a zero-copy
    view, so slicing host-side skips a ~200us XLA slice dispatch per
    request). Per-fetch row policy comes from the engine's static
    classification: "rows" (declared leading dim -1: always slice),
    "whole" (parameters/persistables/scalars: never per-row), "dynamic"
    (concrete non-param leading dim: slice whenever the runtime leading
    dim equals the bucket — when ambiguous, slicing is the safe default,
    since returning the full batch would hand one client co-batched
    strangers' rows)."""

    __slots__ = ("_fetch_names", "_handles", "_row_policy",
                 "_device_slice", "_lo", "_hi", "_bucket_rows", "bucket",
                 "_trace")

    def __init__(self, fetch_names, handles, row_policy, lo, hi,
                 bucket_rows, bucket, device_slice=True, trace=None):
        self._fetch_names = fetch_names
        self._handles = handles
        self._row_policy = row_policy  # name -> rows|whole|dynamic
        self._device_slice = device_slice
        self._lo = lo
        self._hi = hi
        self._bucket_rows = bucket_rows
        self.bucket = bucket  # (batch_bucket, seq_bucket | None)
        self._trace = trace   # the request's trace id: the materialize
        # span records under it, completing the per-request timeline

    def numpy(self):
        from .. import profiler as _prof
        _prof.note_sync("serving/materialize")
        with _trace.span("serving/materialize", cat="serving",
                         trace=self._trace):
            out = {}
            for name, h in zip(self._fetch_names, self._handles):
                policy = self._row_policy[name]
                slice_rows = policy == "rows" or (
                    policy == "dynamic" and h.shape
                    and h.shape[0] == self._bucket_rows)
                if not slice_rows:
                    out[name] = np.asarray(h.array)
                elif self._device_slice:
                    out[name] = np.asarray(h.array[self._lo:self._hi])
                else:
                    out[name] = np.asarray(h.array)[self._lo:self._hi]
            return out

    def __repr__(self):
        return "ResultSlice(rows=[%d:%d), bucket=%r)" % (
            self._lo, self._hi, self.bucket)


class _NormalizedRequest(object):
    """A request's feeds, validated and split by kind: dense arrays
    (dtype-cast, [rows, *feat]) and sequence LoDTensors (+max length).
    `shape_sig` captures every CONCRETE feature shape: requests only
    coalesce within a signature, so a model with free (-1) feature dims
    can serve mixed widths without one width poisoning the other's
    batch (they can't share one padded array)."""

    __slots__ = ("rows", "dense", "seqs", "max_seq_len", "shape_sig")

    def __init__(self, rows, dense, seqs, max_seq_len):
        self.rows = rows
        self.dense = dense          # name -> np.ndarray [rows, *feat]
        self.seqs = seqs            # name -> LoDTensor with `rows` seqs
        self.max_seq_len = max_seq_len
        self.shape_sig = tuple(sorted(
            [(n, a.shape[1:]) for n, a in dense.items()] +
            [(n, lt.data.shape[1:]) for n, lt in seqs.items()]))


class InferenceEngine(object):
    def __init__(self, model_dir=None, model_format="auto",
                 model_filename=None, params_filename=None, place=None,
                 name=None, program=None, feed_names=None, fetch_vars=None,
                 batch_buckets=None, seq_buckets=None, max_batch_size=None,
                 max_queue_delay_ms=None, queue_capacity=256,
                 default_deadline_ms=None, validate=True, warmup=True,
                 latency_window=2048, apply_tuned=False,
                 pipeline_depth=None, tp=None, mesh_devices=None,
                 weights_dtype=None):
        from ..places import CPUPlace
        self.name = name or (os.path.basename(os.path.normpath(model_dir))
                             if model_dir else "model")
        self._scope = Scope()
        self._exe = Executor(place if place is not None else CPUPlace())
        self._run_lock = threading.Lock()   # Executor cache isn't
        self.default_deadline_ms = default_deadline_ms  # thread-safe
        self.closed = False
        # tensor-parallel engine (ARCHITECTURE.md §23): tp=M spans this
        # replica over M devices — one mesh {'dp': 1, 'tp': M}, params
        # sharded 1/M per chip at rest by the ShardingPlan's auto
        # row/col rule (gather placement: bit-identical results to a
        # mesh-1 engine on the same weights), dispatch through a
        # ParallelExecutor bound to this engine's program + Scope. The
        # loader Executor above stays: model files load host-side; the
        # first TP dispatch device_puts the scope per the plan.
        # mesh_devices pins the exact device span (the ReplicaPool's
        # per-replica slicing); default = the first M visible devices.
        if tp is not None and int(tp) < 1:
            # validate BEFORE the falsy-None mapping: tp=0 (a
            # miscomputed ndev//replicas) silently serving single-device
            # replicas would be the worst kind of "sharded" deployment
            raise ValueError("tp must be >= 1, got %r" % (tp,))
        self.tp = int(tp) if tp is not None else None
        self._mesh_devices = list(mesh_devices) if mesh_devices else None
        if self._mesh_devices is not None and self.tp is None:
            self.tp = len(self._mesh_devices)
        self.mesh = None
        self.plan = None
        self._pexe = None
        # device-side row slicing only pays for itself when there is a
        # transfer to shrink; on the CPU backend it's a pure ~200us
        # dispatch tax per request (np.asarray is zero-copy there)
        self._device_slice = \
            self._exe.place.device().platform != "cpu"

        validated_at_load = False
        if program is None:
            if model_dir is None:
                raise ValueError("need model_dir or an in-memory program")
            program, feed_names, fetch_vars = self._load(
                model_dir, model_format, model_filename, params_filename)
            # under FLAGS_validate_program=1 the native loader already
            # ran the full pipeline (io.load_inference_model) — don't
            # walk the program a second time at startup
            from ..core.executor import _validate_program_flag
            validated_at_load = (self._loaded_format == "native"
                                 and _validate_program_flag())
        elif feed_names is None or fetch_vars is None:
            raise ValueError("in-memory program needs feed_names and "
                             "fetch_vars")
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = [v if isinstance(v, str) else v.name
                            for v in fetch_vars]

        if validate and not validated_at_load:
            from .. import analysis
            analysis.validate_or_raise(self.program,
                                       feed_names=self.feed_names,
                                       fetch_names=self.fetch_names)

        # weight-dtype reduction (ARCHITECTURE.md §25 / serving/
        # quantize.py): bf16 halves weight HBM + runs the MXU ops bf16;
        # int8 stores matmul/conv weights quantized per channel behind
        # an in-graph dequantize. Applied to the loaded scope before
        # the first trace; fp32 master checkpoints/exports untouched.
        self.quantize_report = None
        self._set_weights_dtype(weights_dtype)
        if model_dir is not None:
            # params are in the scope already (loaded above)
            self._apply_weights_dtype()
        elif self.weights_dtype != "fp32":
            # an in-memory program has no loaded weights to quantize;
            # silently serving fp32 under an int8 label would pass every
            # divergence gate trivially. from_checkpoint owns the one
            # deferred path (it applies after its verified arrays land).
            raise ValueError(
                "weights_dtype=%r needs a model_dir load or "
                "InferenceEngine.from_checkpoint; an in-memory program= "
                "engine has no loaded weights to quantize"
                % (self.weights_dtype,))

        # apply_tuned: start at the recorded batching config for this
        # model's content signature on this device (paddle_tpu.tuning).
        # Explicit constructor arguments always win — a tuned config
        # fills only the knobs the caller left unset, so deploy-time
        # overrides stay overrides. No recorded entry = defaults.
        tuned_knobs = {}
        self.tuned_config = None  # the store entry in effect, if any
        if apply_tuned:
            from .. import tuning
            entry = tuning.lookup_program(self.program,
                                          self._exe.place.device())
            if entry is not None:
                tuned_knobs = entry.get("knobs", {})
                self.tuned_config = entry
        # the lattice knobs form one coherent set (buckets bound
        # max_batch): they apply all-or-nothing, only when the caller
        # pinned NONE of them — a tuned max_batch under explicit
        # buckets could exceed the caller's largest bucket
        if (batch_buckets is None and max_batch_size is None
                and seq_buckets is None):
            if tuned_knobs.get("batch_buckets"):
                batch_buckets = list(tuned_knobs["batch_buckets"])
            if tuned_knobs.get("max_batch_size"):
                max_batch_size = int(tuned_knobs["max_batch_size"])
            if tuned_knobs.get("seq_buckets"):
                seq_buckets = list(tuned_knobs["seq_buckets"])
        if max_queue_delay_ms is None:
            max_queue_delay_ms = tuned_knobs.get("max_queue_delay_ms", 5.0)

        # feed contract: per-feed declared feature dims + sequence-ness
        self._feed_vars = {}
        self._seq_feeds = set()
        for n in self.feed_names:
            var = find_var(self.program, n)
            if var is None:
                # a broken ARTIFACT (deploy fault), not a bad request —
                # InvalidRequestError here would file it as a client 400
                raise ValueError(
                    "model metadata names feed %r but the program has no "
                    "such variable" % n)
            self._feed_vars[n] = var
            if var.lod_level > 1:
                raise ValueError(
                    "feed %r has lod_level=%d: the serving batcher "
                    "coalesces single-level sequences only (the era "
                    "served nested-LoD decodes from host loops, not "
                    "saved graphs)" % (n, var.lod_level))
            if var.lod_level > 0 or find_var(
                    self.program, n + SEQLEN_SUFFIX) is not None:
                self._seq_feeds.add(n)

        # per-fetch row policy, decided ONCE: leading dim -1 = "rows"
        # (what layers.data/infer-shape propagate for batch outputs);
        # parameters/persistables/scalars = "whole" (never per-row);
        # a concrete non-param leading dim = "dynamic" — sliced when it
        # matches the dispatched bucket, because returning it whole
        # would leak co-batched strangers' rows to every client.
        from ..core.framework import Parameter
        self._fetch_row_policy = {}
        for n in self.fetch_names:
            var = find_var(self.program, n)
            shape = list(var.shape or []) if var is not None else []
            if var is not None and (isinstance(var, Parameter)
                                    or var.persistable or not shape):
                self._fetch_row_policy[n] = "whole"
            elif shape and shape[0] == -1:
                self._fetch_row_policy[n] = "rows"
            else:
                self._fetch_row_policy[n] = "dynamic"

        if self.tp is not None:
            import jax
            from ..parallel.mesh import make_mesh
            from ..parallel.parallel_executor import ParallelExecutor
            from ..parallel.plan import ShardingPlan
            devices = self._mesh_devices
            if devices is None:
                avail = jax.devices()
                if len(avail) < self.tp:
                    raise ValueError(
                        "tp=%d needs %d devices but only %d are visible"
                        % (self.tp, self.tp, len(avail)))
                devices = avail[:self.tp]
            elif len(devices) != self.tp:
                raise ValueError(
                    "tp=%d but mesh_devices has %d devices"
                    % (self.tp, len(devices)))
            # dp stays in the mesh at size 1 so the ParallelExecutor's
            # feed sharding path is untouched: request batches replicate
            # over the tp axis (no divisibility constraint on buckets)
            self.mesh = make_mesh({"dp": 1, "tp": self.tp}, devices)
            self.plan = ShardingPlan.build(self.program, self.mesh,
                                           tp_axis="tp")
            self._pexe = ParallelExecutor(main_program=self.program,
                                          plan=self.plan)
            self._pexe._scope = self._scope
            self._device_slice = devices[0].platform != "cpu"

        # deployment tier (analysis/deployment.py): prove the serving
        # contracts on the REWRITTEN program — row-independence of every
        # sliced fetch (the Batcher's coalescing contract), quant-pair
        # well-formedness after _apply_weights_dtype, plan coherence for
        # tp engines — then let warmup's empirical probes confirm what
        # was already proven. The per-fetch certificates are recorded
        # and CONSUMED below: a sliced fetch the analysis could not
        # certify row-independent (a warning-severity mix on a
        # "dynamic"/"whole" fetch — error-severity mixes on "rows"
        # fetches raise here) disables cross-request coalescing, so
        # correctness degrades to per-request batches instead of letting
        # strangers' rows bleed into each other. validate=False skips
        # the tier entirely and keeps full coalescing — the caller owns
        # the contract, exactly as before this tier existed.
        self.deployment_report = None
        self.row_certificates = {}
        self._row_safe = True
        if validate:
            from .. import analysis
            sliced = [n for n in self.fetch_names
                      if self._fetch_row_policy[n] != "whole"]
            deploy = analysis.DeploymentContext.for_serving(
                row_fetches=[n for n in self.fetch_names
                             if self._fetch_row_policy[n] == "rows"],
                whole_fetches=[n for n in self.fetch_names
                               if self._fetch_row_policy[n] != "rows"],
                weights_dtype=("bf16" if self.weights_dtype == "bf16"
                               else "int8" if self.weights_dtype == "int8"
                               else None),
                plan=self.plan)
            self.deployment_report = analysis.analyze_deployment(
                self.program, deploy, feed_names=self.feed_names,
                fetch_names=self.fetch_names)
            self.deployment_report.raise_if_errors()
            self.row_certificates = dict(
                self.deployment_report.certificates)
            self._row_safe = all(
                self.row_certificates.get(n, {}).get("status") != "mixed"
                for n in sliced)

        if batch_buckets:
            self.batch_buckets = sorted(set(int(b) for b in batch_buckets))
            self.max_batch_size = (int(max_batch_size) if max_batch_size
                                   else self.batch_buckets[-1])
        else:
            self.max_batch_size = int(max_batch_size or 32)
            self.batch_buckets = _default_batch_buckets(self.max_batch_size)
        if self.max_batch_size > self.batch_buckets[-1]:
            raise ValueError(
                "max_batch_size %d exceeds the largest batch bucket %d"
                % (self.max_batch_size, self.batch_buckets[-1]))
        self.seq_buckets = (sorted(set(int(s) for s in seq_buckets))
                            if seq_buckets else
                            ([16, 32, 64, 128, 256] if self._seq_feeds
                             else []))

        # continuous batching (ARCHITECTURE.md §22): how many dispatches
        # may be outstanding on the device while the next batch forms.
        # Default 2 — the device executes one batch while the next is
        # already enqueued behind it. 0 = the serial PR-3 loop (bench
        # baseline). FLAGS_serving_pipeline_depth overrides the default;
        # an explicit constructor argument wins.
        if pipeline_depth is None:
            try:
                pipeline_depth = int(os.environ.get(
                    "FLAGS_serving_pipeline_depth", "2"))
            except ValueError:
                pipeline_depth = 2
        self.pipeline_depth = int(pipeline_depth)

        self.metrics = ServingMetrics(latency_window=latency_window)
        self._batcher = Batcher(
            self._dispatch, max_batch_size=self.max_batch_size,
            max_queue_delay_ms=max_queue_delay_ms,
            queue_capacity=queue_capacity, metrics=self.metrics,
            name=self.name, pipeline_depth=self.pipeline_depth,
            coalesce=self._row_safe)
        if warmup:
            try:
                self.warmup()
            except Exception:
                # the batcher worker is already running: a constructor
                # that raises must not leak a live thread per retry
                self.close(drain=False)
                raise

    # ------------------------------------------------------------ load --
    @classmethod
    def from_checkpoint(cls, checkpoint_dir, fetch_list, feed_names=None,
                        step=None, warmup=True, **engine_kw):
        """Serve the newest VALID training checkpoint directly — no
        export step between "training saved a snapshot" and "it takes
        traffic". The snapshot's recorded program is pruned to the fetch
        subgraph (backward/optimizer ops dropped, exactly like
        save_inference_model), its hash-verified param values load into
        the engine's private Scope, and the engine warms up its bucket
        lattice as usual. A torn or bit-flipped newest snapshot is
        skipped for the newest one that verifies, so a crashed trainer
        can never push garbage weights into serving.

        fetch_list: fetch var names in the training program.
        feed_names: defaults to the pruned program's data vars (the
        layers.data inputs feeding the fetch subgraph).
        step pins an exact snapshot; default newest valid.
        """
        from ..checkpoint import CheckpointManager, load_verified_arrays
        target_names = [v if isinstance(v, str) else v.name
                        for v in fetch_list]
        mgr = CheckpointManager(checkpoint_dir, async_save=False)
        try:
            before = None
            while True:
                program, found_step, snap_path = mgr.load_program(
                    step=step, before=before)
                inference = program.prune(target_names, for_test=True)
                wanted = set(v.name for v in inference.list_vars()
                             if v.persistable)
                try:
                    # single pass: each param file is read once, hashed
                    # against the manifest, and decoded from those bytes
                    arrays = load_verified_arrays(snap_path, names=wanted)
                    break
                except (OSError, ValueError):
                    if step is not None:
                        raise  # the user pinned THIS snapshot
                    before = found_step  # corrupt arrays: walk back
        finally:
            mgr.close()
        if feed_names is None:
            feed_names = [v.name for v in inference.list_vars()
                          if getattr(v, "is_data", False)
                          and not v.persistable]
        fetch_vars = [inference.global_block().var(n)
                      for n in target_names]
        # weights_dtype is handled HERE, not by the program= constructor
        # (which rejects it: an in-memory program has no weights yet)
        weights_dtype = engine_kw.pop("weights_dtype", None)
        engine = cls(program=inference, feed_names=feed_names,
                     fetch_vars=fetch_vars,
                     name=engine_kw.pop("name", None)
                     or "ckpt-step-%d" % found_step,
                     warmup=False, **engine_kw)
        try:
            # params BEFORE warmup: the first traced bucket already needs
            # initialized persistables
            for name, arr in arrays.items():
                engine._scope.set(name, arr)
            # weights_dtype applies HERE, after the verified fp32 arrays
            # land and before any trace — the checkpoint on disk stays
            # the fp32 master copy
            engine._set_weights_dtype(weights_dtype)
            engine._apply_weights_dtype()
            if warmup:
                engine.warmup()
        except Exception:
            engine.close(drain=False)  # no thread leak per failed load
            raise
        engine.checkpoint_step = found_step
        return engine

    def _set_weights_dtype(self, weights_dtype):
        """Validate + record the weight-dtype contract (shared by the
        constructor and from_checkpoint's deferred path)."""
        from .quantize import WEIGHTS_DTYPES
        self.weights_dtype = (weights_dtype or "fp32").lower()
        if self.weights_dtype not in WEIGHTS_DTYPES:
            raise ValueError("weights_dtype must be one of %s, got %r"
                             % (WEIGHTS_DTYPES, weights_dtype))
        if self.weights_dtype == "int8" and self.tp is not None:
            raise ValueError(
                "weights_dtype='int8' does not compose with "
                "tensor-parallel engines yet (the sharding plan "
                "partitions the fp32 param names, not the @QVAL "
                "rewrite); use weights_dtype='bf16' for TP replicas")

    def _apply_weights_dtype(self):
        """Apply weights_dtype to the loaded (program, scope) pair —
        once, before the first trace. __init__ calls it for model_dir
        loads; from_checkpoint calls it after the verified arrays land
        in the scope (the constructor defers — the values aren't there
        yet). No-op for fp32 or when already applied."""
        if self.weights_dtype == "fp32" or self.quantize_report is not None:
            return
        from .quantize import apply_weights_dtype
        self.quantize_report = apply_weights_dtype(
            self.program, self._scope, self.weights_dtype)

    def _load(self, model_dir, model_format, model_filename,
              params_filename):
        from .. import io as _io
        if model_format == "auto":
            native_meta = os.path.join(model_dir, "__model_meta__.json")
            model_format = ("native" if os.path.exists(native_meta)
                            else "reference")
        self._loaded_format = model_format
        with scope_guard(self._scope):
            if model_format == "native":
                return _io.load_inference_model(
                    model_dir, self._exe, model_filename=model_filename,
                    params_filename=params_filename)
            if model_format == "reference":
                return _io.load_reference_model(
                    model_dir, self._exe, model_filename=model_filename,
                    params_filename=params_filename)
        raise ValueError("model_format must be auto|native|reference, "
                         "got %r" % model_format)

    # ------------------------------------------------------- normalize --
    def normalize_feed(self, feed):
        """Validate one request's feed dict against the model contract.
        Dense feeds: array-likes [rows, *feat] (feature dims checked
        against declared dims where those are concrete). Sequence feeds:
        a LoDTensor or a list of per-sequence arrays."""
        missing = [n for n in self.feed_names if n not in feed]
        if missing:
            raise InvalidRequestError("request is missing feeds %r (model "
                                      "expects %r)" % (missing,
                                                       self.feed_names))
        extra = [n for n in feed if n not in self.feed_names]
        if extra:
            raise InvalidRequestError("request has unknown feeds %r (model "
                                      "expects %r)" % (extra,
                                                       self.feed_names))
        rows = None
        dense, seqs, max_seq_len = {}, {}, 0
        for n in self.feed_names:
            var, value = self._feed_vars[n], feed[n]
            if n in self._seq_feeds:
                if isinstance(value, LoDTensor):
                    if value.lod_level() > 1:
                        raise InvalidRequestError(
                            "feed %r: nested (multi-level) LoD is not "
                            "servable; send single-level sequences" % n)
                    lt = value
                elif isinstance(value, (list, tuple)):
                    lt = LoDTensor.from_sequences(
                        [np.asarray(s) for s in value])
                else:
                    raise InvalidRequestError(
                        "feed %r is a sequence input: send a LoDTensor or "
                        "a list of per-sequence arrays" % n)
                lengths = lt.seq_lengths() if lt.lod else \
                    np.asarray([len(lt.data)], dtype=np.int32)
                n_seqs = len(lengths)
                if n_seqs == 0:
                    raise InvalidRequestError(
                        "feed %r carries zero sequences" % n)
                if len(lengths) and int(lengths.min()) < 1:
                    # a real row with @SEQLEN=0 divides-by-zero in
                    # length-normalizing ops — the client's fault, so a
                    # typed 400 here, not a NaN-shaped 500 later
                    raise InvalidRequestError(
                        "feed %r contains an empty sequence; every "
                        "sequence needs at least one step" % n)
                # per-token feature dims must match the declaration HERE:
                # a bad shape discovered inside the batcher's concat
                # would fail every innocent co-batched request
                want = list(var.shape or [])[2:]
                got = list(lt.data.shape)[1:]
                if len(got) != len(want) or any(
                        w >= 0 and w != g for w, g in zip(want, got)):
                    raise InvalidRequestError(
                        "feed %r has per-token shape %r but the model "
                        "declares %r" % (n, got, want))
                max_seq_len = max(max_seq_len,
                                  int(lengths.max()) if n_seqs else 0)
                seqs[n] = lt
                r = n_seqs
            else:
                arr = np.asarray(value)
                if var.dtype is not None:
                    arr = arr.astype(convert_dtype(var.dtype), copy=False)
                if arr.ndim < 1:
                    raise InvalidRequestError(
                        "feed %r must carry a leading batch-rows dim, "
                        "got a scalar" % n)
                want = list(var.shape or [])[1:]
                got = list(arr.shape)[1:]
                if len(got) != len(want) or any(
                        w >= 0 and w != g for w, g in zip(want, got)):
                    raise InvalidRequestError(
                        "feed %r has per-row shape %r but the model "
                        "declares %r" % (n, got, want))
                dense[n] = arr
                r = arr.shape[0]
            if rows is None:
                rows = r
            elif r != rows:
                raise InvalidRequestError(
                    "feeds disagree on batch rows: %r carries %d, earlier "
                    "feeds carry %d" % (n, r, rows))
        if rows < 1:
            raise InvalidRequestError("request carries zero rows")
        return _NormalizedRequest(rows, dense, seqs, max_seq_len)

    # --------------------------------------------------------- padding --
    def _pad_batch(self, normalized, batch_bucket, seq_bucket):
        """Coalesce normalized requests into one bucket-shaped feed dict.
        Shared by the batcher dispatch AND `run_direct`, so the reference
        path pads byte-identically to the serving path."""
        feed = {}
        for n in self.feed_names:
            var = self._feed_vars[n]
            if n in self._seq_feeds:
                data_parts, len_parts = [], []
                for req in normalized:
                    padded, lengths = req.seqs[n].to_padded(
                        max_len=seq_bucket)
                    if var.dtype is not None:
                        padded = padded.astype(convert_dtype(var.dtype),
                                               copy=False)
                    data_parts.append(padded)
                    len_parts.append(lengths)
                data = np.concatenate(data_parts, axis=0)
                lengths = np.concatenate(len_parts, axis=0)
                pad_rows = batch_bucket - data.shape[0]
                if pad_rows:
                    data = np.concatenate(
                        [data, np.zeros((pad_rows,) + data.shape[1:],
                                        dtype=data.dtype)], axis=0)
                    lengths = np.concatenate(
                        [lengths, np.ones(pad_rows, dtype=lengths.dtype)])
                feed[n] = data
                feed[n + SEQLEN_SUFFIX] = lengths
            else:
                arr = np.concatenate([req.dense[n] for req in normalized],
                                     axis=0)
                pad_rows = batch_bucket - arr.shape[0]
                if pad_rows:
                    arr = np.concatenate(
                        [arr, np.zeros((pad_rows,) + arr.shape[1:],
                                       dtype=arr.dtype)], axis=0)
                feed[n] = arr
        return feed

    def _pick_buckets(self, rows, max_seq_len):
        batch_bucket = _covering_bucket(self.batch_buckets, rows,
                                        "batch rows")
        seq_bucket = None
        if self._seq_feeds:
            seq_bucket = _covering_bucket(self.seq_buckets,
                                          max(max_seq_len, 1),
                                          "sequence length")
        return batch_bucket, seq_bucket

    # -------------------------------------------------------- dispatch --
    def _run(self, feed):
        """One executor dispatch under the run lock; returns lazy
        FetchHandles and whether this call compiled a new bucket.
        Compile detection compares the cache KEY SET, not its length —
        at LRU capacity an insert+evict keeps the length constant.
        A tensor-parallel engine dispatches through its mesh-bound
        ParallelExecutor instead (same Scope, same bucket lattice,
        same FetchHandle surface — the batcher can't tell)."""
        from ..core.dispatch import run_compile_probe
        with self._run_lock:
            if self._pexe is not None:
                return run_compile_probe(
                    self._pexe._cache,
                    lambda: self._pexe.run(self.fetch_names, feed=feed,
                                           return_numpy=False))
            # validate=False: the engine already verified the program at
            # load; re-validating per (bucket) feed signature would walk
            # the whole program once more per warmup shape under
            # FLAGS_validate_program=1
            return run_compile_probe(
                self._exe._cache,
                lambda: self._exe.run(self.program, feed=feed,
                                      fetch_list=self.fetch_names,
                                      scope=self._scope,
                                      return_numpy=False,
                                      validate=False))

    def _dispatch(self, requests):
        """Batcher callback. Requests are grouped by concrete-shape
        signature (one group, in the common all-dims-declared case) and
        each group pads into one bucket dispatch; a group that fails
        fails only ITS requests, never a co-batched group's. Returns the
        batch's lazy fetch handles so the batcher's in-flight window can
        observe device completion (off this thread)."""
        groups = {}
        for req in requests:
            groups.setdefault(req.feed.shape_sig, []).append(req)
        all_handles = []
        for reqs in groups.values():
            try:
                all_handles.extend(self._dispatch_group(reqs) or ())
            except Exception as e:  # noqa: BLE001 — isolate the group
                for r in reqs:
                    if not r.future.done():
                        r.future.set_exception(e)
                self.metrics.on_error(len(reqs))
        return all_handles

    # pre-dispatch tap: the ReplicaPool points this at its per-replica
    # fault/bookkeeping hook (dispatch counting, injected replica faults).
    # Raising here fails only this group — the batcher's group isolation
    # turns it into per-request exceptions the pool can fail over.
    _replica_tap = None

    def _dispatch_group(self, requests):
        """Pad one shape-compatible group -> one run -> scatter."""
        tap = self._replica_tap
        if tap is not None:
            tap()
        t0 = time.monotonic()
        normalized = [req.feed for req in requests]  # pre-normalized
        traces = [getattr(req, "trace", None) for req in requests]
        rows = sum(r.rows for r in normalized)
        batch_bucket, seq_bucket = self._pick_buckets(
            rows, max(r.max_seq_len for r in normalized))
        # with-blocks, not manual end(): a raise here is the routine
        # fail-this-group-not-the-worker path (the _dispatch wrapper
        # catches it) and must not strand the spans open
        with _trace.span("serving/pad_h2d", cat="serving",
                         traces=traces, rows=rows) as psp:
            feed = self._pad_batch(normalized, batch_bucket, seq_bucket)
            psp.set(bucket=batch_bucket)
        with _trace.span("serving/enqueue", cat="serving",
                         traces=traces, bucket=batch_bucket) as esp:
            handles, compiled = self._run(feed)
            esp.set(compiled=compiled)
        now = time.monotonic()
        offset, latencies = 0, []
        for req, norm, rtrace in zip(requests, normalized, traces):
            req.future.bucket = (batch_bucket, seq_bucket)
            req.future.latency_s = now - req.enqueued_at
            latencies.append(req.future.latency_s)
            req.future.set_result(ResultSlice(
                self.fetch_names, handles, self._fetch_row_policy,
                offset, offset + norm.rows, batch_bucket,
                (batch_bucket, seq_bucket),
                device_slice=self._device_slice, trace=rtrace))
            offset += norm.rows
        self.metrics.on_batch(len(requests), rows, batch_bucket, latencies)
        from .. import profiler as _prof
        if _prof.is_active():
            tag = "serving/%s b%d%s" % (
                self.name, batch_bucket,
                "s%d" % seq_bucket if seq_bucket else "")
            _prof.record_run(tag, now - t0, compiled=compiled)
        return handles

    # ---------------------------------------------------------- public --
    def submit(self, feed, deadline_ms=None):
        """Enqueue one request for coalesced dispatch; returns a
        RequestFuture whose result is a ResultSlice. Normalization happens
        HERE, on the caller's thread — a malformed request fails fast and
        never costs the batcher loop anything. Oversized requests are the
        batcher's check (RequestTooLargeError at its submit)."""
        return self.submit_normalized(self.normalize_feed(feed),
                                      deadline_ms=deadline_ms)

    def submit_normalized(self, norm, deadline_ms=None):
        """Enqueue an already-normalized request (a `normalize_feed`
        result). The ReplicaPool normalizes once on the caller's thread
        and resubmits the SAME normalized request to a different replica
        on failover — every engine of a pool serves one program, so the
        contract check never needs repeating."""
        if self._seq_feeds:     # reject unservable lengths before queueing
            _covering_bucket(self.seq_buckets, max(norm.max_seq_len, 1),
                             "sequence length")
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        return self._batcher.submit(norm, norm.rows,
                                    deadline_ms=deadline_ms)

    def infer(self, feed, deadline_ms=None, timeout=30.0):
        """Synchronous convenience: submit + wait + materialize this
        request's rows. Returns {fetch_name: np.ndarray}."""
        return self.submit(feed, deadline_ms=deadline_ms) \
            .result(timeout).numpy()

    def run_direct(self, feed, batch_bucket=None, seq_bucket=None):
        """The reference path every test leans on: ONE request, padded by
        the same `_pad_batch` helper, run directly through Executor.run —
        no queue, no coalescing. At a given bucket shape this is
        bit-identical to the rows the same request gets back from a
        coalesced batch, because both run the same compiled executable at
        the same shape. Returns ({fetch_name: np.ndarray}, bucket)."""
        norm = self.normalize_feed(feed)
        auto_b, auto_s = self._pick_buckets(norm.rows, norm.max_seq_len)
        batch_bucket = batch_bucket or auto_b
        seq_bucket = seq_bucket or auto_s
        if batch_bucket < norm.rows:
            raise InvalidRequestError(
                "batch_bucket=%d cannot hold the request's %d rows"
                % (batch_bucket, norm.rows))
        if seq_bucket is not None and seq_bucket < norm.max_seq_len:
            raise InvalidRequestError(
                "seq_bucket=%d cannot hold the request's longest "
                "sequence (%d steps)" % (seq_bucket, norm.max_seq_len))
        padded = self._pad_batch([norm], batch_bucket, seq_bucket)
        handles, _ = self._run(padded)
        res = ResultSlice(self.fetch_names, handles,
                          self._fetch_row_policy, 0, norm.rows,
                          batch_bucket, (batch_bucket, seq_bucket),
                          device_slice=self._device_slice)
        return res.numpy(), (batch_bucket, seq_bucket)

    def warmup(self, buckets=None):
        """Pre-trace the bucket lattice so steady state never compiles.
        `buckets`: explicit [(batch, seq|None), ...] (default: the full
        configured lattice). Feature dims that the model declares as -1
        warm up at 1 — real traffic at other dims compiles on first hit."""
        if buckets is None:
            if self._seq_feeds:
                buckets = [(b, s) for b in self.batch_buckets
                           for s in self.seq_buckets]
            else:
                buckets = [(b, None) for b in self.batch_buckets]
        from ..core.executor import _jit_cache_capacity
        capacity = _jit_cache_capacity()
        if 0 < capacity < len(buckets):
            raise ValueError(
                "bucket lattice has %d shapes but the executor keeps at "
                "most %d compiled programs (LRU): warmup would evict its "
                "own buckets and steady state would recompile. Shrink "
                "the lattice or raise PADDLE_TPU_JIT_CACHE_SIZE."
                % (len(buckets), capacity))
        compiled = 0
        for batch_bucket, seq_bucket in buckets:
            feed = {}
            for n in self.feed_names:
                var = self._feed_vars[n]
                dtype = convert_dtype(var.dtype) if var.dtype else "float32"
                if n in self._seq_feeds:
                    feat = [d if d >= 0 else 1
                            for d in list(var.shape or [])[2:]]
                    feed[n] = np.zeros([batch_bucket, seq_bucket or 1]
                                       + feat, dtype=dtype)
                    feed[n + SEQLEN_SUFFIX] = np.ones(batch_bucket,
                                                      dtype=np.int32)
                else:
                    feat = [d if d >= 0 else 1
                            for d in list(var.shape or [])[1:]]
                    feed[n] = np.zeros([batch_bucket] + feat, dtype=dtype)
            _, did_compile = self._run(feed)
            compiled += bool(did_compile)
        self.metrics.on_warmup_compile(compiled)
        return compiled

    def queue_depth(self):
        return self._batcher.queue_depth()

    def device_span(self):
        """The devices this engine's dispatches own: the mesh's devices
        for a tensor-parallel engine (M entries), else the single place
        device — what the pool's `pool_state()` and `/metrics` expose so
        an operator can see which chips a replica holds."""
        if self.mesh is not None:
            return [str(d) for d in self.mesh.devices.flat]
        return [str(self._exe.place.device())]

    def describe(self):
        """The /v1/models entry for this engine."""
        return {
            "name": self.name,
            "tp": self.tp,
            "weights_dtype": self.weights_dtype,
            "devices": self.device_span(),
            "feeds": [
                {"name": n,
                 "shape": list(self._feed_vars[n].shape or []),
                 "dtype": convert_dtype(self._feed_vars[n].dtype)
                 if self._feed_vars[n].dtype else None,
                 "sequence": n in self._seq_feeds}
                for n in self.feed_names],
            "fetches": self.fetch_names,
            "batch_buckets": self.batch_buckets,
            "seq_buckets": self.seq_buckets,
            "max_batch_size": self.max_batch_size,
            "pipeline_depth": self.pipeline_depth,
            "status": "closed" if self.closed else "serving",
            "metrics": self.metrics.snapshot(),
        }

    def drain(self, timeout=None):
        """Complete everything queued/mid-dispatch WITHOUT closing — the
        batcher's shared drain implementation, the same one
        close(drain=True) runs. The pool's zero-downtime engine swap
        rides it (via close) on the outgoing engine after the atomic
        pointer flip: requests accepted before the flip finish against
        the weights they were accepted under, with nothing dropped."""
        return self._batcher.drain(timeout)

    def close(self, drain=True, timeout=None):
        """Graceful shutdown: stop intake, drain queued requests (every
        in-flight batch completes and scatters), join the worker."""
        self.closed = True
        self._batcher.close(drain=drain, timeout=timeout)


# ---------------------------------------------------------------------------
# DecodeEngine: slot-resident generative serving (ARCHITECTURE.md §27)
# ---------------------------------------------------------------------------

class DecodeEngine(object):
    """A decode-step program + private Scope + iteration-level batcher.

    The served artifact is ONE step of an autoregressive loop, authored
    (or exported) at a fixed [max_slots, ...] batch shape with its
    carried state — KV caches, hidden state, token cursors — held in
    persistable "slot vars" (one slot per batch row). Every iteration is
    one `Executor.run` of that step at the ONE compiled shape: the
    executor's state machinery keeps the slot state device-resident and
    DONATES the read-and-written arrays (the KV cache never round-trips
    the host), the AOT compile cache / tuned-kernel trace keys compose
    unchanged because a step IS an ordinary run, and the DecodeBatcher
    admits/retires streams between iterations (Orca-style continuous
    batching — see serving/batcher.DecodeBatcher).

    Bit-exactness contract: the program must be deterministic (greedy
    decode — no dropout/sampling ops), and then a stream's token
    sequence is bit-identical to a solo decode of that stream on a
    fresh engine, whatever shared the batch or previously used its
    slot: at the fixed shape a row's outputs and next state depend only
    on that row, and admit rewrites EVERY slot var's row (init rows
    provided by the stream, zeros otherwise), so no previous resident
    can leak through carried state.

    Export caveat: `save_inference_model` prunes to the fetch subgraph —
    a decode step must be saved with its state-writing outputs among the
    fetch targets (token and finished vars first; the engine takes
    fetch[0]/fetch[1] as token/finished by default) or the state
    `assign`s would be silently pruned."""

    def __init__(self, model_dir=None, model_format="auto",
                 model_filename=None, params_filename=None, place=None,
                 name=None, program=None, startup_program=None,
                 token_var=None,
                 finished_var=None, slot_vars=None, max_slots=8,
                 queue_capacity=256, default_max_new_tokens=128,
                 default_deadline_ms=None, validate=True, warmup=True,
                 latency_window=4096):
        from ..places import CPUPlace
        from .metrics import DecodeMetrics
        self.name = name or (os.path.basename(os.path.normpath(model_dir))
                             if model_dir else "decode")
        self._scope = Scope()
        self._exe = Executor(place if place is not None else CPUPlace())
        self._run_lock = threading.Lock()
        self.closed = False
        self.max_slots = int(max_slots)
        if self.max_slots < 1:
            raise ValueError("max_slots must be >= 1, got %r"
                             % (max_slots,))
        self.default_deadline_ms = default_deadline_ms

        if program is None:
            if model_dir is None:
                raise ValueError("need model_dir or an in-memory program")
            program, _feeds, fetch_vars = InferenceEngine._load(
                self, model_dir, model_format, model_filename,
                params_filename)
            fetch_names = [v if isinstance(v, str) else v.name
                           for v in fetch_vars]
            if token_var is None or finished_var is None:
                if len(fetch_names) < 2:
                    raise ValueError(
                        "a decode model dir must be saved with at least "
                        "[token, finished] fetch targets (got %r); or "
                        "pass token_var/finished_var explicitly"
                        % (fetch_names,))
                token_var = token_var or fetch_names[0]
                finished_var = finished_var or fetch_names[1]
        elif token_var is None or finished_var is None:
            raise ValueError("an in-memory decode program needs "
                             "token_var and finished_var")
        self.program = program
        self.token_name = token_var if isinstance(token_var, str) \
            else token_var.name
        self.finished_name = finished_var if isinstance(finished_var, str) \
            else finished_var.name
        self.fetch_names = [self.token_name, self.finished_name]
        for n in self.fetch_names:
            if find_var(self.program, n) is None:
                raise ValueError("decode program has no variable %r" % n)
        if validate:
            from .. import analysis
            analysis.validate_or_raise(self.program, feed_names=[],
                                       fetch_names=self.fetch_names)
        if startup_program is not None:
            # in-memory authoring path: initialize weights into the
            # private scope (deterministic given the program seeds, so
            # two engines over the same pair decode identically). Slot
            # vars re-zero below regardless — slot state always starts
            # from the same zeros a fresh solo engine starts from.
            self._exe.run(startup_program, scope=self._scope)

        # state classification: the step feeds on NOTHING (everything
        # it consumes is carried persistable state), so analyze_state
        # sees every scope read/write
        from ..core.lowering import analyze_state, build_slot_update_fn
        self._state_rw, self._state_ro, self._state_out = analyze_state(
            self.program, feed_names=[], fetch_names=self.fetch_names)
        state_read = list(self._state_rw) + list(self._state_ro)

        # slot vars: explicit list wins; else every WRITTEN persistable
        # (inference programs never write weights, so written state is
        # carried decode state) plus read-only state whose leading dim
        # is exactly max_slots (per-slot context set at admit). The
        # leading-dim heuristic can mistake a [max_slots, d] weight for
        # slot state — pass slot_vars explicitly in that case.
        if slot_vars is None:
            slot_vars = list(self._state_out)
            for n in self._state_ro:
                var = find_var(self.program, n)
                shape = list(var.shape or []) if var is not None else []
                if shape and shape[0] in (-1, self.max_slots):
                    slot_vars.append(n)
        self.slot_vars = [v if isinstance(v, str) else v.name
                          for v in slot_vars]
        if not self.slot_vars:
            raise ValueError(
                "decode program carries no slot state (no persistable "
                "var is written and none matches max_slots=%d); a decode "
                "step must carry its loop state in persistables"
                % self.max_slots)
        self._slot_var_meta = {}   # name -> (row_shape, dtype)
        for n in self.slot_vars:
            var = find_var(self.program, n)
            if var is None or not var.persistable:
                raise ValueError(
                    "slot var %r is not a persistable variable of the "
                    "decode program" % n)
            shape = list(var.shape or [])
            if not shape or shape[0] not in (-1, self.max_slots):
                raise ValueError(
                    "slot var %r has shape %r; its leading dim must be "
                    "the slot count (max_slots=%d, or -1)"
                    % (n, shape, self.max_slots))
            feat = shape[1:]
            if any(d < 0 for d in feat):
                raise ValueError(
                    "slot var %r has free feature dims %r; decode slot "
                    "state needs concrete per-slot shapes" % (n, feat))
            dtype = convert_dtype(var.dtype) if var.dtype else "float32"
            self._slot_var_meta[n] = (tuple(feat), dtype)

        # deployment tier with the DECODE context: slot vars are the row
        # sources (row i of every fetch may depend only on slot i's own
        # state — the DecodeBatcher's isolation contract), slot state
        # must be written exactly once per step with static shapes, and
        # no fetch may alias a donated slot update. Runs after slot
        # inference so the context describes what the engine will
        # actually carry; errors here name the offending op instead of
        # surfacing as a wrong token three streams later.
        self.deployment_report = None
        self.row_certificates = {}
        if validate:
            from .. import analysis
            deploy = analysis.DeploymentContext.for_decode(
                slot_vars=self.slot_vars, max_slots=self.max_slots,
                row_fetches=self.fetch_names)
            self.deployment_report = analysis.analyze_deployment(
                self.program, deploy, feed_names=[],
                fetch_names=self.fetch_names)
            self.deployment_report.raise_if_errors()
            self.row_certificates = dict(
                self.deployment_report.certificates)

        # non-slot state the step reads must exist in the scope too
        # (zero-init whatever the model load didn't provide)
        self._reset_slot_state()
        for n in state_read:
            if n not in self._slot_var_meta \
                    and self._scope.get(n) is None:
                var = find_var(self.program, n)
                shape = [d if d >= 0 else 1 for d in (var.shape or [1])]
                dtype = convert_dtype(var.dtype) if var.dtype \
                    else "float32"
                self._scope.set(n, np.zeros(shape, dtype=dtype))

        self._update_rows = build_slot_update_fn()
        self.metrics = DecodeMetrics(latency_window=latency_window)
        self._batcher = DecodeBatcher(
            self._step, self._admit, self.max_slots,
            queue_capacity=queue_capacity,
            default_max_new_tokens=default_max_new_tokens,
            metrics=self.metrics, name=self.name)
        if warmup:
            try:
                self.warmup()
            except Exception:
                self.close(drain=False)   # no thread leak per failed
                raise                     # constructor

    # ----------------------------------------------------- slot state --
    def _zero_row(self, name):
        feat, dtype = self._slot_var_meta[name]
        return np.zeros(feat, dtype=dtype)

    def _reset_slot_state(self):
        """All slots to zeros — startup and post-warmup (a warmup step
        mutates carried state; serving must start from the same zeros a
        fresh solo engine starts from)."""
        for n, (feat, dtype) in self._slot_var_meta.items():
            self._scope.set(n, np.zeros((self.max_slots,) + feat,
                                        dtype=dtype))

    def _admit(self, slot, feeds):
        """DecodeBatcher admit callback: overwrite row `slot` of EVERY
        slot var — the stream's init rows where provided, zeros
        otherwise. One donated jitted row-write per admit; rows of other
        slots flow through bit-untouched (the slot-reuse half of the
        invariant)."""
        feeds = feeds or {}
        names = list(self.slot_vars)
        with self._run_lock:
            vals = tuple(self._scope.get(n) for n in names)
            rows = tuple(feeds[n] if n in feeds else self._zero_row(n)
                         for n in names)
            new_vals = self._update_rows(vals, np.int32(slot), rows)
            for n, v in zip(names, new_vals):
                self._scope.set(n, v)

    def _step(self):
        """DecodeBatcher step callback: ONE fixed-shape decode
        iteration through the ordinary Executor path (donated rw state,
        AOT cache, dispatch guards all compose). Returns host copies of
        the token/finished fetches — the per-iteration host sync is
        inherent to decode scheduling (the loop must see `finished` to
        admit/retire) — plus the lazy handles for window tracking."""
        with self._run_lock:
            handles = self._exe.run(self.program, feed={},
                                    fetch_list=self.fetch_names,
                                    scope=self._scope,
                                    return_numpy=False, validate=False)
        tokens = np.asarray(handles[0].array)
        finished = np.asarray(handles[1].array).reshape(-1).astype(bool)
        return tokens, finished, handles

    def warmup(self):
        """Compile the step (one run) and reset slot state to zeros, so
        the first admitted stream never pays the trace/compile."""
        from ..core.dispatch import run_compile_probe
        with self._run_lock:
            _, compiled = run_compile_probe(
                self._exe._cache,
                lambda: self._exe.run(self.program, feed={},
                                      fetch_list=self.fetch_names,
                                      scope=self._scope,
                                      return_numpy=False,
                                      validate=False))
        self._reset_slot_state()
        return int(bool(compiled))

    # ---------------------------------------------------------- public --
    def normalize_stream_feed(self, feeds):
        """Validate one stream's init rows: {slot var: row} with row
        shape == the var's per-slot shape (dtype cast here). Unknown
        names and shape mismatches are client faults (400s)."""
        feeds = dict(feeds or {})
        out = {}
        for n, value in feeds.items():
            if n not in self._slot_var_meta:
                raise InvalidRequestError(
                    "unknown slot var %r (decode slot state: %r)"
                    % (n, self.slot_vars))
            feat, dtype = self._slot_var_meta[n]
            row = np.asarray(value).astype(dtype, copy=False)
            if tuple(row.shape) != feat:
                raise InvalidRequestError(
                    "init row for %r has shape %r but the slot carries "
                    "%r per stream" % (n, tuple(row.shape), feat))
            out[n] = row
        return out

    def submit(self, feeds=None, max_new_tokens=None, deadline_ms=None):
        """Admit one sequence for continuous-batched decode; returns its
        DecodeStream (tokens arrive incrementally). `feeds` are per-slot
        init rows for a subset of `slot_vars` (e.g. the start token and
        an encoder context vector); everything else resets to zeros."""
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        return self._batcher.submit(self.normalize_stream_feed(feeds),
                                    max_new_tokens=max_new_tokens,
                                    deadline_ms=deadline_ms)

    def decode(self, feeds=None, max_new_tokens=None, deadline_ms=None,
               timeout=120.0):
        """Synchronous convenience: submit + wait; returns the stacked
        token array."""
        return self.submit(feeds, max_new_tokens=max_new_tokens,
                           deadline_ms=deadline_ms).result(timeout)

    def solo_clone(self, name=None, warmup=True):
        """A fresh engine over the SAME program and weights — the
        bit-exactness reference: decode one stream at a time on the
        clone and compare against the continuously-batched original.
        Read-only persistables (the weights — never donated) are shared
        by reference; writable non-slot state is copied (two engines
        must not donate one buffer); slot state starts from zeros, as
        always."""
        clone = DecodeEngine(
            program=self.program, token_var=self.token_name,
            finished_var=self.finished_name,
            slot_vars=list(self.slot_vars), max_slots=self.max_slots,
            place=self._exe.place, name=name or (self.name + "-solo"),
            validate=False, warmup=False,
            default_max_new_tokens=self._batcher.default_max_new_tokens)
        for n in self._state_ro:
            if n not in self._slot_var_meta:
                v = self._scope.get(n)
                if v is not None:
                    clone._scope.set(n, v)
        for n in set(self._state_rw) | set(self._state_out):
            if n not in self._slot_var_meta:
                v = self._scope.get(n)
                if v is not None:
                    clone._scope.set(n, np.array(np.asarray(v)))
        if warmup:
            try:
                clone.warmup()
            except Exception:
                clone.close(drain=False)
                raise
        return clone

    def decode_stats(self):
        return self._batcher.decode_stats()

    def queue_depth(self):
        return self._batcher.queue_depth()

    def device_span(self):
        return [str(self._exe.place.device())]

    def describe(self):
        """The /v1/models entry for this engine."""
        return {
            "name": self.name,
            "mode": "decode",
            "devices": self.device_span(),
            "slot_vars": [
                {"name": n, "row_shape": list(feat), "dtype": dtype}
                for n, (feat, dtype) in sorted(
                    self._slot_var_meta.items())],
            "token_var": self.token_name,
            "finished_var": self.finished_name,
            "max_slots": self.max_slots,
            "default_max_new_tokens":
                self._batcher.default_max_new_tokens,
            "status": "closed" if self.closed else "serving",
            "metrics": self.decode_stats(),
        }

    def drain(self, timeout=None):
        return self._batcher.drain(timeout)

    def close(self, drain=True, timeout=None):
        """Stop intake; drain=True retires every pending and resident
        stream first, drain=False fails them typed (no hang)."""
        self.closed = True
        self._batcher.close(drain=drain, timeout=timeout)
