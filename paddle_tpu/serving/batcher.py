"""Continuous micro-batching: bounded queue + pipelined form/dispatch.

Requests enter via `submit()` (any thread) and wait at most
`max_queue_delay_ms` — or until `max_batch_size` rows are pending — before
the FORMATION worker pops a contiguous batch. With `pipeline_depth >= 1`
(the default) formation is decoupled from execution: formed batches ride
a short queue to a DISPATCH worker that pads and enqueues them on the
device behind a bounded in-flight window (core/dispatch.InflightWindow),
so new rows admit into the *forming* batch while the current one
executes, and the device always has the next batch queued behind the
running one — continuous batching. Safe because dispatch returns
per-request result slices over lazy pre-D2H FetchHandles (no sync on the
dispatch path; the window's completion thread owns the only
block_until_ready) and because row results at a fixed compiled shape
depend only on that row (the engine's bucket-lattice invariant), so
overlapping batches can't perturb each other. `pipeline_depth=0` keeps
the PR-3 serial loop (form -> pad -> dispatch -> scatter on one thread)
for comparison benches.

Robustness contract (the parts of serving that are the subsystem, not an
afterthought):
  * bounded queue — `submit()` on a full queue raises `QueueFullError`
    immediately (backpressure beats unbounded latency),
  * per-request deadlines — expired requests never reach the device:
    checked at batch formation AND re-checked when a formed batch is
    popped for dispatch (it may have waited behind a full window),
  * graceful shutdown — `close(drain=True)` stops intake, drains every
    queued, formed and in-flight request, then joins both workers;
    `close(drain=False)` fails queued AND formed requests immediately.
"""
import collections
import threading
import time

from ..observability import registry as _obsreg
from ..observability import trace as _trace

__all__ = ["Batcher", "RequestFuture", "ServingError", "QueueFullError",
           "DeadlineExceededError", "ServingClosedError",
           "RequestTooLargeError", "DecodeStream", "DecodeBatcher"]


class ServingError(RuntimeError):
    """Base class for serving-runtime errors (HTTP layer maps these to
    status codes)."""


class QueueFullError(ServingError):
    """Fast rejection: the bounded request queue is at capacity.
    `retry_after_s`, when set (the ReplicaPool/fleet derive it from the
    AIMD admission state), is the client backoff hint the HTTP layer
    surfaces as a 429 `Retry-After` header."""
    retry_after_s = None


class DeadlineExceededError(ServingError):
    """The request's deadline passed while it waited in the queue."""


class ServingClosedError(ServingError):
    """The engine is shutting down (or closed) and rejects new work."""


class RequestTooLargeError(ServingError):
    """A single request exceeds max_batch_size rows — it could never be
    dispatched; reject at submit time instead of wedging the queue."""


class RequestFuture(object):
    """Completion handle for one submitted request.

    `result(timeout)` blocks until the batcher scatters the batch output
    (or fails the request) and returns the per-request value. The value a
    successful dispatch sets is an `engine.ResultSlice`: device-resident,
    row-sliced lazily — `result()` triggers only this request's D2H.
    """

    __slots__ = ("_event", "_value", "_error", "_callbacks", "_cb_lock",
                 "latency_s", "bucket")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error = None
        self._callbacks = []
        self._cb_lock = threading.Lock()
        self.latency_s = None   # submit -> scatter, set by the worker
        self.bucket = None      # (batch_bucket, seq_bucket|None) dispatched

    def done(self):
        return self._event.is_set()

    def add_done_callback(self, fn):
        """Run fn(self) once the future completes — immediately (on the
        calling thread) if it already has, otherwise on the completing
        thread (the batcher worker). The ReplicaPool rides this for
        health accounting and failover wakeups; callbacks must be cheap
        and must not block (they run inside the dispatch loop)."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _fire_callbacks(self):
        self._event.set()
        with self._cb_lock:
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:
            try:
                fn(self)
            except Exception:  # noqa: BLE001 — an observer must never
                pass           # fail the dispatch loop that notified it

    def set_result(self, value):
        self._value = value
        self._fire_callbacks()

    def set_exception(self, exc):
        self._error = exc
        self._fire_callbacks()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("request not completed within %rs" % timeout)
        if self._error is not None:
            raise self._error
        return self._value


# dispatch this far ahead of a pending deadline: a batch released exactly
# AT the deadline would lose the strict expiry check to scheduler jitter
_DEADLINE_MARGIN_S = 1e-3


class _Request(object):
    __slots__ = ("feed", "rows", "future", "deadline", "enqueued_at",
                 "trace", "span", "qspan")

    def __init__(self, feed, rows, deadline):
        self.feed = feed
        self.rows = rows
        self.future = RequestFuture()
        self.deadline = deadline          # monotonic seconds, or None
        self.enqueued_at = time.monotonic()
        # distributed-trace identity (ARCHITECTURE.md §24): one trace
        # per request; the root span + queue-wait child are armed at
        # submit, downstream batch spans carry this trace in their args
        self.trace = None
        self.span = _trace._NOOP
        self.qspan = _trace._NOOP


def _span_closer(span):
    """Future done-callback that ends the request's root span — runs on
    the completing thread (scatter or failure), cheap by contract."""
    def _cb(fut):
        err = getattr(fut, "_error", None)
        span.end(**({"error": type(err).__name__}
                    if err is not None else {}))
    return _cb


class Batcher(object):
    """The coalescing pipeline. `dispatch_fn(requests)` (the engine) pads
    the requests into one bucket, runs the executor once, scatters
    per-request results into `req.future`, and returns the batch's lazy
    fetch handles — the batcher decides WHAT rides in a batch, WHEN it
    leaves, and HOW MANY batches may be in flight on the device at once.

    pipeline_depth >= 1: continuous batching — a formation worker owns
    the request queue and a dispatch worker owns the device, joined by a
    short formed-batch queue; up to `pipeline_depth` dispatches stay
    outstanding (an InflightWindow completion thread recycles slots as
    the device finishes, off the dispatch path). pipeline_depth=0: the
    serial PR-3 loop, kept as the bench baseline."""

    def __init__(self, dispatch_fn, max_batch_size=32, max_queue_delay_ms=5,
                 queue_capacity=256, metrics=None, name="batcher",
                 pipeline_depth=2, coalesce=True):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if pipeline_depth < 0:
            raise ValueError("pipeline_depth must be >= 0")
        self._dispatch = dispatch_fn
        # coalesce=False is the row-independence certificate's fallback
        # (analysis/row_independence.py): the engine could not prove that
        # row i of every sliced fetch depends only on input row i, so
        # requests from different callers must not share a device batch.
        # Each batch then carries exactly one request — dispatch overhead
        # returns to per-request, but nobody reads a stranger's rows.
        self.coalesce = bool(coalesce)
        self.max_batch_size = int(max_batch_size)
        self.max_queue_delay_s = float(max_queue_delay_ms) / 1e3
        self.queue_capacity = int(queue_capacity)
        self.pipeline_depth = int(pipeline_depth)
        self._metrics = metrics
        self._queue = collections.deque()
        self._pending_rows = 0   # running sum over _queue (O(1) wakeups:
        self._deadlined = 0      # a burst must not cost O(n^2) rescans)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._draining = False
        self._drainers = 0       # live drain() calls: worker skips the
        self._dispatching = False  # coalescing window while any waits
        self._formed = collections.deque()  # formed, awaiting dispatch
        self._formed_cap = max(1, self.pipeline_depth)
        self._form_busy = False  # formation holds a popped batch
        self._form_done = False  # formation worker exited
        self._window = None
        if self.pipeline_depth >= 1:
            from ..core.dispatch import InflightWindow
            self._window = InflightWindow(self.pipeline_depth,
                                          tag="serving/%s/window" % name)
            self._workers = [
                threading.Thread(target=self._form_loop, daemon=True,
                                 name="ptpu-%s-form" % name),
                threading.Thread(target=self._dispatch_loop, daemon=True,
                                 name="ptpu-%s-dispatch" % name)]
        else:
            self._workers = [threading.Thread(
                target=self._loop, daemon=True, name="ptpu-" + name)]
        if metrics is not None:
            metrics.bind_queue_depth(lambda: len(self._queue))
        _obsreg.note_batcher(self, name)  # queue depths on /metrics
        for w in self._workers:
            w.start()

    # ---------------------------------------------------------- intake --
    def submit(self, feed, rows, deadline_ms=None):
        """Enqueue one request; returns its RequestFuture. Raises
        QueueFullError / ServingClosedError / RequestTooLargeError
        WITHOUT blocking — backpressure must be cheap for the caller."""
        if rows < 1:
            raise ValueError("request must carry at least one row")
        if rows > self.max_batch_size:
            raise RequestTooLargeError(
                "request has %d rows but max_batch_size is %d"
                % (rows, self.max_batch_size))
        deadline = (time.monotonic() + float(deadline_ms) / 1e3
                    if deadline_ms is not None else None)
        req = _Request(feed, rows, deadline)
        # per-request trace: root span submit -> scatter (ended by the
        # future's done callback, whatever thread completes it) with a
        # queue-wait child ended when the formation worker pops the
        # request. Armed BEFORE the lock: span creation is just an
        # object + perf_counter, but no reason to hold the queue lock
        req.trace = _trace.new_trace()
        req.span = _trace.span("serving/request", cat="serving",
                               trace=req.trace, rows=rows)
        req.qspan = req.span.child("serving/queue")
        if req.span is not _trace._NOOP:
            # recorder disabled = genuinely zero per-request cost: the
            # BENCH_OBS off leg is the baseline the <5% gate compares
            # against, so it must not keep the callback overhead
            req.future.add_done_callback(_span_closer(req.span))
        with self._cond:
            if self._closed:
                req.qspan.end(error="ServingClosedError")
                req.span.end(error="ServingClosedError")
                raise ServingClosedError("serving engine is shut down")
            if len(self._queue) >= self.queue_capacity:
                if self._metrics is not None:
                    self._metrics.on_queue_full()
                req.qspan.end(error="QueueFullError")
                req.span.end(error="QueueFullError")
                raise QueueFullError(
                    "request queue at capacity (%d); retry with backoff"
                    % self.queue_capacity)
            self._queue.append(req)
            self._pending_rows += req.rows
            if req.deadline is not None:
                self._deadlined += 1
            # notify_all: the formation worker, dispatch worker and any
            # drainers share this condition — a single notify could land
            # on a thread that isn't waiting for new requests
            self._cond.notify_all()
        if self._metrics is not None:
            self._metrics.on_submit()
        return req.future

    def queue_depth(self):
        return len(self._queue)

    def pipeline_stats(self):
        """Continuous-batching window stats ({"depth", "completed",
        "idle_s", "gaps"}), or None in serial mode — the public surface
        for pool/engine observability (the window itself stays an
        implementation detail)."""
        if self._window is None:
            return None
        stats = self._window.stats()
        stats["depth"] = self._window.depth
        return stats

    # ---------------------------------------------------------- worker --
    def _collect_batch(self):
        """Wait for work, honor the delay/size policy, pop one batch.
        Returns (requests, expired) or (None, None) on shutdown."""
        with self._cond:
            while not self._queue:
                if self._closed:
                    return None, None
                self._cond.wait()
            # coalescing window: anchored at the OLDEST pending request so
            # queue time is bounded by max_queue_delay even under trickle
            # arrivals; a full batch releases immediately. A pending
            # DEADLINE inside the window caps it — a request whose
            # deadline is shorter than max_queue_delay must be dispatched
            # before it expires, not held for coalescing it can't afford
            # (waiting the full window would 504 every such request under
            # light load).
            leave_at = self._queue[0].enqueued_at + self.max_queue_delay_s
            if not self.coalesce:
                leave_at = self._queue[0].enqueued_at  # nothing to wait for
            while not (self._closed or self._draining or self._drainers):
                if self._pending_rows >= self.max_batch_size \
                        or leave_at <= time.monotonic():
                    break  # O(1) fast paths BEFORE any deadline scan
                wake_at = leave_at
                if self._deadlined:  # only then is a scan needed at all
                    wake_at = min(
                        [leave_at] + [r.deadline - _DEADLINE_MARGIN_S
                                      for r in self._queue
                                      if r.deadline is not None])
                remaining = wake_at - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            batch, expired, rows, now = [], [], 0, time.monotonic()
            while self._queue:
                req = self._queue[0]
                if req.deadline is not None and req.deadline < now:
                    expired.append(self._pop_head())
                    continue
                if rows + req.rows > self.max_batch_size:
                    break
                if batch and not self.coalesce:
                    break  # one request per batch: see coalesce above
                batch.append(self._pop_head())
                rows += req.rows
            # mark the worker busy while STILL holding the lock: between
            # popping a batch and handing it on (formed queue or
            # dispatch) the queue may be empty, and a drain() that
            # declared victory in that window would return with requests
            # mid-flight
            if self._window is not None:
                self._form_busy = bool(batch)
            else:
                self._dispatching = bool(batch)
            return batch, expired

    def _pop_head(self):
        """Pop the queue head, keeping the incremental counters true.
        Caller holds the lock."""
        req = self._queue.popleft()
        self._pending_rows -= req.rows
        if req.deadline is not None:
            self._deadlined -= 1
        req.qspan.end()  # queue wait over: forming (or expiring) now
        return req

    def _fail_expired(self, expired):
        for req in expired:
            if not req.future.done():
                req.future.set_exception(DeadlineExceededError(
                    "deadline passed after %.1fms in queue"
                    % ((time.monotonic() - req.enqueued_at) * 1e3)))
        if expired and self._metrics is not None:
            self._metrics.on_deadline_expired(len(expired))

    def _run_batch(self, batch):
        """Pad+dispatch one formed batch: deadline re-check (a formed
        batch may have waited behind a full in-flight window), window
        slot, dispatch, completion tracking. The dispatch call itself is
        wrapped in profiler.dispatch_path() — any host sync inside is a
        pipeline stall the no-premature-sync regression test catches."""
        now = time.monotonic()
        live = [r for r in batch
                if r.deadline is None or r.deadline >= now]
        if len(live) != len(batch):
            self._fail_expired([r for r in batch if r not in live])
        if not live:
            return
        traces = [r.trace for r in live]
        # one BATCH trace groups this dispatch's spans — and is scoped
        # ambient around the dispatch call, so the engine's pad/enqueue
        # spans AND the Executor's exec/step span (minted layers below,
        # no trace parameter in run()) inherit it instead of starting
        # uncorrelated traces; the request traces ride in args
        btrace = _trace.new_trace()
        window = self._window
        if window is not None:
            # bounded in-flight: park until the device finishes a batch.
            # Poll so a hard close (drain=False) can't wedge this worker
            # behind a slot that will never free.
            wspan = _trace.span("serving/window_wait", cat="serving",
                                trace=btrace, traces=traces)
            while not window.acquire(timeout=0.1):
                with self._cond:
                    if self._closed and not self._draining:
                        wspan.end(error="ServingClosedError")
                        for req in live:
                            if not req.future.done():
                                req.future.set_exception(
                                    ServingClosedError(
                                        "serving engine shut down before "
                                        "dispatch"))
                        return
            wspan.end()
        enq_t = time.monotonic()
        dspan = _trace.span("serving/dispatch", cat="serving",
                            trace=btrace, reqs=len(live), traces=traces)
        try:
            from .. import profiler as _prof
            with _prof.dispatch_path(), _trace.scope_trace(btrace):
                handles = self._dispatch(live)
        except Exception as e:  # noqa: BLE001 — fail the batch, not the
            dspan.end(error=type(e).__name__)
            if window is not None:   # worker: serving must outlive one
                window.release()     # bad request batch
            for req in live:
                if not req.future.done():
                    req.future.set_exception(e)
            if self._metrics is not None:
                self._metrics.on_error(len(live))
        else:
            dspan.end()
            if window is not None:
                # window-slot occupancy span: enqueue -> the completion
                # thread observes the device finish (its one host sync
                # closes the span at the REAL completion instant — the
                # overlap of these spans across batches IS the
                # continuous-batching picture, bounded by the depth)
                espan = _trace.span("serving/execute", cat="serving",
                                    trace=btrace, traces=traces)
                window.track(handles or (), enq_t,
                             on_complete=espan.end)

    def _loop(self):
        """Serial mode (pipeline_depth=0): form -> dispatch, one thread."""
        while True:
            batch, expired = self._collect_batch()
            if batch is None:
                return
            self._fail_expired(expired)
            if not batch:
                if expired:
                    # an expired-only collection may have just emptied
                    # the queue: a drain() waiter parked on the
                    # condition would otherwise never be woken (the
                    # dispatch path's finally-notify is skipped here)
                    with self._cond:
                        self._cond.notify_all()
                continue
            try:
                self._run_batch(batch)
            finally:
                with self._cond:
                    self._dispatching = False
                    self._cond.notify_all()   # wake drain() waiters

    def _form_loop(self):
        """Pipelined formation: owns the request queue; hands formed
        batches to the dispatch worker through the bounded formed
        queue. While a batch dispatches, the NEXT one forms here."""
        while True:
            batch, expired = self._collect_batch()
            if batch is None:
                break
            self._fail_expired(expired)
            if not batch:
                if expired:
                    with self._cond:
                        self._cond.notify_all()
                continue
            # formed-batch span: formation done -> popped for dispatch
            # (the stage where a batch waits behind a full window)
            fspan = _trace.span("serving/formed_wait", cat="serving",
                                reqs=len(batch),
                                traces=[r.trace for r in batch])
            with self._cond:
                while len(self._formed) >= self._formed_cap \
                        and not self._closed:
                    self._cond.wait()
                if self._closed and not self._draining:
                    # hard close caught us holding a formed batch
                    self._form_busy = False
                    self._cond.notify_all()
                    fspan.end(error="ServingClosedError")
                    for req in batch:
                        if not req.future.done():
                            req.future.set_exception(ServingClosedError(
                                "serving engine shut down before "
                                "dispatch"))
                    continue
                self._formed.append((batch, fspan))
                self._form_busy = False
                self._cond.notify_all()
        with self._cond:
            self._form_done = True
            self._cond.notify_all()

    def _dispatch_loop(self):
        """Pipelined dispatch: pads and enqueues formed batches behind
        the in-flight window; exits once formation has exited and the
        formed queue is drained."""
        while True:
            with self._cond:
                while not self._formed and not self._form_done:
                    self._cond.wait()
                if not self._formed:
                    return  # formation exited, nothing left
                batch, fspan = self._formed.popleft()
                fspan.end()
                self._dispatching = True
                self._cond.notify_all()  # formation may wait on space
            try:
                self._run_batch(batch)
            finally:
                with self._cond:
                    self._dispatching = False
                    self._cond.notify_all()   # wake drain() waiters

    # ----------------------------------------------------------- drain --
    def drain(self, timeout=None):
        """Block until everything queued or mid-dispatch has been
        scattered (results set on every future). Intake stays open —
        this is the ONE drain implementation: `close(drain=True)` calls
        it after stopping intake, and the ReplicaPool's engine swap
        calls it directly on the outgoing engine (new submissions
        already route to the fresh engine, so the wait converges).
        While a drain is waiting the worker skips the coalescing window
        — queued work leaves in max_batch_size chunks immediately.
        Returns True when drained, False on timeout."""
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        with self._cond:
            self._drainers += 1
            self._cond.notify_all()        # cut the coalescing wait short
            try:
                while self._queue or self._formed or self._form_busy \
                        or self._dispatching:
                    if not any(w.is_alive() for w in self._workers) \
                            and not self._queue and not self._formed:
                        return True        # workers exited post-dispatch
                    remaining = None
                    if deadline is not None:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            return False
                    self._cond.wait(timeout=remaining)
                return True
            finally:
                self._drainers -= 1

    # -------------------------------------------------------- shutdown --
    def close(self, drain=True, timeout=None):
        """Stop intake; with drain=True the worker finishes every queued
        request first (via the shared `drain()` implementation — no
        further coalescing delay), otherwise pending requests fail with
        ServingClosedError."""
        with self._cond:
            already = self._closed
            self._closed = True
            if drain and not already:
                self._draining = True
            if not drain and not already:
                while self._queue:
                    self._pop_head().future.set_exception(
                        ServingClosedError("serving engine shut down "
                                           "before dispatch"))
                while self._formed:
                    formed_batch, fspan = self._formed.popleft()
                    fspan.end(error="ServingClosedError")
                    for req in formed_batch:
                        if not req.future.done():
                            req.future.set_exception(ServingClosedError(
                                "serving engine shut down before "
                                "dispatch"))
            self._cond.notify_all()
        if already:
            return
        if drain:
            self.drain(timeout)
        for w in self._workers:
            w.join(timeout)
        if self._window is not None:
            # after the workers: every tracked dispatch gets its
            # completion observed, then the completion thread exits
            self._window.close(timeout)


# ---------------------------------------------------------------------------
# Iteration-level continuous batching for autoregressive decode
# ---------------------------------------------------------------------------

class DecodeStream(object):
    """Handle for ONE decoding sequence under a DecodeBatcher.

    The request-shaped analogue of RequestFuture, except completion is
    incremental: the step-loop worker `_deliver`s a token per iteration
    while the stream occupies a slot, and `_finish`es it at retire.
    Consumers read tokens as they land (`next_token` / iteration) or
    wait for the whole sequence (`result`). Thread contract: `_deliver`/
    `_finish` are worker-only; everything public is any-thread."""

    __slots__ = ("stream_id", "feeds", "max_new_tokens", "deadline",
                 "enqueued_at", "slot", "trace", "span", "qspan",
                 "_cond", "_tokens", "_done", "_error", "_read",
                 "_last_tok_t", "admitted_at")

    def __init__(self, feeds, max_new_tokens, deadline):
        self.stream_id = None        # assigned at submit
        self.feeds = feeds           # per-slot init rows {var: row}
        self.max_new_tokens = int(max_new_tokens)
        self.deadline = deadline     # monotonic seconds, or None
        self.enqueued_at = time.monotonic()
        self.admitted_at = None
        self.slot = None             # batch row while resident
        self.trace = None
        self.span = _trace._NOOP
        self.qspan = _trace._NOOP
        self._cond = threading.Condition()
        self._tokens = []
        self._done = False
        self._error = None
        self._read = 0               # next_token cursor
        self._last_tok_t = None      # for inter-token gap accounting

    # ------------------------------------------------------- consumers --
    def done(self):
        with self._cond:
            return self._done

    def token_count(self):
        with self._cond:
            return len(self._tokens)

    def tokens(self):
        """Tokens delivered so far (list of per-step numpy values)."""
        with self._cond:
            return list(self._tokens)

    def next_token(self, timeout=None):
        """Block for the next undelivered token; returns it, or None
        once the stream finished and every token was read. Raises the
        stream's error (DeadlineExceededError / ServingClosedError /
        dispatch failure) as soon as it is observed past the delivered
        tokens — a consumer always sees every good token first."""
        with self._cond:
            if not self._cond.wait_for(
                    lambda: self._read < len(self._tokens) or self._done,
                    timeout):
                raise TimeoutError(
                    "no token within %rs (stream %r)"
                    % (timeout, self.stream_id))
            if self._read < len(self._tokens):
                tok = self._tokens[self._read]
                self._read += 1
                return tok
            if self._error is not None:
                raise self._error
            return None

    def __iter__(self):
        return self

    def __next__(self):
        tok = self.next_token()
        if tok is None:
            raise StopIteration
        return tok

    def result(self, timeout=None):
        """Block until the stream retires; returns ALL tokens stacked
        into one np.ndarray [n_tokens, ...]. Raises the stream's error
        (after a partial decode the delivered prefix stays readable via
        `tokens()`)."""
        import numpy as np
        with self._cond:
            if not self._cond.wait_for(lambda: self._done, timeout):
                raise TimeoutError(
                    "stream not finished within %rs" % (timeout,))
            if self._error is not None:
                raise self._error
            return np.stack(self._tokens) if self._tokens \
                else np.zeros((0,))

    # ---------------------------------------------------... worker-only --
    def _deliver(self, token, now):
        with self._cond:
            if self._done:
                return None
            gap = (now - self._last_tok_t) if self._last_tok_t is not None \
                else (now - (self.admitted_at or self.enqueued_at))
            self._last_tok_t = now
            self._tokens.append(token)
            self._cond.notify_all()
            return gap

    def _finish(self, error=None):
        with self._cond:
            if self._done:
                return False
            self._done = True
            self._error = error
            self._cond.notify_all()
        self.span.end(**({"error": type(error).__name__}
                         if error is not None else {}))
        return True


class DecodeBatcher(object):
    """Iteration-level (Orca-style) continuous batching for
    autoregressive decode: one step-loop worker owns a fixed lattice of
    `max_slots` batch rows and a compiled decode step at that ONE shape;
    streams are admitted into free slots and retired from finished ones
    BETWEEN iterations, so a long decode never blocks short strangers
    and slots refill mid-flight instead of waiting for the whole batch
    to drain.

    The engine supplies the device halves:
      admit_fn(slot, feeds) — reset slot `slot`'s carried state and
        write the stream's init rows (per-slot reset-on-admit: the
        invariant guard for slot reuse);
      step_fn() — one fixed-shape decode step over all slots; returns
        (tokens [slots, ...] np, finished [slots] bool np, handles)
        where handles are the step's lazy fetch handles for window
        completion tracking.

    Correctness under slot sharing is the engine's bucket-lattice
    invariant applied per step: at the fixed compiled shape a row's
    outputs and carried state depend only on that row, so a stream's
    token sequence is bit-identical to a solo decode regardless of who
    shares the batch or what previously occupied its slot
    (ARCHITECTURE.md §27). The step loop is intentionally serial
    (depth-1 window): each iteration must observe `finished` before it
    can schedule the next admit/retire, so decode pipelining happens
    ACROSS slots, not across iterations."""

    def __init__(self, step_fn, admit_fn, max_slots,
                 queue_capacity=256, default_max_new_tokens=128,
                 metrics=None, name="decode"):
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1, got %r"
                             % (max_slots,))
        from ..core.dispatch import InflightWindow
        from .metrics import DecodeMetrics
        self._step = step_fn
        self._admit = admit_fn
        self.max_slots = int(max_slots)
        self.queue_capacity = int(queue_capacity)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self._metrics = metrics if metrics is not None else DecodeMetrics()
        self._slots = [None] * self.max_slots   # slot -> DecodeStream
        self._free = list(range(self.max_slots - 1, -1, -1))
        self._pending = collections.deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._draining = False
        self._next_id = 0
        # depth 1: iterations are serial by construction (see class
        # docstring) but ride the window anyway — its completion thread
        # observes per-step device completion and its stats carry the
        # iteration counter to /metrics
        self._window = InflightWindow(1, tag="serving/%s/decode" % name)
        self._worker = threading.Thread(
            target=self._step_loop, daemon=True,
            name="ptpu-%s-decode" % name)
        _obsreg.note_decoder(self, name)
        self._worker.start()

    # ---------------------------------------------------------- intake --
    def submit(self, feeds, max_new_tokens=None, deadline_ms=None):
        """Enqueue one sequence; returns its DecodeStream. Raises
        QueueFullError / ServingClosedError without blocking."""
        if max_new_tokens is None:
            max_new_tokens = self.default_max_new_tokens
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1, got %r"
                             % (max_new_tokens,))
        deadline = (time.monotonic() + float(deadline_ms) / 1e3
                    if deadline_ms is not None else None)
        stream = DecodeStream(feeds, max_new_tokens, deadline)
        stream.trace = _trace.new_trace()
        stream.span = _trace.span("serving/stream", cat="serving",
                                  trace=stream.trace,
                                  max_new_tokens=int(max_new_tokens))
        stream.qspan = stream.span.child("serving/queue")
        with self._cond:
            if self._closed:
                stream.qspan.end(error="ServingClosedError")
                stream.span.end(error="ServingClosedError")
                raise ServingClosedError("decode engine is shut down")
            if len(self._pending) >= self.queue_capacity:
                self._metrics.on_queue_full()
                stream.qspan.end(error="QueueFullError")
                stream.span.end(error="QueueFullError")
                raise QueueFullError(
                    "decode queue at capacity (%d); retry with backoff"
                    % self.queue_capacity)
            self._next_id += 1
            stream.stream_id = self._next_id
            self._pending.append(stream)
            self._cond.notify_all()
        return stream

    def queue_depth(self):
        return len(self._pending)

    def decode_stats(self):
        """One snapshot joining slot occupancy (live) with the
        DecodeMetrics counters — the per-replica decode block
        `pool_state()` carries and the registry's decoder collector
        renders on /metrics."""
        with self._lock:
            occupied = sum(1 for s in self._slots if s is not None)
            pending = len(self._pending)
        snap = self._metrics.snapshot()
        snap.update({
            "slots": self.max_slots,
            "occupied_slots": occupied,
            "active_streams": occupied,
            "pending_streams": pending,
            "window": self._window.stats(),
        })
        return snap

    # ---------------------------------------------------------- worker --
    def _fail_stream(self, stream, exc, deadline=False):
        if stream._finish(exc):
            if deadline:
                self._metrics.on_deadline_expired()
            else:
                self._metrics.on_stream_failed()

    def _expire_pending_locked(self, now):
        """Drop overdue pending streams (typed, at the boundary)."""
        kept = collections.deque()
        while self._pending:
            s = self._pending.popleft()
            if s.deadline is not None and s.deadline < now:
                s.qspan.end(error="DeadlineExceededError")
                self._fail_stream(s, DeadlineExceededError(
                    "deadline passed after %.1fms waiting for a slot"
                    % ((now - s.enqueued_at) * 1e3)), deadline=True)
            else:
                kept.append(s)
        self._pending = kept

    def _collect_iteration(self):
        """Admit pending streams into free slots; return (admits,
        active) or (None, None) on shutdown. Blocks while idle."""
        with self._cond:
            while True:
                now = time.monotonic()
                self._expire_pending_locked(now)
                occupied = any(s is not None for s in self._slots)
                if self._closed and not self._draining:
                    return None, None           # hard close: streams
                if occupied or self._pending:   # already failed
                    break
                if self._closed:
                    return None, None           # drained dry
                self._cond.wait(timeout=0.5)
            admits = []
            while self._free and self._pending:
                s = self._pending.popleft()
                if s.deadline is not None and s.deadline < now:
                    s.qspan.end(error="DeadlineExceededError")
                    self._fail_stream(s, DeadlineExceededError(
                        "deadline passed after %.1fms waiting for a slot"
                        % ((now - s.enqueued_at) * 1e3)), deadline=True)
                    continue
                slot = self._free.pop()
                s.slot = slot
                self._slots[slot] = s
                admits.append(s)
            active = [(i, s) for i, s in enumerate(self._slots)
                      if s is not None]
            return admits, active

    def _retire_locked(self, slot, stream):
        """Free `slot` iff `stream` still owns it (a hard close may have
        reaped it concurrently — double-freeing would hand one slot to
        two streams)."""
        if self._slots[slot] is stream:
            self._slots[slot] = None
            self._free.append(slot)

    def _step_loop(self):
        from .. import profiler as _prof
        while True:
            admits, active = self._collect_iteration()
            if admits is None:
                return
            # device-side admit: reset-on-admit + the stream's init rows,
            # OUTSIDE the lock (submit/consumers must not wait on device
            # writes). The worker is the only device-touching thread.
            for s in admits:
                s.qspan.end()      # slot granted: queue wait over
                s.admitted_at = time.monotonic()
                try:
                    with _trace.span("serving/decode_admit", cat="serving",
                                     trace=s.trace, slot=s.slot,
                                     stream=s.stream_id):
                        self._admit(s.slot, s.feeds)
                    self._metrics.on_admit()
                except Exception as e:  # noqa: BLE001 — fail THIS
                    with self._cond:    # stream, not the loop
                        self._retire_locked(s.slot, s)
                        self._fail_stream(s, e)
                        self._cond.notify_all()
            # admits are already in the slot table (placed under the
            # lock in _collect_iteration); drop any stream a failed
            # admit or concurrent hard close finished meanwhile
            active = [(i, s) for i, s in active if not s.done()]
            if not active:
                continue
            # one decode iteration at the fixed compiled shape
            if not self._acquire_slot_or_bail(active):
                continue
            btrace = _trace.new_trace()
            enq_t = time.monotonic()
            dspan = _trace.span(
                "serving/decode_step", cat="serving", trace=btrace,
                slots=len(active),
                streams=[s.stream_id for _, s in active],
                traces=[s.trace for _, s in active])
            try:
                with _prof.dispatch_path(), _trace.scope_trace(btrace):
                    tokens, finished, handles = self._step()
            except Exception as e:  # noqa: BLE001 — fail the resident
                dspan.end(error=type(e).__name__)   # streams, keep the
                self._window.release()              # loop serving
                with self._cond:
                    for slot, s in active:
                        self._retire_locked(slot, s)
                        self._fail_stream(s, e)
                    self._cond.notify_all()
                continue
            dspan.end()
            espan = _trace.span("serving/decode_execute", cat="serving",
                                trace=btrace,
                                streams=[s.stream_id for _, s in active])
            self._window.track(handles or (), enq_t,
                               on_complete=espan.end)
            self._window.note_iteration()
            self._deliver_iteration(active, tokens, finished)

    def _acquire_slot_or_bail(self, active):
        """Window slot for this iteration; a hard close while the
        window is busy fails the resident streams instead of wedging."""
        while not self._window.acquire(timeout=0.1):
            with self._cond:
                if self._closed and not self._draining:
                    for slot, s in active:
                        self._retire_locked(slot, s)
                        self._fail_stream(s, ServingClosedError(
                            "decode engine shut down mid-stream"))
                    self._cond.notify_all()
                    return False
        return True

    def _deliver_iteration(self, active, tokens, finished):
        """Scatter this iteration's tokens to their streams and retire
        finished ones — the admit/retire boundary the next
        `_collect_iteration` sees."""
        now = time.monotonic()
        delivered, gaps = 0, []
        with self._cond:
            for slot, stream in active:
                if stream.done():   # hard close raced the step
                    self._retire_locked(slot, stream)
                    continue
                gap = stream._deliver(tokens[slot], now)
                if gap is not None:
                    delivered += 1
                    gaps.append(gap)
                n = stream.token_count()
                if bool(finished[slot]) or n >= stream.max_new_tokens:
                    self._retire_locked(slot, stream)
                    if stream._finish():
                        self._metrics.on_stream_completed()
                elif stream.deadline is not None and stream.deadline < now:
                    self._retire_locked(slot, stream)
                    self._fail_stream(stream, DeadlineExceededError(
                        "per-stream deadline passed after %d token(s)"
                        % n), deadline=True)
            self._cond.notify_all()   # admits may proceed; drain waiters
        self._metrics.on_iteration(len(active), delivered, gaps)

    # ----------------------------------------------------------- drain --
    def drain(self, timeout=None):
        """Block until every pending and resident stream has retired
        (tokens delivered, futures finished). Intake stays open, like
        Batcher.drain. Returns True when drained, False on timeout."""
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        with self._cond:
            while self._pending \
                    or any(s is not None for s in self._slots):
                if not self._worker.is_alive():
                    return True
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                self._cond.wait(timeout=remaining)
            return True

    # -------------------------------------------------------- shutdown --
    def close(self, drain=True, timeout=None):
        """Stop intake; drain=True finishes every pending and resident
        stream first, drain=False fails them ALL with
        ServingClosedError — typed, immediate, no hang: the worker bails
        at the next boundary and mid-flight consumers wake with the
        error after reading every already-delivered token."""
        with self._cond:
            already = self._closed
            self._closed = True
            if drain and not already:
                self._draining = True
            if not drain and not already:
                while self._pending:
                    s = self._pending.popleft()
                    s.qspan.end(error="ServingClosedError")
                    self._fail_stream(s, ServingClosedError(
                        "decode engine shut down before admit"))
                for slot, s in enumerate(self._slots):
                    if s is not None:
                        self._retire_locked(slot, s)
                        self._fail_stream(s, ServingClosedError(
                            "decode engine shut down mid-stream"))
            self._cond.notify_all()
        if already:
            return
        if drain:
            self.drain(timeout)
        self._worker.join(timeout)
        self._window.close(timeout)
