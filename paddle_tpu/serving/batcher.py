"""Dynamic micro-batching: a bounded request queue + one coalescing loop.

Requests enter via `submit()` (any thread) and wait at most
`max_queue_delay_ms` — or until `max_batch_size` rows are pending — before
the worker pops a contiguous batch, drops requests whose deadline already
passed (answered with `DeadlineExceededError` BEFORE any padding/dispatch
work is spent on them), and hands the rest to the engine's dispatch
function in one call. Dispatch returns per-request result slices built on
lazy FetchHandles: the device dispatch is enqueued but no D2H has
happened; each future materializes only its own rows when asked.

Robustness contract (the parts of serving that are the subsystem, not an
afterthought):
  * bounded queue — `submit()` on a full queue raises `QueueFullError`
    immediately (backpressure beats unbounded latency),
  * per-request deadlines — expired requests never reach the device,
  * graceful shutdown — `close(drain=True)` stops intake, drains every
    in-flight and queued request, then joins the worker.
"""
import collections
import threading
import time

__all__ = ["Batcher", "RequestFuture", "ServingError", "QueueFullError",
           "DeadlineExceededError", "ServingClosedError",
           "RequestTooLargeError"]


class ServingError(RuntimeError):
    """Base class for serving-runtime errors (HTTP layer maps these to
    status codes)."""


class QueueFullError(ServingError):
    """Fast rejection: the bounded request queue is at capacity."""


class DeadlineExceededError(ServingError):
    """The request's deadline passed while it waited in the queue."""


class ServingClosedError(ServingError):
    """The engine is shutting down (or closed) and rejects new work."""


class RequestTooLargeError(ServingError):
    """A single request exceeds max_batch_size rows — it could never be
    dispatched; reject at submit time instead of wedging the queue."""


class RequestFuture(object):
    """Completion handle for one submitted request.

    `result(timeout)` blocks until the batcher scatters the batch output
    (or fails the request) and returns the per-request value. The value a
    successful dispatch sets is an `engine.ResultSlice`: device-resident,
    row-sliced lazily — `result()` triggers only this request's D2H.
    """

    __slots__ = ("_event", "_value", "_error", "_callbacks", "_cb_lock",
                 "latency_s", "bucket")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error = None
        self._callbacks = []
        self._cb_lock = threading.Lock()
        self.latency_s = None   # submit -> scatter, set by the worker
        self.bucket = None      # (batch_bucket, seq_bucket|None) dispatched

    def done(self):
        return self._event.is_set()

    def add_done_callback(self, fn):
        """Run fn(self) once the future completes — immediately (on the
        calling thread) if it already has, otherwise on the completing
        thread (the batcher worker). The ReplicaPool rides this for
        health accounting and failover wakeups; callbacks must be cheap
        and must not block (they run inside the dispatch loop)."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _fire_callbacks(self):
        self._event.set()
        with self._cb_lock:
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:
            try:
                fn(self)
            except Exception:  # noqa: BLE001 — an observer must never
                pass           # fail the dispatch loop that notified it

    def set_result(self, value):
        self._value = value
        self._fire_callbacks()

    def set_exception(self, exc):
        self._error = exc
        self._fire_callbacks()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("request not completed within %rs" % timeout)
        if self._error is not None:
            raise self._error
        return self._value


# dispatch this far ahead of a pending deadline: a batch released exactly
# AT the deadline would lose the strict expiry check to scheduler jitter
_DEADLINE_MARGIN_S = 1e-3


class _Request(object):
    __slots__ = ("feed", "rows", "future", "deadline", "enqueued_at")

    def __init__(self, feed, rows, deadline):
        self.feed = feed
        self.rows = rows
        self.future = RequestFuture()
        self.deadline = deadline          # monotonic seconds, or None
        self.enqueued_at = time.monotonic()


class Batcher(object):
    """The coalescing loop. `dispatch_fn(requests)` (the engine) pads the
    requests into one bucket, runs the executor once, and scatters
    per-request results into `req.future` — the worker only decides WHAT
    rides in a batch and WHEN it leaves."""

    def __init__(self, dispatch_fn, max_batch_size=32, max_queue_delay_ms=5,
                 queue_capacity=256, metrics=None, name="batcher"):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self._dispatch = dispatch_fn
        self.max_batch_size = int(max_batch_size)
        self.max_queue_delay_s = float(max_queue_delay_ms) / 1e3
        self.queue_capacity = int(queue_capacity)
        self._metrics = metrics
        self._queue = collections.deque()
        self._pending_rows = 0   # running sum over _queue (O(1) wakeups:
        self._deadlined = 0      # a burst must not cost O(n^2) rescans)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._draining = False
        self._drainers = 0       # live drain() calls: worker skips the
        self._dispatching = False  # coalescing window while any waits
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="ptpu-" + name)
        if metrics is not None:
            metrics.bind_queue_depth(lambda: len(self._queue))
        self._worker.start()

    # ---------------------------------------------------------- intake --
    def submit(self, feed, rows, deadline_ms=None):
        """Enqueue one request; returns its RequestFuture. Raises
        QueueFullError / ServingClosedError / RequestTooLargeError
        WITHOUT blocking — backpressure must be cheap for the caller."""
        if rows < 1:
            raise ValueError("request must carry at least one row")
        if rows > self.max_batch_size:
            raise RequestTooLargeError(
                "request has %d rows but max_batch_size is %d"
                % (rows, self.max_batch_size))
        deadline = (time.monotonic() + float(deadline_ms) / 1e3
                    if deadline_ms is not None else None)
        req = _Request(feed, rows, deadline)
        with self._cond:
            if self._closed:
                raise ServingClosedError("serving engine is shut down")
            if len(self._queue) >= self.queue_capacity:
                if self._metrics is not None:
                    self._metrics.on_queue_full()
                raise QueueFullError(
                    "request queue at capacity (%d); retry with backoff"
                    % self.queue_capacity)
            self._queue.append(req)
            self._pending_rows += req.rows
            if req.deadline is not None:
                self._deadlined += 1
            self._cond.notify()
        if self._metrics is not None:
            self._metrics.on_submit()
        return req.future

    def queue_depth(self):
        return len(self._queue)

    # ---------------------------------------------------------- worker --
    def _collect_batch(self):
        """Wait for work, honor the delay/size policy, pop one batch.
        Returns (requests, expired) or (None, None) on shutdown."""
        with self._cond:
            while not self._queue:
                if self._closed:
                    return None, None
                self._cond.wait()
            # coalescing window: anchored at the OLDEST pending request so
            # queue time is bounded by max_queue_delay even under trickle
            # arrivals; a full batch releases immediately. A pending
            # DEADLINE inside the window caps it — a request whose
            # deadline is shorter than max_queue_delay must be dispatched
            # before it expires, not held for coalescing it can't afford
            # (waiting the full window would 504 every such request under
            # light load).
            leave_at = self._queue[0].enqueued_at + self.max_queue_delay_s
            while not (self._closed or self._draining or self._drainers):
                if self._pending_rows >= self.max_batch_size \
                        or leave_at <= time.monotonic():
                    break  # O(1) fast paths BEFORE any deadline scan
                wake_at = leave_at
                if self._deadlined:  # only then is a scan needed at all
                    wake_at = min(
                        [leave_at] + [r.deadline - _DEADLINE_MARGIN_S
                                      for r in self._queue
                                      if r.deadline is not None])
                remaining = wake_at - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            batch, expired, rows, now = [], [], 0, time.monotonic()
            while self._queue:
                req = self._queue[0]
                if req.deadline is not None and req.deadline < now:
                    expired.append(self._pop_head())
                    continue
                if rows + req.rows > self.max_batch_size:
                    break
                batch.append(self._pop_head())
                rows += req.rows
            # mark the worker busy while STILL holding the lock: between
            # popping a batch and scattering its results the queue may be
            # empty, and a drain() that declared victory in that window
            # would return with requests mid-dispatch
            self._dispatching = bool(batch)
            return batch, expired

    def _pop_head(self):
        """Pop the queue head, keeping the incremental counters true.
        Caller holds the lock."""
        req = self._queue.popleft()
        self._pending_rows -= req.rows
        if req.deadline is not None:
            self._deadlined -= 1
        return req

    def _loop(self):
        while True:
            batch, expired = self._collect_batch()
            if batch is None:
                return
            for req in expired:
                req.future.set_exception(DeadlineExceededError(
                    "deadline passed after %.1fms in queue"
                    % ((time.monotonic() - req.enqueued_at) * 1e3)))
            if expired and self._metrics is not None:
                self._metrics.on_deadline_expired(len(expired))
            if not batch:
                if expired:
                    # an expired-only collection may have just emptied
                    # the queue: a drain() waiter parked on the
                    # condition would otherwise never be woken (the
                    # dispatch path's finally-notify is skipped here)
                    with self._cond:
                        self._cond.notify_all()
                continue
            try:
                self._dispatch(batch)
            except Exception as e:  # noqa: BLE001 — fail the batch, not
                for req in batch:   # the worker: serving must outlive one
                    if not req.future.done():   # bad request batch
                        req.future.set_exception(e)
                if self._metrics is not None:
                    self._metrics.on_error(len(batch))
            finally:
                with self._cond:
                    self._dispatching = False
                    self._cond.notify_all()   # wake drain() waiters

    # ----------------------------------------------------------- drain --
    def drain(self, timeout=None):
        """Block until everything queued or mid-dispatch has been
        scattered (results set on every future). Intake stays open —
        this is the ONE drain implementation: `close(drain=True)` calls
        it after stopping intake, and the ReplicaPool's engine swap
        calls it directly on the outgoing engine (new submissions
        already route to the fresh engine, so the wait converges).
        While a drain is waiting the worker skips the coalescing window
        — queued work leaves in max_batch_size chunks immediately.
        Returns True when drained, False on timeout."""
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        with self._cond:
            self._drainers += 1
            self._cond.notify_all()        # cut the coalescing wait short
            try:
                while self._queue or self._dispatching:
                    if not self._worker.is_alive() and not self._queue:
                        return True        # worker exited post-dispatch
                    remaining = None
                    if deadline is not None:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            return False
                    self._cond.wait(timeout=remaining)
                return True
            finally:
                self._drainers -= 1

    # -------------------------------------------------------- shutdown --
    def close(self, drain=True, timeout=None):
        """Stop intake; with drain=True the worker finishes every queued
        request first (via the shared `drain()` implementation — no
        further coalescing delay), otherwise pending requests fail with
        ServingClosedError."""
        with self._cond:
            already = self._closed
            self._closed = True
            if drain and not already:
                self._draining = True
            if not drain and not already:
                while self._queue:
                    self._pop_head().future.set_exception(
                        ServingClosedError("serving engine shut down "
                                           "before dispatch"))
            self._cond.notify_all()
        if already:
            return
        if drain:
            self.drain(timeout)
        self._worker.join(timeout)
