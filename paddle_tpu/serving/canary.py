"""Canary / shadow promotion on top of ReplicaPool.reload().

`pool.reload()` is all-or-nothing: every replica flips to the new
weights, and a bad push serves garbage from 100% of the fleet until an
operator notices. `pool.promote()` makes promotion SAFE: the candidate
snapshot first earns its traffic.

  * **canary mode** — a configurable slice of requests
    (`traffic_fraction`, counter-based so the slice is deterministic)
    is answered by ONE warmed canary engine built off the candidate.
    Every canaried request is also MIRRORED to an incumbent replica
    through the pool's normal failover machinery, which is what makes
    the zero-client-error guarantee structural: the client's answer is
    the canary's only when it was already in hand when the incumbent's
    completed AND this request's gate passes (finite outputs,
    divergence vs the mirror within the bound, latency within the
    ratio); on any breach — or a canary still running — the client
    silently gets the incumbent's answer with zero added latency (the
    gate is then judged off the response path, and a canary that never
    answers is reaped as a timeout breach) — a corrupt or wedged canary
    can NEVER surface as a client error or a latency spike, only as
    gate breaches that roll the promotion back.
  * **shadow mode** — same machinery, but the client always gets the
    incumbent's answer and the canary is judged off the response path
    (compare-only). Zero client risk by construction; use it to soak a
    candidate before a canary run.

Gating rides the PR-13 divergence machinery: the per-request divergence
measure is max |c - i| / (max|i| + 1e-6) over the fetches — the same
formula as the quantized-serving selfcheck — and the default bound
resolves PADDLE_TPU_CANARY_BOUND -> `quantize.divergence_bound(dtype)`
for a quantized canary -> 0.05. Latency gates on canary-vs-mirror
submit->scatter time (`latency_ratio` x mirror + `latency_margin_s`).

The state machine (exposed as `pool.pool_state()["promotion"]`):

    canary|shadow --breaches >= max_breaches--> rolled_back
    canary|shadow --oks >= min_requests------> promoting
    promoting --pool.reload(candidate) ok----> promoted
    promoting --reload raises----------------> rolled_back
    canary|shadow --cancel()-----------------> cancelled

`rolled_back` closes the canary engine (no drain — its weights are
suspect) and routes 100% of traffic to the incumbent replicas, which
never stopped serving; `promoted` runs the ordinary zero-downtime
`reload()` onto the candidate source (AOT-warm, nothing dropped) and
then retires the canary engine gracefully. Fault injection:
`canary_poison@N` (resilience/faults.py) corrupts the canary engine's
weights at its Nth dispatch — the CI-provable bad-canary case. Design
notes: ARCHITECTURE.md §26.
"""
import os
import threading
import time

import numpy as np

__all__ = ["CanaryController", "CanaryFuture"]

# active (routing) -> terminal states
CANARY, SHADOW = "canary", "shadow"
PROMOTING, PROMOTED = "promoting", "promoted"
ROLLED_BACK, CANCELLED = "rolled_back", "cancelled"
_ROUTING = (CANARY, SHADOW)


def _default_bound(engine):
    """Explicit arg > PADDLE_TPU_CANARY_BOUND > the quantized-serving
    bound for a non-fp32 canary > 0.05 (a same-architecture candidate
    that moves outputs more than 5% relative is not a safe promote
    without an explicit, intentional bound)."""
    env = os.environ.get("PADDLE_TPU_CANARY_BOUND", "")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    dtype = getattr(engine, "weights_dtype", "fp32")
    if dtype != "fp32":
        from .quantize import divergence_bound
        return divergence_bound(dtype)
    return 0.05


def _divergence(canary_out, mirror_out):
    """max over fetches of max |c - i| / (max|i| + 1e-6) — the PR-13
    quantized-serving formula, per request."""
    worst = 0.0
    for name, ref in mirror_out.items():
        if name not in canary_out:
            return float("inf")   # missing fetch = maximally divergent
        f = np.asarray(ref, dtype=np.float64)
        q = np.asarray(canary_out[name], dtype=np.float64)
        if f.shape != q.shape:
            return float("inf")
        if f.size:
            worst = max(worst, float(np.abs(q - f).max()
                                     / (np.abs(f).max() + 1e-6)))
    return worst


class CanaryFuture(object):
    """One canaried request: a normal pool future (the incumbent
    mirror, full failover guarantees) plus the canary engine's future.
    `result()` NEVER waits on the canary: the canary's answer is served
    only when it was already in hand by the time the incumbent's answer
    completed AND this request's gate passed; in every other case —
    breach, canary still running, canary wedged — the client silently
    gets the mirror's answer with zero added latency, and the gate is
    judged off the response path (the controller's pending reaper
    breaches a canary that never answers within `canary_wait_s`). A
    mirror failure propagates exactly as it would for a non-canaried
    request — the canary can only ever improve on the incumbent path,
    never regress it."""

    __slots__ = ("_ctrl", "_mirror", "_cfut", "_submitted_at",
                 "_gate_done", "_final", "latency_s", "bucket")

    def __init__(self, ctrl, mirror, cfut):
        self._ctrl = ctrl
        self._mirror = mirror
        self._cfut = cfut          # engine RequestFuture, or the submit
        self._submitted_at = time.monotonic()  # exception instance
        self._gate_done = False    # controller recorded ONE sample
        self._final = None         # the answer served (stable across
        self.latency_s = None      # repeated result() calls)
        self.bucket = None

    def done(self):
        return self._mirror.done()

    def result(self, timeout=None):
        if self._final is not None:
            return self._final
        value = self._mirror.result(timeout)   # raises = the incumbent
        # path failed; identical to a non-canaried request
        self.latency_s = self._mirror.latency_s
        self.bucket = self._mirror.bucket
        ctrl = self._ctrl
        out = value
        cfut = self._cfut
        if not hasattr(cfut, "result"):
            # canary submit failed at claim time: breach, mirror serves
            ctrl.judge(self, value.numpy(), self.latency_s)
        elif cfut.done():
            if ctrl.mode == CANARY:
                verdict, canary_value = ctrl.judge(
                    self, value.numpy(), self.latency_s,
                    want_value=True)
                if verdict == "ok" and canary_value is not None:
                    out = canary_value
            else:
                ctrl.judge(self, value.numpy(), self.latency_s)
        else:
            # the canary hasn't answered and the incumbent has: serve
            # the mirror NOW and judge on the canary's completing
            # thread later — a slow or wedged canary must not add a
            # millisecond to any client's latency
            mirror_out = value.numpy()
            lat = self.latency_s
            ctrl.note_pending(self)
            cfut.add_done_callback(
                lambda _f: ctrl.judge(self, mirror_out, lat))
        self._final = out
        return out


class CanaryController(object):
    def __init__(self, pool, engine, source, mode=CANARY,
                 traffic_fraction=0.05, min_requests=32, max_breaches=3,
                 divergence_bound=None, latency_ratio=3.0,
                 latency_margin_s=0.05, canary_wait_s=None,
                 auto_finalize=True):
        if not (0.0 < float(traffic_fraction) <= 1.0):
            raise ValueError("traffic_fraction must be in (0, 1], got %r"
                             % (traffic_fraction,))
        self.pool = pool
        self.engine = engine            # the warmed candidate engine
        self._source = dict(source)     # reload(**source) on promote
        self.mode = mode
        self.traffic_fraction = float(traffic_fraction)
        self._interval = max(1, int(round(1.0 / self.traffic_fraction)))
        self.min_requests = int(min_requests)
        self.max_breaches = int(max_breaches)
        self.divergence_bound = (float(divergence_bound)
                                 if divergence_bound is not None
                                 else _default_bound(engine))
        self.latency_ratio = (float(latency_ratio)
                              if latency_ratio is not None else None)
        self.latency_margin_s = float(latency_margin_s)
        self.canary_wait_s = (float(canary_wait_s)
                              if canary_wait_s is not None
                              else (pool.attempt_timeout_s or 10.0))
        self.auto_finalize = bool(auto_finalize)

        self._lock = threading.Lock()
        self._state = mode
        self._pending = []     # (fut, deadline): canaries judged off
        # the response path, reaped as timeout breaches if they never
        # answer (see _reap_pending)
        self._sel = 0          # request counter for the traffic slice
        self.sampled = 0       # canaried requests judged
        self.oks = 0
        self.breaches = 0
        self.breach_kinds = {}
        self.max_divergence = 0.0
        self.reason = None
        self.promoted_step = None
        self.started_at = time.monotonic()

    # ---------------------------------------------------------- routing --
    def is_routing(self):
        return self._state in _ROUTING

    def maybe_submit(self, norm, deadline_ms):
        """Called by pool.submit for every accepted request: claim this
        one for the slice (deterministic counter, not randomness) or
        return None for the normal path. A claimed request gets the
        mirror attempt (pool machinery) + the canary attempt."""
        if not self.is_routing():
            return None
        self._reap_pending()   # a wedged canary's unanswered requests
        # become timeout breaches here — without this touchpoint a
        # canary that never answers would stall the promotion forever
        if not self.is_routing():
            return None        # the reap may just have rolled back
        with self._lock:
            take = self._sel % self._interval == 0
            self._sel += 1
        if not take:
            return None
        from .pool import PoolFuture
        mirror = PoolFuture(self.pool, norm, deadline_ms)
        self.pool._submit_attempt(mirror)
        try:
            cfut = self.engine.submit_normalized(norm,
                                                 deadline_ms=deadline_ms)
        except Exception as e:  # noqa: BLE001 — a canary that cannot
            # even accept its slice is a breach, never a client error
            cfut = e
        return CanaryFuture(self, mirror, cfut)

    # ---------------------------------------------------------- judging --
    def note_pending(self, fut):
        """A canaried request whose mirror answered first: judged when
        the canary completes (done-callback), or reaped as a timeout
        breach canary_wait_s after the mirror served."""
        with self._lock:
            self._pending.append((fut,
                                  time.monotonic() + self.canary_wait_s))

    def _reap_pending(self):
        """Expire unanswered off-path canaries as timeout breaches.
        Called from the controller's touchpoints (new claims, later
        judgments) — no dedicated thread; the clients involved were
        served mirror answers long ago."""
        now = time.monotonic()
        expired = []
        with self._lock:
            keep = []
            for fut, deadline in self._pending:
                if fut._gate_done:
                    continue           # judged by its callback already
                if now >= deadline:
                    fut._gate_done = True
                    expired.append(fut)
                else:
                    keep.append((fut, deadline))
            self._pending = keep
        for _ in expired:
            self._record_breach(
                "timeout", "canary did not answer within %.1fs"
                % self.canary_wait_s)

    def judge(self, fut, mirror_out, mirror_latency_s, want_value=False):
        """Gate one canaried request — on the client thread when the
        canary answered before the mirror, else on the canary's
        completing thread (off the response path). Idempotent per
        request. Returns (verdict, canary_PoolResult|None); verdict
        'ok' means the canary's answer may be served."""
        from .pool import PoolResult
        with self._lock:
            if fut._gate_done:
                return "skip", None
            fut._gate_done = True
        self._reap_pending()
        if not self.is_routing():
            return "skip", None
        cfut = fut._cfut
        if not hasattr(cfut, "result"):       # submit failed at claim
            self._record_breach("submit", repr(cfut))
            return "breach", None
        try:
            # the canary future is DONE on every path that reaches here
            # (inline = done-check, callback = completion): this never
            # blocks a client
            slice_ = cfut.result(1.0)
            outputs = slice_.numpy()
        except Exception as e:  # noqa: BLE001 — canary error/timeout:
            self._record_breach("error", repr(e))   # breach, not client
            return "breach", None                   # visible
        for name, arr in outputs.items():
            a = np.asarray(arr)
            if np.issubdtype(a.dtype, np.floating) \
                    and not np.isfinite(a).all():
                self._record_breach("non_finite", name)
                return "breach", None
        div = _divergence(outputs, mirror_out)
        with self._lock:
            self.max_divergence = max(self.max_divergence, div)
        if div > self.divergence_bound:
            self._record_breach("divergence",
                                "%.3e > %.3e" % (div,
                                                 self.divergence_bound))
            return "breach", None
        if (self.latency_ratio is not None
                and mirror_latency_s is not None
                and cfut.latency_s is not None
                and cfut.latency_s > self.latency_ratio * mirror_latency_s
                + self.latency_margin_s):
            self._record_breach(
                "latency", "%.3fs vs mirror %.3fs"
                % (cfut.latency_s, mirror_latency_s))
            return "breach", None
        self._record_ok()
        if not want_value:
            return "ok", None
        return "ok", PoolResult(outputs, cfut.bucket)

    def _record_ok(self):
        finalize = False
        with self._lock:
            if self._state not in _ROUTING:
                return
            self.sampled += 1
            self.oks += 1
            if (self.auto_finalize and self.oks >= self.min_requests
                    and self.breaches < self.max_breaches):
                self._state = PROMOTING
                finalize = True
        if finalize:
            self.pool._event("canary_promote", "canary",
                             "%d/%d ok, max divergence %.3e"
                             % (self.oks, self.sampled,
                                self.max_divergence))
            threading.Thread(target=self._do_finalize, daemon=True,
                             name="ptpu-canary-promote").start()

    def _record_breach(self, kind, detail):
        rollback = False
        with self._lock:
            if self._state not in _ROUTING:
                return
            self.sampled += 1
            self.breaches += 1
            self.breach_kinds[kind] = self.breach_kinds.get(kind, 0) + 1
            if self.breaches >= self.max_breaches:
                self._state = ROLLED_BACK
                self.reason = "%s: %s" % (kind, detail)
                rollback = True
        self.pool._event("canary_breach", "canary",
                         "%s: %s" % (kind, detail))
        if rollback:
            self.pool._event("canary_rollback", "canary", self.reason)
            from ..observability import trace as _otrace
            _otrace.instant("pool/canary_rollback", cat="serving")
            self._close_engine(drain=False)

    # --------------------------------------------------------- lifecycle --
    def finalize(self):
        """Manually promote (auto_finalize=False flows). Raises unless
        the canary has earned it (enough oks, breaches under budget)."""
        with self._lock:
            if self._state not in _ROUTING:
                raise RuntimeError("promotion is %s" % self._state)
            if self.oks < self.min_requests \
                    or self.breaches >= self.max_breaches:
                raise RuntimeError(
                    "canary has not earned promotion: %d/%d oks, "
                    "%d breaches" % (self.oks, self.min_requests,
                                     self.breaches))
            self._state = PROMOTING
        self._do_finalize()
        return self.promoted_step

    def _do_finalize(self):
        """The ordinary zero-downtime reload onto the candidate source —
        every replica flips AOT-warm, nothing dropped — then the canary
        engine retires gracefully."""
        try:
            step = self.pool.reload(**self._source)
        except Exception as e:  # noqa: BLE001 — a failed final reload
            # leaves the incumbent fleet serving; the candidate is NOT
            # promoted
            with self._lock:
                self._state = ROLLED_BACK
                self.reason = "final reload failed: %r" % (e,)
            self.pool._event("canary_rollback", "canary", self.reason)
            self._close_engine(drain=False)
            return
        with self._lock:
            self._state = PROMOTED
            self.promoted_step = step
        self.pool._event("promoted", "canary",
                         "step %r at 100%%" % (step,))
        from ..observability import trace as _otrace
        _otrace.instant("pool/promoted", cat="serving")
        self._close_engine(drain=True)

    def cancel(self, reason="cancelled"):
        with self._lock:
            if self._state not in _ROUTING:
                return
            self._state = CANCELLED
            self.reason = reason
        self.pool._event("canary_cancel", "canary", reason)
        self._close_engine(drain=False)

    def _close_engine(self, drain):
        """Always off-thread: judge() runs on client threads and (shadow
        mode) on the canary's own batcher worker — engine.close joins
        that very worker."""
        eng = self.engine
        threading.Thread(
            target=lambda: eng.close(drain=drain, timeout=5.0),
            daemon=True, name="ptpu-canary-close").start()

    def state(self):
        with self._lock:
            return {
                "state": self._state,
                "mode": self.mode,
                "traffic_fraction": self.traffic_fraction,
                "sampled": self.sampled,
                "oks": self.oks,
                "breaches": self.breaches,
                "breach_kinds": dict(self.breach_kinds),
                "min_requests": self.min_requests,
                "max_breaches": self.max_breaches,
                "divergence_bound": self.divergence_bound,
                "max_divergence": round(self.max_divergence, 6),
                "reason": self.reason,
                "promoted_step": self.promoted_step,
            }
