"""Weight-dtype reduction for serving engines (the quantized serving
path of ARCHITECTURE.md §25).

`InferenceEngine(..., weights_dtype=...)` trades weight precision for
memory/throughput PER ENGINE, at load time, without touching the fp32
master checkpoint or export:

* "fp32" — no-op (the default).
* "bf16" — the matmul/conv weight params cast to bfloat16 in the
  engine's private Scope AND the program's AMP flag flips on, so the
  MXU contractions run bf16 end to end (the same lowering path training
  AMP uses; norm statistics and losses stay f32). Half the weight HBM,
  2x MXU throughput on real TPU.
* "int8" — the matmul/conv weight params are quantized per output
  channel (symmetric, scale = max|W_c| / 127) and REWRITTEN into the
  program: the param var is demoted to a computed intermediate fed by a
  prepended `dequantize_channel` op over two new persistables,
  <name>@QVAL (int8 values) and <name>@QSCALE (f32 per-channel scales).
  Consumers are untouched — they read the same var name, now produced
  in-graph; XLA fuses the dequantize multiply into the consumer, so the
  weight is stored at 1/4 size and widened to f32 on the way into the
  MXU. Compute precision is unchanged — the divergence vs fp32 is
  exactly the per-channel rounding, which is what the selfcheck /
  bench divergence gates bound.

Only params consumed as matmul/conv weights quantize (mul/matmul "Y",
conv "Filter"); biases, norm parameters and embedding tables stay f32 —
they are small, and their error would compound differently. The program
rewrite bumps the program version and content hash, so the jit caches
and the AOT compile cache key the quantized build separately from the
fp32 one by construction.
"""
import numpy as np

__all__ = ["WEIGHTS_DTYPES", "QVAL_SUFFIX", "QSCALE_SUFFIX",
           "quantizable_params", "apply_weights_dtype",
           "divergence_bound"]

WEIGHTS_DTYPES = ("fp32", "bf16", "int8")
QVAL_SUFFIX = "@QVAL"
QSCALE_SUFFIX = "@QSCALE"

# op type -> (weight input slot, per-OUTPUT-channel axis of that param)
_WEIGHT_SLOTS = {
    "mul": ("Y", -1),
    "matmul": ("Y", -1),
    "conv2d": ("Filter", 0),            # OIHW: O is axis 0
    "depthwise_conv2d": ("Filter", 0),
    "conv2d_transpose": ("Filter", 1),  # IOHW: O is axis 1
}

# default max-abs-divergence gates for the selfcheck / bench legs,
# relative to the fp32 engine's output magnitude (see divergence_bound).
_DEFAULT_BOUNDS = {"bf16": 5e-2, "int8": 5e-2, "fp32": 0.0}


def divergence_bound(weights_dtype):
    """The bounded-divergence gate for a quantized engine vs its fp32
    twin: max |q - f| / (max|f| + 1e-6) must stay under this.
    PADDLE_TPU_QUANT_BOUND overrides (deploy-specific models can be
    deeper or shallower than the default budget assumes)."""
    import os
    env = os.environ.get("PADDLE_TPU_QUANT_BOUND", "")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    return _DEFAULT_BOUNDS.get(weights_dtype, 0.0)


def quantizable_params(program):
    """{param name: per-output-channel axis} for every persistable
    float32 param (>= 2 dims) the program consumes as a matmul/conv
    weight. A name consumed under conflicting channel axes is skipped —
    one scale vector can't serve both layouts."""
    block = program.global_block()
    axes = {}
    skip = set()
    for op in block.ops:
        slot_axis = _WEIGHT_SLOTS.get(op.type)
        if slot_axis is None:
            continue
        slot, axis = slot_axis
        for name in op.inputs.get(slot, ()):
            var = block.vars.get(name)
            if var is None or not var.persistable:
                continue
            if var.dtype not in ("float32", None) or \
                    len(var.shape or ()) < 2:
                continue
            norm_axis = axis % len(var.shape)
            if name in axes and axes[name] != norm_axis:
                skip.add(name)
            axes[name] = norm_axis
    for name in skip:
        axes.pop(name, None)
    return axes


def _quantize_array(arr, axis):
    """(int8 values, f32 per-channel scales) for a float array, symmetric
    per channel along `axis`."""
    arr = np.asarray(arr, dtype=np.float32)
    reduce_axes = tuple(i for i in range(arr.ndim) if i != axis)
    amax = np.abs(arr).max(axis=reduce_axes)
    scales = np.maximum(amax / 127.0, 1e-8).astype(np.float32)
    bshape = [1] * arr.ndim
    bshape[axis] = arr.shape[axis]
    q = np.clip(np.round(arr / scales.reshape(bshape)), -127, 127)
    return q.astype(np.int8), scales


def apply_weights_dtype(program, scope, weights_dtype):
    """Apply the weight-dtype contract to a loaded (program, scope)
    pair, in place, BEFORE the first trace. Returns a report dict:
    {mode, params: [names], bytes_before, bytes_after}. Raises on a
    param named by the census but missing from the scope (a half-loaded
    model must fail loudly, not serve garbage-scaled weights)."""
    mode = (weights_dtype or "fp32").lower()
    if mode not in WEIGHTS_DTYPES:
        raise ValueError("weights_dtype must be one of %s, got %r"
                         % (WEIGHTS_DTYPES, weights_dtype))
    report = {"mode": mode, "params": [], "bytes_before": 0,
              "bytes_after": 0}
    if mode == "fp32":
        return report
    targets = quantizable_params(program)
    block = program.global_block()
    for name in sorted(targets):
        value = scope.get(name)
        if value is None:
            raise ValueError(
                "weights_dtype=%r: param %r is not initialized in the "
                "engine scope (load weights before quantizing)"
                % (mode, name))
        arr = np.asarray(value)
        report["params"].append(name)
        report["bytes_before"] += arr.size * 4
        if mode == "bf16":
            import jax.numpy as jnp
            scope.set(name, jnp.asarray(arr).astype(jnp.bfloat16))
            report["bytes_after"] += arr.size * 2
            continue
        axis = targets[name]
        q, scales = _quantize_array(arr, axis)
        var = block.var(name)
        qv = block.create_var(name=name + QVAL_SUFFIX, shape=var.shape,
                              dtype="int8", persistable=True)
        qs = block.create_var(name=name + QSCALE_SUFFIX,
                              shape=[int(arr.shape[axis])],
                              dtype="float32", persistable=True)
        # the param becomes a computed intermediate: same name, now
        # produced by the prepended dequantize — consumers untouched
        var.persistable = False
        block.prepend_op(
            "dequantize_channel",
            inputs={"X": [qv], "Scale": [qs]},
            outputs={"Out": [var]},
            attrs={"axis": int(axis)})
        scope.set(name + QVAL_SUFFIX, q)
        scope.set(name + QSCALE_SUFFIX, scales)
        scope.drop(name)
        report["bytes_after"] += q.size + scales.size * 4
    if mode == "bf16":
        # the same trace-time AMP pass training uses: MXU contractions
        # run bf16, statistics/losses stay f32 (core/lowering.py)
        program.enable_mixed_precision(True)
    return report
