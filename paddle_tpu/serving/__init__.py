"""paddle_tpu.serving — batched online inference runtime.

The deploy surface the reference era scattered across
`listen_and_serv_op`, the capi, and hand-rolled frontends, rebuilt as a
TPU-native in-process engine:

    from paddle_tpu import serving
    engine = serving.InferenceEngine("my_model_dir")   # native or
                                                       # era-wire format
    out = engine.infer({"x": batch})                   # coalesced with
                                                       # concurrent callers
    serving.ModelServer(engine, port=8080).serve_forever()

Pieces: `engine.InferenceEngine` (model load + verify + bucketed traced
dispatch + warmup), `batcher.Batcher` (dynamic micro-batching with
deadlines, bounded-queue backpressure, graceful drain),
`server.ModelServer` (stdlib threaded HTTP JSON frontend),
`metrics.ServingMetrics` (QPS/latency/occupancy, Prometheus + profiler
integration). CLI: `tools/ptpu_serve.py`. Design notes:
ARCHITECTURE.md §15.
"""
from .batcher import (Batcher, DeadlineExceededError, QueueFullError,
                      RequestFuture, RequestTooLargeError, ServingClosedError,
                      ServingError)
from .engine import InferenceEngine, InvalidRequestError, ResultSlice
from .metrics import ServingMetrics
from .server import ModelServer

__all__ = [
    "InferenceEngine", "ModelServer", "Batcher", "ServingMetrics",
    "RequestFuture", "ResultSlice", "ServingError", "QueueFullError",
    "DeadlineExceededError", "ServingClosedError", "RequestTooLargeError",
    "InvalidRequestError",
]
