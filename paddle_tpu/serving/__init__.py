"""paddle_tpu.serving — batched online inference runtime.

The deploy surface the reference era scattered across
`listen_and_serv_op`, the capi, and hand-rolled frontends, rebuilt as a
TPU-native in-process engine:

    from paddle_tpu import serving
    engine = serving.InferenceEngine("my_model_dir")   # native or
                                                       # era-wire format
    out = engine.infer({"x": batch})                   # coalesced with
                                                       # concurrent callers
    serving.ModelServer(engine, port=8080).serve_forever()

Pieces: `engine.InferenceEngine` (model load + verify + bucketed traced
dispatch + warmup), `batcher.Batcher` (dynamic micro-batching with
deadlines, bounded-queue backpressure, graceful drain),
`server.ModelServer` (stdlib threaded HTTP JSON frontend),
`metrics.ServingMetrics` (QPS/latency/occupancy, Prometheus + profiler
integration), `pool.ReplicaPool` (N replicas behind one endpoint:
health-gated least-loaded routing, circuit breakers, failover retry +
tail hedging, adaptive admission, zero-downtime weight reload). Both
engine and pool serve models BIGGER than one chip: `tp=M` spans a
replica over M devices with weights sharded 1/M at rest by the
tensor-parallel ShardingPlan, bit-identical to a mesh-1 engine. The
fleet layer makes the pool self-driving: `autoscaler.PoolAutoscaler`
grows/shrinks replicas off the admission/queue/idle signals
(`ReplicaPool(autoscale=True, ...)`), `canary.CanaryController`
(`pool.promote()`) gates snapshot promotion on a mirrored traffic
slice with auto-rollback at zero client errors, and `fleet.ModelFleet`
serves N models with per-model replica sets and priority brownout.
CLI: `tools/ptpu_serve.py` (`--replicas N`, `--tp M`, `--autoscale
MIN,MAX`, `--extra-model NAME=DIR@PRIO`, `--selfcheck
--kill-replica`). Generative decode: `engine.DecodeEngine` +
`batcher.DecodeBatcher` run a state-carrying step program with one
batch-row slot per stream and admit/retire sequences BETWEEN decode
iterations (Orca-style continuous batching) at one fixed compiled
shape, each stream bit-exact vs a solo decode (`tools/ptpu_serve.py
--decode`, ARCHITECTURE.md §27). Design notes: ARCHITECTURE.md §15
(engine/batcher), §20 (the pool), §23 (tensor-parallel replicas), §26
(the fleet), §27 (continuous-batched decode).
"""
from .autoscaler import PoolAutoscaler
from .batcher import (Batcher, DeadlineExceededError, DecodeBatcher,
                      DecodeStream, QueueFullError, RequestFuture,
                      RequestTooLargeError, ServingClosedError, ServingError)
from .canary import CanaryController, CanaryFuture
from .engine import (DecodeEngine, InferenceEngine, InvalidRequestError,
                     ResultSlice)
from .fleet import BrownoutError, ModelFleet
from .metrics import DecodeMetrics, ServingMetrics
from .pool import (AttemptTimeoutError, DecodePool, PoisonedOutputError,
                   PoolFuture, PoolMetrics, PoolResult, ReplicaPool)
from .server import ModelServer

__all__ = [
    "InferenceEngine", "ModelServer", "Batcher", "ServingMetrics",
    "RequestFuture", "ResultSlice", "ServingError", "QueueFullError",
    "DeadlineExceededError", "ServingClosedError", "RequestTooLargeError",
    "InvalidRequestError",
    "ReplicaPool", "PoolFuture", "PoolResult", "PoolMetrics",
    "AttemptTimeoutError", "PoisonedOutputError",
    "PoolAutoscaler", "CanaryController", "CanaryFuture",
    "ModelFleet", "BrownoutError",
    "DecodeEngine", "DecodeBatcher", "DecodeStream", "DecodeMetrics",
    "DecodePool",
]
