"""ReplicaPool: N InferenceEngine replicas behind one submit surface.

The high-availability layer ROADMAP item 3 asks for: one wedged or
poisoned engine must never take every request down with it, and
promoting a new checkpoint must never drop a request. The TensorFlow
system paper's stance (replica-level fault tolerance is RUNTIME design,
not deployment glue) applied to this repo's serving stack:

  * N `InferenceEngine` replicas, each with its own private Scope and
    batcher, placed round-robin over the visible devices. One program,
    one weight set — at a fixed bucket shape every replica produces
    BIT-IDENTICAL rows, so routing (and failover) is invisible in the
    results.
  * least-loaded routing over the replicas the health machine calls
    routable, with a per-replica state machine

        healthy -> degraded -> ejected -> (cooldown probe) -> healthy

    driven by rolling error-rate and latency circuit breakers plus a
    consecutive-failure fast path. Ejected replicas take no traffic
    until their cooldown passes; then ONE live request probes them
    (half-open breaker) — success readmits as degraded, failure re-arms
    the cooldown.
  * bounded retry-with-backoff onto a DIFFERENT replica for retryable
    failures (dispatch errors, a replica closing mid-swap, non-finite
    outputs from poisoned weights, per-attempt timeouts — the only
    signal a silently wedged replica emits), plus optional tail hedging
    (`hedge_delay_ms`): after the delay, a duplicate attempt races on
    another replica and the first completion wins.
  * adaptive admission control: an AIMD limit on pool-wide in-flight
    attempts shrinks multiplicatively on overload signals (every queue
    full, attempt timeouts) and recovers additively on successes, so
    overload degrades to fast 429s instead of collapsing latency for
    everyone.
  * zero-downtime weight reload: `pool.reload()` warms a FRESH engine
    per replica off the newest valid snapshot (an AOT-cache hit, PR 6)
    or re-read model dir, atomically swaps the engine pointer under the
    replica's submit lock, then drains the outgoing engine with the
    batcher's shared drain — every accepted request completes against
    the weights it was accepted under; every request after the flip
    sees the new ones. A training job promotes snapshots into serving
    with zero dropped requests.

Fault injection: the pre-dispatch tap consults the armed
`resilience.faults.FaultPlan` (`replica_exc@N` / `replica_wedge@N[:s]` /
`replica_poison@N`, keyed on the replica's own dispatch count), so every
failover path above is provable in CI. Design notes: ARCHITECTURE.md §20.
"""
import collections
import os
import threading
import time

import numpy as np

from ..core import dispatch as _dispatch
from ..observability import trace as _otrace
from .batcher import (DeadlineExceededError, QueueFullError,
                      RequestTooLargeError, ServingClosedError,
                      ServingError)
from .engine import InferenceEngine, InvalidRequestError

__all__ = ["ReplicaPool", "PoolFuture", "PoolResult", "PoolMetrics",
           "AttemptTimeoutError", "PoisonedOutputError", "DecodePool",
           "HEALTHY", "DEGRADED", "EJECTED"]

HEALTHY, DEGRADED, EJECTED = "healthy", "degraded", "ejected"
_STATE_GAUGE = {HEALTHY: 0, DEGRADED: 1, EJECTED: 2}


class AttemptTimeoutError(ServingError):
    """One replica attempt exceeded `attempt_timeout_s` — the replica is
    presumed wedged; the request fails over. Never client-visible unless
    every retry also fails."""


class PoisonedOutputError(ServingError):
    """A replica returned non-finite values (`check_finite=True`):
    treated as a replica failure — retried elsewhere, counted against
    the replica's breaker — never returned to the client as a 200."""


def _retryable(exc):
    """Failures that are the REPLICA's fault (or transient) retry on a
    different replica; failures that are the request's own fault (bad
    feed, too large, deadline passed) never do — retrying them would
    burn capacity reproducing a 4xx."""
    if isinstance(exc, (InvalidRequestError, RequestTooLargeError,
                        DeadlineExceededError)):
        return False
    return True


class PoolResult(object):
    """A materialized pool response (`check_finite` pools validate the
    arrays before handing them over, so the lazy slice is already paid
    for). Duck-types ResultSlice.numpy()."""

    __slots__ = ("_outputs", "bucket")

    def __init__(self, outputs, bucket):
        self._outputs = outputs
        self.bucket = bucket

    def numpy(self):
        return self._outputs


class PoolMetrics(object):
    """Pool-level counters + a bounded client-latency window (submit ->
    terminal). Per-replica QPS/occupancy/queue metrics stay on each
    replica engine's own ServingMetrics — /metrics labels them
    {model, replica}."""

    def __init__(self, latency_window=2048):
        self._lock = threading.Lock()
        self.requests_total = 0
        self.responses_total = 0
        self.errors_total = 0            # client-visible failures
        self.retries_total = 0           # failover resubmissions
        self.hedges_total = 0            # tail-hedge duplicates fired
        self.rejected_queue_full = 0     # admission + all-queues-full 429s
        self.attempt_timeouts_total = 0  # wedge detections
        self.poisoned_results_total = 0  # non-finite outputs caught
        self.reloads_total = 0
        self.replica_kills_total = 0
        self.ejections_total = 0
        self._latencies = collections.deque(maxlen=latency_window)

    def _bump(self, field, n=1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def on_submit(self):
        self._bump("requests_total")

    def on_success(self, latency_s):
        with self._lock:
            self.responses_total += 1
            if latency_s is not None:
                self._latencies.append(latency_s)

    def on_error(self):
        self._bump("errors_total")

    def on_retry(self):
        self._bump("retries_total")

    def on_hedge(self):
        self._bump("hedges_total")

    def on_queue_full(self):
        self._bump("rejected_queue_full")

    def on_attempt_timeout(self):
        self._bump("attempt_timeouts_total")

    def on_poisoned(self):
        self._bump("poisoned_results_total")

    def on_reload(self):
        self._bump("reloads_total")
        _otrace.instant("pool/reload", cat="serving")

    def on_kill(self):
        self._bump("replica_kills_total")
        _otrace.instant("pool/kill_replica", cat="serving")

    def on_eject(self):
        self._bump("ejections_total")
        # flight-recorder instant (ARCHITECTURE.md §24): breaker trips
        # land in the same timeline as the dispatch spans they follow
        _otrace.instant("pool/eject", cat="serving")

    def snapshot(self):
        from .metrics import _percentile
        with self._lock:
            lat = sorted(self._latencies)
            return {
                "requests_total": self.requests_total,
                "responses_total": self.responses_total,
                "errors_total": self.errors_total,
                "retries_total": self.retries_total,
                "hedges_total": self.hedges_total,
                "rejected_queue_full": self.rejected_queue_full,
                "attempt_timeouts_total": self.attempt_timeouts_total,
                "poisoned_results_total": self.poisoned_results_total,
                "reloads_total": self.reloads_total,
                "replica_kills_total": self.replica_kills_total,
                "ejections_total": self.ejections_total,
                "latency_ms": {
                    "p50": round(_percentile(lat, 0.50) * 1e3, 3),
                    "p95": round(_percentile(lat, 0.95) * 1e3, 3),
                    "p99": round(_percentile(lat, 0.99) * 1e3, 3),
                    "window": len(lat),
                },
            }


class _Admission(object):
    """AIMD concurrency limit over pool-wide in-flight attempts. Starts
    wide open (the sum of replica queue capacities); every overload
    signal multiplies it down, every success creeps it back up (+1 per
    `limit` successes). The floor keeps one slot per replica so the pool
    can always probe its way out of a shrunken limit."""

    def __init__(self, hi, lo, decrease=0.85):
        self._lock = threading.Lock()
        self.hi = float(max(hi, lo))
        self.lo = float(max(lo, 1))
        self.limit = self.hi
        self._decrease = decrease

    def allow(self, inflight):
        with self._lock:
            return inflight < self.limit

    def on_success(self):
        with self._lock:
            self.limit = min(self.hi, self.limit + 1.0 / max(self.limit, 1))

    def on_overload(self):
        with self._lock:
            self.limit = max(self.lo, self.limit * self._decrease)

    def set_bounds(self, hi, lo):
        """Pool membership changed (autoscale / kill / restart). On a
        GROWN ceiling the limit opens straight to it — the whole point
        of scaling up under load is absorbing the overload NOW, not
        after additive +1-per-success recovery crawls there; on a shrunk
        ceiling the limit clamps into the new bounds."""
        with self._lock:
            grew = float(max(hi, lo)) > self.hi
            self.hi = float(max(hi, lo))
            self.lo = float(max(lo, 1))
            self.limit = self.hi if grew else min(self.limit, self.hi)
            self.limit = max(self.limit, self.lo)

    def retry_after_s(self):
        """The 429 `Retry-After` hint, derived from the AIMD state: the
        deeper the limit has shrunk below the ceiling (= the more
        overload signals the pool has absorbed recently), the longer
        clients should back off. Bounded [0.05s, 5s]."""
        with self._lock:
            pressure = self.hi / max(self.limit, 1.0)
        return min(5.0, max(0.05, 0.05 * pressure))


class _Replica(object):
    __slots__ = ("idx", "engine", "state", "dead", "retired", "inflight",
                 "tap_counter", "generation", "window",
                 "consecutive_failures", "ejected_until", "probe_inflight",
                 "lock", "swap_lock")

    def __init__(self, idx, engine, window):
        self.idx = idx
        self.engine = engine
        self.state = HEALTHY
        self.dead = False          # hard-killed: never routed, no probes
        self.retired = False       # autoscale drain-down: never routed,
        # but in-flight/queued work still completes (then it is removed)
        self.inflight = 0          # attempts submitted, not yet completed
        # pre-dispatch tap count (the serving fault key) — pool-owned so
        # the sequence survives engine swaps (core/dispatch.TapCounter)
        self.tap_counter = _dispatch.TapCounter()
        self.generation = 0        # bumps on every engine swap
        self.window = collections.deque(maxlen=window)  # (ok, latency_s)
        self.consecutive_failures = 0
        self.ejected_until = 0.0
        self.probe_inflight = False
        self.lock = threading.Lock()       # health state + counters
        self.swap_lock = threading.Lock()  # engine pointer flips

    @property
    def dispatches(self):
        return self.tap_counter.n


class _Attempt(object):
    __slots__ = ("replica", "generation", "future", "started_at",
                 "timeout_at", "hedge", "probe", "consumed", "timed_out")

    def __init__(self, replica, future, timeout_s, hedge=False,
                 probe=False):
        self.replica = replica
        self.generation = replica.generation
        self.future = future
        self.started_at = time.monotonic()
        self.timeout_at = (self.started_at + timeout_s
                           if timeout_s is not None else None)
        self.hedge = hedge
        self.probe = probe
        self.consumed = False    # result() has judged this attempt
        self.timed_out = False


class PoolFuture(object):
    """Completion handle for one pool request. `result(timeout)` drives
    the failover machine on the CALLER's thread: it waits on the live
    attempts, fails retryable errors over to other replicas (bounded,
    with exponential backoff), fires the optional tail hedge, validates
    outputs, and returns a PoolResult (or the lazy ResultSlice when
    `check_finite=False`). Attempt completions recorded by the batcher
    workers only set a wake flag — no device or blocking work ever runs
    on a dispatch thread."""

    def __init__(self, pool, norm, deadline_ms):
        self._pool = pool
        self._norm = norm
        self._t0 = time.monotonic()
        self._deadline_at = (self._t0 + deadline_ms / 1e3
                             if deadline_ms is not None else None)
        self._attempts = []
        self._driver = threading.Lock()   # one result() driver at a time
        self._wake = threading.Event()
        self._value = None
        self._error = None
        self._retries_used = 0
        self._hedged = False
        self._last_error = None
        self.latency_s = None
        self.bucket = None

    def done(self):
        """Terminal only: a pool future is done once a `result()` call
        has produced a value or a final error. The failover machine is
        caller-driven, so an attempt completing with a RETRYABLE error
        does not make the future done — result() may still rescue it on
        another replica."""
        return self._value is not None or self._error is not None

    def remaining_deadline_ms(self):
        if self._deadline_at is None:
            return None
        rem = (self._deadline_at - time.monotonic()) * 1e3
        if rem <= 0:
            raise DeadlineExceededError(
                "deadline passed after %.1fms (during failover)"
                % ((time.monotonic() - self._t0) * 1e3))
        return rem

    # ------------------------------------------------------------ drive --
    def result(self, timeout=None):
        with self._driver:
            if self._error is not None:
                raise self._error
            if self._value is not None:
                return self._value
            return self._drive(timeout)

    def _fail(self, exc):
        self._error = exc
        self._pool.metrics.on_error()
        raise exc

    def _succeed(self, att, value):
        self.latency_s = time.monotonic() - self._t0
        self.bucket = att.future.bucket
        if hasattr(value, "bucket") and value.bucket is None:
            value.bucket = self.bucket
        self._value = value
        self._pool.metrics.on_success(self.latency_s)
        return value

    def _drive(self, timeout):
        pool = self._pool
        end = time.monotonic() + timeout if timeout is not None else None
        while True:
            now = time.monotonic()
            wake_at = []
            for att in list(self._attempts):
                if att.consumed:
                    continue
                if att.future.done():
                    att.consumed = True
                    err = att.future._error
                    if err is None:
                        ok, payload = pool._validate_result(att)
                        if ok:
                            return self._succeed(att, payload)
                        err = payload
                    if not _retryable(err):
                        self._fail(err)
                    self._last_error = err
                elif att.timeout_at is not None and now >= att.timeout_at:
                    att.consumed = True
                    att.timed_out = True
                    pool._on_attempt_timeout(att)
                    self._last_error = AttemptTimeoutError(
                        "replica %d did not answer within %.3fs (presumed "
                        "wedged); failing over" % (att.replica.idx,
                                                   pool.attempt_timeout_s))
                elif att.timeout_at is not None:
                    wake_at.append(att.timeout_at)

            live = [a for a in self._attempts if not a.consumed]
            if not live:
                if self._deadline_at is not None \
                        and now >= self._deadline_at:
                    self._fail(DeadlineExceededError(
                        "deadline passed after %.1fms (all attempts "
                        "failed or timed out)" % ((now - self._t0) * 1e3)))
                if self._retries_used >= pool.retries:
                    self._fail(self._last_error if self._last_error
                               is not None else RuntimeError(
                                   "pool request ended with no attempts"))
                delay = pool.retry_backoff_s * (2 ** self._retries_used)
                self._retries_used += 1
                pool.metrics.on_retry()
                if delay > 0:
                    if end is not None:
                        delay = min(delay, max(end - time.monotonic(), 0))
                    time.sleep(delay)
                try:
                    pool._submit_attempt(
                        self, exclude={a.replica for a in self._attempts})
                except DeadlineExceededError as e:
                    self._fail(e)
                except (QueueFullError, ServingClosedError) as e:
                    # transient: capacity may free / swap may finish —
                    # loop again and spend another retry on it. Keep the
                    # FIRST real failure as the reported cause: a
                    # poisoned/wedged outage must not surface to the
                    # client dressed up as a capacity 429 just because
                    # the failed replicas are now all excluded
                    if self._last_error is None:
                        self._last_error = e
                continue

            # tail hedging: one duplicate attempt on another replica once
            # the primary has been quiet for hedge_delay
            if (pool.hedge_delay_s is not None and not self._hedged
                    and len(live) == 1 and not live[0].hedge):
                hedge_due = live[0].started_at + pool.hedge_delay_s
                if now >= hedge_due:
                    self._hedged = True
                    try:
                        pool._submit_attempt(
                            self,
                            exclude={a.replica for a in self._attempts},
                            hedge=True)
                        pool.metrics.on_hedge()
                    except (QueueFullError, ServingClosedError,
                            DeadlineExceededError):
                        pass   # hedging is best-effort by definition
                    continue
                wake_at.append(hedge_due)

            if end is not None:
                if now >= end:
                    raise TimeoutError(
                        "pool request not completed within %rs" % timeout)
                wake_at.append(end)
            dt = min(wake_at) - now if wake_at else None
            self._wake.wait(dt if dt is None or dt > 0 else 0)
            self._wake.clear()


class ReplicaPool(object):
    """N engine replicas behind one engine-shaped surface (submit /
    infer / run_direct / describe / metrics / close), plus the pool
    verbs: reload, kill_replica, restart_replica, pool_state."""

    def __init__(self, model_dir=None, replicas=2, place=None, name=None,
                 checkpoint_dir=None, fetch_list=None, feed_names=None,
                 step=None, engine_factory=None, tp=None,
                 # failover / hedging
                 retries=2, retry_backoff_ms=5.0, attempt_timeout_s=30.0,
                 hedge_delay_ms=None, check_finite=True,
                 # health machine / breakers
                 window=64, min_samples=8, degrade_error_rate=0.25,
                 eject_error_rate=0.5, eject_consecutive=3,
                 latency_degrade_s=None, eject_cooldown_s=2.0,
                 recover_samples=4,
                 # admission
                 admission=True, default_deadline_ms=None,
                 latency_window=2048,
                 # autoscale (serving/autoscaler.py): replicas= is the
                 # STARTING size; the controller grows/shrinks between
                 # [min_replicas, max_replicas] off the admission/queue/
                 # idle signals the pool already measures
                 autoscale=False, min_replicas=None, max_replicas=None,
                 autoscale_kw=None, **engine_kw):
        if int(replicas) < 1:
            raise ValueError("ReplicaPool needs replicas >= 1, got %r"
                             % (replicas,))
        if not autoscale and (min_replicas is not None
                              or max_replicas is not None):
            # validate BEFORE any engine builds: a raise below this
            # point would leak live batcher workers per failed ctor
            raise ValueError("min_replicas/max_replicas need "
                             "autoscale=True")
        self._autoscale_bounds = None
        if autoscale:
            # `is not None`, not truthiness: an explicit 0 must hit the
            # validation below, not silently fall back to the default
            _mn = (int(min_replicas) if min_replicas is not None
                   else int(replicas))
            _mx = (int(max_replicas) if max_replicas is not None
                   else 2 * int(replicas))
            if _mn < 1 or _mx < _mn:
                raise ValueError(
                    "autoscale wants 1 <= min_replicas <= max_replicas, "
                    "got [%r, %r]" % (min_replicas, max_replicas))
            if int(replicas) > _mx:
                raise ValueError(
                    "replicas=%d starts ABOVE max_replicas=%d: the "
                    "controller could never shrink past its own "
                    "ceiling; raise max_replicas or start smaller"
                    % (int(replicas), _mx))
            self._autoscale_bounds = (_mn, _mx)
        if engine_factory is None and model_dir is None \
                and checkpoint_dir is None:
            raise ValueError("need model_dir, checkpoint_dir or an "
                             "engine_factory")
        self.name = name or self._default_name(model_dir, checkpoint_dir)
        self.num_replicas = int(replicas)
        self.retries = int(retries)
        self.retry_backoff_s = float(retry_backoff_ms) / 1e3
        self.attempt_timeout_s = (float(attempt_timeout_s)
                                  if attempt_timeout_s else None)
        self.hedge_delay_s = (float(hedge_delay_ms) / 1e3
                              if hedge_delay_ms is not None else None)
        self.check_finite = bool(check_finite)
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.degrade_error_rate = float(degrade_error_rate)
        self.eject_error_rate = float(eject_error_rate)
        self.eject_consecutive = int(eject_consecutive)
        self.latency_degrade_s = latency_degrade_s
        self.eject_cooldown_s = float(eject_cooldown_s)
        self.recover_samples = int(recover_samples)
        self.default_deadline_ms = default_deadline_ms
        self.closed = False
        self.metrics = PoolMetrics(latency_window=latency_window)
        self.events = []              # (monotonic, kind, replica, detail)
        self._events_lock = threading.Lock()
        self._route_lock = threading.Lock()
        self._reload_lock = threading.Lock()
        self._source = {"model_dir": model_dir,
                        "checkpoint_dir": checkpoint_dir,
                        "fetch_list": fetch_list,
                        "feed_names": feed_names, "step": step}
        self._factory = engine_factory
        if engine_factory is not None and \
                (engine_kw.get("weights_dtype") or "fp32") != "fp32":
            # a factory builds its engines itself — weights_dtype would
            # be silently dropped, and fp32 replicas serving under a
            # bf16/int8 label pass every divergence gate trivially (the
            # same refusal InferenceEngine makes for program= builds)
            raise ValueError(
                "weights_dtype=%r is ignored with engine_factory: pass "
                "it to InferenceEngine inside the factory instead"
                % (engine_kw["weights_dtype"],))
        self._place = place
        # tensor-parallel replicas (ARCHITECTURE.md §23): tp=M makes
        # every replica an M-device engine — replica i owns the
        # contiguous device span [i*M, (i+1)*M) (modulo the visible
        # count: more replica-devices than chips share spans, same as
        # the 1-device round-robin). Health/failover/reload all stay
        # replica-granular: a replica IS its M-device engine.
        if tp is not None and int(tp) < 1:
            # before the falsy mapping: tp=0 must raise, not silently
            # run single-device replicas (see InferenceEngine)
            raise ValueError("tp must be >= 1, got %r" % (tp,))
        self.tp = int(tp) if tp is not None else None
        self._engine_kw = dict(engine_kw)

        self._replicas = []
        self._next_idx = self.num_replicas   # stable ids across scaling
        self._canary = None                  # CanaryController when a
        # promotion is in flight (serving/canary.py)
        try:
            for i in range(self.num_replicas):
                eng = self._build_engine(i)
                rep = _Replica(i, eng, self.window)
                self._attach_tap(rep)
                self._replicas.append(rep)
        except Exception:
            for rep in self._replicas:   # no thread leak per failed ctor
                rep.engine.close(drain=False)
            raise
        cap = sum(r.engine._batcher.queue_capacity for r in self._replicas)
        self._admission = _Admission(hi=cap, lo=self.num_replicas) \
            if admission else None
        self._autoscaler = None
        if autoscale:
            from .autoscaler import PoolAutoscaler
            mn, mx = self._autoscale_bounds
            self._autoscaler = PoolAutoscaler(
                self, min_replicas=mn, max_replicas=mx,
                **(autoscale_kw or {}))
            self._autoscaler.start()

    # ------------------------------------------------------------ build --
    @staticmethod
    def _default_name(model_dir, checkpoint_dir):
        for d in (model_dir, checkpoint_dir):
            if d:
                return os.path.basename(os.path.normpath(d))
        return "pool"

    def _place_for(self, idx):
        """Round-robin placement over the visible devices. An explicit
        place (or list of places) wins; default is TPUPlace(idx), whose
        device() already wraps modulo the accelerator count and falls
        back to CPU when none exist. Tensor-parallel replicas default
        to CPUPlace instead: the place is only the LOADER's device (the
        mesh owns dispatch), and materializing a bigger-than-one-chip
        model's full weights on TPUPlace(idx) — a chip inside some
        OTHER replica's span — would OOM exactly the models tp exists
        for; loading host-side lets the first dispatch commit straight
        to the sharded layout."""
        from ..places import CPUPlace, TPUPlace
        place = self._place
        if isinstance(place, (list, tuple)):
            return place[idx % len(place)]
        if place is not None:
            return place
        if self.tp is not None:
            return CPUPlace()
        return TPUPlace(idx)

    def _tp_span(self, idx):
        """Replica idx's contiguous tp-device span. The span START wraps
        modulo the visible device count (an over-subscribed pool shares
        chips ACROSS replicas the way 1-device replicas already do
        under round-robin), but one span can never exceed the visible
        devices: a mesh with the same chip twice is not a bigger mesh,
        and jax rejects it with an unhelpful construction error deep in
        engine init — raise the same readable ValueError the bare
        InferenceEngine gives for too-few devices."""
        import jax
        devs = jax.devices()
        if self.tp > len(devs):
            raise ValueError(
                "tp=%d needs %d devices per replica but only %d are "
                "visible" % (self.tp, self.tp, len(devs)))
        return [devs[(idx * self.tp + k) % len(devs)]
                for k in range(self.tp)]

    def _build_engine(self, idx, source=None, ename=None):
        """One warmed replica engine off the current source (or, for a
        canary, an explicit candidate `source`). With the AOT compile
        cache on (ptpu_serve defaults it on), warmup is a disk load,
        not a recompile — what makes reload/restart/scale-up cheap."""
        place = self._place_for(idx)
        ename = ename or "%s@%d" % (self.name, idx)
        if self._factory is not None:
            return self._factory(idx, place)
        kw = dict(self._engine_kw)
        if self.tp is not None:
            kw["tp"] = self.tp
            kw["mesh_devices"] = self._tp_span(idx)
        src = source if source is not None else self._source
        if src["checkpoint_dir"] is not None:
            if src["fetch_list"] is None:
                raise ValueError("checkpoint_dir serving needs fetch_list")
            return InferenceEngine.from_checkpoint(
                src["checkpoint_dir"], src["fetch_list"],
                feed_names=src["feed_names"], step=src["step"],
                place=place, name=ename, **kw)
        return InferenceEngine(src["model_dir"], place=place, name=ename,
                               **kw)

    def _attach_tap(self, rep, engine=None):
        # the fault-tap plumbing lives once in the shared dispatch core
        # (core/dispatch.ReplicaTap): it captures the engine it is
        # ATTACHED to (a replica_poison landing in a draining outgoing
        # engine must not NaN the freshly promoted replacement), while
        # the pool-owned TapCounter keeps the per-replica dispatch
        # sequence consistent across engine swaps
        eng = engine if engine is not None else rep.engine
        eng._replica_tap = _dispatch.ReplicaTap(rep.idx, eng,
                                                rep.tap_counter)

    def _event(self, kind, replica, detail=""):
        with self._events_lock:
            self.events.append((time.monotonic(), kind, replica, detail))

    # ----------------------------------------------------------- health --
    def _record_outcome(self, rep, ok, latency_s=None):
        """One attempt outcome -> the replica's rolling window -> state
        transitions. Called from done-callbacks (failures, and successes
        on check_finite=False pools) and from result() validation."""
        now = time.monotonic()
        with rep.lock:
            rep.window.append((1 if ok else 0, latency_s))
            was_probe, rep.probe_inflight = rep.probe_inflight, False
            if ok:
                rep.consecutive_failures = 0
            else:
                rep.consecutive_failures += 1
            if rep.dead:
                return
            if rep.state == EJECTED:
                if was_probe and ok:
                    rep.state = DEGRADED     # half-open probe succeeded
                    rep.window.clear()
                    rep.window.append((1, latency_s))
                    self._event("probe_ok", rep.idx)
                elif not ok:
                    rep.ejected_until = now + self.eject_cooldown_s
                    if was_probe:
                        self._event("probe_failed", rep.idx)
                return
            n = len(rep.window)
            errs = sum(1 for o, _ in rep.window if not o)
            if rep.consecutive_failures >= self.eject_consecutive or (
                    n >= self.min_samples
                    and errs / n >= self.eject_error_rate):
                rep.state = EJECTED
                rep.ejected_until = now + self.eject_cooldown_s
                self.metrics.on_eject()
                self._event("eject", rep.idx,
                            "%d consecutive failures, %d/%d window errors"
                            % (rep.consecutive_failures, errs, n))
                return
            if n >= self.min_samples \
                    and errs / n >= self.degrade_error_rate:
                if rep.state != DEGRADED:
                    rep.state = DEGRADED
                    self._event("degrade", rep.idx,
                                "error rate %d/%d" % (errs, n))
                return
            if self.latency_degrade_s is not None and n >= self.min_samples:
                lats = sorted(l for _, l in rep.window if l is not None)
                if lats:
                    p99 = lats[min(len(lats) - 1,
                                   int(round(0.99 * (len(lats) - 1))))]
                    if p99 > self.latency_degrade_s:
                        if rep.state != DEGRADED:
                            rep.state = DEGRADED
                            self._event("degrade", rep.idx,
                                        "p99 %.3fs" % p99)
                        return
            if rep.state == DEGRADED and n >= self.recover_samples:
                tail = list(rep.window)[-self.recover_samples:]
                if all(o for o, _ in tail):
                    rep.state = HEALTHY
                    self._event("recover", rep.idx)

    def _release_probe(self, att):
        """Unblock the half-open slot when a probe attempt ends WITHOUT
        reaching _record_outcome (deadline expiry, engine closed):
        neither outcome says anything about replica health, but leaving
        probe_inflight set would block every future probe and strand
        the replica in EJECTED forever."""
        if att.probe:
            with att.replica.lock:
                att.replica.probe_inflight = False

    def _on_attempt_timeout(self, att):
        self.metrics.on_attempt_timeout()
        if self._admission is not None:
            self._admission.on_overload()
        if att.generation == att.replica.generation:
            self._record_outcome(att.replica, ok=False)

    def _attempt_done(self, fut, att):
        """Inner-future done-callback: bookkeeping only (the caller's
        result() drive does the judging). Runs on the completing batcher
        worker — must stay cheap and non-blocking."""
        rep = att.replica
        with rep.lock:
            rep.inflight -= 1
        err = att.future._error
        if att.timed_out:
            pass          # already counted as a failure at timeout time
        elif err is None:
            if self._admission is not None:
                self._admission.on_success()
            if not self.check_finite:
                # finite-checking pools record success at validation
                self._record_outcome(rep, ok=True,
                                     latency_s=att.future.latency_s)
        elif isinstance(err, DeadlineExceededError):
            # not the replica's fault (client deadline), but a deadline
            # expiring IN QUEUE is the latency-collapse signal adaptive
            # admission exists for: shed earlier next time
            if self._admission is not None:
                self._admission.on_overload()
            self._release_probe(att)
        elif isinstance(err, ServingClosedError):
            # swap/kill closed the engine: no health signal
            self._release_probe(att)
        elif att.generation != rep.generation:
            pass          # outcome of a swapped-out engine: stale signal
        else:
            self._record_outcome(rep, ok=False)
        fut._wake.set()

    def _validate_result(self, att):
        """Judge a completed attempt's payload on the caller's thread.
        check_finite pools materialize here (the client was about to
        anyway) and treat non-finite floats as a replica failure —
        poisoned weights produce well-formed NaN tensors, which is
        exactly the corruption a 200 must never carry."""
        slice_ = att.future._value
        if not self.check_finite:
            return True, slice_
        try:
            outputs = slice_.numpy()
        except Exception as e:  # noqa: BLE001 — materialize failure =
            if att.generation == att.replica.generation:  # replica fault
                self._record_outcome(att.replica, ok=False)
            return False, e
        for fname, arr in outputs.items():
            a = np.asarray(arr)
            if np.issubdtype(a.dtype, np.floating) \
                    and not np.isfinite(a).all():
                self.metrics.on_poisoned()
                if att.generation == att.replica.generation:
                    self._record_outcome(att.replica, ok=False)
                return False, PoisonedOutputError(
                    "replica %d returned non-finite values in fetch %r"
                    % (att.replica.idx, fname))
        if att.generation == att.replica.generation:
            self._record_outcome(att.replica, ok=True,
                                 latency_s=att.future.latency_s)
        # a stale-generation success (engine swapped mid-flight) is still
        # a valid result for the client — it just isn't a health signal
        return True, PoolResult(outputs, att.future.bucket)

    # ---------------------------------------------------------- routing --
    def _pick(self, exclude=()):
        """(replica, is_probe) — least-loaded healthy first; degraded
        only when no healthy candidate exists; a cooldown-expired
        ejected replica gets ONE concurrent live-traffic probe
        (half-open breaker) ahead of regular routing, else ejected
        replicas are last-resort only."""
        now = time.monotonic()
        with self._route_lock:
            healthy, degraded, last_resort = [], [], []
            probe = None
            for rep in self._replicas:
                if rep.dead or rep.retired or rep in exclude:
                    continue
                with rep.lock:
                    state, load = rep.state, rep.inflight
                    probe_due = (state == EJECTED and not rep.probe_inflight
                                 and now >= rep.ejected_until)
                if state == HEALTHY:
                    healthy.append((load, rep.idx, rep))
                elif state == DEGRADED:
                    degraded.append((load, rep.idx, rep))
                elif probe_due and probe is None:
                    probe = rep
                else:
                    last_resort.append((load, rep.idx, rep))
            if probe is not None:
                with probe.lock:
                    probe.probe_inflight = True
                return probe, True
            for bucket in (healthy, degraded, last_resort):
                if bucket:
                    return min(bucket)[2], False
        return None, False

    def _submit_attempt(self, fut, exclude=(), hedge=False):
        """Route one attempt; on a full/closed replica move on to the
        next candidate. Raises QueueFullError when EVERY routable
        replica rejected (the admission controller hears about it)."""
        tried = set(exclude)
        rejected_any = False
        deadline_ms = fut.remaining_deadline_ms()   # raises when spent
        while True:
            rep, probe = self._pick(exclude=tried)
            if rep is None:
                # overload signals (admission shrink, 429 counter) only
                # when a replica actually REJECTED here — exhausting the
                # exclude set on a failover is the request running out
                # of replicas, not the pool running out of capacity
                if rejected_any:
                    if self._admission is not None:
                        self._admission.on_overload()
                    self.metrics.on_queue_full()
                exc = QueueFullError(
                    "no replica can accept the request (all full, "
                    "ejected or excluded); retry with backoff")
                if rejected_any and self._admission is not None:
                    exc.retry_after_s = self._admission.retry_after_s()
                raise exc
            try:
                with rep.swap_lock:
                    inner = rep.engine.submit_normalized(
                        fut._norm, deadline_ms=deadline_ms)
            except (QueueFullError, ServingClosedError):
                if probe:
                    with rep.lock:
                        rep.probe_inflight = False
                tried.add(rep)
                rejected_any = True
                continue
            except Exception:
                if probe:
                    with rep.lock:
                        rep.probe_inflight = False
                raise
            with rep.lock:
                rep.inflight += 1
            att = _Attempt(rep, inner, self.attempt_timeout_s,
                           hedge=hedge, probe=probe)
            fut._attempts.append(att)
            inner.add_done_callback(
                lambda _f, a=att, f=fut: self._attempt_done(f, a))
            return att

    # ----------------------------------------------------------- public --
    def submit(self, feed, deadline_ms=None):
        """Normalize once (caller's thread — malformed requests fail
        fast, before any routing), admission-check, route the first
        attempt. Returns a PoolFuture."""
        if self.closed:
            raise ServingClosedError("replica pool is shut down")
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        norm = self._any_engine().normalize_feed(feed)
        if self._admission is not None and not self._admission.allow(
                self.total_inflight()):
            self.metrics.on_queue_full()
            exc = QueueFullError(
                "pool admission limit %.0f reached (overload shedding); "
                "retry with backoff" % self._admission.limit)
            # the 429 carries an intelligent backoff hint instead of
            # letting every client hammer a saturated fleet in lockstep
            exc.retry_after_s = self._admission.retry_after_s()
            raise exc
        can = self._canary
        if can is not None:
            # an in-flight promotion claims its deterministic traffic
            # slice: the request rides the canary engine AND an
            # incumbent mirror (serving/canary.py) — the mirror is what
            # makes a corrupt canary invisible to the client
            cfut = can.maybe_submit(norm, deadline_ms)
            if cfut is not None:
                self.metrics.on_submit()
                return cfut
        fut = PoolFuture(self, norm, deadline_ms)
        self._submit_attempt(fut)
        self.metrics.on_submit()
        return fut

    def infer(self, feed, deadline_ms=None, timeout=30.0):
        return self.submit(feed, deadline_ms=deadline_ms) \
            .result(timeout).numpy()

    def run_direct(self, feed, batch_bucket=None, seq_bucket=None):
        """The single-request reference path, on any live replica — the
        pool invariant is that WHICH replica is unobservable in the
        bits."""
        return self._any_engine().run_direct(
            feed, batch_bucket=batch_bucket, seq_bucket=seq_bucket)

    def _any_engine(self):
        for rep in list(self._replicas):
            if not rep.dead and not rep.retired and not rep.engine.closed:
                return rep.engine
        raise ServingClosedError("no live replica in the pool")

    def _replica(self, idx):
        """Replica by STABLE id (autoscaling means ids are not list
        positions — a removed replica's id is never reused)."""
        for rep in list(self._replicas):
            if rep.idx == idx:
                return rep
        raise KeyError("no replica %r in the pool (have %r)"
                       % (idx, [r.idx for r in self._replicas]))

    def total_inflight(self):
        return sum(rep.inflight for rep in list(self._replicas))

    def live_replica_count(self):
        """Replicas that can take NEW traffic (not dead, not retired)."""
        return sum(1 for rep in list(self._replicas)
                   if not rep.dead and not rep.retired)

    def queue_capacity_total(self):
        return sum(rep.engine._batcher.queue_capacity
                   for rep in list(self._replicas)
                   if not rep.dead and not rep.retired)

    @property
    def fetch_names(self):
        return self._any_engine().fetch_names

    @property
    def feed_names(self):
        return self._any_engine().feed_names

    @property
    def max_batch_size(self):
        return self._any_engine().max_batch_size

    @property
    def batch_buckets(self):
        return self._any_engine().batch_buckets

    @property
    def seq_buckets(self):
        return self._any_engine().seq_buckets

    def queue_depth(self):
        return sum(rep.engine.queue_depth() for rep in list(self._replicas)
                   if not rep.dead)

    def replica_metrics(self):
        """{replica_index: ServingMetrics} for /metrics labeling."""
        return {rep.idx: rep.engine.metrics
                for rep in list(self._replicas)}

    def pool_state(self):
        """The /healthz payload: per-replica state + aggregate counts."""
        reps = []
        counts = {HEALTHY: 0, DEGRADED: 0, EJECTED: 0}
        for rep in list(self._replicas):
            with rep.lock:
                st = rep.state
                entry = {"replica": rep.idx, "state": st,
                         "dead": rep.dead, "retired": rep.retired,
                         "inflight": rep.inflight,
                         "dispatches": rep.tap_counter.n,
                         "generation": rep.generation,
                         # per-replica engine config (mixed-config pools
                         # must be VISIBLE, not silent): dtype + depth
                         # ride /healthz and ptpu_serve --selfcheck
                         "weights_dtype": getattr(rep.engine,
                                                  "weights_dtype", "fp32"),
                         "pipeline_depth": getattr(rep.engine,
                                                   "pipeline_depth", None),
                         # the device span this replica's engine owns —
                         # M entries for a tensor-parallel replica, so
                         # an operator can map replicas to chips
                         "tp": getattr(rep.engine, "tp", None),
                         "devices": rep.engine.device_span()
                         if hasattr(rep.engine, "device_span") else []}
                # continuous-batching window (ARCHITECTURE.md §22):
                # per-replica device in-flight/idle accounting — the
                # operator's view of whether this replica's device is
                # actually kept busy behind the pipeline
                ws = rep.engine._batcher.pipeline_stats()
                if ws is not None:
                    entry["pipeline"] = {
                        "depth": ws["depth"],
                        "completed": ws["completed"],
                        "device_idle_s": round(ws["idle_s"], 4)}
            reps.append(entry)
            counts[st] += 1
        out = {"replicas": reps, "healthy": counts[HEALTHY],
               "degraded": counts[DEGRADED], "ejected": counts[EJECTED],
               "inflight": self.total_inflight()}
        if self._admission is not None:
            out["admission_limit"] = round(self._admission.limit, 1)
        if self._autoscaler is not None:
            out["autoscale"] = self._autoscaler.state()
        can = self._canary
        if can is not None:
            out["promotion"] = can.state()
        return out

    def describe(self):
        base = self._any_engine().describe()
        base["name"] = self.name
        base["status"] = "closed" if self.closed else "serving"
        base["pool"] = self.pool_state()
        base["metrics"] = self.metrics.snapshot()
        return base

    # -------------------------------------------------- reload / verbs --
    def reload(self, checkpoint_dir=None, model_dir=None, step=None,
               timeout=None):
        """Zero-downtime weight promotion, one replica at a time: build
        and WARM a fresh engine off the newest valid snapshot of
        `checkpoint_dir` (or re-read `model_dir`; no argument = re-read
        the pool's current source, which for a checkpoint pool means
        "newest valid snapshot NOW" — the trainer-promotes flow), then
        atomically swap it in under the replica's submit lock and drain
        the outgoing engine. Requests accepted before a replica's flip
        complete against the old weights; requests after it get the new
        ones; nothing is ever dropped, and the other replicas keep
        serving throughout. Returns the served checkpoint step (None
        for model-dir pools)."""
        with self._reload_lock:
            if self.closed:
                raise ServingClosedError("replica pool is shut down")
            can = self._canary
            if can is not None and can.is_routing():
                raise RuntimeError(
                    "a canary promotion is in flight (%s); let it "
                    "finish, or cancel_promotion() first — an unguarded "
                    "reload would promote around the gate"
                    % can.state()["state"])
            if checkpoint_dir is not None:
                self._source["checkpoint_dir"] = checkpoint_dir
                self._source["model_dir"] = None
            if model_dir is not None:
                self._source["model_dir"] = model_dir
                self._source["checkpoint_dir"] = None
            if step is not None:
                self._source["step"] = step
            served_step = None
            for rep in list(self._replicas):
                if rep.dead or rep.retired:
                    continue    # killed replicas stay down (restart_
                                # replica is the explicit revive);
                                # retired ones are mid-drain-out
                fresh = self._build_engine(rep.idx)
                served_step = getattr(fresh, "checkpoint_step",
                                      served_step)
                with rep.swap_lock:
                    old, rep.engine = rep.engine, fresh
                    rep.generation += 1
                self._attach_tap(rep, engine=fresh)
                with rep.lock:
                    was_ejected = rep.state == EJECTED
                    rep.window.clear()
                    rep.consecutive_failures = 0
                    rep.probe_inflight = False
                    if rep.state == DEGRADED:
                        rep.state = HEALTHY
                    elif was_ejected:
                        # new weights cure a poisoned-weights ejection,
                        # but a wedge-class cause can be environmental
                        # (the old worker may literally still be stuck):
                        # keep the half-open path — the cooldown
                        # restarts and ONE live probe readmits a
                        # genuinely recovered replica immediately,
                        # instead of routing preferred traffic straight
                        # back into a bad device
                        rep.ejected_until = (time.monotonic()
                                             + self.eject_cooldown_s)
                self._event("swap", rep.idx,
                            "generation %d" % rep.generation)
                # close rides the batcher's shared drain: everything
                # accepted pre-flip completes (old weights) before the
                # old engine's worker joins. An EJECTED replica's old
                # engine may be WEDGED mid-dispatch — draining it could
                # block this reload (and, via _reload_lock, every future
                # reload) forever; its queued work was already failed
                # over, so fail the leftovers fast instead
                if was_ejected:
                    old.close(drain=False, timeout=1.0)
                else:
                    old.close(drain=True, timeout=timeout)
            self.metrics.on_reload()
            return served_step

    def promote(self, checkpoint_dir=None, model_dir=None, step=None,
                traffic_fraction=0.05, shadow=False, **canary_kw):
        """Gated promotion (serving/canary.py): build and WARM one
        canary engine off the candidate (`checkpoint_dir`/`model_dir`/
        `step`; no argument = the pool's current source re-read, i.e.
        "newest valid snapshot NOW"), route `traffic_fraction` of
        requests to it with incumbent mirroring, gate every canaried
        request on finite outputs + output divergence
        (PADDLE_TPU_CANARY_BOUND / divergence_bound()) + latency vs the
        mirror, and:

          * breaches >= max_breaches  -> AUTO-ROLLBACK, zero client
            errors (breached requests already served mirror answers);
          * oks >= min_requests       -> promote to 100% via the
            ordinary zero-downtime reload().

        shadow=True judges the canary entirely off the response path
        (clients always get the incumbent). Returns the
        CanaryController; watch it via pool_state()["promotion"].
        canary_kw: min_requests, max_breaches, divergence_bound,
        latency_ratio, latency_margin_s, canary_wait_s, auto_finalize."""
        from .canary import CanaryController, CANARY, SHADOW
        with self._reload_lock:
            if self.closed:
                raise ServingClosedError("replica pool is shut down")
            old = self._canary
            if old is not None and old.is_routing():
                raise RuntimeError(
                    "a promotion is already in flight (%s); cancel it "
                    "first" % old.state()["state"])
            source = dict(self._source)
            if checkpoint_dir is not None:
                source["checkpoint_dir"] = checkpoint_dir
                source["model_dir"] = None
            if model_dir is not None:
                source["model_dir"] = model_dir
                source["checkpoint_dir"] = None
            if step is not None:
                source["step"] = step
            # RESERVE a placement id: peeking _next_idx would collide
            # with a concurrent autoscale add_replica and stack the new
            # replica on the canary's device span (ids need not be
            # dense, so burning one is free)
            with self._route_lock:
                cidx = self._next_idx
                self._next_idx += 1
            eng = self._build_engine(cidx, source=source,
                                     ename="%s@canary" % self.name)
            # the canary fronts the same fault-tap seam as every
            # replica, under the reserved id the canary_poison fault
            # kind targets
            eng._replica_tap = _dispatch.ReplicaTap("canary", eng)
            ctrl = CanaryController(
                self, eng,
                # the final reload's source arguments (reload re-reads
                # a checkpoint source, so a trainer that kept writing
                # promotes the newest snapshot >= the judged one; pin
                # step= to promote exactly the judged snapshot)
                {"checkpoint_dir": checkpoint_dir,
                 "model_dir": model_dir, "step": step},
                mode=SHADOW if shadow else CANARY,
                traffic_fraction=traffic_fraction, **canary_kw)
            self._canary = ctrl
        self._event("canary_start", "canary",
                    "%s %.0f%% of traffic" % (ctrl.mode,
                                              100 * traffic_fraction))
        _otrace.instant("pool/canary_start", cat="serving")
        return ctrl

    def cancel_promotion(self, reason="operator cancel"):
        can = self._canary
        if can is not None:
            can.cancel(reason)

    def promotion_state(self):
        """The current (or last finished) promotion's state dict, or
        None if this pool never promoted."""
        can = self._canary
        return can.state() if can is not None else None

    def kill_replica(self, idx, drain=False):
        """Hard-eject one replica (deploy gates, ops): never routed
        again, no probes, engine closed. Queued requests on it fail
        with ServingClosedError and the pool fails them over — the
        kill-a-replica invariant is zero client-visible errors."""
        rep = self._replica(idx)
        with rep.lock:
            rep.dead = True
            rep.state = EJECTED
            rep.ejected_until = float("inf")
        self.metrics.on_kill()
        self._event("kill", idx)
        # drain=False by default: a kill simulates failure, and a WEDGED
        # engine's close(drain=True) would never return. Admission
        # bounds deliberately NOT rebalanced: kill/restart are FAULT
        # verbs — the pool should shed via real overload signals (AIMD
        # shrink below the static ceiling, the PR-8 contract), not have
        # the ceiling quietly redefined under it; only the SCALING
        # verbs (add/remove_replica) move the bounds.
        rep.engine.close(drain=drain, timeout=1.0)

    def restart_replica(self, idx):
        """Revive a killed (or just unhealthy) replica with a freshly
        built engine off the current source."""
        rep = self._replica(idx)
        fresh = self._build_engine(idx)
        with rep.swap_lock:
            old, rep.engine = rep.engine, fresh
            rep.generation += 1
        self._attach_tap(rep, engine=fresh)
        with rep.lock:
            rep.dead = False
            rep.state = HEALTHY
            rep.window.clear()
            rep.consecutive_failures = 0
            rep.probe_inflight = False
            rep.ejected_until = 0.0
        self._event("restart", idx, "generation %d" % rep.generation)
        if not old.closed:
            old.close(drain=True, timeout=1.0)

    # ------------------------------------------------------- autoscale --
    def _rebalance_admission(self):
        """Re-derive the AIMD bounds from the CURRENT live membership.
        Called by the SCALING verbs only (add/remove_replica): the
        fault verbs (kill/restart) deliberately keep the original
        bounds so overload after a kill still sheds via real AIMD
        shrink below the static ceiling — the PR-8 contract."""
        if self._admission is None:
            return
        self._admission.set_bounds(hi=max(self.queue_capacity_total(), 1),
                                   lo=max(self.live_replica_count(), 1))

    def add_replica(self):
        """Grow the pool by one freshly built, WARMED replica (with the
        AOT compile cache armed — ptpu_serve defaults it on — warmup is
        a disk load, which is what makes scale-up seconds, not minutes).
        The new replica gets a stable never-reused id, joins routing
        atomically, and the admission ceiling opens to the grown
        capacity immediately. Returns the new replica id."""
        with self._reload_lock:
            if self.closed:
                raise ServingClosedError("replica pool is shut down")
            with self._route_lock:
                idx = self._next_idx
                self._next_idx += 1
            eng = self._build_engine(idx)     # build OUTSIDE the route
            rep = _Replica(idx, eng, self.window)  # lock: it compiles/
            self._attach_tap(rep)                  # loads artifacts
            with self._route_lock:
                self._replicas.append(rep)
            self._rebalance_admission()
            self._event("scale_up", idx)
            _otrace.instant("pool/scale_up", cat="serving")
            return idx

    def remove_replica(self, idx=None, timeout=None):
        """Shrink the pool by one replica — DRAINING, never killing:
        the victim stops taking new traffic (retired), everything
        already accepted on it completes against its engine, then the
        engine closes and the replica leaves the pool. Default victim:
        the youngest (highest-id) live replica. Refuses to remove the
        last live replica. Returns the removed replica id."""
        with self._reload_lock:
            with self._route_lock:
                live = [r for r in self._replicas
                        if not r.dead and not r.retired]
                if idx is None:
                    if len(live) <= 1:
                        raise ValueError(
                            "cannot remove the last live replica")
                    rep = max(live, key=lambda r: r.idx)
                else:
                    rep = self._replica(idx)
                    if rep.dead or rep.retired:
                        raise ValueError(
                            "replica %r is already %s" % (
                                idx, "dead" if rep.dead else "retired"))
                    if len(live) <= 1:
                        raise ValueError(
                            "cannot remove the last live replica")
                rep.retired = True   # _pick holds this lock: from here
                # on no new attempt routes to it
            self._event("scale_down", rep.idx)
            _otrace.instant("pool/scale_down", cat="serving")
            # drain completes every accepted request (zero dropped); an
            # EJECTED victim may be wedged — fail its leftovers fast
            # instead of holding the reload lock forever (its queued
            # work was already failed over by attempt timeouts)
            with rep.lock:
                wedged = rep.state == EJECTED
            rep.engine.close(drain=not wedged,
                             timeout=1.0 if wedged else timeout)
            with self._route_lock:
                try:
                    self._replicas.remove(rep)
                except ValueError:
                    pass
            self._rebalance_admission()
            return rep.idx

    def close(self, drain=True, timeout=None):
        self.closed = True
        if self._autoscaler is not None:
            self._autoscaler.stop()
        if self._canary is not None:
            self._canary.cancel("pool closed")
        for rep in list(self._replicas):
            if rep.dead:
                continue
            # never drain an EJECTED replica: a wedged worker would hold
            # the close forever, and its queued requests were already
            # failed over (attempt timeouts) — fail the leftovers fast
            rep_drain = drain and rep.state != EJECTED
            rep.engine.close(drain=rep_drain,
                             timeout=timeout if rep_drain else 1.0)


class DecodePool(object):
    """N DecodeEngine replicas behind one ``submit()`` surface.

    Continuous-batched decode (ARCHITECTURE.md §27) shifts what
    "least-loaded" means: an engine's capacity is its FREE SLOTS, not
    its queue depth — a replica with 6 of 8 slots open can absorb six
    new streams at the very next iteration boundary, while a full one
    parks them in its pending queue.  Routing therefore picks the
    replica with the most free slots (free = max_slots - occupied -
    already-pending streams, floored at the pending backlog penalty),
    breaking ties by fewest pending.  Because every replica compiles
    the SAME fixed-[max_slots] step and per-stream results depend only
    on that stream's row (the bucket-lattice invariant, §27), routing
    is invisible in the tokens: any replica decodes any stream
    bit-identically.

    Deliberately thinner than :class:`ReplicaPool`: a decode stream is
    STATEFUL (its KV rows live in one replica's scope), so there is no
    mid-stream failover, hedging, or retry — a replica failure fails
    its resident streams typed and the caller resubmits.  What it does
    share: ``pool_state()`` for /healthz (per-replica
    ``decode_stats()``), drain/close semantics, and the observability
    registry gauges each engine already exports.
    """

    def __init__(self, engines, name="decode-pool"):
        if not engines:
            raise ValueError("DecodePool needs at least one DecodeEngine")
        self.name = name
        self._engines = list(engines)
        self._route_lock = threading.Lock()
        self._rr = 0  # tiebreak rotation so equal replicas share load
        self.closed = False

    # ---------------------------------------------------- routing --
    def _free_slots(self, eng):
        st = eng.decode_stats()
        return (st.get("slots", 0) - st.get("occupied_slots", 0)
                - st.get("pending_streams", 0))

    def _pick(self):
        with self._route_lock:
            engines = list(self._engines)
            n = len(engines)
            order = [engines[(self._rr + i) % n] for i in range(n)]
            self._rr = (self._rr + 1) % n
        best, best_key = None, None
        for eng in order:
            try:
                st = eng.decode_stats()
            except Exception:
                continue
            key = (st.get("slots", 0) - st.get("occupied_slots", 0)
                   - st.get("pending_streams", 0),
                   -st.get("pending_streams", 0))
            if best_key is None or key > best_key:
                best, best_key = eng, key
        if best is None:
            raise ServingClosedError("no live decode replicas")
        return best

    def submit(self, feeds=None, max_new_tokens=None, deadline_ms=None):
        if self.closed:
            raise ServingClosedError("decode pool %r is closed" % self.name)
        return self._pick().submit(feeds=feeds, max_new_tokens=max_new_tokens,
                                   deadline_ms=deadline_ms)

    def decode(self, feeds=None, max_new_tokens=None, deadline_ms=None,
               timeout=None):
        return self.submit(feeds=feeds, max_new_tokens=max_new_tokens,
                           deadline_ms=deadline_ms).result(timeout=timeout)

    # ------------------------------------------------ introspection --
    @property
    def replicas(self):
        return list(self._engines)

    def queue_depth(self):
        return sum(e.queue_depth() for e in self._engines)

    def decode_stats(self):
        """Aggregate decode stats (sums over replicas; rates summed)."""
        total = {"replicas": len(self._engines), "slots": 0,
                 "occupied_slots": 0, "active_streams": 0,
                 "pending_streams": 0, "tokens_total": 0,
                 "streams_completed": 0, "tokens_per_s": 0.0}
        for eng in self._engines:
            st = eng.decode_stats()
            for k in ("slots", "occupied_slots", "active_streams",
                      "pending_streams", "tokens_total",
                      "streams_completed"):
                total[k] += st.get(k, 0)
            total["tokens_per_s"] += st.get("tokens_per_s", 0.0)
        total["tokens_per_s"] = round(total["tokens_per_s"], 3)
        return total

    def pool_state(self):
        """The /healthz payload: per-replica decode stats + aggregate."""
        reps = []
        for i, eng in enumerate(self._engines):
            st = eng.decode_stats()
            reps.append({"replica": i, "name": eng.name,
                         "slots": st.get("slots", 0),
                         "occupied_slots": st.get("occupied_slots", 0),
                         "active_streams": st.get("active_streams", 0),
                         "pending_streams": st.get("pending_streams", 0),
                         "tokens_total": st.get("tokens_total", 0),
                         "tokens_per_s": st.get("tokens_per_s", 0.0),
                         "inter_token_p50_ms":
                             st.get("inter_token_p50_ms", 0.0),
                         "inter_token_p99_ms":
                             st.get("inter_token_p99_ms", 0.0),
                         "devices": eng.device_span()})
        agg = self.decode_stats()
        agg["mode"] = "decode"
        agg["replicas"] = reps
        return agg

    def describe(self):
        base = self._engines[0].describe()
        base["name"] = self.name
        base["status"] = "closed" if self.closed else "serving"
        base["pool"] = self.pool_state()
        return base

    # ----------------------------------------------------- lifecycle --
    def drain(self, timeout=None):
        ok = True
        for eng in self._engines:
            ok = eng.drain(timeout=timeout) and ok
        return ok

    def close(self, drain=True, timeout=None):
        self.closed = True
        for eng in self._engines:
            eng.close(drain=drain, timeout=timeout)
