"""ModelFleet: N models behind one serving surface, with priority
brownout and weighted capacity shares.

One deployment rarely serves one model: the era's answer was one
`listen_and_serv` process per model, each sized by hand, each melting
down independently. A `ModelFleet` owns a {name: ReplicaPool} registry
— per-model replica sets, so every pool keeps its own health machine,
failover, admission, autoscaling and canary promotion — plus the one
thing no single pool can decide: WHO gets shed when the fleet as a
whole is overloaded.

  * **priority brownout** — every model carries an integer `priority`
    (higher = more important). The fleet tracks aggregate pressure
    (in-flight vs the pools' AIMD admission limits, and queue
    occupancy); when it stays above `pressure_high` the brownout level
    rises one priority TIER at a time (dwell-limited, no flapping):
    the lowest tier's requests start getting fast 429s (with a
    Retry-After hint) while higher tiers keep serving. When pressure
    falls below `pressure_low` the level steps back down. The top tier
    is never shed — brownout degrades the fleet, it never turns it off.
  * **weighted shares** — `weight` is a model's share of the fleet's
    aggregate in-flight budget. Under pressure (above `pressure_high`),
    a model running past `weight/total_weight` of the aggregate limit
    is shed even inside a surviving tier — one greedy model cannot
    starve its peers.
  * **per-model /metrics** — the fleet's `registry()` plugs straight
    into `ModelServer`: every serving/pool family is labeled
    {model, replica} per pool exactly as before, and `/healthz` carries
    every pool's state plus the fleet's brownout level.

Brownout decisions are recomputed at submit time from live counters
(deterministic, no controller thread to race tests against) with a
`shed_dwell_s` hysteresis. Design notes: ARCHITECTURE.md §26.
"""
import threading
import time

from .batcher import QueueFullError, ServingClosedError
from .pool import ReplicaPool

__all__ = ["ModelFleet", "BrownoutError"]


class BrownoutError(QueueFullError):
    """Fleet-level shed: the request's model is browned out (fleet
    overloaded and this model's priority tier — or weighted share — is
    the one being sacrificed). Maps to 429 + Retry-After like every
    other backpressure signal."""


class _FleetModel(object):
    """The engine-shaped registry entry `ModelServer` talks to: submits
    route through the fleet (brownout), everything else delegates to
    the model's own pool."""

    def __init__(self, fleet, name, pool, priority, weight):
        self._fleet = fleet
        self._pool = pool
        self.name = name
        self.priority = int(priority)
        self.weight = float(weight)
        self.shed_total = 0

    def submit(self, feed, deadline_ms=None):
        return self._fleet.submit(self.name, feed,
                                  deadline_ms=deadline_ms)

    def infer(self, feed, deadline_ms=None, timeout=30.0):
        return self.submit(feed, deadline_ms=deadline_ms) \
            .result(timeout).numpy()

    def describe(self):
        d = self._pool.describe()
        d["priority"] = self.priority
        d["weight"] = self.weight
        d["browned_out"] = self._fleet.is_browned_out(self.name)
        d["shed_total"] = self.shed_total
        return d

    def __getattr__(self, attr):
        # pool_state / replica_metrics / metrics / run_direct /
        # closed / ... — the pool surface, unchanged
        return getattr(self._pool, attr)

    def close(self, drain=True, timeout=None):
        self._pool.close(drain=drain, timeout=timeout)


class ModelFleet(object):
    def __init__(self, brownout=True, pressure_high=0.85,
                 pressure_low=0.5, shed_dwell_s=1.0, name="fleet"):
        self.name = name
        self.brownout = bool(brownout)
        self.pressure_high = float(pressure_high)
        self.pressure_low = float(pressure_low)
        self.shed_dwell_s = float(shed_dwell_s)
        self.closed = False
        self._models = {}            # name -> _FleetModel
        self._lock = threading.Lock()
        self._level = 0              # priority tiers currently shed
        self._level_changed_at = 0.0

    # ---------------------------------------------------------- registry --
    def add_model(self, name, pool=None, priority=0, weight=1.0,
                  **pool_kw):
        """Register a model: hand in a built ReplicaPool (or any
        engine-shaped object) via `pool=`, or pass ReplicaPool kwargs
        (model_dir=..., replicas=..., autoscale=..., ...) and the fleet
        builds one. Returns the pool."""
        if weight <= 0:
            raise ValueError("weight must be > 0, got %r" % (weight,))
        with self._lock:
            if name in self._models:
                raise ValueError("model %r already registered" % name)
        if pool is None:
            pool = ReplicaPool(name=name, **pool_kw)
        entry = _FleetModel(self, name, pool, priority, weight)
        with self._lock:
            self._models[name] = entry
        return pool

    def remove_model(self, name, drain=True, timeout=None):
        with self._lock:
            entry = self._models.pop(name)
        entry._pool.close(drain=drain, timeout=timeout)

    def pool(self, name):
        return self._models[name]._pool

    def models(self):
        return sorted(self._models)

    def registry(self):
        """{name: engine-shaped entry} for ModelServer — fleet-routed
        submits, per-model pool metrics."""
        return dict(self._models)

    # ---------------------------------------------------------- pressure --
    def _pressure(self):
        """Fleet pressure in [0, inf): the MAX over pools of per-pool
        occupancy (in-flight vs the AIMD admission limit, queued vs
        queue capacity). Max, not aggregate — one saturated model means
        the fleet is already failing someone, and an idle peer's spare
        queue slots don't serve the saturated model's clients; shedding
        low-priority work is how the shared hardware gets back to the
        high-priority tier."""
        p = 0.0
        for entry in list(self._models.values()):
            pool = entry._pool
            adm = getattr(pool, "_admission", None)
            if adm is not None and adm.limit > 0:
                p = max(p, pool.total_inflight() / adm.limit)
            qcap = (pool.queue_capacity_total()
                    if hasattr(pool, "queue_capacity_total") else 0)
            if qcap:
                p = max(p, pool.queue_depth() / qcap)
        return p

    def _tiers(self):
        """Distinct priorities, lowest first."""
        return sorted({e.priority for e in self._models.values()})

    def _update_level(self, pressure, now):
        """Dwell-limited level machine: one tier up per dwell while hot,
        one tier down per dwell while cool; the top tier is never
        shed."""
        with self._lock:
            max_level = max(len(self._tiers()) - 1, 0)
            if now - self._level_changed_at < self.shed_dwell_s:
                return self._level
            if pressure >= self.pressure_high and self._level < max_level:
                self._level += 1
                self._level_changed_at = now
            elif pressure <= self.pressure_low and self._level > 0:
                self._level -= 1
                self._level_changed_at = now
            return min(self._level, max_level)

    def brownout_level(self):
        return self._level

    def is_browned_out(self, name):
        entry = self._models[name]
        tiers = self._tiers()
        return self._level > 0 and entry.priority in tiers[:self._level]

    # ------------------------------------------------------------ submit --
    def submit(self, name, feed, deadline_ms=None):
        if self.closed:
            raise ServingClosedError("model fleet is shut down")
        entry = self._models.get(name)
        if entry is None:
            raise KeyError("no model %r in the fleet (have %r)"
                           % (name, self.models()))
        if self.brownout:
            now = time.monotonic()
            pressure = self._pressure()
            level = self._update_level(pressure, now)
            shed_reason = None
            if level > 0:
                tiers = self._tiers()
                if entry.priority in tiers[:level]:
                    shed_reason = ("model %r (priority %d) browned out "
                                   "at fleet pressure %.2f"
                                   % (name, entry.priority, pressure))
            if shed_reason is None and pressure >= self.pressure_high:
                # weighted-share enforcement inside surviving tiers: a
                # model past its share of the aggregate budget sheds
                # first even at its own priority
                total_w = sum(e.weight
                              for e in self._models.values()) or 1.0
                total_limit = sum(
                    e._pool._admission.limit
                    for e in self._models.values()
                    if getattr(e._pool, "_admission", None) is not None)
                if total_limit > 0:
                    share = entry.weight / total_w * total_limit
                    if entry._pool.total_inflight() > share:
                        shed_reason = (
                            "model %r over its weighted share "
                            "(%.0f in flight > %.1f) at fleet "
                            "pressure %.2f"
                            % (name, entry._pool.total_inflight(),
                               share, pressure))
            if shed_reason is not None:
                entry.shed_total += 1
                exc = BrownoutError(shed_reason + "; retry with backoff")
                adm = getattr(entry._pool, "_admission", None)
                exc.retry_after_s = (adm.retry_after_s()
                                     if adm is not None else 1.0)
                raise exc
        return entry._pool.submit(feed, deadline_ms=deadline_ms)

    def infer(self, name, feed, deadline_ms=None, timeout=30.0):
        return self.submit(name, feed, deadline_ms=deadline_ms) \
            .result(timeout).numpy()

    # ------------------------------------------------------------- state --
    def fleet_state(self):
        out = {"models": {}, "brownout_level": self._level,
               "pressure": round(self._pressure(), 4),
               "tiers": self._tiers()}
        for name, entry in sorted(self._models.items()):
            out["models"][name] = {
                "priority": entry.priority,
                "weight": entry.weight,
                "browned_out": self.is_browned_out(name),
                "shed_total": entry.shed_total,
                "pool": (entry._pool.pool_state()
                         if hasattr(entry._pool, "pool_state") else None),
            }
        return out

    def close(self, drain=True, timeout=None):
        self.closed = True
        for entry in list(self._models.values()):
            entry._pool.close(drain=drain, timeout=timeout)
