"""PoolAutoscaler: the fleet controller that grows and shrinks a
ReplicaPool from signals the pool already measures.

The reference era made the *user* own deployment sizing: listen_and_serv
was a fixed-size endpoint, and a traffic step either fit or 429'd until
an operator noticed. The TensorFlow system paper's stance (the runtime,
not the user, owns placement and scaling — arXiv:1605.08695) applied to
this repo's serving stack: a small control loop samples three signals
every `interval_s` and drives the pool's membership verbs
(`add_replica` / `remove_replica`) between `[min_replicas,
max_replicas]`:

  * **AIMD admission pressure** — the delta of the pool's 429 counter
    (`PoolMetrics.rejected_queue_full`) since the last tick. Any
    rejection means clients are being shed RIGHT NOW: the strongest
    scale-up signal there is.
  * **queue depth** — aggregate queued requests vs aggregate queue
    capacity; a queue filling past `up_queue_frac` scales up BEFORE the
    429s start.
  * **idle** — no rejections, no queued work, nothing in flight for
    `down_idle_s` continuous seconds scales down one replica (never
    below `min_replicas`).

Scale-up builds and WARMS the new engine before it joins routing — with
the AOT compile cache armed (ptpu_serve defaults it on) warmup is a
disk load, so scale-up is seconds; the admission ceiling opens to the
grown capacity immediately (`_Admission.set_bounds`), so absorbed load
does not wait for additive recovery. Scale-down retires the youngest
replica (no new traffic), DRAINS everything already accepted on it, and
only then closes — a contraction can never fail an accepted request.

Cooldowns bound the loop: `scale_up_cooldown_s` between grows (one
warmup at a time; a burst scales one replica per cooldown until the
signal clears or max is hit) and `scale_down_cooldown_s` between
shrinks (and after any grow — flapping wastes exactly the warm starts
scale-up depends on). Decisions land in `pool.events`
(`scale_up`/`scale_down`) and the flight recorder
(`pool/scale_up` instants); `state()` rides `pool_state()` onto
/healthz. Design notes: ARCHITECTURE.md §26.
"""
import threading
import time

__all__ = ["PoolAutoscaler"]


class PoolAutoscaler(object):
    def __init__(self, pool, min_replicas, max_replicas,
                 interval_s=0.25, up_queue_frac=0.5,
                 scale_up_cooldown_s=1.0, scale_down_cooldown_s=5.0,
                 down_idle_s=3.0):
        if int(min_replicas) < 1:
            raise ValueError("min_replicas must be >= 1, got %r"
                             % (min_replicas,))
        if int(max_replicas) < int(min_replicas):
            raise ValueError(
                "max_replicas (%r) must be >= min_replicas (%r)"
                % (max_replicas, min_replicas))
        self.pool = pool
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.interval_s = float(interval_s)
        self.up_queue_frac = float(up_queue_frac)
        self.scale_up_cooldown_s = float(scale_up_cooldown_s)
        self.scale_down_cooldown_s = float(scale_down_cooldown_s)
        self.down_idle_s = float(down_idle_s)

        self._stop = threading.Event()
        self._thread = None
        self._lock = threading.Lock()
        self._last_rejects = pool.metrics.snapshot()["rejected_queue_full"]
        self._idle_since = None
        self._up_ok_at = 0.0     # monotonic cooldown gates
        self._down_ok_at = 0.0
        self.scale_ups = 0
        self.scale_downs = 0
        self.last_scale_up_s = None    # wall seconds of the last grow
        # (engine build + warmup) — the "rides AOT warm starts" number
        self.last_error = None

    # ----------------------------------------------------------- control --
    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ptpu-autoscaler")
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the control loop
                # must outlive a transient failure (e.g. a scale-up
                # racing close()); the error is visible, not fatal
                self.last_error = repr(e)

    # -------------------------------------------------------------- tick --
    def tick(self, now=None):
        """One control decision. Public (and `now`-injectable) so tests
        can drive the loop deterministically without the thread."""
        pool = self.pool
        if pool.closed:
            return None
        now = time.monotonic() if now is None else now
        snap = pool.metrics.snapshot()
        rejects = snap["rejected_queue_full"]
        with self._lock:
            reject_delta = rejects - self._last_rejects
            self._last_rejects = rejects
        live = pool.live_replica_count()
        qd = pool.queue_depth()
        cap = pool.queue_capacity_total()
        inflight = pool.total_inflight()

        busy = reject_delta > 0 or qd > 0 or inflight > 0
        if busy:
            self._idle_since = None
        elif self._idle_since is None:
            self._idle_since = now

        want_up = (reject_delta > 0
                   or (cap > 0 and qd >= self.up_queue_frac * cap))
        if want_up and live < self.max_replicas and now >= self._up_ok_at:
            t0 = time.monotonic()
            idx = pool.add_replica()
            self.last_scale_up_s = time.monotonic() - t0
            self.scale_ups += 1
            self._up_ok_at = now + self.scale_up_cooldown_s
            # a fresh grow resets the shrink clock: don't contract the
            # capacity we just paid a warmup for
            self._down_ok_at = now + self.scale_down_cooldown_s
            self._idle_since = None
            return ("up", idx)

        if (live > self.min_replicas
                and self._idle_since is not None
                and now - self._idle_since >= self.down_idle_s
                and now >= self._down_ok_at):
            idx = pool.remove_replica(timeout=30.0)
            self.scale_downs += 1
            self._down_ok_at = now + self.scale_down_cooldown_s
            return ("down", idx)
        return None

    # ------------------------------------------------------------- state --
    def state(self):
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "live_replicas": self.pool.live_replica_count(),
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "last_scale_up_s": (round(self.last_scale_up_s, 3)
                                if self.last_scale_up_s is not None
                                else None),
            "interval_s": self.interval_s,
            "last_error": self.last_error,
        }
