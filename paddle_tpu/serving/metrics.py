"""Serving metrics: QPS, latency percentiles, batch occupancy, queue
depth, rejection/deadline counters.

One `ServingMetrics` per `InferenceEngine`. Writers are the request
threads (submit/reject) and the batcher worker (dispatch); readers are
`/metrics` (Prometheus text), `/v1/models` (JSON), and bench.py — all
under one lock, all O(window) worst case.

The batcher worker also threads every dispatch into
`profiler.record_run` (tag `serving/<model> b<batch>[xs<seq>]`) when the
profiler is active, so `profile_report()` shows training and serving
entries side by side in the same Event table.
"""
import collections
import threading
import time

__all__ = ["ServingMetrics", "DecodeMetrics"]


def _percentile(sorted_vals, q):
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class ServingMetrics(object):
    """Thread-safe counters + a bounded latency window.

    Occupancy bookkeeping distinguishes REQUESTS from ROWS: a batch of 5
    one-row requests padded into an 8-row bucket counts occupancy 5
    (requests/batch — the coalescing win) and row utilization 5/8 (how
    much of the compiled bucket carried real data).
    """

    def __init__(self, latency_window=2048):
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.requests_total = 0        # accepted into the queue
        self.responses_total = 0       # scattered back successfully
        self.rejected_queue_full = 0   # fast backpressure rejections
        self.deadline_expired = 0      # dropped before batching
        self.errors_total = 0          # dispatch/scatter failures
        self.batches_total = 0         # device dispatches
        self.batch_requests_total = 0  # requests across all batches
        self.batch_rows_total = 0      # real rows across all batches
        self.bucket_rows_total = 0     # padded bucket rows dispatched
        self.warmup_compiles = 0       # buckets traced at startup
        self._latencies = collections.deque(maxlen=latency_window)
        self._queue_depth_fn = None    # live gauge, set by the batcher

    def bind_queue_depth(self, fn):
        self._queue_depth_fn = fn

    def on_submit(self):
        with self._lock:
            self.requests_total += 1

    def on_queue_full(self):
        with self._lock:
            self.rejected_queue_full += 1

    def on_deadline_expired(self, n=1):
        with self._lock:
            self.deadline_expired += n

    def on_error(self, n=1):
        with self._lock:
            self.errors_total += n

    def on_warmup_compile(self, n=1):
        with self._lock:
            self.warmup_compiles += n

    def on_batch(self, num_requests, num_rows, bucket_rows, latencies_s):
        """One dispatch scattered: latencies_s are per-request
        submit->scatter times (dispatch enqueued; D2H still pending —
        that cost is the caller's, paid per-request on materialize)."""
        with self._lock:
            self.batches_total += 1
            self.batch_requests_total += num_requests
            self.batch_rows_total += num_rows
            self.bucket_rows_total += bucket_rows
            self.responses_total += num_requests
            self._latencies.extend(latencies_s)

    def queue_depth(self):
        fn = self._queue_depth_fn
        return fn() if fn is not None else 0

    def snapshot(self):
        with self._lock:
            lat = sorted(self._latencies)
            elapsed = max(time.monotonic() - self._t0, 1e-9)
            batches = max(self.batches_total, 1)
            return {
                "uptime_s": round(elapsed, 3),
                "requests_total": self.requests_total,
                "responses_total": self.responses_total,
                "rejected_queue_full": self.rejected_queue_full,
                "deadline_expired": self.deadline_expired,
                "errors_total": self.errors_total,
                "batches_total": self.batches_total,
                "qps": round(self.responses_total / elapsed, 3),
                "mean_batch_occupancy":
                    round(self.batch_requests_total / batches, 3),
                "row_utilization":
                    round(self.batch_rows_total /
                          max(self.bucket_rows_total, 1), 4),
                "warmup_compiles": self.warmup_compiles,
                "queue_depth": self.queue_depth(),
                "latency_ms": {
                    "p50": round(_percentile(lat, 0.50) * 1e3, 3),
                    "p95": round(_percentile(lat, 0.95) * 1e3, 3),
                    "p99": round(_percentile(lat, 0.99) * 1e3, 3),
                    "window": len(lat),
                },
            }

    def render_prometheus(self, model="default"):
        """Prometheus text exposition for one model (the /metrics
        contract). Multi-model servers must use `render_prometheus_all`
        — concatenating per-model expositions would repeat each family's
        HELP/TYPE header, which Prometheus rejects as a whole scrape."""
        return render_prometheus_all({model: self})


class DecodeMetrics(object):
    """Counters for one decode step-loop (serving.DecodeEngine).

    The unit of work is the ITERATION (one fixed-shape step over all
    slots), not the request: occupancy is slots-carrying-streams per
    iteration (the continuous-batching win — admits refill slots
    mid-flight, so mean occupancy > 1 under concurrent load), the
    latency window holds inter-token gaps (wall time between a stream's
    consecutive tokens — the latency a generative client feels), and
    tokens/s is measured over a recent bounded window so the gauge
    tracks current load, not lifetime average.  Readers: the
    observability-registry decoder collector (`/metrics`),
    `pool_state()`, and bench.py."""

    def __init__(self, latency_window=4096):
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.streams_admitted = 0      # admitted into a slot
        self.streams_completed = 0     # retired after finishing
        self.streams_failed = 0        # retired with an error/deadline
        self.rejected_queue_full = 0   # pending-queue backpressure
        self.deadline_expired = 0      # per-stream deadline retires
        self.tokens_total = 0          # tokens delivered to streams
        self.iterations_total = 0      # step-loop dispatches
        self.occupied_rows_total = 0   # sum of occupied slots per iter
        self._inter_token = collections.deque(maxlen=latency_window)
        self._rate = collections.deque(maxlen=latency_window)  # (t, n)

    def on_admit(self, n=1):
        with self._lock:
            self.streams_admitted += n

    def on_queue_full(self):
        with self._lock:
            self.rejected_queue_full += 1

    def on_deadline_expired(self, n=1):
        with self._lock:
            self.deadline_expired += n
            self.streams_failed += n

    def on_stream_failed(self, n=1):
        with self._lock:
            self.streams_failed += n

    def on_stream_completed(self, n=1):
        with self._lock:
            self.streams_completed += n

    def on_iteration(self, occupied, tokens, inter_token_gaps_s=()):
        """One decode step delivered: `occupied` slots carried live
        streams, `tokens` tokens went out, `inter_token_gaps_s` are the
        per-stream gaps since each stream's previous token."""
        with self._lock:
            self.iterations_total += 1
            self.occupied_rows_total += occupied
            self.tokens_total += tokens
            self._inter_token.extend(inter_token_gaps_s)
            self._rate.append((time.monotonic(), tokens))

    def snapshot(self):
        with self._lock:
            gaps = sorted(self._inter_token)
            elapsed = max(time.monotonic() - self._t0, 1e-9)
            if len(self._rate) >= 2:
                span = max(self._rate[-1][0] - self._rate[0][0], 1e-9)
                recent = sum(n for _, n in self._rate) / span
            else:
                recent = self.tokens_total / elapsed
            iters = max(self.iterations_total, 1)
            return {
                "uptime_s": round(elapsed, 3),
                "streams_admitted": self.streams_admitted,
                "streams_completed": self.streams_completed,
                "streams_failed": self.streams_failed,
                "rejected_queue_full": self.rejected_queue_full,
                "deadline_expired": self.deadline_expired,
                "tokens_total": self.tokens_total,
                "iterations": self.iterations_total,
                "tokens_per_s": round(recent, 3),
                "mean_slot_occupancy":
                    round(self.occupied_rows_total / iters, 3),
                "inter_token_p50_ms":
                    round(_percentile(gaps, 0.50) * 1e3, 3),
                "inter_token_p99_ms":
                    round(_percentile(gaps, 0.99) * 1e3, 3),
                "inter_token_window": len(gaps),
            }


# (family, type, help, snapshot key) — one HELP/TYPE per family in the
# exposition, one labeled sample line per model
_FAMILIES = [
    ("requests_total", "counter", "accepted requests", "requests_total"),
    ("responses_total", "counter", "completed requests",
     "responses_total"),
    ("rejected_queue_full_total", "counter",
     "fast rejections due to a full queue (backpressure)",
     "rejected_queue_full"),
    ("deadline_expired_total", "counter",
     "requests dropped before batching: deadline passed",
     "deadline_expired"),
    ("errors_total", "counter", "dispatch failures", "errors_total"),
    ("batches_total", "counter", "device dispatches", "batches_total"),
    ("qps", "gauge", "responses per second since start", "qps"),
    ("mean_batch_occupancy", "gauge",
     "mean requests coalesced per dispatch", "mean_batch_occupancy"),
    ("row_utilization", "gauge", "real rows / padded bucket rows",
     "row_utilization"),
    ("queue_depth", "gauge", "requests waiting right now", "queue_depth"),
]


def _escape_label(value):
    """Prometheus exposition label escaping: backslash, double quote,
    newline — an unescaped quote in a model name would invalidate the
    whole scrape for every model on the server."""
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


# pool-level families, read from PoolMetrics.snapshot() (one labeled
# sample per pool; HELP/TYPE once, like everything else)
_POOL_FAMILIES = [
    ("pool_requests_total", "counter", "requests accepted by the pool",
     "requests_total"),
    ("pool_responses_total", "counter", "pool requests completed",
     "responses_total"),
    ("pool_errors_total", "counter",
     "client-visible pool failures (after failover exhausted)",
     "errors_total"),
    ("pool_retries_total", "counter",
     "failover resubmissions onto a different replica", "retries_total"),
    ("pool_hedges_total", "counter", "tail-hedge duplicate attempts",
     "hedges_total"),
    ("pool_rejected_total", "counter",
     "admission/backpressure rejections (429s)", "rejected_queue_full"),
    ("pool_attempt_timeouts_total", "counter",
     "per-attempt timeouts (wedged-replica detections)",
     "attempt_timeouts_total"),
    ("pool_poisoned_results_total", "counter",
     "non-finite replica outputs caught before the client",
     "poisoned_results_total"),
    ("pool_reloads_total", "counter", "zero-downtime weight reloads",
     "reloads_total"),
    ("pool_ejections_total", "counter", "circuit-breaker ejections",
     "ejections_total"),
]


def render_prometheus_all(named_metrics, pools=None):
    """One valid exposition covering plain engines
    ({model: ServingMetrics}) and replica pools ({model: ReplicaPool}).
    A pool's replicas each emit one sample per serving family labeled
    {model, replica}; pool-level families (replica state gauge, retry /
    hedge / admission / reload counters, client latency) follow —
    HELP/TYPE still exactly once per family across everything."""
    entries = []    # (label_str, snapshot) for the per-engine families
    for name, m in sorted(named_metrics.items()):
        entries.append(('model="%s"' % _escape_label(name), m.snapshot()))
    pools = dict(pools or {})
    for name, pool in sorted(pools.items()):
        for ridx, m in sorted(pool.replica_metrics().items()):
            entries.append(('model="%s",replica="%s"'
                            % (_escape_label(name), ridx), m.snapshot()))
    lines = []
    for family, mtype, help_text, key in _FAMILIES:
        lines.append("# HELP ptpu_serving_%s %s" % (family, help_text))
        lines.append("# TYPE ptpu_serving_%s %s" % (family, mtype))
        for labels, s in entries:
            lines.append('ptpu_serving_%s{%s} %s' % (family, labels,
                                                     s[key]))
    lines.append("# HELP ptpu_serving_latency_ms request latency "
                 "percentiles (submit -> scatter)")
    lines.append("# TYPE ptpu_serving_latency_ms gauge")
    for labels, s in entries:
        for q in ("p50", "p95", "p99"):
            lines.append('ptpu_serving_latency_ms{%s,quantile="%s"} %s'
                         % (labels, q, s["latency_ms"][q]))
    if pools:
        from .pool import _STATE_GAUGE
        lines.append("# HELP ptpu_serving_replica_state replica health "
                     "(0=healthy, 1=degraded, 2=ejected; +4 when dead)")
        lines.append("# TYPE ptpu_serving_replica_state gauge")
        pool_replica_states = {name: pool.pool_state()["replicas"]
                               for name, pool in sorted(pools.items())}
        for name, reps in pool_replica_states.items():
            model = _escape_label(name)
            for r in reps:
                val = _STATE_GAUGE[r["state"]] + (4 if r["dead"] else 0)
                lines.append('ptpu_serving_replica_state{model="%s",'
                             'replica="%s"} %d' % (model, r["replica"],
                                                   val))
        # device ownership: one sample per (replica, device) — a
        # tensor-parallel replica spans M devices, so operators can see
        # exactly which chips each replica holds (ARCHITECTURE.md §23)
        lines.append("# HELP ptpu_serving_replica_device 1 for each "
                     "device in a replica's span (tensor-parallel "
                     "replicas span tp devices)")
        lines.append("# TYPE ptpu_serving_replica_device gauge")
        for name, reps in pool_replica_states.items():
            model = _escape_label(name)
            for r in reps:
                for dev in r.get("devices", ()):
                    lines.append(
                        'ptpu_serving_replica_device{model="%s",'
                        'replica="%s",device="%s"} 1'
                        % (model, r["replica"], _escape_label(dev)))
        psnaps = {name: pool.metrics.snapshot()
                  for name, pool in sorted(pools.items())}
        for family, mtype, help_text, key in _POOL_FAMILIES:
            lines.append("# HELP ptpu_serving_%s %s" % (family, help_text))
            lines.append("# TYPE ptpu_serving_%s %s" % (family, mtype))
            for name, s in psnaps.items():
                lines.append('ptpu_serving_%s{model="%s"} %s'
                             % (family, _escape_label(name), s[key]))
        lines.append("# HELP ptpu_serving_pool_latency_ms client-observed "
                     "pool latency percentiles (submit -> result, "
                     "failovers included)")
        lines.append("# TYPE ptpu_serving_pool_latency_ms gauge")
        for name, s in psnaps.items():
            for q in ("p50", "p95", "p99"):
                lines.append('ptpu_serving_pool_latency_ms{model="%s",'
                             'quantile="%s"} %s'
                             % (_escape_label(name), q,
                                s["latency_ms"][q]))
    return "\n".join(lines) + "\n"
