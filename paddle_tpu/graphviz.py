"""Minimal graphviz dot writer.

Parity: python/paddle/fluid/graphviz.py — enough surface (Graph, add_node,
add_edge, Node/Edge attrs, code emission) for debuger.draw_block_graphviz;
`show` writes the .dot and best-effort invokes `dot` if present.
"""
import os
import subprocess

__all__ = ["Graph"]


def crepr(v):
    if isinstance(v, str):
        return '"%s"' % v
    return str(v)


class Rank(object):
    def __init__(self, kind, name, priority):
        self.kind = kind
        self.name = name
        self.priority = priority
        self.nodes = []


class Node(object):
    counter = 1

    def __init__(self, label, prefix, description="", **attrs):
        self.label = label
        self.name = "%s_%d" % (prefix, Node.counter)
        Node.counter += 1
        self.attrs = attrs
        self.attrs["label"] = label

    def __str__(self):
        return "%s [%s];" % (self.name, ",".join(
            "%s=%s" % (k, crepr(v)) for k, v in sorted(self.attrs.items())))


class Edge(object):
    def __init__(self, source, target, **attrs):
        self.source = source
        self.target = target
        self.attrs = attrs

    def __str__(self):
        attrs = ",".join("%s=%s" % (k, crepr(v))
                         for k, v in sorted(self.attrs.items()))
        return "%s -> %s%s;" % (self.source.name, self.target.name,
                                " [%s]" % attrs if attrs else "")


class Graph(object):
    def __init__(self, title, **attrs):
        self.title = title
        self.attrs = attrs
        self.nodes = []
        self.edges = []

    def add_node(self, label, prefix="node", description="", **attrs):
        node = Node(label, prefix, description, **attrs)
        self.nodes.append(node)
        return node

    def add_edge(self, source, target, **attrs):
        edge = Edge(source, target, **attrs)
        self.edges.append(edge)
        return edge

    def code(self):
        lines = ["digraph G {"]
        lines += ['  label = %s;' % crepr(self.title)]
        for k, v in sorted(self.attrs.items()):
            lines.append("  %s=%s;" % (k, crepr(v)))
        lines += ["  " + str(n) for n in self.nodes]
        lines += ["  " + str(e) for e in self.edges]
        lines.append("}")
        return "\n".join(lines)

    def show(self, path):
        with open(path, "w") as f:
            f.write(self.code())
        img_path = os.path.splitext(path)[0] + ".png"
        try:
            subprocess.run(["dot", "-Tpng", path, "-o", img_path],
                           check=False, capture_output=True, timeout=30)
        except (OSError, subprocess.SubprocessError):
            pass  # graphviz binary not installed; .dot file still written
        return path
