"""Thread-local scope stack (parity: python/paddle/fluid/default_scope_funcs.py).

A thread-local stack of Scopes; the top is the current scope. `var`/`find_var`
operate on the current scope (find_var searches ancestors, like
framework::Scope::FindVar). `scoped_function` runs a callable inside a fresh
kid scope that is dropped afterwards.
"""
import threading

from .core.executor import global_scope

__tl_scope__ = threading.local()

__all__ = [
    "get_cur_scope",
    "enter_local_scope",
    "leave_local_scope",
    "var",
    "find_var",
    "scoped_function",
]


def get_cur_scope():
    """Current scope (bottom of the stack = the process global scope)."""
    stack = getattr(__tl_scope__, "cur_scope", None)
    if stack is None:
        stack = __tl_scope__.cur_scope = []
    if not stack:
        stack.append(global_scope())
    return stack[-1]


def enter_local_scope():
    """Push a new kid of the current scope."""
    cur = get_cur_scope()
    __tl_scope__.cur_scope.append(cur.new_scope())


def leave_local_scope():
    """Pop the current scope and drop the parent's kids."""
    __tl_scope__.cur_scope.pop()
    get_cur_scope().drop_kids()


def var(name):
    """Create (or get) a variable in the current scope."""
    return get_cur_scope().var(name)


def find_var(name):
    """Find a variable in the current scope or its ancestors."""
    return get_cur_scope().find_var(name)


def scoped_function(func):
    """Invoke `func` inside a fresh local scope."""
    enter_local_scope()
    try:
        func()
    finally:
        leave_local_scope()
