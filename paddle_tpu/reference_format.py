"""Readers for reference-era on-disk artifacts (no protobuf runtime).

The reference serializes programs as the `ProgramDesc` protobuf of
paddle/fluid/framework/framework.proto (written by
python/paddle/fluid/io.py:384 save_inference_model via
`program.desc.serialize_to_string()`), and parameters as the LoDTensor
stream of paddle/fluid/framework/lod_tensor.cc:243 SerializeToStream /
tensor_util.cc:191 TensorToStream (written by operators/save_op.cc, one
file per variable named after it).

This module hand-rolls the protobuf wire format (proto2, only the field
shapes framework.proto actually uses) so a model saved by reference-era
code loads into a TPU-native Program — the one migration path source-level
compatibility can't cover.
"""
import struct

import numpy as np

from .core.framework import Block, Program

__all__ = ["parse_program_desc", "read_lod_tensor_file",
           "read_combined_lod_tensor_file",
           "write_combined_lod_tensor_file",
           "adapt_sequence_layout",
           "strip_feed_fetch",
           "serialize_program_desc", "write_lod_tensor_file",
           "save_reference_inference_model"]


# ---------------------------------------------------------------------------
# protobuf wire primitives (proto2)
# ---------------------------------------------------------------------------

def _varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("malformed varint")


def _fields(buf):
    """Yield (field_number, wire_type, value) over one message's bytes.
    value: int for varint/fixed, bytes for length-delimited."""
    pos, end = 0, len(buf)
    while pos < end:
        key, pos = _varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, pos = _varint(buf, pos)
        elif wire == 1:
            v = struct.unpack("<q", buf[pos:pos + 8])[0]
            pos += 8
        elif wire == 2:
            n, pos = _varint(buf, pos)
            v = buf[pos:pos + n]
            pos += n
        elif wire == 5:
            v = struct.unpack("<i", buf[pos:pos + 4])[0]
            pos += 4
        else:
            raise ValueError("unsupported wire type %d" % wire)
        yield field, wire, v


def _sint32(v):
    """proto int32 arrives as a 64-bit varint two's complement."""
    v &= (1 << 64) - 1
    return v - (1 << 64) if v >= (1 << 63) else v


def _repeated_varints(wire, v):
    """A repeated varint field: packed (length-delimited) or one value."""
    if wire == 2:
        out, pos = [], 0
        while pos < len(v):
            x, pos = _varint(v, pos)
            out.append(_sint32(x))
        return out
    return [_sint32(v)]


def _f32(wire, v):
    if wire == 5:
        return struct.unpack("<f", struct.pack("<i", v))[0]
    raise ValueError("expected fixed32 float, wire %d" % wire)


# ---------------------------------------------------------------------------
# framework.proto messages
# ---------------------------------------------------------------------------

_DTYPE = {0: "bool", 1: "int16", 2: "int32", 3: "int64",
          4: "float16", 5: "float32", 6: "float64"}
# era op registrations whose name our registry modernized; applied on
# load (era->ours) via THIS dict in parse_program_desc, and inverted on
# export so the wire always carries the era registration
_ERA_TO_OURS_NAME = {"top_k": "topk"}
_OURS_TO_ERA_NAME = {v: k for k, v in _ERA_TO_OURS_NAME.items()}
# VarType.Type values describing non-dense runtime objects
_LOD_TENSOR, _READER = 7, 15
_FEED_MINIBATCH, _FETCH_LIST = 9, 10


def _parse_tensor_desc(buf):
    dtype, dims = None, []
    for field, wire, v in _fields(buf):
        if field == 1:
            dtype = _DTYPE.get(v, "float32")
        elif field == 2:
            dims.extend(_repeated_varints(wire, v))
    return dtype, dims


def _parse_var_type(buf):
    """VarType -> (type_enum, dtype, dims, lod_level)."""
    t, dtype, dims, lod_level = None, None, None, 0
    for field, wire, v in _fields(buf):
        if field == 1:
            t = v
        elif field == 3:  # LoDTensorDesc
            for f2, w2, v2 in _fields(v):
                if f2 == 1:
                    dtype, dims = _parse_tensor_desc(v2)
                elif f2 == 2:
                    lod_level = v2
    return t, dtype, dims, lod_level


def _parse_var_desc(buf):
    name, vtype, persistable = None, None, False
    for field, wire, v in _fields(buf):
        if field == 1:
            name = v.decode("utf-8")
        elif field == 2:
            vtype = _parse_var_type(v)
        elif field == 3:
            persistable = bool(v)
    return name, vtype, persistable


def _parse_op_var(buf):
    slot, args = None, []
    for field, wire, v in _fields(buf):
        if field == 1:
            slot = v.decode("utf-8")
        elif field == 2:
            args.append(v.decode("utf-8"))
    return slot, args


def _parse_attr(buf):
    name = None
    atype = None
    vals = {}
    for field, wire, v in _fields(buf):
        if field == 1:
            name = v.decode("utf-8")
        elif field == 2:
            atype = v
        elif field == 3:
            vals["i"] = _sint32(v)
        elif field == 4:
            vals["f"] = _f32(wire, v)
        elif field == 5:
            vals["s"] = v.decode("utf-8")
        elif field == 6:
            vals.setdefault("ints", []).extend(_repeated_varints(wire, v))
        elif field == 7:
            if wire == 2:  # packed floats
                vals.setdefault("floats", []).extend(
                    struct.unpack("<%df" % (len(v) // 4), v))
            else:
                vals.setdefault("floats", []).append(_f32(wire, v))
        elif field == 8:
            vals.setdefault("strings", []).append(v.decode("utf-8"))
        elif field == 10:
            vals["b"] = bool(v)
        elif field == 11:
            vals.setdefault("bools", []).extend(
                [bool(x) for x in _repeated_varints(wire, v)])
        elif field == 12:
            vals["block_idx"] = _sint32(v)
        elif field == 13:
            vals["l"] = _sint32(v)
    # AttrType: INT FLOAT STRING INTS FLOATS STRINGS BOOLEAN BOOLEANS
    #           BLOCK LONG
    pick = {0: vals.get("i"), 1: vals.get("f"), 2: vals.get("s"),
            3: vals.get("ints", []), 4: vals.get("floats", []),
            5: vals.get("strings", []), 6: vals.get("b"),
            7: vals.get("bools", []), 8: vals.get("block_idx"),
            9: vals.get("l")}
    if atype not in pick:
        raise ValueError("unknown AttrType %r for attr %r" % (atype, name))
    return name, pick[atype]


def _parse_op_desc(buf):
    inputs, outputs, attrs = {}, {}, {}
    op_type = None
    for field, wire, v in _fields(buf):
        if field == 1:
            slot, args = _parse_op_var(v)
            inputs[slot] = args
        elif field == 2:
            slot, args = _parse_op_var(v)
            outputs[slot] = args
        elif field == 3:
            op_type = v.decode("utf-8")
        elif field == 4:
            name, value = _parse_attr(v)
            attrs[name] = value
    return op_type, inputs, outputs, attrs


def _parse_block_desc(buf):
    idx, parent, varz, ops = 0, -1, [], []
    for field, wire, v in _fields(buf):
        if field == 1:
            idx = _sint32(v)
        elif field == 2:
            parent = _sint32(v)
        elif field == 3:
            varz.append(_parse_var_desc(v))
        elif field == 4:
            ops.append(_parse_op_desc(v))
    return idx, parent, varz, ops


def _parse_blocks(raw):
    """ProgramDesc bytes -> [(idx, parent, vars, ops)] sorted by idx —
    the single wire-decode both parse_program_desc and strip_feed_fetch
    build on."""
    blocks = []
    for field, wire, v in _fields(raw):
        if field == 1:
            blocks.append(_parse_block_desc(v))
    blocks.sort(key=lambda b: b[0])
    return blocks


def parse_program_desc(raw):
    """ProgramDesc protobuf bytes -> Program (cites framework.proto;
    the writer is python/paddle/fluid/framework.py Program.desc)."""
    blocks = _parse_blocks(raw) if isinstance(raw, (bytes, bytearray)) \
        else raw

    program = Program()
    # Program() starts with block 0; create the rest preserving parents
    for idx, parent, _, _ in blocks[1:]:
        program.create_block(parent_idx=max(parent, 0))
    program.current_block_idx = 0

    for idx, parent, varz, ops in blocks:
        blk = program.blocks[idx]
        for name, vtype, persistable in varz:
            t, dtype, dims, lod_level = vtype if vtype else (
                None, None, None, 0)
            if t in (_FEED_MINIBATCH, _FETCH_LIST):
                continue  # feed/fetch plumbing; the Executor feeds directly
            blk.create_var(
                name=name, shape=tuple(dims) if dims is not None else None,
                dtype=dtype or "float32", lod_level=lod_level or 0,
                persistable=persistable)
        for op_type, ins, outs, attrs in ops:
            if op_type in ("feed", "fetch"):
                continue  # recovered separately by strip_feed_fetch
            # era registrations our registry modernized (top_k -> topk)
            blk.append_op(type=_ERA_TO_OURS_NAME.get(op_type, op_type),
                          inputs=ins, outputs=outs,
                          attrs=attrs, infer_shape=False)
    program.current_block_idx = 0
    return program


def strip_feed_fetch(blocks):
    """Feed/fetch targets of a reference inference ProgramDesc: the names
    wired through its prepended `feed` / appended `fetch` ops
    (python/paddle/fluid/io.py get_feed_targets_names). Accepts the
    _parse_blocks result (or raw bytes)."""
    if isinstance(blocks, (bytes, bytearray)):
        blocks = _parse_blocks(blocks)
    feeds, fetches = [], []
    if blocks:
        _, _, _, ops = blocks[0]  # feed/fetch live in the global block
        for op_type, ins, outs, attrs in ops:
            if op_type == "feed":
                feeds.append((attrs.get("col", len(feeds)),
                              outs["Out"][0]))
            elif op_type == "fetch":
                fetches.append((attrs.get("col", len(fetches)),
                                ins["X"][0]))
    # the era's prepend_feed_ops inserts at block index 0, so a real
    # __model__ lists feed ops col n-1..0 — order by col, not block order
    return [n for _, n in sorted(feeds)], [n for _, n in sorted(fetches)]


# ---------------------------------------------------------------------------
# LoDTensor stream (save_op output, one file per variable)
# ---------------------------------------------------------------------------

def _read_lod_tensor_stream(buf, pos):
    """One LoDTensor stream at buf[pos:] -> (arr, lod, end_pos).

    Layout (lod_tensor.cc SerializeToStream):
      u32 version(0) | u64 lod_level | per level: u64 nbytes + size_t data
      | u32 tensor version(0) | i32 desc_size | TensorDesc proto | raw data
    """
    def u32():
        nonlocal pos
        v = struct.unpack_from("<I", buf, pos)[0]
        pos += 4
        return v

    def u64():
        nonlocal pos
        v = struct.unpack_from("<Q", buf, pos)[0]
        pos += 8
        return v

    version = u32()
    if version != 0:
        raise ValueError("unsupported LoDTensor version %d" % version)
    lod = []
    for _ in range(u64()):
        nbytes = u64()
        level = np.frombuffer(buf, "<u8", count=nbytes // 8, offset=pos)
        pos += nbytes
        lod.append(level.tolist())
    tversion = u32()
    if tversion != 0:
        raise ValueError("unsupported Tensor version %d" % tversion)
    desc_size = struct.unpack_from("<i", buf, pos)[0]
    pos += 4
    dtype, dims = _parse_tensor_desc(buf[pos:pos + desc_size])
    pos += desc_size
    n = int(np.prod(dims)) if dims else 1
    arr = np.frombuffer(buf, np.dtype(dtype), count=n,
                        offset=pos).reshape(dims)
    pos += arr.nbytes
    return arr, lod, pos


def read_lod_tensor_file(path):
    """Parse one reference save_op file -> (np.ndarray, lod levels)."""
    with open(path, "rb") as f:
        buf = f.read()
    arr, lod, end = _read_lod_tensor_stream(buf, 0)
    if end != len(buf):
        raise ValueError(
            "param file %r has %d trailing bytes after the tensor (a "
            "COMBINED save_combine file needs params_filename=...)"
            % (path, len(buf) - end))
    return arr, lod


def read_combined_lod_tensor_file(path, names):
    """Parse a save_combine file (save_combine_op.cc: the named tensors'
    streams CONCATENATED, in sorted-by-name order — the era's io.py:120
    sorts before emitting the op) -> {name: np.ndarray}."""
    with open(path, "rb") as f:
        buf = f.read()
    out, pos = {}, 0
    for name in sorted(names):
        if pos >= len(buf):
            raise ValueError(
                "combined params file %r exhausted before %r (have the "
                "var names changed since save?)" % (path, name))
        arr, _lod, pos = _read_lod_tensor_stream(buf, pos)
        out[name] = arr
    if pos != len(buf):
        raise ValueError(
            "combined params file %r has %d trailing bytes after the "
            "%d named tensors" % (path, len(buf) - pos, len(names)))
    return out


# ---------------------------------------------------------------------------
# layout adaptation: flat LoD rows -> padded-dense + @SEQLEN companions
# ---------------------------------------------------------------------------

# recurrences: attach XLen to Input; sequence-shaped outputs keep the
# segmentation via the generic propagation rule below
_RECURRENT = frozenset(("lstm", "lstmp", "gru"))

# Sequence-RESTRUCTURING ops this adapter does not rewrite: each changes
# the segmentation itself (not just per-step values), so the generic
# "propagate X's lengths to Out" rule below would be silently WRONG for
# them.  Reject at load time instead (ADVICE r4 #2).
_UNHANDLED_SEQ_RESTRUCTURING = frozenset((
    "lod_reset", "sequence_concat", "sequence_slice", "sequence_erase",
    "sequence_reshape", "sequence_pad", "sequence_unpad",
))


def adapt_sequence_layout(program, feed_names):
    """Rewire a loaded reference program from the flat-LoD-rows layout to
    the padded-dense layout (SURVEY §6.3), in place.

    The reference addresses a lod_level-1 tensor as [total_rows, D] and
    carries the segmentation out of band (LoD offsets in the runtime
    tensor). Here the same variable is [num_seqs, max_len, D] plus an
    int32 ``name@SEQLEN`` lengths companion that the Executor feeds
    automatically for LoDTensor feeds. Three rewrites follow from that:

    - row-semantics ops gain a rank: ``mul`` x_num_col_dims += 1, and the
      broadcast/concat axis of ``elementwise_*``/``concat`` += 1 when the
      data is sequence-shaped (a program built through our own layers
      encodes the same thing as fc(num_flatten_dims=2) — layers/nn.py);
    - sequence/recurrence ops (lstm/lstmp/gru/sequence_*) get their
      ``XLen``/``YLen`` input wired to the segmentation companion;
    - segmentation PROPAGATES by the same generic rule Block.append_op
      applies to layer-built programs: every op except the
      ``_LOD_CLEARING_OPS`` (sequence_pool & co) hands its first
      sequence-input's lengths to its outputs — one shared invariant,
      not a second allowlist.

    Cites: lod_tensor.md design + lstm_op.cc (the era's in-op LoD walk
    this replaces). Known limit: ``concat`` with axis=0 on sequence data
    (time-axis concat, i.e. sequence_concat semantics) is not rewritten.
    """
    block = program.global_block()
    seqlen = {}

    def ensure_len_var(name):
        ln = name + "@SEQLEN"
        if ln not in block.vars:
            v = block.create_var(name=ln, shape=(-1,), dtype="int32")
            v.stop_gradient = True
        return ln

    for name in feed_names:
        v = block.vars.get(name)
        if v is not None and getattr(v, "lod_level", 0):
            seqlen[name] = ensure_len_var(name)

    def first(slot_map, slot):
        names = slot_map.get(slot) or []
        return names[0] if names else None

    for op in block.ops:
        t = op.type
        ins_names = [n for ns in op.inputs.values() for n in ns if n]
        # --- reject segmentation-restructuring ops we cannot rewrite ---
        if any(n in seqlen for n in ins_names):
            if t in _UNHANDLED_SEQ_RESTRUCTURING:
                raise ValueError(
                    "adapt_sequence_layout: op %r restructures sequence "
                    "segmentation and is not supported by the layout "
                    "adapter; rebuild this program with the native "
                    "paddle_tpu layers instead of loading the reference "
                    "desc" % t)
            # flat sequence vars are rank-2 [total_rows, D]: axis 0 and
            # its negative alias -2 both denote the time axis
            if t == "concat" and op.attrs.get("axis", 0) in (0, -2):
                raise ValueError(
                    "adapt_sequence_layout: concat with axis=0 on "
                    "sequence data is time-axis concatenation "
                    "(sequence_concat semantics) and is not supported "
                    "by the layout adapter")
        # --- op-specific rank/wiring rewrites --------------------------
        if t == "mul" and first(op.inputs, "X") in seqlen:
            op.attrs["x_num_col_dims"] = \
                op.attrs.get("x_num_col_dims", 1) + 1
        elif t.startswith("elementwise_"):
            x, y = first(op.inputs, "X"), first(op.inputs, "Y")
            if x in seqlen and y not in seqlen:
                ax = op.attrs.get("axis", -1)
                if ax >= 1:
                    op.attrs["axis"] = ax + 1
        elif t == "concat":
            if any(n in seqlen for n in op.inputs.get("X", ()) or ()):
                ax = op.attrs.get("axis", 0)
                if ax >= 1:
                    op.attrs["axis"] = ax + 1
        elif t in _RECURRENT:
            inp = first(op.inputs, "Input")
            if inp in seqlen:
                op.inputs["XLen"] = [seqlen[inp]]
        elif t in ("sequence_pool", "sequence_last_step",
                   "sequence_first_step", "sequence_softmax",
                   "sequence_conv"):
            x = first(op.inputs, "X")
            if x in seqlen:
                op.inputs["XLen"] = [seqlen[x]]
        elif t == "sequence_expand":
            y = first(op.inputs, "Y")
            if y in seqlen:
                op.inputs["YLen"] = [seqlen[y]]
                for o in op.outputs.get("Out", ()) or ():
                    if o:   # expand follows Y's lengths, not X's
                        seqlen[o] = seqlen[y]
        # --- generic segmentation propagation (Block.append_op's rule:
        #     first sequence input wins, clearing ops consume) ----------
        if t not in Block._LOD_CLEARING_OPS:
            src = next((n for n in ins_names if n in seqlen), None)
            if src is not None:
                for ns in op.outputs.values():
                    for o in ns:
                        if o and o not in seqlen:
                            seqlen[o] = seqlen[src]

    for name, ln in seqlen.items():
        v = block.vars.get(name)
        if v is not None:
            # seq_len_var already pointing at the companion means this var
            # was adapted by a previous call — don't bump its rank twice
            already = getattr(v, "seq_len_var", None) == ln
            if not getattr(v, "lod_level", 0):
                v.lod_level = 1
            v.seq_len_var = ln
            # the era DECLARED this var flat ([total_rows, ...]); it now
            # holds the padded layout ([num_seqs, max_len, ...]) — keep
            # the declaration truthful so padded-array feeds pass
            # convert_feeds' rank check and the static analyzer's shape
            # re-inference matches what the lowering actually produces
            if v.shape is not None and not already:
                v.shape = (-1, -1) + tuple(v.shape[1:])
    return program


# ---------------------------------------------------------------------------
# era-format EXPORT: write ProgramDesc protobuf + save_op param files so
# REFERENCE-era deployments can load models trained here. The wire layout
# mirrors this module's own parser (field numbers cited there from
# framework.proto); nothing below is translated reference code.
# ---------------------------------------------------------------------------


# Every op name the reference registers (frozen grep of REGISTER_OP* over
# paddle/fluid/operators/*.cc, minus *_grad — the same snapshot the op
# audit test asserts against; that test imports THIS list). The era
# runtime can only load descs whose op types are in this set.
ERA_REGISTERED_OP_NAMES = frozenset("""
accuracy adadelta adagrad adam adamax array_to_lod_tensor assign
assign_value auc average_accumulates batch_norm beam_search
beam_search_decode bilinear_tensor_product bipartite_match box_coder cast
channel_close channel_create channel_recv channel_send chunk_eval clip
clip_by_norm concat cond conditional_block conv2d conv2d_transpose conv3d
conv3d_transpose conv_shift cos_sim crf_decoding crop cross_entropy
ctc_align cumsum decayed_adagrad delete_var depthwise_conv2d detection_map
dropout edit_distance elementwise_add elementwise_div elementwise_max
elementwise_min elementwise_mul elementwise_pow elementwise_sub expand
feed fetch fill fill_constant fill_constant_batch_size_like
fill_zeros_like ftrl gather gaussian_random
gaussian_random_batch_size_like get_places go gru gru_unit hinge_loss
huber_loss im2sequence increment iou_similarity is_empty l1_norm
label_smooth layer_norm linear_chain_crf listen_and_serv load
load_combine lod_array_length lod_rank_table lod_reset
lod_tensor_to_array log_loss lookup_table lrn lstm lstm_unit lstmp
margin_rank_loss matmul max_pool2d_with_index max_pool3d_with_index
max_sequence_len maxout mean merge_lod_tensor mine_hard_examples minus
modified_huber_loss momentum mul multiclass_nms multiplex nce norm
one_hot pad parallel_do pool2d pool3d positive_negative_pair
precision_recall prelu print prior_box proximal_adagrad proximal_gd
rank_loss read read_from_array recurrent recv reorder_lod_tensor_by_rank
reshape rmsprop rnn_memory_helper roi_pool row_conv save save_combine
scale scatter select send sequence_concat sequence_conv sequence_erase
sequence_expand sequence_pool sequence_reshape sequence_slice
sequence_softmax sgd shrink_rnn_memory sigmoid_cross_entropy_with_logits
sign smooth_l1_loss softmax softmax_with_cross_entropy split
split_lod_tensor split_selected_rows spp squared_l2_distance
squared_l2_norm sum target_assign top_k transpose uniform_random
uniform_random_batch_size_like unpool warpctc while write_to_array
""".split())

_DTYPE_ENUM = {v: k for k, v in _DTYPE.items()}          # name -> enum

# ops the era registers through family MACROS rather than REGISTER_OP
# (REGISTER_ACTIVATION_OP / compare / logical / reduce) — they don't show
# in the REGISTER_OP grep snapshot above but are loadable era types
ERA_MACRO_REGISTERED_NAMES = frozenset("""
sigmoid logsigmoid exp relu tanh tanh_shrink softshrink sqrt abs ceil
floor cos sin round reciprocal log square softplus softsign brelu
leaky_relu soft_relu elu relu6 pow stanh hard_shrink thresholded_relu
hard_sigmoid swish
less_than less_equal greater_than greater_equal equal not_equal
logical_and logical_or logical_xor logical_not
reduce_sum reduce_mean reduce_max reduce_min reduce_prod
""".split())


def _w_varint(v):
    out = b""
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _w_tag(field, wire):
    return _w_varint((field << 3) | wire)


def _w_ld(field, payload):
    if isinstance(payload, str):
        payload = payload.encode("utf-8")
    return _w_tag(field, 2) + _w_varint(len(payload)) + payload


def _w_vi(field, v):
    return _w_tag(field, 0) + _w_varint(v)


def _encode_wire_attr(name, value):
    """One OpDesc.Attr message. AttrType order mirrors _parse_attr's pick
    table: INT FLOAT STRING INTS FLOATS STRINGS BOOLEAN BOOLEANS BLOCK
    LONG."""
    out = _w_ld(1, name)
    if isinstance(value, bool):            # before int: bool IS int
        return out + _w_vi(2, 6) + _w_vi(10, int(value))
    if isinstance(value, (int, np.integer)):
        v = int(value)
        if not (-(1 << 31) <= v < (1 << 31)):
            # outside int32: the era's proto2 parser would silently
            # truncate an INT varint — emit AttrType LONG (field 13)
            return out + _w_vi(2, 9) + _w_vi(13, v & ((1 << 64) - 1))
        return out + _w_vi(2, 0) + _w_vi(3, v)
    if isinstance(value, (float, np.floating)):
        return out + _w_vi(2, 1) + _w_tag(4, 5) + struct.pack(
            "<f", float(value))
    if isinstance(value, str):
        return out + _w_vi(2, 2) + _w_ld(5, value)
    if isinstance(value, (list, tuple)):
        vals = list(value)
        if not vals:
            # an empty list has no observable element type; the era's
            # OpDesc type check compares declared AttrType, so writing a
            # guessed type would be wrong — omit the attr entirely (a
            # repeated proto2 field left unset reads back as empty, and
            # era ops' list attrs SetDefault to empty)
            return None
        if all(isinstance(x, bool) for x in vals) and vals:
            return out + _w_vi(2, 7) + _w_ld(
                11, b"".join(_w_varint(int(x)) for x in vals))
        if all(isinstance(x, (int, np.integer)) for x in vals):
            return out + _w_vi(2, 3) + _w_ld(
                6, b"".join(_w_varint(int(x) & ((1 << 64) - 1))
                            for x in vals))
        if all(isinstance(x, (float, np.floating)) for x in vals):
            return out + _w_vi(2, 4) + _w_ld(
                7, struct.pack("<%df" % len(vals),
                               *[float(x) for x in vals]))
        if all(isinstance(x, str) for x in vals):
            return out + _w_vi(2, 5) + b"".join(
                _w_ld(8, x) for x in vals)
    raise ValueError(
        "cannot encode attr %r=%r (%s) in the era wire format"
        % (name, value, type(value).__name__))


def _encode_wire_var(var, var_type=7):
    """VarDesc: name, VarType{type, LoDTensorDesc{TensorDesc, lod}},
    persistable."""
    body = _w_vi(1, var_type)
    if var_type == 7:       # LOD_TENSOR
        dims = var.shape if var.shape is not None else ()
        dtype = var.dtype or "float32"
        if dtype not in _DTYPE_ENUM:
            # loud-failure rule (same as _write_lod_tensor_stream): a
            # silent FP32 fallback would write a wrong data_type into the
            # exported desc — e.g. uint8 image-feed vars
            raise ValueError(
                "era export: var %r has dtype %r with no era VarType "
                "data_type enum — the reference runtime cannot load it"
                % (var.name, dtype))
        tensor = _w_vi(1, _DTYPE_ENUM[dtype])
        tensor += b"".join(
            _w_vi(2, int(d) & ((1 << 64) - 1)) for d in dims)
        lodt = _w_ld(1, tensor)
        if getattr(var, "lod_level", 0):
            lodt += _w_vi(2, int(var.lod_level))
        body += _w_ld(3, lodt)
    out = _w_ld(1, var.name) + _w_ld(2, body)
    if var.persistable:
        out += _w_vi(3, 1)
    return out


def _encode_wire_op(op_type, inputs, outputs, attrs):
    out = _w_ld(3, op_type)
    for slot, args in inputs.items():
        out += _w_ld(1, _w_ld(1, slot) + b"".join(
            _w_ld(2, a) for a in args))
    for slot, args in outputs.items():
        out += _w_ld(2, _w_ld(1, slot) + b"".join(
            _w_ld(2, a) for a in args))
    for k in sorted(attrs):
        if k.startswith("__"):
            continue        # internal bookkeeping, never on the era wire
        enc = _encode_wire_attr(k, attrs[k])
        if enc is not None:
            out += _w_ld(4, enc)
    return out


def _deadapt_for_wire(blk):
    """The inverse of adapt_sequence_layout, computed per-op for the
    wire: padded-dense sequence wiring (@SEQLEN companions, XLen/OutLen
    slots, rank-bumped mul/elementwise/concat attrs, [B, T, ...] var
    dims) becomes the era's flat-LoD-rows convention. Returns
    (seq_names, skip_vars, op_view) where op_view(op) -> (inputs,
    outputs, attrs) era-shaped, or raises for sequence ops outside the
    adapter's handled set (the same set the import side rewires)."""
    seq = {n for n, v in blk.vars.items() if getattr(v, "lod_level", 0)}
    skip = {getattr(v, "seq_len_var", None) for v in blk.vars.values()}
    skip.discard(None)

    def _strip_len_slots(slot_map, op_type):
        """Drop every slot that refers exclusively to @SEQLEN companion
        vars (XLen/OutLen/YLen/DetectLen/... — driven by the skip set,
        not a name allowlist); a slot mixing companion and real names
        has no era form."""
        out = {}
        for s, names in slot_map.items():
            hits = [n in skip for n in names if n]
            if hits and all(hits):
                continue
            if any(hits):
                raise ValueError(
                    "era export: op %r slot %r mixes sequence-length "
                    "companions with data vars" % (op_type, s))
            out[s] = list(names)
        return out

    def op_view(op):
        t = op.type
        ins = _strip_len_slots(op.inputs, t)
        outs = _strip_len_slots(op.outputs, t)
        attrs = dict(op.attrs)
        ins_names = [n for ns in ins.values() for n in ns if n]
        if any(n in seq for n in ins_names):
            if t in _UNHANDLED_SEQ_RESTRUCTURING:
                raise ValueError(
                    "era export: sequence op %r is outside the layout "
                    "adapter's handled set" % t)
            # The load-side adapter only ever PRODUCES the padded attr
            # values inverted here (mul >=2, elementwise/concat axis
            # >=2); a padded value outside that range (e.g. time-axis
            # concat at axis 1) has no flat-era preimage — writing it
            # would silently change semantics on the era side AND on
            # re-import. Refuse loudly instead.
            if t == "mul" and ins.get("X", [None])[0] in seq:
                ncd = attrs.get("x_num_col_dims", 1)
                if ncd < 2:
                    raise ValueError(
                        "era export: mul over sequence %r with "
                        "x_num_col_dims=%d has no flat-era preimage"
                        % (ins["X"][0], ncd))
                attrs["x_num_col_dims"] = ncd - 1
            elif t.startswith("elementwise_"):
                x = ins.get("X", [None])[0]
                y = ins.get("Y", [None])[0]
                if x in seq and y not in seq:
                    ax = attrs.get("axis", -1)
                    if ax == 1:
                        raise ValueError(
                            "era export: elementwise %s over sequence "
                            "%r broadcasts along the padded TIME axis "
                            "(axis=1) — no flat-era preimage" % (t, x))
                    if ax >= 2:
                        attrs["axis"] = ax - 1
            elif t == "concat":
                ax = attrs.get("axis", 0)
                if ax in (1, -2):
                    raise ValueError(
                        "era export: concat along the padded TIME axis "
                        "is sequence_concat semantics — no flat-era "
                        "preimage")
                if ax >= 2:
                    attrs["axis"] = ax - 1
        return ins, outs, attrs

    return seq, skip, op_view


class _OpStub(object):
    """Era-composition op produced by _decompose_for_era (quacks like
    Operator for the wire encoder / op_view)."""

    def __init__(self, type, inputs, outputs, attrs):
        self.type = type
        self.inputs = inputs
        self.outputs = outputs
        self.attrs = attrs


class _TmpLike(object):
    """Wire view of a decomposition temporary: dtype/lod follow an
    existing var; sequence sources get the era FLAT dims directly
    ([B, T, ...] -> [-1, ...]) since this view bypasses the _FlatView
    path real seq vars take."""

    def __init__(self, name, src):
        self.name = name
        self.dtype = src.dtype
        self.lod_level = getattr(src, "lod_level", 0)
        if self.lod_level and src.shape is not None \
                and len(src.shape) >= 2:
            self.shape = (-1,) + tuple(src.shape[2:])
        else:
            self.shape = src.shape
        self.persistable = False


def _decompose_for_era(op, blk, alloc_name):
    """Rewrite a fused parity op into the era op COMPOSITION the
    reference-era layer would have emitted (the export-side analogue of
    the parity layers). Returns ([(type, ins, outs, attrs)], new_vars)
    or None when `op` needs no decomposition. new_vars: [(name,
    like_existing_var_name)] temporaries to declare on the wire."""
    t = op.type
    if t == "square_error_cost":
        x, y = op.inputs["X"][0], op.inputs["Y"][0]
        out = op.outputs["Out"][0]
        tmp = alloc_name(out + ".sub")
        return ([("elementwise_sub", {"X": [x], "Y": [y]},
                  {"Out": [tmp]}, {}),
                 ("square", {"X": [tmp]}, {"Out": [out]}, {})],
                [(tmp, x)])
    if t in ("sequence_first_step", "sequence_last_step"):
        pooltype = "FIRST" if t == "sequence_first_step" else "LAST"
        return ([("sequence_pool", dict(op.inputs),
                  dict(op.outputs),
                  {"pooltype": pooltype})], [])
    if t == "log_softmax":
        x = op.inputs["X"][0]
        out = op.outputs["Out"][0]
        tmp = alloc_name(out + ".sm")
        return ([("softmax", {"X": [x]}, {"Out": [tmp]}, {}),
                 ("log", {"X": [tmp]}, {"Out": [out]}, {})],
                [(tmp, x)])
    if t in ("squeeze", "unsqueeze"):
        x = op.inputs["X"][0]
        xv = blk.vars.get(x)
        if xv is not None and getattr(xv, "lod_level", 0):
            # the padded output shape has no flat-era preimage — same
            # refusal rule as the padded mul/concat attrs
            raise ValueError(
                "era export: %s over sequence %r would bake padded "
                "dims into an era reshape — no flat-era preimage"
                % (t, x))
        out = op.outputs["Out"][0]
        v = blk.vars.get(out)
        shape = None if v is None else v.shape
        if shape is None or sum(1 for d in shape if d == -1) > 1:
            raise ValueError(
                "era export: %s with non-static output shape %r cannot "
                "decompose to era reshape" % (t, shape))
        return ([("reshape", {"X": list(op.inputs["X"])},
                  {"Out": [out]},
                  {"shape": [int(d) for d in shape]})], [])
    return None


def serialize_program_desc(program, feed_names, fetch_names):
    """Program (single-block inference graph) -> era ProgramDesc bytes,
    with the feed/fetch plumbing the era's save_inference_model prepends
    and appends (feed ops listed col n-1..0, the real serializer's
    insert-at-0 order our own strip_feed_fetch handles). Sequence
    programs are de-adapted to the era's flat-LoD-rows convention — the
    exact inverse of what adapt_sequence_layout applies on load."""
    # prune() empties orphaned sub-blocks but keeps their slots so
    # attrs['sub_block'] indices stay stable — an empty trailing block
    # is fine; a NON-empty one means live control flow we can't encode
    for b in program.blocks[1:]:
        if b.ops or b.vars:
            raise ValueError(
                "era export handles single-block inference programs; "
                "block %d still carries ops/vars (export the pruned "
                "inference program)" % b.idx)
    blk = program.global_block()
    # idx 0, parent -1 (64-bit two's-complement varint, as the era wrote)
    body = _w_vi(1, 0) + _w_tag(2, 0) + _w_varint((1 << 64) - 1)
    # feed/fetch carrier vars: persistable=True like the era's
    # prepend_feed_ops/append_fetch_ops wrote them — the era C++ executor
    # creates non-persistable vars in a per-run LOCAL scope, so a
    # non-persistable 'feed' var would shadow the outer-scope one
    # SetFeedVariable filled (feed_list.at(col) out-of-range) and fetch
    # results would land in the discarded local scope
    class _FV:
        def __init__(self, name):
            self.name, self.persistable = name, True
    body += _w_ld(3, _encode_wire_var(_FV("feed"), var_type=9))
    body += _w_ld(3, _encode_wire_var(_FV("fetch"), var_type=10))
    seq_names, skip_vars, op_view = _deadapt_for_wire(blk)

    class _FlatView:
        """Era dims for a padded sequence var: [B, T, ...] -> [-1, ...]
        flat rows (the dims adapt_sequence_layout re-pads on load)."""
        def __init__(self, v):
            self.name, self.dtype = v.name, v.dtype
            self.persistable = v.persistable
            self.lod_level = v.lod_level
            self.shape = ((-1,) + tuple(v.shape[2:])) \
                if v.shape is not None and len(v.shape) >= 2 else v.shape

    for name in sorted(blk.vars):
        if name in skip_vars:
            continue        # @SEQLEN companions never existed in the era
        v = blk.vars[name]
        if getattr(v, "type", None) in ("tensor_array", "rank_table"):
            raise ValueError(
                "era export supports dense inference graphs; var %r has "
                "runtime type %r" % (name, v.type))
        body += _w_ld(3, _encode_wire_var(
            _FlatView(v) if name in seq_names else v))
    # feed ops inserted at index 0 each -> serialized order col n-1..0
    for col in range(len(feed_names) - 1, -1, -1):
        body += _w_ld(4, _encode_wire_op(
            "feed", {"X": ["feed"]}, {"Out": [feed_names[col]]},
            {"col": col}))
    from .core.lowering import _SPECIAL
    tmp_counter = [0]

    def _alloc_name(base):
        tmp_counter[0] += 1
        return "%s.era%d" % (base, tmp_counter[0])

    wire_ops = []
    extra_vars = []
    for op in blk.ops:
        if op.type == "grad_of":
            raise ValueError("era export takes the INFERENCE program; "
                             "prune the backward first")
        if op.type in _SPECIAL:
            raise ValueError(
                "era export supports dense inference graphs; op %r is a "
                "graph-level (sub-block / LoD-structure) construct"
                % op.type)
        dec = _decompose_for_era(op, blk, _alloc_name)
        if dec is not None:
            sub_ops, new_vars = dec
            extra_vars.extend(new_vars)
            wire_ops.extend(
                (_OpStub(t2, i2, o2, a2), op) for t2, i2, o2, a2 in sub_ops)
        else:
            wire_ops.append((op, op))
    for tmp_name, like in extra_vars:
        src = blk.vars[like]
        body += _w_ld(3, _encode_wire_var(_TmpLike(tmp_name, src)))

    for op, src_op in wire_ops:
        # our registry uses a few modernized names; the wire must carry
        # the era registration (the load side aliases back)
        wire_type = _OURS_TO_ERA_NAME.get(op.type, op.type)
        if wire_type not in ERA_REGISTERED_OP_NAMES and \
                wire_type not in ERA_MACRO_REGISTERED_NAMES:
            # A desc naming a non-era op type would be unloadable by the
            # reference runtime — refuse at write time. Covers both a
            # TPU-native addition (fused_attention, pipeline, moe, ...)
            # and the handful of this framework's FUSED parity lowerings
            # of era APIs (square_error_cost, l2_normalize, ...) that
            # the era expressed as op compositions; lowering those to
            # era compositions at export is not implemented.
            raise ValueError(
                "era export: op %r has no era registration (it is "
                "either a TPU-native addition or a fused parity "
                "lowering the era expressed as an op composition) — "
                "express the inference head with primitive era ops to "
                "export" % src_op.type)
        w_ins, w_outs, w_attrs = op_view(op)
        body += _w_ld(4, _encode_wire_op(wire_type, w_ins, w_outs,
                                         w_attrs))
    for col, name in enumerate(fetch_names):
        body += _w_ld(4, _encode_wire_op(
            "fetch", {"X": [name]}, {"Out": ["fetch"]}, {"col": col}))
    return _w_ld(1, body)


def _write_lod_tensor_stream(f, arr, lod=None):
    """One save_op stream (the exact inverse of _read_lod_tensor_stream):
    u32 version | u64 lod levels (+ per-level u64 nbytes + offsets) |
    u32 tensor version | i32 desc size | TensorDesc | raw data."""
    arr = np.ascontiguousarray(arr)
    desc = _w_vi(1, _DTYPE_ENUM[str(arr.dtype)]) + b"".join(
        _w_vi(2, d) for d in arr.shape)
    f.write(struct.pack("<I", 0))
    levels = lod or []
    f.write(struct.pack("<Q", len(levels)))
    for level in levels:
        level = np.asarray(level, "<u8")
        f.write(struct.pack("<Q", level.nbytes))
        f.write(level.tobytes())
    f.write(struct.pack("<I", 0))
    f.write(struct.pack("<i", len(desc)))
    f.write(desc)
    f.write(arr.tobytes())


def write_lod_tensor_file(path, arr, lod=None):
    with open(path, "wb") as f:
        _write_lod_tensor_stream(f, arr, lod)


def write_combined_lod_tensor_file(path, name_to_array):
    """save_combine layout: the tensors' streams concatenated in
    sorted-by-name order (matching the era's io.py sort and
    read_combined_lod_tensor_file)."""
    with open(path, "wb") as f:
        for name in sorted(name_to_array):
            _write_lod_tensor_stream(f, name_to_array[name])


def save_reference_inference_model(dirname, feeded_var_names, target_vars,
                                   executor, main_program=None,
                                   scope=None, model_filename=None,
                                   params_filename=None):
    """Era-format save_inference_model: __model__ ProgramDesc protobuf +
    one save_op-layout file per persistable param — a directory the
    REFERENCE runtime (and this framework's load_reference_model) can
    serve. The era counterpart wrote the same layout from C++
    (save_op + Program.desc serialization)."""
    import os as _os
    from .core.executor import global_scope
    from .core.framework import default_main_program

    program = main_program if main_program is not None \
        else default_main_program()
    targets = [t if isinstance(t, str) else t.name for t in target_vars]
    inference = program.prune(
        [program.global_block().var(t) for t in targets], for_test=True)
    scope = scope or global_scope()

    _os.makedirs(dirname, exist_ok=True)
    with open(_os.path.join(dirname, model_filename or "__model__"),
              "wb") as f:
        f.write(serialize_program_desc(
            inference, list(feeded_var_names), targets))
    params = {}
    for v in inference.global_block().vars.values():
        if not v.persistable:
            continue
        val = scope.get(v.name)
        if val is None:
            raise ValueError(
                "persistable var %r has no value in the scope — run the "
                "startup program (or load params) first" % v.name)
        params[v.name] = np.asarray(val)
    if params_filename:
        # save_combine: one file, streams in sorted-name order
        write_combined_lod_tensor_file(
            _os.path.join(dirname, params_filename), params)
    else:
        for name, val in params.items():
            write_lod_tensor_file(_os.path.join(dirname, name), val)
    return inference
