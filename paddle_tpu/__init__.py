"""paddle_tpu: a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid (reference: mozga-intel/Paddle).

The public surface mirrors `import paddle.fluid as fluid`:

    import paddle_tpu as fluid
    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    y = fluid.layers.fc(input=x, size=1)
    ...
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(fluid.default_startup_program())
    exe.run(feed={...}, fetch_list=[...])

Execution is whole-program XLA compilation (core/lowering.py), autodiff is
jax.vjp over op lowering rules (core/backward.py), and multi-device runs ride
jax.sharding Meshes (parallel/).
"""
from . import tpu_guard  # MUST be first: installs the exclusive TPU-client
                         # lock on jax backend init (see tpu_guard.py)

# Sharding-invariant PRNG, process-wide: with the legacy (non-
# partitionable) threefry, the SAME program traced under a tensor-
# parallel mesh draws DIFFERENT random bits than single-device (XLA's
# partition of the counter math changes the stream) — a dropout mask
# that silently depends on the distribution plan would break every
# mesh-1/replicated bit-exactness contract in parallel/plan.py. The
# partitionable formulation makes every draw a pure function of
# (key, position) regardless of mesh, at the cost of a one-time stream
# change vs the legacy formulation (no test pins legacy absolute
# values; trace_env_key() carries the flag so stale AOT artifacts
# re-key rather than silently serving legacy-stream executables).
import jax as _jax
_jax.config.update("jax_threefry_partitionable", True)

from .core import framework
from .core.framework import (Program, Operator, Variable, Parameter,
                             default_main_program, default_startup_program,
                             program_guard, switch_main_program,
                             switch_startup_program)
from .core.executor import (Executor, FetchHandle, Scope, global_scope,
                            scope_guard)
from .core.readers import EOFException
from .core.backward import append_backward, calc_gradient
from .core.framework import Block, get_var
from .core.executor import switch_scope, fetch_var
from .core.lod import LoDTensor, create_lod_tensor
from .core.param_attr import ParamAttr, WeightNormParamAttr
from .core import initializer
from .core import unique_name
from .places import CPUPlace, CUDAPlace, TPUPlace, is_compiled_with_cuda, \
    is_compiled_with_tpu

from . import ops as _ops  # registers all op lowerings
from . import layers
from . import optimizer
from . import regularizer
from . import clip
from .clip import ErrorClipByValue, GradientClipByValue, GradientClipByNorm, \
    GradientClipByGlobalNorm
from . import nets
from . import io
from .io import save_params, load_params, save_persistables, \
    load_persistables, save_inference_model, load_inference_model
from . import metrics
from . import profiler
from . import observability
from . import evaluator
from . import average
from .average import WeightedAverage
from . import debuger
from . import graphviz
from . import memory_optimization_transpiler
from .memory_optimization_transpiler import memory_optimize, release_memory
from .data_feeder import DataFeeder
from . import backward
from .parallel.parallel_executor import ParallelExecutor
from . import transpiler
from .transpiler import DistributeTranspiler, SimpleDistributeTranspiler
from .transpiler import distributed_spliter
from . import default_scope_funcs
from . import net_drawer
from . import concurrency
from .concurrency import (make_channel, channel_send, channel_recv,
                          channel_close, Select)
from . import reader
from .reader import batch
from . import datasets
from . import recordio
from . import recordio_writer
from . import analysis
from .analysis import ProgramVerificationError
from . import serving
from . import checkpoint
from .checkpoint import CheckpointManager
from . import resilience
from .resilience import (Supervisor, TrainingAborted,
                         install_numeric_guards, NumericalGuardError,
                         DispatchTimeoutError)

Tensor = LoDTensor

__version__ = "0.1.0"
