"""Python half of the C inference API (native/inference_c.cc).

The reference ships a C++ inference library + C API
(paddle/fluid/inference/io.cc, paddle/capi) whose job is: load a saved
inference model, feed C buffers, run, read C buffers back. TPU-native,
the inference engine IS the XLA runtime, so the C entry embeds CPython
and delegates here; this module keeps the C side to a dozen stable calls
(create/run/destroy + buffer marshalling). Each predictor owns a private
Scope; jit caching makes repeated run() calls compile-free.
"""
import os

import numpy as np

from .core.executor import Executor, scope_guard, Scope
from . import io as _io
from .places import CPUPlace, TPUPlace

_predictors = {}
_next_handle = [1]


def _place():
    """PTPU_PLACE=tpu serves on the accelerator; default CPU (the safe
    choice for a C host process that may not own the TPU lease)."""
    return TPUPlace() if os.environ.get("PTPU_PLACE", "cpu") == "tpu" \
        else CPUPlace()


def create(model_dir):
    """Load a saved inference model (this framework's format when
    __model_meta__.json is present, otherwise a reference-era
    save_inference_model directory). Returns an int handle.

    create() is NOT thread-safe (the io loaders write through the
    process-global scope guard); initialize predictors before spawning
    serving threads. run() is safe to call concurrently across handles.
    """
    exe = Executor(_place())
    scope = Scope()
    with scope_guard(scope):
        if os.path.exists(os.path.join(model_dir, "__model_meta__.json")):
            program, feeds, fetches = _io.load_inference_model(
                model_dir, exe)
        else:
            program, feeds, fetches = _io.load_reference_model(
                model_dir, exe)
    h = _next_handle[0]
    _next_handle[0] += 1
    _predictors[h] = (exe, scope, program, list(feeds), fetches)
    return h


def feed_names(handle):
    return list(_predictors[handle][3])


def num_fetches(handle):
    return len(_predictors[handle][4])


def run(handle, names, buffers, shapes):
    """names: feed names; buffers: per-feed bytes-like of float32 data;
    shapes: per-feed int lists. Returns list of float32 C-contiguous
    numpy arrays (one per fetch target)."""
    exe, scope, program, _feeds, fetches = _predictors[handle]
    feed = {}
    for name, buf, shape in zip(names, buffers, shapes):
        feed[name] = np.frombuffer(buf, dtype=np.float32).reshape(
            [int(s) for s in shape])
    # scope passed explicitly — scope_guard mutates a process global and
    # would race when a multithreaded C host runs two predictors at once
    outs = exe.run(program, feed=feed, fetch_list=fetches, scope=scope)
    return [np.ascontiguousarray(np.asarray(o, dtype=np.float32))
            for o in outs]


def destroy(handle):
    _predictors.pop(handle, None)
