"""Python half of the C inference API (native/inference_c.cc).

The reference ships a C++ inference library + C API
(paddle/fluid/inference/io.cc, paddle/capi) whose job is: load a saved
inference model, feed C buffers, run, read C buffers back. TPU-native,
the inference engine IS the XLA runtime, so the C entry embeds CPython
and delegates here; this module keeps the C side to a dozen stable calls
(create/run/destroy + buffer marshalling). Each predictor owns a private
Scope; jit caching makes repeated run() calls compile-free.

v2 (era-complete like paddle/capi paddle_matrix/paddle_ivector): feeds
are reinterpreted with each feed var's DECLARED dtype (int64 ids for
embedding models arrive as int64 buffers, not floats smuggled through a
float32 contract), and ALL fetch targets are retained per run for
multi-output predictors; the C side reads them back one at a time with
their dtype and shape.
"""
import os

import numpy as np

from .core.executor import Executor, scope_guard, Scope
from . import io as _io
from .places import CPUPlace, TPUPlace


class _Predictor(object):
    __slots__ = ("exe", "scope", "program", "feeds", "fetches", "outputs",
                 "dtypes")

    def __init__(self, exe, scope, program, feeds, fetches):
        self.exe = exe
        self.scope = scope
        self.program = program
        self.feeds = list(feeds)
        self.fetches = fetches
        self.outputs = []  # last run's fetch arrays (native dtypes)
        # feed name -> declared dtype, resolved once (run() is hot)
        self.dtypes = {}
        for n in self.feeds:
            try:
                v = program.global_block().var_recursive(n)
                self.dtypes[n] = str(v.dtype)
            except Exception:
                self.dtypes[n] = "float32"


_predictors = {}
_next_handle = [1]


def _place():
    """PTPU_PLACE=tpu serves on the accelerator; default CPU (the safe
    choice for a C host process that may not own the TPU lease)."""
    return TPUPlace() if os.environ.get("PTPU_PLACE", "cpu") == "tpu" \
        else CPUPlace()


def create(model_dir):
    """Load a saved inference model (this framework's format when
    __model_meta__.json is present, otherwise a reference-era
    save_inference_model directory). Returns an int handle.

    create() is NOT thread-safe (the io loaders write through the
    process-global scope guard); initialize predictors before spawning
    serving threads. run() is safe to call concurrently across handles.
    """
    exe = Executor(_place())
    scope = Scope()
    with scope_guard(scope):
        if os.path.exists(os.path.join(model_dir, "__model_meta__.json")):
            program, feeds, fetches = _io.load_inference_model(
                model_dir, exe)
        else:
            # era dirs come in two layouts: one save_op file per param
            # (the default) or everything combined into a single params
            # file (params_filename / save_combine — the common era
            # C-API deployment shape). The C ABI has no params_filename
            # argument, so detect generically: a lone non-model file in
            # the dir IS the combined file, whatever it is named.
            extras = [n for n in os.listdir(model_dir)
                      if n not in ("__model__", "__model_meta__.json")
                      and os.path.isfile(os.path.join(model_dir, n))]
            params = extras[0] if len(extras) == 1 else None
            program, feeds, fetches = _io.load_reference_model(
                model_dir, exe, params_filename=params)
    h = _next_handle[0]
    _next_handle[0] += 1
    _predictors[h] = _Predictor(exe, scope, program, feeds, fetches)
    return h


def feed_names(handle):
    return list(_predictors[handle].feeds)


def _feed_dtype(p, name):
    """Declared dtype of a feed var ('float32', 'int64', ...); float32 when
    the name is unknown (defensive: reference models always declare)."""
    return p.dtypes.get(name, "float32")


def feed_dtypes(handle):
    p = _predictors[handle]
    return [_feed_dtype(p, n) for n in p.feeds]


def feed_elem_sizes(handle, names):
    """Per-name element byte widths, aligned with the PASSED names list —
    one call resolves every feed's marshalling width for the C side."""
    p = _predictors[handle]
    return [int(np.dtype(_feed_dtype(p, n)).itemsize) for n in names]


def num_fetches(handle):
    return len(_predictors[handle].fetches)


def run(handle, names, buffers, shapes):
    """names: feed names; buffers: per-feed bytes-like whose payload is in
    each feed var's DECLARED dtype; shapes: per-feed int lists. Executes
    and retains every fetch target (read back via output_*). Returns the
    number of outputs."""
    return run_lod(handle, names, buffers, shapes, [()] * len(names))


def run_lod(handle, names, buffers, shapes, lods):
    """Like run(), plus per-feed sequence lengths (era paddle_arguments'
    sequence_start_positions, as lengths): a feed with a non-empty lods
    entry carries FLAT rows ([total, D], the reference serving layout) and
    is re-segmented into a LoDTensor; an empty entry is a dense feed."""
    from .core.lod import create_lod_tensor

    # zip() would silently drop trailing feeds on a short list (the C
    # entry point always builds nfeeds-length arrays, but direct Python
    # callers can get it wrong) — validate up front (ADVICE r4 #1).
    if not (len(names) == len(buffers) == len(shapes) == len(lods)):
        raise ValueError(
            "run_lod: mismatched feed lists: %d names, %d buffers, "
            "%d shapes, %d lods" % (len(names), len(buffers),
                                    len(shapes), len(lods)))
    p = _predictors[handle]
    feed = {}
    for name, buf, shape, lens in zip(names, buffers, shapes, lods):
        dt = np.dtype(_feed_dtype(p, name))
        a = np.frombuffer(buf, dtype=dt).reshape([int(s) for s in shape])
        if lens:
            lens = [int(v) for v in lens]
            if min(lens) < 0:
                raise ValueError(
                    "feed %r: negative sequence length in %r"
                    % (name, lens))
            total = sum(lens)
            if total != a.shape[0]:
                raise ValueError(
                    "feed %r: sequence lengths sum to %d but the flat "
                    "buffer has %d rows" % (name, total, a.shape[0]))
            # zero-copy: the buffer is already the flat row stream
            feed[name] = create_lod_tensor(a, [lens])
        else:
            feed[name] = a
    # scope passed explicitly — scope_guard mutates a process global and
    # would race when a multithreaded C host runs two predictors at once
    outs = p.exe.run(p.program, feed=feed, fetch_list=p.fetches,
                     scope=p.scope)
    p.outputs = [np.ascontiguousarray(np.asarray(o)) for o in outs]
    return len(p.outputs)


def run_legacy(handle, names, buffers, shapes):
    """v1 contract: every buffer is float32 regardless of declared dtype
    (ints were smuggled through floats); returns the float32-cast outputs
    list. Kept so binaries linked against the v1 ptpu_run keep working."""
    p = _predictors[handle]
    p.outputs = []  # a later ptpu_output must not see a prior run2's arrays
    feed = {}
    for name, buf, shape in zip(names, buffers, shapes):
        a = np.frombuffer(buf, dtype=np.float32).reshape(
            [int(s) for s in shape])
        dt = np.dtype(_feed_dtype(p, name))
        feed[name] = a.astype(dt) if dt != np.float32 else a
    outs = p.exe.run(p.program, feed=feed, fetch_list=p.fetches,
                     scope=p.scope)
    # v1 clients never call output_*; don't retain arrays on the handle
    return [np.ascontiguousarray(np.asarray(o, dtype=np.float32))
            for o in outs]


def output_info(handle, i):
    """(dtype_str, shape_list, nbytes) of retained output i."""
    o = _predictors[handle].outputs[i]
    return (str(o.dtype), [int(s) for s in o.shape], int(o.nbytes))


def output_array(handle, i):
    """The retained output array itself (C reads it via buffer protocol)."""
    return _predictors[handle].outputs[i]


def destroy(handle):
    _predictors.pop(handle, None)
