"""Optimizers: graph-building classes appending update ops.

Parity: python/paddle/fluid/optimizer.py — same classes, same accumulator
names, same minimize() contract (append_backward → regularization → clip →
per-param update ops). The update ops lower to fused XLA (ops/optimizer_ops.py)
and their ParamOut writes make the executor's donated-state write-back an
in-place TPU update.
"""
from collections import defaultdict

from .core.framework import (Variable, Parameter, default_main_program,
                             default_startup_program, program_guard)
from .core.layer_helper import LayerHelper
from .core.initializer import ConstantInitializer
from .core.backward import append_backward
from .core import unique_name
from . import regularizer as regularizer_mod

__all__ = [
    "SGD", "Momentum", "Adagrad", "Adam", "Adamax", "DecayedAdagrad",
    "Adadelta", "RMSProp", "Ftrl", "SGDOptimizer", "MomentumOptimizer",
    "AdagradOptimizer", "AdamOptimizer", "AdamaxOptimizer",
    "DecayedAdagradOptimizer", "AdadeltaOptimizer", "RMSPropOptimizer",
    "FtrlOptimizer", "ModelAverage", "Optimizer",
    "ProximalGD", "ProximalAdagrad", "ProximalGDOptimizer",
    "ProximalAdagradOptimizer", "scale_learning_rate",
    "persistable_lr_names",
]


def persistable_lr_names(program):
    """Names of the PERSISTABLE learning-rate variables the program's
    update ops read (in op order, deduped). Empty for scheduler-derived
    rates, which are recomputed in-graph each step — the single source
    of truth for both scale_learning_rate and the resilience
    Supervisor's construction-time lr_scale validation."""
    names = []
    for op in program.global_block().ops:
        for n in op.inputs.get("LearningRate", ()):
            if n and n not in names:
                v = program.global_block().vars.get(n)
                if v is not None and v.persistable:
                    names.append(n)
    return names


def scale_learning_rate(program, scope, factor):
    """Scale every persistable learning-rate variable the program's
    update ops read by `factor`, in the scope (device- or host-side
    value, dtype preserved). The resilience supervisor's rollback
    re-entry damping: after restoring a snapshot it can re-enter the
    divergent region at e.g. 0.5x LR instead of replaying the same blowup.

    Returns the list of scaled var names. Scheduler-computed rates
    (exponential_decay etc.) are re-derived in-graph from their counter
    every step, so there is no persistable to scale — if NO update op
    reads a persistable LR, this raises so the caller knows the damping
    did not take (wrap the scheduler output in a persistable var, or
    rebuild with a float learning_rate, to use lr_scale)."""
    import numpy as np
    scaled = []
    for n in persistable_lr_names(program):
        val = scope.get(n)
        if val is None:
            continue
        arr = np.asarray(val)
        scope.set(n, (arr * factor).astype(arr.dtype))
        scaled.append(n)
    if not scaled:
        raise ValueError(
            "scale_learning_rate: no persistable learning-rate variable "
            "holds a value in the scope — scheduler-derived rates are "
            "recomputed in-graph each step and cannot be damped this "
            "way")
    return scaled


class Optimizer(object):
    def __init__(self, learning_rate, regularization=None, LARS_weight_decay=0.0):
        if not isinstance(learning_rate, (float, Variable)):
            raise TypeError("learning rate should be float or Variable")
        self.regularization = regularization
        self._learning_rate = learning_rate
        self._learning_rate_map = {}
        self._accumulators = defaultdict(dict)
        self.helper = None
        self._LARS_weight_decay = LARS_weight_decay

    def _create_global_learning_rate(self):
        program = default_main_program()
        lr = self._learning_rate_map.get(program)
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[program] = self._learning_rate
            return
        from .layers import tensor
        self._learning_rate_map[program] = tensor.create_global_var(
            name=unique_name.generate("learning_rate"),
            shape=[1], value=float(self._learning_rate),
            dtype="float32", persistable=True)

    def _global_learning_rate(self, program=None):
        if program is None:
            program = default_main_program()
        return self._learning_rate_map.get(program)

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        param_lr = param.optimize_attr.get("learning_rate", 1.0) \
            if param.optimize_attr else 1.0
        base = self._global_learning_rate()
        if param_lr == 1.0:
            return base
        return base * param_lr

    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block):
        pass

    def _add_accumulator(self, name, param, dtype="float32", fill_value=0.0,
                         shape=None):
        # called in the canonical sorted-param order established by
        # _create_optimization_pass (ModelAverage's construction-time
        # sums ride all_parameters' insertion order, which is
        # deterministic per build): the unique_name counter baked into
        # the accumulator's name (and so into the program bytes, the
        # compile-cache key and the ShardingPlan walk) must not depend
        # on a caller-assembled order
        if param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        if shape is None:
            shape = param.shape
        helper = LayerHelper(name)
        # persistable=True is load-bearing twice over: the executor's
        # donated state write-back keeps the accumulator device-resident
        # across steps, and checkpoint.CheckpointManager snapshots exactly
        # the persistable set — a non-persistable moment would silently
        # reset at every resume
        var = helper.create_global_variable(
            name=unique_name.generate(name + "_" + param.name),
            persistable=True, dtype=dtype, shape=shape)
        helper.set_variable_initializer(
            var, initializer=ConstantInitializer(value=float(fill_value)))
        self._accumulators[name][param.name] = var
        var.block.program._accumulator_owner[var.name] = param.name
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _create_optimization_pass(self, parameters_and_grads, loss,
                                  startup_program=None):
        program = loss.block.program
        # Canonical order contract (ARCHITECTURE.md §21): accumulators
        # are created — and update ops appended — in sorted-param-name
        # order, never whatever order the caller assembled. Accumulator
        # names carry unique_name counters, so the iteration order here
        # IS part of the serialized program bytes: a hash-seed- or
        # caller-order-dependent walk would re-key the persistent
        # compile cache and shuffle the ShardingPlan's shard walk on
        # every process restart. append_backward already returns pairs
        # sorted; re-sort + assert here so a hand-built list gets the
        # same guarantee.
        parameters_and_grads = sorted(parameters_and_grads,
                                      key=lambda pg: pg[0].name)
        names = [p.name for p, _ in parameters_and_grads]
        assert len(set(names)) == len(names), \
            "duplicate params break the canonical update order: %r" % names
        with program_guard(program, startup_program or
                           default_startup_program()):
            self.helper = LayerHelper(self.__class__.__name__)
            self._create_accumulators(
                loss.block, [p[0] for p in parameters_and_grads])
            self._create_global_learning_rate()

            optimize_ops = []
            for param_and_grad in parameters_and_grads:
                if param_and_grad[1] is None:
                    continue
                if param_and_grad[0].trainable:
                    op = self._append_optimize_op(loss.block, param_and_grad)
                    optimize_ops.append(op)
            self._finish_update(loss.block)
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = append_backward(loss, parameter_list, no_grad_set)
        from .clip import append_gradient_clip_ops
        with program_guard(loss.block.program, startup_program or
                           default_startup_program()):
            params_grads = append_gradient_clip_ops(params_grads)
            params_grads = regularizer_mod.append_regularization_ops(
                params_grads, self.regularization)
        optimize_ops = self._create_optimization_pass(
            params_grads, loss, startup_program)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    """Parity: sgd_op.cc."""

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            type="sgd",
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]]},
            infer_shape=False)


class MomentumOptimizer(Optimizer):
    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super(MomentumOptimizer, self).__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._use_nesterov = bool(use_nesterov)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity_acc = self._get_accumulator(
            self._velocity_acc_str, param_and_grad[0])
        return block.append_op(
            type="momentum",
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "Velocity": [velocity_acc],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "VelocityOut": [velocity_acc]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
            infer_shape=False)


class AdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, epsilon=1e-6, **kwargs):
        super(AdagradOptimizer, self).__init__(learning_rate, **kwargs)
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment_acc = self._get_accumulator(self._moment_acc_str,
                                           param_and_grad[0])
        return block.append_op(
            type="adagrad",
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "Moment": [moment_acc],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "MomentOut": [moment_acc]},
            attrs={"epsilon": self._epsilon},
            infer_shape=False)


class AdamOptimizer(Optimizer):
    _moment1_acc_str = "moment1"
    _moment2_acc_str = "moment2"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super(AdamOptimizer, self).__init__(learning_rate, **kwargs)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment1_acc_str, p)
            self._add_accumulator(self._moment2_acc_str, p)
        self._beta1_pow_acc = self._add_global_accumulator(
            "beta1_pow_acc", self._beta1)
        self._beta2_pow_acc = self._add_global_accumulator(
            "beta2_pow_acc", self._beta2)

    def _add_global_accumulator(self, name, fill_value):
        helper = LayerHelper(name)
        var = helper.create_or_get_global_variable(
            name=unique_name.generate(name), persistable=True,
            dtype="float32", shape=[1])
        helper.set_variable_initializer(
            var, initializer=ConstantInitializer(value=float(fill_value)))
        # optimizer-global state (beta pows): owner "" marks it in
        # program._accumulator_owner so the checkpoint manifest tags it as
        # optimizer state and the sharded-weight-update path never
        # pattern-matches it to some unlucky param
        var.block.program._accumulator_owner.setdefault(var.name, "")
        return var

    def _append_optimize_op(self, block, param_and_grad):
        moment1 = self._get_accumulator(self._moment1_acc_str,
                                        param_and_grad[0])
        moment2 = self._get_accumulator(self._moment2_acc_str,
                                        param_and_grad[0])
        return block.append_op(
            type="adam",
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "LearningRate": [self._create_param_lr(param_and_grad)],
                    "Moment1": [moment1], "Moment2": [moment2],
                    "Beta1Pow": [self._beta1_pow_acc],
                    "Beta2Pow": [self._beta2_pow_acc]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "Moment1Out": [moment1], "Moment2Out": [moment2]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon},
            infer_shape=False)

    def _finish_update(self, block):
        block.append_op(
            type="adam_beta_pow_update",
            inputs={"Beta1Pow": [self._beta1_pow_acc],
                    "Beta2Pow": [self._beta2_pow_acc]},
            outputs={"Beta1PowOut": [self._beta1_pow_acc],
                     "Beta2PowOut": [self._beta2_pow_acc]},
            attrs={"beta1": self._beta1, "beta2": self._beta2},
            infer_shape=False)


class AdamaxOptimizer(AdamOptimizer):
    _moment_acc_str = "moment"
    _inf_norm_acc_str = "inf_norm"

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._inf_norm_acc_str, p)
        self._beta1_pow_acc = self._add_global_accumulator(
            "beta1_pow_acc", self._beta1)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        inf_norm = self._get_accumulator(self._inf_norm_acc_str,
                                         param_and_grad[0])
        return block.append_op(
            type="adamax",
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "LearningRate": [self._create_param_lr(param_and_grad)],
                    "Moment": [moment], "InfNorm": [inf_norm],
                    "Beta1Pow": [self._beta1_pow_acc]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "MomentOut": [moment], "InfNormOut": [inf_norm]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon},
            infer_shape=False)

    def _finish_update(self, block):
        block.append_op(
            type="scale",
            inputs={"X": [self._beta1_pow_acc]},
            outputs={"Out": [self._beta1_pow_acc]},
            attrs={"scale": self._beta1},
            infer_shape=False)


class DecayedAdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super(DecayedAdagradOptimizer, self).__init__(learning_rate, **kwargs)
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        return block.append_op(
            type="decayed_adagrad",
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "Moment": [moment],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [moment]},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
            infer_shape=False)


class AdadeltaOptimizer(Optimizer):
    _avg_squared_grad_acc_str = "_avg_squared_grad"
    _avg_squared_update_acc_str = "_avg_squared_update"

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super(AdadeltaOptimizer, self).__init__(learning_rate, **kwargs)
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._avg_squared_grad_acc_str, p)
            self._add_accumulator(self._avg_squared_update_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        g = self._get_accumulator(self._avg_squared_grad_acc_str,
                                  param_and_grad[0])
        u = self._get_accumulator(self._avg_squared_update_acc_str,
                                  param_and_grad[0])
        return block.append_op(
            type="adadelta",
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "AvgSquaredGrad": [g], "AvgSquaredUpdate": [u]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "AvgSquaredGradOut": [g], "AvgSquaredUpdateOut": [u]},
            attrs={"epsilon": self._epsilon, "rho": self._rho},
            infer_shape=False)


class RMSPropOptimizer(Optimizer):
    _momentum_acc_str = "momentum"
    _mean_square_acc_str = "mean_square"

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 **kwargs):
        super(RMSPropOptimizer, self).__init__(learning_rate, **kwargs)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._momentum_acc_str, p)
            self._add_accumulator(self._mean_square_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        momentum_acc = self._get_accumulator(self._momentum_acc_str,
                                             param_and_grad[0])
        mean_square_acc = self._get_accumulator(self._mean_square_acc_str,
                                                param_and_grad[0])
        return block.append_op(
            type="rmsprop",
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "Moment": [momentum_acc],
                    "MeanSquare": [mean_square_acc],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "MomentOut": [momentum_acc],
                     "MeanSquareOut": [mean_square_acc]},
            attrs={"epsilon": self._epsilon, "decay": self._rho,
                   "momentum": self._momentum},
            infer_shape=False)


class FtrlOptimizer(Optimizer):
    _squared_acc_str = "squared"
    _linear_acc_str = "linear"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super(FtrlOptimizer, self).__init__(learning_rate, **kwargs)
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._squared_acc_str, p)
            self._add_accumulator(self._linear_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        sq = self._get_accumulator(self._squared_acc_str, param_and_grad[0])
        lin = self._get_accumulator(self._linear_acc_str, param_and_grad[0])
        return block.append_op(
            type="ftrl",
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "SquaredAccumulator": [sq],
                    "LinearAccumulator": [lin],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "SquaredAccumOut": [sq], "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
            infer_shape=False)


class ProximalGDOptimizer(Optimizer):
    """Parity: proximal_gd_op.cc (FOBOS; the reference registers the op
    without an era Python class): prox = param - lr * grad;
    param = sign(prox) / (1 + lr*l2) * max(|prox| - lr*l1, 0)."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kwargs):
        super(ProximalGDOptimizer, self).__init__(learning_rate, **kwargs)
        self._l1 = l1
        self._l2 = l2

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            type="proximal_gd",
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]]},
            attrs={"l1": self._l1, "l2": self._l2},
            infer_shape=False)


class ProximalAdagradOptimizer(Optimizer):
    """Parity: proximal_adagrad_op.cc — adagrad-scaled proximal step."""

    _moment_acc_str = "moment"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kwargs):
        super(ProximalAdagradOptimizer, self).__init__(learning_rate,
                                                       **kwargs)
        self._l1 = l1
        self._l2 = l2

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str,
                                       param_and_grad[0])
        return block.append_op(
            type="proximal_adagrad",
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "Moment": [moment],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "MomentOut": [moment]},
            attrs={"l1": self._l1, "l2": self._l2},
            infer_shape=False)


class ModelAverage(Optimizer):
    """Parity: fluid.optimizer.ModelAverage (average_accumulates_op).

    Maintains running parameter sums; `apply()` swaps averaged params in,
    `restore()` swaps them back.
    """

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, **kwargs):
        super(ModelAverage, self).__init__(0.0, **kwargs)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self.params_grads = []
        self._sums = {}
        self._num_updates = {}
        program = default_main_program()
        for param in program.global_block().all_parameters():
            if param.do_model_average is False:
                continue
            s = self._add_accumulator("sum_1", param)
            self._sums[param.name] = s
            program.current_block().append_op(
                type="elementwise_add",
                inputs={"X": [s], "Y": [param]},
                outputs={"Out": [s]},
                attrs={"axis": -1},
                infer_shape=False)
        self._counter = self._add_counter()

    def _add_counter(self):
        helper = LayerHelper("ma_counter")
        var = helper.create_or_get_global_variable(
            name=unique_name.generate("ma_counter"), persistable=True,
            dtype="float32", shape=[1])
        helper.set_variable_initializer(var, ConstantInitializer(0.0))
        var.block.program._accumulator_owner.setdefault(var.name, "")
        default_main_program().current_block().append_op(
            type="increment", inputs={"X": [var]}, outputs={"Out": [var]},
            attrs={"step": 1.0}, infer_shape=False)
        return var

    def apply(self, executor, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            from .core.executor import global_scope
            import numpy as np
            scope = global_scope()
            backup = {}
            counter = float(np.asarray(scope.get(self._counter.name))[0])
            counter = max(counter, 1.0)
            for pname, svar in self._sums.items():
                backup[pname] = scope.get(pname)
                s = np.asarray(scope.get(svar.name))
                scope.set(pname, (s / counter).astype(s.dtype))
            yield
            if need_restore:
                for pname, val in backup.items():
                    scope.set(pname, val)
        return _ctx()

    def restore(self, executor):
        pass


# short aliases (parity: fluid exposes both)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
ProximalGD = ProximalGDOptimizer
ProximalAdagrad = ProximalAdagradOptimizer
