"""The long-tail operator library: ops the reference registers as C++
CPU+CUDA kernel pairs but that never made the era's Python ``__all__``.

Parity: paddle/fluid/operators/{prelu_op,pad_op,crop_op,roi_pool_op,
sequence_slice_op,sequence_concat_op,pool_with_index_op,unpool_op,spp_op,
norm_op,l1_norm_op,squared_l2_norm_op,squared_l2_distance_op,
modified_huber_loss_op,conv_shift_op,bilinear_tensor_product_op,
precision_recall_op,positive_negative_pair_op,proximal_gd_op,
proximal_adagrad_op}.{cc,cu,h}.

TPU-native design notes: every op is a single pure-JAX function with static
output shapes (the reference's per-element loops become masked/vectorized
XLA computations), so backward comes free via jax.vjp and XLA fuses the
masks into neighbouring ops. Data-dependent *regions* (roi_pool bins,
sequence_slice windows) are expressed as value-dependent masks/gathers over
statically-shaped tensors — never as dynamic shapes, which would break MXU
tiling and the jit cache.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..core.registry import register, single


def _out(x):
    return {"Out": [x]}


# ---------------------------------------------------------------------------
# elementwise / loss tail
# ---------------------------------------------------------------------------

@register("prelu")
def _prelu(ctx, ins, attrs):
    """prelu_op.cc: f(x) = x if x >= 0 else alpha * x, scalar alpha."""
    x = single(ins, "X")
    alpha = single(ins, "Alpha").reshape(())
    return _out(jnp.where(x >= 0, x, alpha * x))


@register("pad")
def _pad(ctx, ins, attrs):
    """pad_op.cc: constant-pad; paddings = [lo0, hi0, lo1, hi1, ...]."""
    x = single(ins, "X")
    p = attrs["paddings"]
    widths = [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(x.ndim)]
    return _out(jnp.pad(x, widths, mode="constant",
                        constant_values=attrs.get("pad_value", 0.0)))


@register("crop")
def _crop(ctx, ins, attrs):
    """crop_op.cc: static-offset slice of `shape` starting at `offsets`.
    A -1 dim takes the full remaining extent (offset..end) — needed for
    cropping feature dims of dynamic-batch tensors."""
    x = single(ins, "X")
    offsets = [int(o) for o in attrs["offsets"]]
    shape = [int(s) for s in attrs["shape"]]
    limits = [x.shape[d] if s == -1 else o + s
              for d, (o, s) in enumerate(zip(offsets, shape))]
    return _out(jax.lax.slice(x, offsets, limits))


@register("modified_huber_loss")
def _modified_huber_loss(ctx, ins, attrs):
    """modified_huber_loss_op.h: inter = x*(2y-1);
    loss = -4*inter if inter < -1; (1-inter)^2 if inter < 1; else 0."""
    x = single(ins, "X").reshape(-1)
    y = single(ins, "Y").reshape(-1)
    inter = x * (2.0 * y - 1.0)
    loss = jnp.where(inter < -1.0, -4.0 * inter,
                     jnp.where(inter < 1.0, jnp.square(1.0 - inter), 0.0))
    n = single(ins, "X").shape[0]
    return {"IntermediateVal": [inter.reshape(n, -1)],
            "Out": [loss.reshape(n, 1)]}


@register("squared_l2_distance")
def _squared_l2_distance(ctx, ins, attrs):
    """squared_l2_distance_op.h: row-wise ||x - y||^2 (y row-broadcast)."""
    x = single(ins, "X")
    y = single(ins, "Y")
    x2 = x.reshape(x.shape[0], -1)
    y2 = y.reshape(y.shape[0], -1)
    sub = x2 - y2  # broadcasts when y has one row
    return {"sub_result": [sub],
            "Out": [jnp.sum(jnp.square(sub), axis=1, keepdims=True)]}


@register("l1_norm")
def _l1_norm(ctx, ins, attrs):
    """l1_norm_op.h: Out = sum |x| (scalar, shape [1])."""
    return _out(jnp.sum(jnp.abs(single(ins, "X"))).reshape(1))


@register("squared_l2_norm")
def _squared_l2_norm(ctx, ins, attrs):
    """squared_l2_norm_op.h: Out = sum x^2 (scalar, shape [1])."""
    return _out(jnp.sum(jnp.square(single(ins, "X"))).reshape(1))


@register("norm")
def _norm(ctx, ins, attrs):
    """norm_op.h (the SSD cross-channel L2Norm): per spatial position,
    out[n,c,h,w] = x[n,c,h,w] / sqrt(sum_c x^2 + eps) * scale[c]."""
    x = single(ins, "X")                      # [N, C, H, W]
    scale = single(ins, "Scale").reshape(-1)  # [C]
    eps = attrs.get("epsilon", 1e-10)
    denom = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True) + eps)
    return _out(x / denom * scale.reshape(1, -1, 1, 1))


@register("conv_shift")
def _conv_shift(ctx, ins, attrs):
    """conv_shift_op.cc: NTM circular convolution.
    out[b,i] = sum_j x[b, (i + j - (N-1)/2) mod M] * y[b, j]."""
    x = single(ins, "X")  # [B, M]
    y = single(ins, "Y")  # [B, N], N odd
    m, n = x.shape[1], y.shape[1]
    half = (n - 1) // 2
    # index matrix [M, N]: gathered x columns per (i, j)
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    idx = (i + j - half) % m
    return _out(jnp.einsum("bmn,bn->bm", x[:, idx], y))


@register("bilinear_tensor_product")
def _bilinear_tensor_product(ctx, ins, attrs):
    """bilinear_tensor_product_op.h: out[b,i] = x[b]^T W_i y[b] (+ bias)."""
    x = single(ins, "X")       # [B, Dx]
    y = single(ins, "Y")       # [B, Dy]
    w = single(ins, "Weight")  # [size, Dx, Dy]
    out = jnp.einsum("bj,ijk,bk->bi", x, w, y)
    bias = single(ins, "Bias")
    if bias is not None:
        out = out + bias.reshape(1, -1)
    return _out(out)


# ---------------------------------------------------------------------------
# pooling tail
# ---------------------------------------------------------------------------

def _pool_windows(x, ksize, strides, paddings):
    """Gather explicit pooling windows: x [N,C,H,W] ->
    (vals [N,C,Ho,Wo,kh,kw], hidx [Ho,kh], widx [Wo,kw], valid masks).
    Out-of-bounds taps are masked, not materialized (no host padding)."""
    kh, kw = ksize
    sh, sw = strides
    ph, pw = paddings
    h, w = x.shape[2], x.shape[3]
    ho = (h - kh + 2 * ph) // sh + 1
    wo = (w - kw + 2 * pw) // sw + 1
    hidx = (jnp.arange(ho) * sh - ph)[:, None] + jnp.arange(kh)[None, :]
    widx = (jnp.arange(wo) * sw - pw)[:, None] + jnp.arange(kw)[None, :]
    hvalid = (hidx >= 0) & (hidx < h)
    wvalid = (widx >= 0) & (widx < w)
    rows = jnp.take(x, jnp.clip(hidx, 0, h - 1).reshape(-1), axis=2)
    rows = rows.reshape(x.shape[:2] + (ho, kh, w))
    vals = jnp.take(rows, jnp.clip(widx, 0, w - 1).reshape(-1), axis=4)
    vals = vals.reshape(x.shape[:2] + (ho, kh, wo, kw))
    vals = jnp.moveaxis(vals, 3, 4)  # [N,C,Ho,Wo,kh,kw]
    return vals, hidx, widx, hvalid, wvalid, ho, wo


@register("max_pool2d_with_index")
def _max_pool2d_with_index(ctx, ins, attrs):
    """pool_with_index_op.cc: max pool + per-window argmax Mask holding the
    in-plane flat index (h * W + w) of each max."""
    x = single(ins, "X")
    ksize = [int(k) for k in attrs["ksize"]]
    if attrs.get("global_pooling", False):
        ksize = [x.shape[2], x.shape[3]]
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    paddings = [int(p) for p in attrs.get("paddings", [0, 0])]
    vals, hidx, widx, hvalid, wvalid, ho, wo = _pool_windows(
        x, ksize, strides, paddings)
    neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
    valid = hvalid[:, None, :, None] & wvalid[None, :, None, :]  # Ho,Wo,kh,kw
    masked = jnp.where(valid[None, None], vals, neg)
    flat = masked.reshape(masked.shape[:4] + (-1,))
    amax = jnp.argmax(flat, axis=-1)                     # [N,C,Ho,Wo]
    out = jnp.max(flat, axis=-1)
    # window-local argmax -> in-plane flat index
    kh, kw = ksize
    local_h = amax // kw
    local_w = amax % kw
    gh = jnp.take_along_axis(  # [Ho,kh] rows indexed per output position
        hidx[None, None, :, None, :].astype(jnp.int32),
        local_h[..., None].astype(jnp.int32), axis=-1).squeeze(-1)
    gw = jnp.take_along_axis(
        widx[None, None, None, :, :].astype(jnp.int32),
        local_w[..., None].astype(jnp.int32), axis=-1).squeeze(-1)
    mask = (gh * x.shape[3] + gw).astype(jnp.int32)
    return {"Out": [out], "Mask": [mask]}


@register("unpool")
def _unpool(ctx, ins, attrs):
    """unpool_op.h: scatter x back to the in-plane positions recorded by
    max_pool2d_with_index; everything else zero."""
    x = single(ins, "X")              # [N, C, h, w]
    indices = single(ins, "Indices")  # [N, C, h, w] in-plane flat indices
    ksize = [int(k) for k in attrs["ksize"]]
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    paddings = [int(p) for p in attrs.get("paddings", [0, 0])]
    n, c, h, w = x.shape
    ho = (h - 1) * strides[0] - 2 * paddings[0] + ksize[0]
    wo = (w - 1) * strides[1] - 2 * paddings[1] + ksize[1]
    flat = jnp.zeros((n * c, ho * wo), x.dtype)
    rows = jnp.arange(n * c)[:, None]
    out = flat.at[rows, indices.reshape(n * c, -1)].set(
        x.reshape(n * c, -1), mode="drop")
    return _out(out.reshape(n, c, ho, wo))


@register("spp")
def _spp(ctx, ins, attrs):
    """spp_op.h: spatial pyramid pooling — per level p, pool to 2^p x 2^p
    bins (kernel=ceil(dim/bins), stride=kernel, symmetric pad), flatten,
    concat -> [N, C * (4^height - 1) / 3]."""
    x = single(ins, "X")
    height = int(attrs["pyramid_height"])
    ptype = attrs.get("pooling_type", "max")
    n, c, h, w = x.shape
    pieces = []
    for p in range(height):
        bins = 2 ** p
        kh = -(-h // bins)
        kw = -(-w // bins)
        ph = (kh * bins - h + 1) // 2
        pw = (kw * bins - w + 1) // 2
        vals, _, _, hvalid, wvalid, ho, wo = _pool_windows(
            x, [kh, kw], [kh, kw], [ph, pw])
        valid = hvalid[:, None, :, None] & wvalid[None, :, None, :]
        if ptype == "max":
            neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
            lvl = jnp.max(jnp.where(valid[None, None], vals, neg),
                          axis=(-2, -1))
        else:
            # reference AvgPool (math/pooling.cc) divides by the CLIPPED
            # window — only in-bounds taps count, padding excluded
            cnt = jnp.maximum(
                jnp.sum(valid, axis=(-2, -1)).astype(x.dtype), 1.0)
            lvl = jnp.sum(jnp.where(valid[None, None], vals, 0.0),
                          axis=(-2, -1)) / cnt[None, None]
        pieces.append(lvl.reshape(n, -1))
    return _out(jnp.concatenate(pieces, axis=1))


@register("roi_pool")
def _roi_pool(ctx, ins, attrs):
    """roi_pool_op.h: Fast-RCNN ROI max pooling. ROIs [R, 5] rows are
    (batch_id, x1, y1, x2, y2) in input scale; each ROI is divided into
    pooled_h x pooled_w bins, empty bins produce 0 with Argmax -1.

    TPU-native: bin membership is a value-dependent mask over the static
    [H, W] plane (the reference's per-bin scalar loops), so shapes stay
    static and backward is jax.vjp of a masked max."""
    x = single(ins, "X")        # [N, C, H, W]
    rois = single(ins, "ROIs")  # [R, 5]
    phh = int(attrs["pooled_height"])
    pww = int(attrs["pooled_width"])
    scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = x.shape
    r = rois.shape[0]
    rf = rois.astype(jnp.float32)
    batch_id = rois[:, 0].astype(jnp.int32)
    x1 = jnp.round(rf[:, 1] * scale).astype(jnp.int32)
    y1 = jnp.round(rf[:, 2] * scale).astype(jnp.int32)
    x2 = jnp.round(rf[:, 3] * scale).astype(jnp.int32)
    y2 = jnp.round(rf[:, 4] * scale).astype(jnp.int32)
    roi_h = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
    roi_w = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)
    bin_h = roi_h / phh  # [R]
    bin_w = roi_w / pww

    def bounds(start, bin_sz, pooled, limit):
        ip = jnp.arange(pooled, dtype=jnp.float32)
        lo = jnp.floor(ip[None, :] * bin_sz[:, None]).astype(jnp.int32)
        hi = jnp.ceil((ip[None, :] + 1) * bin_sz[:, None]).astype(jnp.int32)
        lo = jnp.clip(lo + start[:, None], 0, limit)
        hi = jnp.clip(hi + start[:, None], 0, limit)
        return lo, hi  # [R, pooled]

    hlo, hhi = bounds(y1, bin_h, phh, h)
    wlo, whi = bounds(x1, bin_w, pww, w)
    hs = jnp.arange(h)
    ws = jnp.arange(w)
    hmask = (hs[None, None, :] >= hlo[:, :, None]) & \
            (hs[None, None, :] < hhi[:, :, None])      # [R, PH, H]
    wmask = (ws[None, None, :] >= wlo[:, :, None]) & \
            (ws[None, None, :] < whi[:, :, None])      # [R, PW, W]
    feat = x[jnp.clip(batch_id, 0, n - 1)]             # [R, C, H, W]
    neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
    # separable two-stage masked max: reduce rows under hmask, then columns
    # under wmask — peak memory O(R*C*PH*H*W), never the PH*PW x H*W cross
    # product a joint-mask formulation would materialize
    vals_h = jnp.where(hmask[:, None, :, :, None],
                       feat[:, :, None, :, :], neg)    # [R, C, PH, H, W]
    rowmax = jnp.max(vals_h, axis=3)                   # [R, C, PH, W]
    vals_w = jnp.where(wmask[:, None, None, :, :],
                       rowmax[:, :, :, None, :], neg)  # [R, C, PH, PW, W]
    rawmax = jnp.max(vals_w, axis=-1)                  # [R, C, PH, PW]
    empty = ~(jnp.any(hmask, 2)[:, :, None] &
              jnp.any(wmask, 2)[:, None, :])           # [R, PH, PW]
    out = jnp.where(empty[:, None], 0.0, rawmax)
    # Argmax must match the reference's ROW-MAJOR first-max scan even when
    # the bin max is duplicated: per pooled column, take the SMALLEST
    # in-plane index h*W+w whose value equals the bin max. One [R,C,PH,H,W]
    # mask per pw (python loop over the small static PW) — never the joint
    # PH*PW x H*W product.
    flatpos = (hs[:, None] * w + ws[None, :]).astype(jnp.int32)  # [H, W]
    args = []
    for pw in range(pww):
        eq = (vals_h == rawmax[:, :, :, pw, None, None]) & \
            hmask[:, None, :, :, None] & \
            wmask[:, pw][:, None, None, None, :]
        pos = jnp.where(eq, flatpos[None, None, None], h * w)
        args.append(jnp.min(pos, axis=(3, 4)))         # [R, C, PH]
    argmax = jnp.stack(args, axis=-1)                  # [R, C, PH, PW]
    argmax = jnp.where(empty[:, None], -1, argmax).astype(
        jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)
    return {"Out": [out.astype(x.dtype)], "Argmax": [argmax]}


# ---------------------------------------------------------------------------
# sequence tail (padded-dense layout: X [B, T, ...] + XLen [B])
# ---------------------------------------------------------------------------

@register("sequence_slice")
def _sequence_slice(ctx, ins, attrs):
    """sequence_slice_op.cc: per-sequence crop [offset, offset+length) in
    the padded layout — a per-row dynamic gather with masking; output keeps
    the static T and carries new lengths in OutLen."""
    x = single(ins, "X")            # [B, T, ...]
    offset = single(ins, "Offset").reshape(-1).astype(jnp.int32)  # [B]
    length = single(ins, "Length").reshape(-1).astype(jnp.int32)  # [B]
    t = x.shape[1]
    pos = jnp.arange(t, dtype=jnp.int32)[None, :]        # [1, T]
    src = jnp.clip(pos + offset[:, None], 0, t - 1)      # [B, T]
    gathered = jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)
    keep = (pos < length[:, None]).reshape(
        x.shape[:2] + (1,) * (x.ndim - 2))
    return {"Out": [jnp.where(keep, gathered, 0)],
            "OutLen": [length]}


@register("sequence_concat")
def _sequence_concat(ctx, ins, attrs):
    """sequence_concat_op.cc: axis=0 concatenates along time per sequence
    (out seq b = x0[b][:len0] ++ x1[b][:len1] ++ ...); other axes are a
    plain feature concat. Gather formulation: for each output step t, find
    the source input via the per-row cumulative-length table."""
    xs = ins["X"]                   # list of [B, Ti, F]
    lens = ins["XLen"]              # list of [B]
    axis = attrs.get("axis", 0)
    if axis != 0:
        return {"Out": [jnp.concatenate(xs, axis=axis)],
                "OutLen": [lens[0].astype(jnp.int32)]}
    b = xs[0].shape[0]
    tmax = max(x.shape[1] for x in xs)
    feat = xs[0].shape[2:]
    stack = jnp.stack(
        [jnp.pad(x, [(0, 0), (0, tmax - x.shape[1])] +
                 [(0, 0)] * (x.ndim - 2)) for x in xs], 0)  # [N,B,Tmax,F]
    ln = jnp.stack([l.reshape(-1).astype(jnp.int32) for l in lens], 0)  # [N,B]
    cum = jnp.concatenate(
        [jnp.zeros((1, b), jnp.int32), jnp.cumsum(ln, axis=0)], 0)  # [N+1,B]
    ttot = sum(x.shape[1] for x in xs)
    t = jnp.arange(ttot, dtype=jnp.int32)                    # [Ttot]
    # seg[b_, t] = index of the input owning output step t for row b_
    seg = (t[None, :, None] >= cum.T[:, None, 1:]).sum(-1)   # [B, Ttot]
    seg = jnp.clip(seg, 0, len(xs) - 1)
    start = jnp.take_along_axis(cum.T, seg, axis=1)          # [B, Ttot]
    local = jnp.clip(t[None, :] - start, 0, tmax - 1)
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]
    flat_idx = (seg * b + rows) * tmax + local               # [B, Ttot]
    flat = stack.reshape((len(xs) * b * tmax,) + feat)
    out = jnp.take(flat, flat_idx.reshape(-1), axis=0).reshape(
        (b, ttot) + feat)
    total = cum[-1]                                          # [B]
    keep = (t[None, :] < total[:, None]).reshape(
        (b, ttot) + (1,) * len(feat))
    return {"Out": [jnp.where(keep, out, 0)],
            "OutLen": [total]}


# ---------------------------------------------------------------------------
# ranking / multiclass metrics tail
# ---------------------------------------------------------------------------

@register("precision_recall")
def _precision_recall(ctx, ins, attrs):
    """precision_recall_op.h: multiclass TP/FP/TN/FN statistics + macro and
    micro precision/recall/F1. Metrics layout (6): [macro-P, macro-R,
    macro-F1, micro-P, micro-R, micro-F1]. Empty-denominator convention
    follows the reference: precision/recall default to 1, F1 to 0."""
    idx = single(ins, "Indices").reshape(-1).astype(jnp.int32)
    label = single(ins, "Labels").reshape(-1).astype(jnp.int32)
    weights = single(ins, "Weights")
    states = single(ins, "StatesInfo")
    cls = int(attrs["class_number"])
    w = (weights.reshape(-1).astype(jnp.float32)
         if weights is not None else jnp.ones(idx.shape[0], jnp.float32))
    oh_pred = jax.nn.one_hot(idx, cls, dtype=jnp.float32)
    oh_label = jax.nn.one_hot(label, cls, dtype=jnp.float32)
    correct = (idx == label).astype(jnp.float32)
    tp = jnp.sum(w[:, None] * oh_pred * oh_label, 0)
    fp = jnp.sum(w[:, None] * oh_pred * (1 - oh_label), 0)
    fn = jnp.sum(w[:, None] * (1 - oh_pred) * oh_label, 0)
    # TN[c] += w except for pred (always) and label (when wrong)
    tn = jnp.sum(w) - jnp.sum(w[:, None] * oh_pred, 0) \
        - jnp.sum((w * (1 - correct))[:, None] * oh_label, 0)
    batch = jnp.stack([tp, fp, tn, fn], axis=1)  # [C, 4]

    def metrics(st):
        tp_, fp_, fn_ = st[:, 0], st[:, 1], st[:, 3]
        def ratio(a, b):
            return jnp.where(a + b > 0, a / jnp.maximum(a + b, 1e-30), 1.0)
        def f1(p, r):
            return jnp.where(p + r > 0,
                             2 * p * r / jnp.maximum(p + r, 1e-30), 0.0)
        # macro F1 is the F1 OF the macro-averaged P/R (reference
        # ComputeMetrics), not the mean of per-class F1s
        map_ = jnp.mean(ratio(tp_, fp_))
        mar = jnp.mean(ratio(tp_, fn_))
        mip = ratio(jnp.sum(tp_), jnp.sum(fp_))
        mir = ratio(jnp.sum(tp_), jnp.sum(fn_))
        return jnp.stack([map_, mar, f1(map_, mar), mip, mir, f1(mip, mir)])

    accum = batch + (states if states is not None else 0.0)
    return {"BatchMetrics": [metrics(batch)],
            "AccumMetrics": [metrics(accum)],
            "AccumStatesInfo": [accum]}


@register("positive_negative_pair")
def _positive_negative_pair(ctx, ins, attrs):
    """positive_negative_pair_op.h: LTR pair counting. For every unordered
    same-query pair with different labels, weight (w_i + w_j)/2 is added to
    PositivePair when score and label order agree, else to NegativePair;
    equal scores ALSO add to NeutralPair (faithful to the reference kernel,
    where the neutral branch falls through into the negative one)."""
    score = single(ins, "Score")
    label = single(ins, "Label").reshape(-1)
    qid = single(ins, "QueryID").reshape(-1)
    weight = single(ins, "Weight")
    col = attrs.get("column", -1)
    s = score[:, col].reshape(-1)
    n = s.shape[0]
    w = (weight.reshape(-1) if weight is not None
         else jnp.ones(n, jnp.float32))
    i = jnp.arange(n)
    pair_mask = ((qid[:, None] == qid[None, :]) & (i[:, None] < i[None, :]) &
                 (label[:, None] != label[None, :])).astype(jnp.float32)
    pw = 0.5 * (w[:, None] + w[None, :]) * pair_mask
    ds = s[:, None] - s[None, :]
    dl = label[:, None] - label[None, :]
    pos = jnp.sum(jnp.where(ds * dl > 0, pw, 0.0)).reshape(1)
    neg = jnp.sum(jnp.where(ds * dl <= 0, pw, 0.0)).reshape(1)
    neu = jnp.sum(jnp.where(ds == 0, pw, 0.0)).reshape(1)
    acc_p = single(ins, "AccumulatePositivePair")
    acc_n = single(ins, "AccumulateNegativePair")
    acc_u = single(ins, "AccumulateNeutralPair")
    if acc_p is not None:
        pos = pos + acc_p.reshape(1)
        neg = neg + acc_n.reshape(1)
        neu = neu + acc_u.reshape(1)
    return {"PositivePair": [pos], "NegativePair": [neg],
            "NeutralPair": [neu]}


# ---------------------------------------------------------------------------
# proximal optimizers (proximal_gd_op.cc / proximal_adagrad_op.cc)
# ---------------------------------------------------------------------------

def _proximal_step(lr, l1, l2, prox):
    return (jnp.sign(prox) / (1.0 + lr * l2) *
            jnp.maximum(jnp.abs(prox) - lr * l1, 0.0))


@register("proximal_gd")
def _proximal_gd(ctx, ins, attrs):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    lr = single(ins, "LearningRate").reshape(())
    prox = p - lr * g
    out = _proximal_step(lr, attrs.get("l1", 0.0), attrs.get("l2", 0.0),
                         prox)
    return {"ParamOut": [out.astype(p.dtype)]}


@register("proximal_adagrad")
def _proximal_adagrad(ctx, ins, attrs):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    m = single(ins, "Moment")
    lr = single(ins, "LearningRate").reshape(())
    gf = g.astype(jnp.float32)
    m_out = m + jnp.square(gf)
    prox = p - lr * gf / jnp.sqrt(m_out)
    out = _proximal_step(lr, attrs.get("l1", 0.0), attrs.get("l2", 0.0),
                         prox)
    return {"ParamOut": [out.astype(p.dtype)], "MomentOut": [m_out]}
