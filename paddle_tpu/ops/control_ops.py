"""Control-flow op lowerings (While, conditional_block, tensor arrays).

Parity: paddle/fluid/operators/{while_op,conditional_block_op,
array_operator,tensor_array_read_write}.cc. Filled out with the
control-flow milestone.
"""
