"""Control-flow op lowerings: loops, conditionals, tensor arrays, rank tables.

Parity: paddle/fluid/operators/{while_op,conditional_block_op,array_operator,
tensor_array_read_write_op,lod_rank_table_op,max_sequence_len_op,
shrink_rnn_memory_op,lod_tensor_to_array_op,array_to_lod_tensor_op,
reorder_lod_tensor_by_rank_op,compare_op,increment_op,beam_search_op,
beam_search_decode_op}.{cc,cu,h} and the reference's recurrent_op.cc.

TPU-first design (SURVEY.md §6.4):
- `while` lowers to one `lax.while_loop` whose carry is (iter, cond, written
  outer vars incl. tensor arrays) — the reference re-enters the op-by-op
  interpreter per iteration with fresh step-Scopes.
- `rnn_scan` (the lowering target of Dynamic/StaticRNN) is a single
  `lax.scan` over time with per-row length masking: memories freeze and
  outputs zero once t >= seqlen. This replaces the reference's
  lod_tensor_to_array + shrink_memory + while machinery (sorted shrinking
  batches) with fixed-shape masked compute — what XLA wants. Because it is a
  registered pure rule, `grad_of` differentiates it with jax.vjp and BPTT
  falls out of lax.scan's transpose; the reference needs while_grad_op and
  hand-maintained step-scope stacks.
- LoDTensorArray = fixed-capacity stacked buffer + current length
  (dynamic_update_slice writes). Capacity is static (XLA) — taken from the
  array var's declared capacity, default 256.
- conditional_block evaluates the sub-block and `where`-selects against the
  out vars' previous values (scalar-cond form used by Switch / LR schedules);
  the non-scalar form (IfElse) runs the block unconditionally and lets
  merge_lod_tensor's row mask do the select — compute-both-and-mask instead
  of the reference's split/merge of ragged sub-batches.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core import registry
from ..core.registry import register, single
from ..core import lowering
from ..core.lowering import (register_special, Env, lower_block,
                             PROGRAM_ERR, accumulate_error)

DEFAULT_ARRAY_CAPACITY = 256


# ---------------------------------------------------------------------------
# pytree value types threaded through the env / loop carries
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
class TensorArray(object):
    """LoDTensorArray value: stacked buffer [capacity, ...] + length scalar.

    Parity: paddle/fluid/framework/lod_tensor_array.h (a std::vector of
    LoDTensors on host). Fixed capacity makes it a legal XLA loop carry.
    """

    def __init__(self, buffer, length, overflow=None):
        self.buffer = buffer
        self.length = length
        # sticky error flag: set by any traced write at index >= capacity.
        # It rides the pytree through loop carries and is surfaced as an
        # in-graph error output (lowering.build_program_fn collect_errors);
        # the Executor raises host-side after the step — the TPU-native
        # stand-in for checkify inside lax control flow.
        self.overflow = jnp.zeros((), bool) if overflow is None else overflow

    def tree_flatten(self):
        return (self.buffer, self.length, self.overflow), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def write(self, i, x):
        # Out-of-capacity writes with a concrete index fail at trace time.
        # A traced index (inside lax loops) is checked in-graph via the
        # sticky overflow flag (XLA clamps the store itself) — size
        # create_array(capacity=...) to the loop bound (layers like
        # decoder_decode use max_length + 1).
        cap = self.buffer.shape[0]
        try:
            if int(i) >= cap:
                raise IndexError(
                    "tensor array write at index %d exceeds capacity %d; "
                    "pass a larger capacity to create_array()" % (int(i), cap))
        except (TypeError, jax.errors.TracerIntegerConversionError,
                jax.errors.ConcretizationTypeError):
            pass
        i = jnp.asarray(i, jnp.int32).reshape(())
        buf = lax.dynamic_update_index_in_dim(
            self.buffer, jnp.asarray(x, self.buffer.dtype), i, axis=0)
        over = self.overflow | (i >= cap) | (i < 0)
        return TensorArray(buf, jnp.maximum(self.length, i + 1), over)

    def read(self, i):
        i = jnp.asarray(i, jnp.int32).reshape(())
        return lax.dynamic_index_in_dim(self.buffer, i, axis=0,
                                        keepdims=False)

    @staticmethod
    def empty(shape, dtype, capacity=DEFAULT_ARRAY_CAPACITY):
        return TensorArray(jnp.zeros((capacity,) + tuple(shape), dtype),
                           jnp.zeros((), jnp.int32))


@jax.tree_util.register_pytree_node_class
class RankTable(object):
    """lod_rank_table value: sequence lengths sorted descending + the
    permutation that sorts them (reference: framework/lod_rank_table.h)."""

    def __init__(self, lengths, index):
        self.lengths = lengths  # int32 [num_seqs], descending
        self.index = index      # int32 [num_seqs], original positions

    def tree_flatten(self):
        return (self.lengths, self.index), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


# increment / compare / is_empty lowerings live in ops/basic.py


def _sweep_overflow(benv, incoming):
    """OR of `incoming`, the sub-env's accumulated error, and every
    TensorArray overflow flag visible in the sub-env — how a flag raised on
    an array that never escapes its sub-block still reaches the top level
    (threaded through the enclosing loop's carry)."""
    err = incoming
    sub = benv.read_opt(PROGRAM_ERR)
    if sub is not None:
        err = err | sub
    for v in benv.values.values():
        if isinstance(v, TensorArray):
            err = err | v.overflow
    return err

# ---------------------------------------------------------------------------
# tensor arrays (special: they produce/consume TensorArray env values)
# ---------------------------------------------------------------------------

def _env_array(ctx, op, env, name, like=None):
    """Fetch the TensorArray for `name`, creating an empty one on first
    write (capacity from the array var's attr, element shape from `like`)."""
    arr = env.read_opt(name)
    if arr is not None:
        return arr
    if like is None:
        raise ValueError("tensor array %r read before any write" % name)
    var = lowering._find_var(ctx.program, name)
    cap = getattr(var, "capacity", None) or DEFAULT_ARRAY_CAPACITY
    return TensorArray.empty(np.shape(like), jnp.result_type(like), cap)


@register_special("write_to_array")
def _write_to_array(ctx, op, env):
    x = env.read(op.inputs["X"][0])
    i = env.read(op.inputs["I"][0])
    out = op.outputs["Out"][0]
    arr = _env_array(ctx, op, env, out, like=x)
    env.write(out, arr.write(i, x))


@register_special("read_from_array")
def _read_from_array(ctx, op, env):
    arr = env.read(op.inputs["X"][0])
    i = env.read(op.inputs["I"][0])
    env.write(op.outputs["Out"][0], arr.read(i))


@register_special("lod_array_length")
def _lod_array_length(ctx, op, env):
    arr = env.read(op.inputs["X"][0])
    env.write(op.outputs["Out"][0], arr.length.reshape((1,)))


@register_special("lod_rank_table")
def _lod_rank_table(ctx, op, env):
    xlen = env.read(op.inputs["XLen"][0]).astype(jnp.int32)
    # stable descending sort (matches reference LoDRankTable ordering)
    order = jnp.argsort(-xlen, stable=True).astype(jnp.int32)
    env.write(op.outputs["Out"][0], RankTable(xlen[order], order))


@register_special("max_sequence_len")
def _max_sequence_len(ctx, op, env):
    rt = env.read(op.inputs["RankTable"][0])
    env.write(op.outputs["Out"][0], rt.lengths[0].reshape((1,)))


@register_special("reorder_lod_tensor_by_rank")
def _reorder_by_rank(ctx, op, env):
    x = env.read(op.inputs["X"][0])
    rt = env.read(op.inputs["RankTable"][0])
    env.write(op.outputs["Out"][0], jnp.take(x, rt.index, axis=0))
    if op.inputs.get("XLen") and op.outputs.get("OutLen"):
        xl = env.read(op.inputs["XLen"][0])
        env.write(op.outputs["OutLen"][0], jnp.take(xl, rt.index, axis=0))


@register_special("shrink_rnn_memory")
def _shrink_rnn_memory(ctx, op, env):
    # The reference shrinks the batch to sequences still alive at step I
    # (sorted-by-length layout). The padded-dense design keeps shapes static
    # and masks updates inside rnn_scan instead, so this is identity.
    env.write(op.outputs["Out"][0], env.read(op.inputs["X"][0]))


@register_special("lod_tensor_to_array")
def _lod_tensor_to_array(ctx, op, env):
    # [B, T, ...] padded sequence -> time-major array of [B, ...] steps.
    # With a RankTable input, rows are permuted into rank (descending-length)
    # order first, matching reorder_lod_tensor_by_rank on companion tensors
    # (the reference idiom pairs the two; array_to_lod_tensor undoes it).
    x = env.read(op.inputs["X"][0])
    if op.inputs.get("RankTable"):
        rt = env.read(op.inputs["RankTable"][0])
        x = jnp.take(x, rt.index, axis=0)
    xt = jnp.moveaxis(x, 1, 0)
    env.write(op.outputs["Out"][0],
              TensorArray(xt, jnp.asarray(x.shape[1], jnp.int32)))


@register_special("array_to_lod_tensor")
def _array_to_lod_tensor(ctx, op, env):
    # Output is [B, capacity, ...]: XLA cannot produce a data-dependent time
    # dim, so the written length goes out as a per-row lengths companion
    # (OutLen) and downstream sequence ops mask the zero tail.
    arr = env.read(op.inputs["X"][0])
    out = jnp.moveaxis(arr.buffer, 0, 1)
    if op.inputs.get("RankTable"):
        # undo the rank permutation applied by lod_tensor_to_array
        rt = env.read(op.inputs["RankTable"][0])
        inv = jnp.argsort(rt.index)
        out = jnp.take(out, inv, axis=0)
    env.write(op.outputs["Out"][0], out)
    if op.outputs.get("OutLen"):
        env.write(op.outputs["OutLen"][0],
                  jnp.full((out.shape[0],), arr.length, jnp.int32))


# ---------------------------------------------------------------------------
# while
# ---------------------------------------------------------------------------

@register_special("while")
def _while(ctx, op, env):
    """lax.while_loop over the sub-block.

    carry = (iter_counter, cond, *carry_vars). carry_names (computed at build
    time by layers.control_flow.While.complete) are the vars written inside
    the sub-block that live in an ancestor block. Tensor arrays in the carry
    must be written at least once before the loop so their buffers exist
    (the usual fluid idiom: array_write(init, i=0, array) precedes While).
    """
    sub = ctx.program.blocks[op.attrs["sub_block"]]
    cond_name = op.inputs["Condition"][0]
    carry_names = list(op.attrs["carry_names"])
    missing = [n for n in carry_names if n not in env]
    if missing:
        raise ValueError(
            "While loop carries %r, but they have no value before the loop. "
            "XLA loop carries need an initial value: assign / array_write / "
            "fill_constant each of them before `with while_op.block():`."
            % missing)

    err0 = env.read_opt(PROGRAM_ERR)
    init = (jnp.zeros((), jnp.int32),
            jnp.reshape(env.read(cond_name), ()).astype(bool),
            tuple(env.read(n) for n in carry_names),
            jnp.zeros((), bool) if err0 is None else err0)

    def cond_fn(carry):
        return carry[1]

    def body_fn(carry):
        it, _, vals, err = carry
        benv = Env()
        benv.values = dict(env.values)
        benv.write(PROGRAM_ERR, err)
        for n, v in zip(carry_names, vals):
            benv.write(n, v)
        ctx._loop_iters.append(it)
        try:
            lower_block(ctx, sub, benv)
        finally:
            ctx._loop_iters.pop()
        new_vals = tuple(
            jnp.asarray(benv.read(n), jnp.result_type(v))
            if not isinstance(v, (TensorArray, RankTable)) else benv.read(n)
            for n, v in zip(carry_names, vals))
        return (it + 1,
                jnp.reshape(benv.read(cond_name), ()).astype(bool), new_vals,
                _sweep_overflow(benv, err))

    _, _, final, final_err = lax.while_loop(cond_fn, body_fn, init)
    for n, v in zip(carry_names, final):
        env.write(n, v)
    env.write(cond_name, jnp.zeros((1,), bool))
    accumulate_error(env, final_err)


# ---------------------------------------------------------------------------
# conditional_block (Switch / IfElse)
# ---------------------------------------------------------------------------

@register_special("conditional_block")
def _conditional_block(ctx, op, env):
    sub = ctx.program.blocks[op.attrs["sub_block"]]
    out_names = list(op.attrs["out_names"])

    zero_err = jnp.zeros((), bool)

    def run_block():
        benv = Env()
        benv.values = dict(env.values)
        benv.write(PROGRAM_ERR, zero_err)  # block-local error contribution
        lower_block(ctx, sub, benv)
        return ([benv.read(n) for n in out_names],
                _sweep_overflow(benv, zero_err))

    if not op.attrs.get("is_scalar_condition", True):
        # IfElse form: merge_lod_tensor's row mask does the select; the
        # block itself runs unconditionally on the full batch.
        outs, berr = run_block()
        for n, v in zip(out_names, outs):
            env.write(n, v)
        accumulate_error(env, berr)
        return

    cond = jnp.reshape(env.read(op.inputs["Cond"][0]), ()).astype(bool)
    # Blocks are pure, so compute the block unconditionally and where-select
    # against each out var's previous value (zeros if first write) — Switch
    # cases each overwrite the same out vars, last-where with exclusive
    # conditions reproduces first-match-wins. XLA dedupes the shared work.
    outs, berr = run_block()
    accumulate_error(env, berr & cond)  # untaken branch can't overflow
    for n, o in zip(out_names, outs):
        p = env.read_opt(n)
        if p is None:
            p = jnp.zeros_like(o)
        else:
            p = jnp.broadcast_to(jnp.asarray(p, o.dtype), o.shape)
        env.write(n, jnp.where(cond, o, p))


@register("split_lod_tensor")
def _split_lod_tensor(ctx, ins, attrs):
    # compute-both-and-mask: both branches see the full batch (see module doc)
    x = single(ins, "X")
    return {"OutTrue": [x], "OutFalse": [x]}


@register("merge_lod_tensor")
def _merge_lod_tensor(ctx, ins, attrs):
    x_true = single(ins, "InTrue")
    x_false = single(ins, "InFalse")
    mask = single(ins, "Mask")  # [B, 1] bool/float
    m = jnp.reshape(mask, (-1,) + (1,) * (x_true.ndim - 1)).astype(bool)
    return {"Out": [jnp.where(m, x_true,
                              jnp.asarray(x_false, x_true.dtype))]}


# ---------------------------------------------------------------------------
# rnn_scan — the lowering target of DynamicRNN / StaticRNN
# ---------------------------------------------------------------------------

def _rnn_scan_lower(ctx, ins, attrs):
    sub = ctx.program.blocks[attrs["sub_block"]]
    xs = ins.get("X", [])                 # step inputs [B, T, feat...]
    boots = ins.get("Boot", [])           # memory boot values [B, h]
    statics = ins.get("Static", [])       # closed-over reads
    seqlen = single(ins, "SeqLen")        # [B] int32 or None (StaticRNN)

    in_names = attrs["in_names"]          # placeholders inside sub-block
    static_names = attrs["static_names"]
    pre_names = attrs["pre_names"]        # memory placeholders
    update_names = attrs["update_names"]  # vars holding the new memory value
    out_names = attrs["out_names"]        # per-step outputs to stack

    T = int(attrs["max_len"]) if attrs.get("max_len") else xs[0].shape[1]
    xs_t = [jnp.moveaxis(x, 1, 0) for x in xs]  # [T, B, ...]

    def step(carry, xt):
        t, mems, err = carry
        benv = Env()
        benv.write(PROGRAM_ERR, err)
        for n, v in zip(static_names, statics):
            benv.write(n, v)
        for n, v in zip(pre_names, mems):
            benv.write(n, v)
        for n, v in zip(in_names, xt):
            benv.write(n, v)
        ctx._loop_iters.append(t)
        try:
            lower_block(ctx, sub, benv)
        finally:
            ctx._loop_iters.pop()
        new_mems = [jnp.asarray(benv.read(n), jnp.result_type(m))
                    for n, m in zip(update_names, mems)]
        outs = [benv.read(n) for n in out_names]
        if seqlen is not None:
            alive = t < seqlen.astype(jnp.int32)  # [B]

            def sel(new, old):
                m = alive.reshape((-1,) + (1,) * (new.ndim - 1))
                return jnp.where(m, new, jnp.asarray(old, new.dtype))

            new_mems = [sel(nm, pm) for nm, pm in zip(new_mems, mems)]
            outs = [sel(o, jnp.zeros_like(o)) for o in outs]
        return (t + 1, tuple(new_mems), _sweep_overflow(benv, err)), \
            tuple(outs)

    (_, final_mems, final_err), stacked = lax.scan(
        step, (jnp.zeros((), jnp.int32), tuple(boots),
               jnp.zeros((), bool)), tuple(xs_t),
        length=T)
    outs = [jnp.moveaxis(o, 0, 1) for o in stacked]  # [B, T, ...]
    # "__errors__" is accumulated into the enclosing env by lower_op
    return {"Out": outs, "LastMem": list(final_mems),
            "__errors__": final_err}


def _rnn_scan_infer(block, op, out_vars):
    sub = block.program.blocks[op.attrs["sub_block"]]
    T = op.attrs.get("max_len")
    if not T and op.inputs.get("X"):
        x0 = block.var_recursive(op.inputs["X"][0])
        T = x0.shape[1] if x0.shape is not None else None
    for name, inner in zip(op.outputs.get("Out", ()),
                           op.attrs["out_names"]):
        iv = sub.var_recursive(inner)
        ov = block.var_recursive(name)
        if iv.shape is not None:
            ov.shape = (iv.shape[0], T if T else -1) + tuple(iv.shape[1:])
        ov.dtype = iv.dtype
    for name, inner in zip(op.outputs.get("LastMem", ()),
                           op.attrs["update_names"]):
        iv = sub.var_recursive(inner)
        ov = block.var_recursive(name)
        ov.shape, ov.dtype = iv.shape, iv.dtype


registry.register("rnn_scan", _rnn_scan_lower, infer=_rnn_scan_infer)


# ---------------------------------------------------------------------------
# beam search (dense [batch, beam] layout)
# ---------------------------------------------------------------------------

@register_special("beam_search")
def _beam_search(ctx, op, env):
    """One step of beam search in dense [batch, beam] layout.

    Parity: paddle/fluid/operators/beam_search_op.cc, which grows/prunes
    LoD-encoded candidate lists on the host. Here each batch row always
    keeps exactly `beam_size` beams (finished beams are frozen: their only
    legal expansion is end_id at zero added cost), so shapes stay static
    for XLA and the whole decode loop lives in one lax.while_loop.

    inputs:  pre_ids [B,K] int, pre_scores [B,K] f32 (cumulative log-prob),
             scores [B,K,V] f32 (log-probs of the next token per beam)
    outputs: selected_ids [B,K], selected_scores [B,K],
             parent_idx [B,K] int32 (which source beam each came from)
    """
    pre_ids = env.read(op.inputs["pre_ids"][0])
    pre_scores = env.read(op.inputs["pre_scores"][0])
    scores = env.read(op.inputs["scores"][0])
    beam_size = int(op.attrs["beam_size"])
    end_id = int(op.attrs["end_id"])

    B, K, V = scores.shape
    finished = (pre_ids == end_id)  # [B,K]

    # expansion scores: live beams add token log-prob; finished beams can
    # only "extend" with end_id at zero cost (keeps their total fixed).
    total = pre_scores[:, :, None] + scores            # [B,K,V]
    only_end = jnp.full((K, V), -1e9, scores.dtype).at[:, end_id].set(0.0)
    total = jnp.where(finished[:, :, None],
                      pre_scores[:, :, None] + only_end[None], total)

    flat = total.reshape(B, K * V)
    top_scores, top_idx = lax.top_k(flat, beam_size)   # [B,K]
    parent = (top_idx // V).astype(jnp.int32)
    token = (top_idx % V).astype(pre_ids.dtype)
    env.write(op.outputs["selected_ids"][0], token)
    env.write(op.outputs["selected_scores"][0], top_scores)
    if op.outputs.get("parent_idx"):
        env.write(op.outputs["parent_idx"][0], parent)


@register_special("beam_search_decode")
def _beam_search_decode(ctx, op, env):
    """Backtrack beam-search step arrays into full sequences.

    Parity: paddle/fluid/operators/beam_search_decode_op.cc (host-side LoD
    backtrace). Here: reverse lax.scan over the (ids, parents) TensorArrays.

    inputs:  Ids (TensorArray of [B,K] tokens), ParentIdx (TensorArray of
             [B,K] parent beam indices), Scores (TensorArray of cumulative
             [B,K] scores — the last written entry is the final total)
    outputs: SentenceIds [B,K,C] (end_id-padded), SentenceScores [B,K]
    """
    ids_arr = env.read(op.inputs["Ids"][0])
    par_arr = env.read(op.inputs["ParentIdx"][0])
    scores_arr = env.read(op.inputs["Scores"][0])
    scores = scores_arr.read(scores_arr.length - 1)
    end_id = int(op.attrs["end_id"])

    buf_ids = ids_arr.buffer      # [C, B, K]
    buf_par = par_arr.buffer      # [C, B, K]
    C, B, K = buf_ids.shape
    n = ids_arr.length            # actual steps written

    binx = jnp.arange(B)[:, None]                      # [B,1]
    init_beam = jnp.tile(jnp.arange(K)[None], (B, 1))  # [B,K]

    def back(beam, t):
        valid = t < n
        tok = jnp.where(valid, buf_ids[t][binx, beam],
                        jnp.asarray(end_id, buf_ids.dtype))
        prev = jnp.where(valid, buf_par[t][binx, beam], beam)
        return prev.astype(jnp.int32), tok

    _, toks = lax.scan(back, init_beam.astype(jnp.int32),
                       jnp.arange(C - 1, -1, -1))
    sentences = jnp.moveaxis(toks[::-1], 0, 2)         # [B,K,C]
    env.write(op.outputs["SentenceIds"][0], sentences)
    env.write(op.outputs["SentenceScores"][0], scores)
