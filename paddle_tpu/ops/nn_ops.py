"""NN op lowerings: conv, pool, norms, losses, embedding, dropout.

Parity: paddle/fluid/operators/{conv_op,conv_cudnn_op,conv_transpose_op,
pool_op,batch_norm_op,layer_norm_op,dropout_op,softmax_op,cross_entropy_op,
softmax_with_cross_entropy_op,sigmoid_cross_entropy_with_logits_op,
lookup_table_op,accuracy_op,smooth_l1_loss_op,log_loss_op,huber_loss_op,
lrn_op,maxout_op,label_smooth_op,nce_op}.{cc,cu,h}.

TPU notes: convs/matmuls keep fluid's NCHW layout at the IR level — XLA's TPU
layout assignment transposes to the MXU-friendly layout internally, so parity
of semantics costs nothing. bf16 convs run bf16-in/bf16-out and rely on the
TPU MXU's internal f32 accumulate (an explicit preferred_element_type breaks
conv's grad rule); mul/matmul request f32 accumulation explicitly.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register, single
from ..core.utils import pair as _pair


def _out(x):
    return {"Out": [x]}


def _conv_layout():
    """FLAGS_conv_layout=NHWC runs the conv/pool family in channels-last
    compute layout (boundary transposes around each op; XLA folds
    adjacent pairs). The fluid-facing contract stays NCHW — this is the
    internal MXU layout knob the perf sweep probes (round-2 verdict
    missing #4). Read at trace time: set it before the first run of a
    program (the jit cache keys on the program, not the flag)."""
    import os
    layout = os.environ.get("FLAGS_conv_layout", "NCHW").upper()
    if layout not in ("NCHW", "NHWC"):
        raise ValueError(
            "FLAGS_conv_layout=%r: expected NCHW or NHWC (a typo here "
            "would otherwise silently run the NCHW path)" % layout)
    return layout


# ---------------------------------------------------------------------------
# convolution family (MXU)
# ---------------------------------------------------------------------------

@register("conv2d")
def _conv2d(ctx, ins, attrs):
    x = single(ins, "Input")    # NCHW
    w = single(ins, "Filter")   # OIHW
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dil = _pair(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1) or 1
    pad2 = [(pads[0], pads[0]), (pads[1], pads[1])]
    # bf16 operands stay bf16 end-to-end: the TPU MXU accumulates in f32
    # internally, and conv's transpose (grad) rule rejects the
    # preferred_element_type + downcast pattern (f32 cotangent meets bf16
    # filter), so an explicit f32 accumulate would break training.
    if _conv_layout() == "NHWC":
        out = lax.conv_general_dilated(
            jnp.transpose(x, (0, 2, 3, 1)),
            jnp.transpose(w, (2, 3, 1, 0)),
            window_strides=strides, padding=pad2, rhs_dilation=dil,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=groups)
        out = jnp.transpose(out, (0, 3, 1, 2))
    else:
        out = lax.conv_general_dilated(
            x, w,
            window_strides=strides, padding=pad2, rhs_dilation=dil,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=groups)
    return {"Output": [out.astype(x.dtype)]}


@register("depthwise_conv2d")
def _depthwise_conv2d(ctx, ins, attrs):
    return _conv2d(ctx, ins, attrs)


@register("conv2d_transpose")
def _conv2d_transpose(ctx, ins, attrs):
    x = single(ins, "Input")    # NCHW
    w = single(ins, "Filter")   # IOHW in fluid transpose conv
    if int(attrs.get("groups", 1) or 1) != 1:
        # era parity: conv_transpose_op.cc:101 "We enforce groups number
        # == 1" — silently ignoring the attr would compute wrong results
        raise ValueError(
            "conv2d_transpose: groups != 1 is not supported (the "
            "reference enforces groups == 1 for transposed convolution)")
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dil = _pair(attrs.get("dilations", [1, 1]))
    # Fluid's filter layout [C_in, C_out, kh, kw] is exactly the OIHW layout
    # of the FORWARD conv this op is the input-gradient of (the transpose
    # maps the forward conv's O channels back to its I channels), so declare
    # it "OIHW" and let transpose_kernel swap I/O + flip the taps. And
    # fluid's `paddings` attr is the FORWARD conv's padding: on the
    # stride-dilated input the gradient conv pads (effective_k - 1 - pad)
    # per side, giving the reference output size (H-1)*stride + k - 2*pad.
    eff = [(w.shape[2] - 1) * dil[0] + 1, (w.shape[3] - 1) * dil[1] + 1]
    out = lax.conv_transpose(
        x, w,
        strides=strides,
        padding=[(eff[0] - 1 - pads[0], eff[0] - 1 - pads[0]),
                 (eff[1] - 1 - pads[1], eff[1] - 1 - pads[1])],
        rhs_dilation=dil,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        transpose_kernel=True)
    return {"Output": [out.astype(x.dtype)]}


# ---------------------------------------------------------------------------
# pooling (reference: pool_op.cc; cuDNN pooling → lax.reduce_window)
# ---------------------------------------------------------------------------

@register("pool2d")
def _pool2d(ctx, ins, attrs):
    x = single(ins, "X")  # NCHW
    ptype = attrs.get("pooling_type", "max")
    ksize = _pair(attrs.get("ksize", [2, 2]))
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    if attrs.get("global_pooling"):
        ksize = (x.shape[2], x.shape[3])
        pads = (0, 0)
        strides = (1, 1)
    # ceil_mode rounds the output size UP; realized as extra trailing
    # padding so reduce_window emits ceil((H - k + 2p)/s) + 1 positions
    # (pool_op.cc ceil_mode attr; the extra rows never enter an avg count)
    extra = [0, 0]
    if attrs.get("ceil_mode", False):
        for d, hw in enumerate((x.shape[2], x.shape[3])):
            span = hw - ksize[d] + 2 * pads[d]
            out_ceil = -(-span // strides[d]) + 1
            extra[d] = max(0, (out_ceil - 1) * strides[d] - span)
    nhwc = _conv_layout() == "NHWC"
    if nhwc:  # channels-last compute layout, same knob as conv2d
        x = jnp.transpose(x, (0, 2, 3, 1))
        window = (1,) + ksize + (1,)
        strides4 = (1,) + strides + (1,)
        padding = ((0, 0), (pads[0], pads[0] + extra[0]),
                   (pads[1], pads[1] + extra[1]), (0, 0))
    else:
        window = (1, 1) + ksize
        strides4 = (1, 1) + strides
        padding = ((0, 0), (0, 0), (pads[0], pads[0] + extra[0]),
                   (pads[1], pads[1] + extra[1]))
    if ptype == "max":
        init = -jnp.inf
        out = lax.reduce_window(x, init, lax.max, window, strides4, padding)
    else:
        s = lax.reduce_window(x, 0.0, lax.add, window, strides4, padding)
        if attrs.get("exclusive", True) and (pads[0] or pads[1] or
                                             extra[0] or extra[1]):
            ones = jnp.ones_like(x)
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides4, padding)
            # a ceil-mode window can sit fully inside padding (count 0);
            # emit 0 there, not 0/0
            out = s / jnp.maximum(cnt, 1.0)
        else:
            out = s / float(ksize[0] * ksize[1])
    if nhwc:
        out = jnp.transpose(out, (0, 3, 1, 2))
    return _out(out.astype(x.dtype))


@register("maxout")
def _maxout(ctx, ins, attrs):
    x = single(ins, "X")  # NCHW
    g = attrs["groups"]
    n, c, h, w = x.shape
    return _out(jnp.max(x.reshape(n, c // g, g, h, w), axis=2))


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

@register("batch_norm")
def _batch_norm(ctx, ins, attrs):
    x = single(ins, "X")          # NCHW or NC
    scale = single(ins, "Scale")  # [C]
    bias = single(ins, "Bias")
    mean = single(ins, "Mean")      # moving mean (persistable)
    var = single(ins, "Variance")   # moving variance (persistable)
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False)
    layout = attrs.get("data_layout", "NCHW")

    axes = tuple(i for i in range(x.ndim)
                 if i != (1 if layout == "NCHW" and x.ndim > 2 else x.ndim - 1))
    caxis = 1 if (layout == "NCHW" and x.ndim > 2) else x.ndim - 1
    bshape = [1] * x.ndim
    bshape[caxis] = x.shape[caxis]

    if is_test:
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean = mean
        saved_var = var
    else:
        xf = x.astype(jnp.float32)
        use_mean = jnp.mean(xf, axis=axes)
        use_var = jnp.var(xf, axis=axes)
        # moving averages updated OUTSIDE the grad path
        use_mean_s = lax.stop_gradient(use_mean)
        use_var_s = lax.stop_gradient(use_var)
        mean_out = momentum * mean + (1 - momentum) * use_mean_s
        var_out = momentum * var + (1 - momentum) * use_var_s
        saved_mean = use_mean
        saved_var = use_var

    inv = lax.rsqrt(use_var.astype(jnp.float32) + eps)
    y = (x.astype(jnp.float32) - use_mean.reshape(bshape)) * inv.reshape(bshape)
    y = y * scale.reshape(bshape) + bias.reshape(bshape)
    return {"Y": [y.astype(x.dtype)],
            "MeanOut": [mean_out], "VarianceOut": [var_out],
            "SavedMean": [saved_mean], "SavedVariance": [saved_var]}


@register("layer_norm")
def _layer_norm(ctx, ins, attrs):
    x = single(ins, "X")
    scale = single(ins, "Scale")
    bias = single(ins, "Bias")
    eps = attrs.get("epsilon", 1e-5)
    begin = attrs.get("begin_norm_axis", 1)
    lead = int(np.prod(x.shape[:begin]))
    if scale is not None and bias is not None and _pallas_enabled("ln"):
        from . import pallas_kernels as pk
        from .kernel_config import tiles_for
        d_norm = int(np.prod(x.shape[begin:]))
        y, mean, var = pk.layer_norm(x.reshape(lead, -1), scale.reshape(-1),
                                     bias.reshape(-1), eps=eps,
                                     block_n=tiles_for("ln",
                                                       d_norm)["block_n"])
        return {"Y": [y.reshape(x.shape).astype(x.dtype)],
                "Mean": [mean], "Variance": [var]}
    x2 = x.reshape(lead, -1).astype(jnp.float32)
    mean = jnp.mean(x2, axis=1, keepdims=True)
    var = jnp.var(x2, axis=1, keepdims=True)
    y = (x2 - mean) * lax.rsqrt(var + eps)
    if scale is not None:
        y = y * scale.reshape(1, -1)
    if bias is not None:
        y = y + bias.reshape(1, -1)
    return {"Y": [y.reshape(x.shape).astype(x.dtype)],
            "Mean": [mean.reshape(lead)], "Variance": [var.reshape(lead)]}


@register("lrn")
def _lrn(ctx, ins, attrs):
    x = single(ins, "X")  # NCHW
    n = attrs.get("n", 5)
    k = attrs.get("k", 2.0)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    return {"Out": [x / jnp.power(mid, beta)], "MidOut": [mid]}


@register("l2_normalize")
def _l2_norm_op(ctx, ins, attrs):
    x = single(ins, "X")
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": [x / norm], "Norm": [norm]}


# ---------------------------------------------------------------------------
# dropout (reference: dropout_op.cc — Mask output keeps fwd/bwd consistent)
# ---------------------------------------------------------------------------

@register("dropout", uses_rng=True)
def _dropout(ctx, ins, attrs):
    x = single(ins, "X")
    p = attrs.get("dropout_prob", 0.5)
    if attrs.get("is_test", False):
        # fluid's default "downgrade_in_infer": scale at inference
        return {"Out": [x * (1.0 - p)], "Mask": [jnp.ones_like(x)]}
    keep = jax.random.bernoulli(ctx.rng(seed=attrs.get("seed", 0)), 1.0 - p, x.shape)
    mask = keep.astype(x.dtype)
    return {"Out": [x * mask], "Mask": [mask]}


# ---------------------------------------------------------------------------
# softmax & losses
# ---------------------------------------------------------------------------

@register("softmax")
def _softmax(ctx, ins, attrs):
    return _out(jax.nn.softmax(single(ins, "X"), axis=-1))


@register("log_softmax")
def _log_softmax(ctx, ins, attrs):
    return _out(jax.nn.log_softmax(single(ins, "X"), axis=-1))


def _gather_label_logits(logp, label):
    # [..., C] logits + [..., 1] (or [...]) labels -> [...] picked values
    lead = logp.shape[:-1]
    flat = logp.reshape(-1, logp.shape[-1])
    lab = label.reshape(-1).astype(jnp.int32)
    rows = jnp.arange(flat.shape[0])
    return flat[rows, lab].reshape(lead)


@register("cross_entropy")
def _cross_entropy(ctx, ins, attrs):
    x = single(ins, "X")        # probabilities [N, C]
    label = single(ins, "Label")
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, 1e-20)), axis=-1,
                        keepdims=True)
    else:
        picked = _gather_label_logits(jnp.log(jnp.maximum(x, 1e-20)), label)
        loss = -picked[..., None]
    return {"Y": [loss]}


def _pallas_enabled(op="xent"):
    """Per-op pallas gating — delegates to ops.kernel_config.pallas_on,
    the ONE owner of the PADDLE_TPU_PALLAS parse (0/1 and the
    per-op allowlist form, e.g. PADDLE_TPU_PALLAS=attn,xent,ln)."""
    from .kernel_config import pallas_on
    return pallas_on(op)


def _flash_min_seq():
    """Flash-vs-dense attention dispatch crossover — delegates to
    ops.kernel_config.flash_min_seq (env pin -> tuned store entry ->
    1024 default). Kept as a name because trace_env_key() historically
    imported it from here."""
    from .kernel_config import flash_min_seq
    return flash_min_seq()


@register("softmax_with_cross_entropy")
def _softmax_xent(ctx, ins, attrs):
    logits = single(ins, "Logits")
    label = single(ins, "Label")
    if not attrs.get("soft_label", False) and logits.ndim == 2 \
            and _pallas_enabled("xent"):
        # fused pallas path: loss + logsumexp in one VMEM pass, softmax
        # never materialized in the forward (the dense Softmax slot below
        # is DCE'd by XLA unless the program actually consumes it)
        from . import pallas_kernels as pk
        from .kernel_config import tiles_for
        loss = pk.softmax_xent(
            logits, label.reshape(-1),
            block_n=tiles_for("xent", logits.shape[-1])["block_n"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return {"Softmax": [jnp.exp(logp).astype(logits.dtype)],
                "Loss": [loss.astype(logits.dtype)]}
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        loss = -_gather_label_logits(logp, label)[..., None]
    return {"Softmax": [jnp.exp(logp).astype(logits.dtype)],
            "Loss": [loss.astype(logits.dtype)]}


@register("fused_attention")
def _fused_attention(ctx, ins, attrs):
    """flash attention over [B, T, H, D] q/k/v (TPU-native addition; see
    ops/pallas_kernels.py). Differentiable via the kernel's custom_vjp.

    Sequence parallelism is Program-reachable here: under a
    ParallelExecutor mesh with an 'sp' axis, the same op dispatches to
    parallel/ring_attention.py — the sequence dim shards over sp, K/V
    blocks rotate the ring via lax.ppermute, and the online softmax
    matches the single-chip kernel exactly (incl. causal + kv_len)."""
    q = single(ins, "Q")
    k = single(ins, "K")
    v = single(ins, "V")
    kv_len = single(ins, "KVLen") if ins.get("KVLen") else None
    causal = attrs.get("causal", False)
    scale = attrs.get("scale", None)
    mesh = ctx.mesh
    if mesh is not None and mesh.shape.get("sp", 1) > 1:
        # sp_impl picks the sequence-parallel algorithm: "ring" (default;
        # K/V blocks rotate over ICI, O(T/sp) memory, any head count) or
        # "ulysses" (all-to-all head sharding — one collective round
        # instead of sp-1 ppermute hops when heads % sp == 0)
        if attrs.get("sp_impl", "ring") == "ulysses":
            from ..parallel.ulysses import ulysses_attention_sharded
            return _out(ulysses_attention_sharded(
                q, k, v, mesh, causal=causal, scale=scale, kv_len=kv_len))
        from ..parallel.ring_attention import ring_attention_sharded
        return _out(ring_attention_sharded(
            q, k, v, mesh, causal=causal, scale=scale, kv_len=kv_len))
    # Per-shape dispatch (round-4 measurements, real v5e: dense XLA
    # attention beat the flash kernel at T=256 — 130.0k vs 102.0k tok/s —
    # while flash was 12.1x dense at T=2048): short sequences take the
    # dense einsum path, long ones the pallas kernel. Crossover from
    # kernel_config.flash_min_seq (FLAGS_flash_min_seq pin -> tuned
    # store entry -> 1024 default; 0 forces flash always — used by
    # kernel-coverage tests and the block-tune sweep). An explicit
    # PADDLE_TPU_PALLAS opt-out (=0, or an allowlist without 'attn')
    # forces the dense path regardless of length.
    # kernel_config.flash_at owns the decision, including the structural
    # decode rule: q_len <= 1 (decode serving steps one token at a time)
    # is dense by construction — no flash tiling exists for a 1-row q
    # block, so not even FLAGS_flash_min_seq=0 forces the kernel there.
    from .kernel_config import flash_at, tiles_for
    t = q.shape[1]
    if not flash_at(t):
        from ..parallel.ring_attention import attention_reference
        return _out(attention_reference(
            q, k, v, causal=causal, scale=scale,
            kv_len=kv_len).astype(q.dtype))
    from . import pallas_kernels as pk
    # explicit layer attrs pin the tiles; otherwise the per-shape tuned
    # table (defaults = the old 128/128 literals) decides
    tiles = tiles_for("attn", t if t else 128)
    out = pk.flash_attention(
        q, k, v, causal=causal, scale=scale, kv_len=kv_len,
        block_q=attrs.get("block_q") or tiles["block_q"],
        block_k=attrs.get("block_k") or tiles["block_k"])
    return _out(out)


@register("sigmoid_cross_entropy_with_logits")
def _sigmoid_xent(ctx, ins, attrs):
    x = single(ins, "X")
    label = single(ins, "Label")
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return _out(loss)


@register("square_error_cost")
def _square_error(ctx, ins, attrs):
    x, y = single(ins, "X"), single(ins, "Y")
    return _out(jnp.square(x - y))


@register("smooth_l1_loss")
def _smooth_l1(ctx, ins, attrs):
    x, y = single(ins, "X"), single(ins, "Y")
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    diff = x - y
    iw = single(ins, "InsideWeight")
    ow = single(ins, "OutsideWeight")
    if iw is not None:
        diff = diff * iw
    ad = jnp.abs(diff)
    elem = jnp.where(ad < 1.0 / s2, 0.5 * s2 * diff * diff, ad - 0.5 / s2)
    if ow is not None:
        elem = elem * ow
    loss = jnp.sum(elem.reshape(elem.shape[0], -1), axis=1, keepdims=True)
    return {"Out": [loss], "Diff": [diff]}


@register("log_loss")
def _log_loss(ctx, ins, attrs):
    p = single(ins, "Predicted")
    label = single(ins, "Labels")
    eps = attrs.get("epsilon", 1e-4)
    loss = -label * jnp.log(p + eps) - (1 - label) * jnp.log(1 - p + eps)
    return {"Loss": [loss]}


@register("huber_loss")
def _huber_loss(ctx, ins, attrs):
    x, y = single(ins, "X"), single(ins, "Y")
    delta = attrs.get("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r, delta * (ar - 0.5 * delta))
    return {"Out": [loss], "Residual": [r]}


@register("hinge_loss")
def _hinge_loss(ctx, ins, attrs):
    logits = single(ins, "Logits")
    labels = single(ins, "Labels")
    return {"Loss": [jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits)]}


@register("rank_loss")
def _rank_loss(ctx, ins, attrs):
    label = single(ins, "Label")
    left = single(ins, "Left")
    right = single(ins, "Right")
    d = left - right
    return _out(jnp.log1p(jnp.exp(d)) - label * d)


@register("margin_rank_loss")
def _margin_rank_loss(ctx, ins, attrs):
    label = single(ins, "Label")
    x1, x2 = single(ins, "X1"), single(ins, "X2")
    margin = attrs.get("margin", 0.0)
    act = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": [act], "Activated": [(act > 0).astype(x1.dtype)]}


@register("label_smooth")
def _label_smooth(ctx, ins, attrs):
    x = single(ins, "X")
    eps = attrs.get("epsilon", 0.0)
    dist = single(ins, "PriorDist")
    if dist is not None:
        out = (1 - eps) * x + eps * dist
    else:
        out = (1 - eps) * x + eps / x.shape[-1]
    return _out(out)


# ---------------------------------------------------------------------------
# embedding (reference: lookup_table_op — the pserver sparse path's hot op)
# ---------------------------------------------------------------------------

@register("lookup_table")
def _lookup_table(ctx, ins, attrs):
    w = single(ins, "W")        # [V, D]
    ids = single(ins, "Ids")    # [N, 1] int64
    flat = ids.reshape(-1).astype(jnp.int32)
    padding_idx = attrs.get("padding_idx", -1)
    out = jnp.take(w, flat, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((flat == padding_idx)[:, None], 0.0, out)
    out_shape = tuple(ids.shape[:-1]) + (w.shape[-1],) \
        if ids.shape and ids.shape[-1] == 1 else tuple(ids.shape) + (w.shape[-1],)
    return _out(out.reshape(out_shape))


# ---------------------------------------------------------------------------
# metrics (reference: accuracy_op.cc, auc_op.cc)
# ---------------------------------------------------------------------------

@register("accuracy")
def _accuracy(ctx, ins, attrs):
    pred_idx = single(ins, "Indices")   # [N, k] from topk
    label = single(ins, "Label")        # [N, 1]
    n = pred_idx.shape[0]
    correct = jnp.any(pred_idx.astype(jnp.int64) ==
                      label.astype(jnp.int64).reshape(-1, 1), axis=1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    return {"Accuracy": [(num_correct / n).reshape(1)],
            "Correct": [num_correct.astype(jnp.int32).reshape(1)],
            "Total": [jnp.full((1,), n, jnp.int32)]}


@register("auc")
def _auc(ctx, ins, attrs):
    # streaming AUC state lives in persistable vars updated here
    pred = single(ins, "Predict")
    label = single(ins, "Label").reshape(-1)
    tp_in = single(ins, "TP")  # stat buckets [num_thresholds]
    fp_in = single(ins, "FP")
    num_t = attrs.get("num_thresholds", 200)
    pos_score = pred[:, 1] if pred.ndim == 2 and pred.shape[1] == 2 else pred.reshape(-1)
    bucket = jnp.clip((pos_score * num_t).astype(jnp.int32), 0, num_t - 1)
    is_pos = (label > 0).astype(jnp.int64)
    tp = tp_in + jnp.zeros_like(tp_in).at[bucket].add(is_pos)
    fp = fp_in + jnp.zeros_like(fp_in).at[bucket].add(1 - is_pos)
    # integrate over thresholds (cumulative from high score to low)
    tp_c = jnp.cumsum(tp[::-1])[::-1].astype(jnp.float64)
    fp_c = jnp.cumsum(fp[::-1])[::-1].astype(jnp.float64)
    tot_pos = jnp.maximum(tp_c[0], 1)
    tot_neg = jnp.maximum(fp_c[0], 1)
    tpr = tp_c / tot_pos
    fpr = fp_c / tot_neg
    auc = -jnp.trapezoid(tpr, fpr)
    return {"AUC": [auc.astype(jnp.float32).reshape(1)],
            "TPOut": [tp], "FPOut": [fp]}


# ---------------------------------------------------------------------------
# nce (reference: nce_op.cc) — negative sampling loss
# ---------------------------------------------------------------------------

@register("nce", uses_rng=True)
def _nce(ctx, ins, attrs):
    x = single(ins, "Input")          # [N, D]
    label = single(ins, "Label")      # [N, num_true]
    w = single(ins, "Weight")         # [V, D]
    b = single(ins, "Bias")           # [V]
    num_neg = attrs.get("num_neg_samples", 10)
    num_total = attrs.get("num_total_classes")
    n = x.shape[0]
    label = label.reshape(n, -1).astype(jnp.int32)
    num_true = label.shape[1]
    neg = jax.random.randint(ctx.rng(seed=attrs.get("seed", 0)), (n, num_neg), 0, num_total)
    samples = jnp.concatenate([label, neg], axis=1)      # [N, T+S]
    sw = jnp.take(w, samples.reshape(-1), axis=0).reshape(n, -1, w.shape[1])
    logits = jnp.einsum("nd,nsd->ns", x, sw)
    if b is not None:
        logits = logits + jnp.take(b.reshape(-1), samples.reshape(-1)).reshape(n, -1)
    labels01 = jnp.concatenate(
        [jnp.ones((n, num_true)), jnp.zeros((n, num_neg))], axis=1)
    ce = jnp.maximum(logits, 0) - logits * labels01 + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    cost = jnp.sum(ce, axis=1, keepdims=True)
    return {"Cost": [cost], "SampleLogits": [logits], "SampleLabels": [samples]}


@register("im2sequence")
def _im2sequence(ctx, ins, attrs):
    """Patches -> per-image sequence (reference im2sequence_op.h Im2Col).

    Input [B, C, H, W] -> Out [B, oh*ow, C*kh*kw] + OutLen (= oh*ow for
    every image; static shapes make it a constant vector). Feature order is
    channel-major (c, kh, kw) like the reference's im2col."""
    x = single(ins, "X")
    kh, kw = attrs["kernels"]
    sh, sw = attrs.get("strides", [1, 1])
    pads = attrs.get("paddings", [0, 0, 0, 0])
    up, left, down, right = (pads if len(pads) == 4 else
                             [pads[0], pads[1], pads[0], pads[1]])
    b, c, h, w = x.shape
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=(kh, kw), window_strides=(sh, sw),
        padding=((up, down), (left, right)))    # [B, C*kh*kw, oh, ow]
    f = patches.shape[1]
    oh, ow = patches.shape[2], patches.shape[3]
    out = patches.reshape(b, f, oh * ow).transpose(0, 2, 1)
    out_len = jnp.full((b,), oh * ow, jnp.int32)
    return {"Out": [out], "OutLen": [out_len]}
