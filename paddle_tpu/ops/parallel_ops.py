"""Program-level lowerings of the parallel subsystems: the `pipeline` op
(GPipe looped pipeline, parallel/pipeline.py) and the `moe` op (top-1
switch expert parallelism, parallel/moe.py).

These make PP and EP reachable from the fluid Program path
(layers.pipelined_stack / layers.switch_moe build the ops; Executor runs
them sequentially / densely on one chip; ParallelExecutor with a mesh
carrying a 'pp' / 'ep' axis runs the real collective schedules). The
reference era had neither — its only model-partitioning story is the
pserver parameter split (python/paddle/fluid/distribute_transpiler.py) —
but SURVEY §2 commits to DP/TP/PP/SP/EP composable on one Mesh *for
Programs*, which is exactly what these two ops close.

Both lower through pure-jax library code, so `grad_of` (core/backward.py)
differentiates them with jax.vjp like any other registered op: the
backward pipeline falls out of lax.scan/ppermute transposition, the MoE
backward out of the einsum transposes. No hand-written grad machinery.
"""
import jax.numpy as jnp
from jax import lax

from ..core import registry
from ..core.registry import single
from ..core.lowering import Env, lower_block, PROGRAM_ERR


def _stage_runner(ctx, attrs):
    """Build stage_fn(param_values, x) -> y that lowers the template
    sub-block with the stage's parameter values bound to the template
    names. `marker` (a python int or traced int32) is folded into the rng
    stream so random ops vary per stage, and suppresses in-graph
    assertion escapes while tracing inside shard_map/scan."""
    sub = ctx.program.blocks[attrs["sub_block"]]
    pnames = list(attrs["param_names"])
    in_name = attrs["in_name"]
    out_name = attrs["out_name"]

    def stage_fn(plist, xin, marker, traced):
        """traced=True while inside shard_map/scan (pp path): assertion
        flags can't escape the trace, so add_error must be suppressed via
        _loop_iters. The sequential path is at top trace level — only the
        rng stream needs the per-stage fold, assertions still escape.
        Returns (out, err): err sweeps the stage env's PROGRAM_ERR and
        TensorArray overflow flags (like control_ops' sub-blocks do) so
        in-stage overflows reach the host on the sequential path."""
        from .control_ops import _sweep_overflow
        benv = Env()
        benv.write(PROGRAM_ERR, jnp.zeros((), bool))
        for n, v in zip(pnames, plist):
            benv.write(n, v)
        benv.write(in_name, xin)
        stack = ctx._loop_iters if traced else ctx._rng_extra
        stack.append(marker)
        try:
            lower_block(ctx, sub, benv)
        finally:
            stack.pop()
        return benv.read(out_name), _sweep_overflow(
            benv, jnp.zeros((), bool))

    return stage_fn


def _pipeline_lower(ctx, ins, attrs):
    x = single(ins, "X")
    flat = list(ins.get("StageParams", []))
    S = int(attrs["num_stages"])
    Pn = int(attrs["params_per_stage"])
    stage_fn = _stage_runner(ctx, attrs)

    mesh = ctx.mesh
    pp = mesh.shape.get("pp", 1) if mesh is not None else 1
    if pp > 1:
        if pp != S:
            raise ValueError(
                "pipeline op has %d stages but the mesh 'pp' axis is %d — "
                "stage count and pipeline ranks must match" % (S, pp))
        from ..parallel.pipeline import pipeline_apply
        # stack each template param across stages -> [S, ...] leaves; the
        # shard_map in_spec P('pp') places stage s's slice on rank s
        stacked = [jnp.stack([flat[s * Pn + j] for s in range(S)])
                   for j in range(Pn)]
        M = int(attrs.get("num_microbatches") or 0) or None
        batch_axis = "dp" if mesh.shape.get("dp", 1) > 1 else None
        out = pipeline_apply(
            # error flags minted inside shard_map/scan can't escape the
            # trace — dropped here, mirroring add_error's loop rule
            lambda plist, xin: stage_fn(plist, xin,
                                        lax.axis_index("pp"), True)[0],
            stacked, x, mesh, num_microbatches=M, axis="pp",
            batch_axis=batch_axis)
        return {"Out": [out]}
    # single-chip / no-pp-axis: run the stages sequentially (the exact
    # math the pipeline schedule computes, minus the ring); stage error
    # flags escape via the "__errors__" channel like rnn_scan's
    out = x
    err = jnp.zeros((), bool)
    for s in range(S):
        out, serr = stage_fn(flat[s * Pn:(s + 1) * Pn], out, s, False)
        err = err | serr
    return {"Out": [out], "__errors__": err}


def _pipeline_infer(block, op, out_vars):
    xv = block.var_recursive(op.inputs["X"][0])
    ov = block.var_recursive(op.outputs["Out"][0])
    ov.shape, ov.dtype = xv.shape, xv.dtype


registry.register("pipeline", _pipeline_lower, infer=_pipeline_infer)


def _moe_lower(ctx, ins, attrs):
    from ..parallel.moe import moe_layer
    x = single(ins, "X")
    params = {"gate": single(ins, "Gate"),
              "w1": single(ins, "W1"), "b1": single(ins, "B1"),
              "w2": single(ins, "W2"), "b2": single(ins, "B2")}
    mesh = ctx.mesh
    ep = mesh.shape.get("ep", 1) if mesh is not None else 1
    d = x.shape[-1]
    x2 = x.reshape(-1, d)
    y, aux = moe_layer(params, x2,
                       capacity_factor=float(attrs["capacity_factor"]),
                       mesh=mesh if ep > 1 else None, axis="ep")
    return {"Out": [y.reshape(x.shape)], "AuxLoss": [aux.reshape(1)]}


def _moe_infer(block, op, out_vars):
    xv = block.var_recursive(op.inputs["X"][0])
    ov = block.var_recursive(op.outputs["Out"][0])
    ov.shape, ov.dtype = xv.shape, xv.dtype
    av = block.var_recursive(op.outputs["AuxLoss"][0])
    av.shape, av.dtype = (1,), "float32"


registry.register("moe", _moe_lower, infer=_moe_infer)
