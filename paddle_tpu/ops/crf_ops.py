"""Linear-chain CRF ops: forward NLL, Viterbi decode, chunk evaluation.

Parity: paddle/fluid/operators/{linear_chain_crf_op,crf_decoding_op,
chunk_eval_op}.h. The reference walks each sequence host-side with
nested per-tag loops; here everything is a batched `lax.scan` over the
padded-dense layout ([B, T, D] + XLen), so the whole batch's DP runs as
one fused XLA loop on device and the gradient of the forward NLL comes
from jax.vjp instead of the hand-written LinearChainCRFGradOpKernel.

Transition layout (linear_chain_crf_op.h:150-162): Transition is
[D+2, D]; row 0 = start weights, row 1 = end weights, rows 2.. =
w[2+j, i] = score of tag j -> tag i. LogLikelihood output is the
per-sequence negative log likelihood [num_seqs, 1] (the reference
returns -(score - logZ); linear_chain_crf_op.h:194).

The reference computes in exp space with per-step L1 renormalization to
avoid under/overflow (NormalizeL1 at linear_chain_crf_op.h:167); in log
space logsumexp gives the same numerics without the trick.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import (register, single, int_dtype as _i64,
                             squeeze_label as _squeeze_label)


def _split_transition(w):
    return w[0], w[1], w[2:]  # start [D], end [D], trans [D, D] (j -> i)


@register("linear_chain_crf")
def _linear_chain_crf(ctx, ins, attrs):
    x = single(ins, "Emission")       # [B, T, D]
    w = single(ins, "Transition")     # [D+2, D]
    label = _squeeze_label(single(ins, "Label"))  # [B, T]
    xlen = single(ins, "XLen").astype(jnp.int32)  # [B]
    b_, t_, d = x.shape
    start, end, trans = _split_transition(w)
    tmask = (jnp.arange(t_, dtype=jnp.int32)[None, :] < xlen[:, None])

    # ---- log partition via forward algorithm ----
    alpha0 = start[None, :] + x[:, 0]                       # [B, D]

    def fwd(alpha, inp):
        xk, mk = inp                                        # [B, D], [B]
        nxt = jax.nn.logsumexp(alpha[:, :, None] + trans[None], axis=1) + xk
        return jnp.where(mk[:, None], nxt, alpha), None

    if t_ > 1:
        xs = jnp.moveaxis(x[:, 1:], 1, 0)                   # [T-1, B, D]
        ms = jnp.moveaxis(tmask[:, 1:], 1, 0)               # [T-1, B]
        alpha, _ = lax.scan(fwd, alpha0, (xs, ms))
    else:
        alpha = alpha0
    log_z = jax.nn.logsumexp(alpha + end[None, :], axis=1)  # [B]

    # ---- gold path score ----
    emit = jnp.take_along_axis(x, label[:, :, None], axis=2)[:, :, 0]
    emit_score = jnp.sum(emit * tmask, axis=1)
    tr = trans[label[:, :-1], label[:, 1:]] if t_ > 1 else jnp.zeros((b_, 0))
    trans_score = jnp.sum(tr * tmask[:, 1:], axis=1)
    last = jnp.maximum(xlen - 1, 0)
    last_label = jnp.take_along_axis(label, last[:, None], axis=1)[:, 0]
    score = start[label[:, 0]] + emit_score + trans_score + end[last_label]

    nll = jnp.where(xlen > 0, log_z - score, 0.0)
    return {"LogLikelihood": [nll[:, None].astype(x.dtype)]}


@register("crf_decoding")
def _crf_decoding(ctx, ins, attrs):
    x = single(ins, "Emission")      # [B, T, D]
    w = single(ins, "Transition")    # [D+2, D]
    xlen = single(ins, "XLen").astype(jnp.int32)
    label = ins.get("Label")
    b_, t_, d = x.shape
    start, end, trans = _split_transition(w)
    tmask = (jnp.arange(t_, dtype=jnp.int32)[None, :] < xlen[:, None])

    # Viterbi forward: alpha[k, i] = best score ending at tag i; track argmax.
    alpha0 = start[None, :] + x[:, 0]

    def fwd(alpha, inp):
        xk, mk = inp
        scores = alpha[:, :, None] + trans[None]            # [B, j, i]
        best = jnp.max(scores, axis=1) + xk
        track = jnp.argmax(scores, axis=1).astype(jnp.int32)
        alpha = jnp.where(mk[:, None], best, alpha)
        return alpha, track

    if t_ > 1:
        xs = jnp.moveaxis(x[:, 1:], 1, 0)
        ms = jnp.moveaxis(tmask[:, 1:], 1, 0)
        alpha, tracks = lax.scan(fwd, alpha0, (xs, ms))     # tracks [T-1,B,D]
    else:
        alpha = alpha0
        tracks = jnp.zeros((0, b_, d), jnp.int32)

    best_last = jnp.argmax(alpha + end[None, :], axis=1).astype(jnp.int32)

    # backtrack from each sequence's true last position. Walking k=T-2..0:
    # if position k+1 is within the sequence, follow the tracked argmax;
    # at k+1 == len-1 the path restarts from best_last.
    def bwd(cur, inp):
        track_k, k = inp                                    # [B, D], scalar
        is_last = (k + 1) == xlen - 1
        nxt = jnp.where(is_last, best_last, cur)
        prev = jnp.take_along_axis(track_k, nxt[:, None], axis=1)[:, 0]
        in_seq = (k + 1) <= xlen - 1
        out_k = jnp.where(in_seq, prev, 0)
        return out_k, out_k

    if t_ > 1:
        ks = jnp.arange(t_ - 2, -1, -1, dtype=jnp.int32)
        init = jnp.where(xlen - 1 == t_ - 1, best_last, 0)
        _, rev_path = lax.scan(bwd, init, (tracks[::-1], ks))
        path_head = rev_path[::-1]                          # [T-1, B]
        path = jnp.concatenate(
            [jnp.moveaxis(path_head, 0, 1),
             jnp.zeros((b_, 1), jnp.int32)], axis=1)
        # position len-1 of each row holds best_last
        path = jnp.where(jnp.arange(t_)[None, :] == (xlen - 1)[:, None],
                         best_last[:, None], path)
    else:
        path = best_last[:, None]
    path = jnp.where(tmask, path, 0)

    if label:
        lbl = _squeeze_label(label[0])
        out = jnp.where(tmask, (lbl == path).astype(jnp.int32), 0)
        return {"ViterbiPath": [out.astype(_i64())]}
    return {"ViterbiPath": [path.astype(_i64())]}


# ---------------------------------------------------------------------------
# chunk_eval (chunk_eval_op.h GetSegments/ChunkBegin/ChunkEnd, vectorized)
# ---------------------------------------------------------------------------

_SCHEMES = {
    # scheme: (num_tag_types, begin, inside, end, single); -1 = absent
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _chunk_flags(label, valid, num_chunk_types, scheme):
    """begin[i], next_end[i] per position, vectorized.

    The reference's stateful walk satisfies the invariant
    in_chunk[i] == (type[i] != other) for every label sequence, which makes
    ChunkBegin/ChunkEnd pure functions of consecutive (tag, type) pairs.
    """
    num_tag, tag_b, tag_i, tag_e, tag_s = _SCHEMES[scheme]
    other = num_chunk_types
    tag = label % num_tag
    typ = jnp.where(valid, label // num_tag, other)
    b_, t_ = label.shape

    prev_tag = jnp.concatenate([jnp.full((b_, 1), -1, tag.dtype),
                                tag[:, :-1]], axis=1)
    prev_typ = jnp.concatenate([jnp.full((b_, 1), other, typ.dtype),
                                typ[:, :-1]], axis=1)

    def chunk_begin(ptag, ptyp, tag, typ):
        res = jnp.where(
            ptyp == other, typ != other,
            jnp.where(
                typ == other, False,
                jnp.where(
                    typ != ptyp, True,
                    (tag == tag_b) | (tag == tag_s) |
                    (((tag == tag_i) | (tag == tag_e)) &
                     ((ptag == tag_e) | (ptag == tag_s))))))
        return res & (typ != other)

    def chunk_end(ptag, ptyp, tag, typ):
        # "does a chunk open at i-1 close before i": reference ChunkEnd
        return jnp.where(
            ptyp == other, False,
            jnp.where(
                typ == other, True,
                jnp.where(
                    typ != ptyp, True,
                    jnp.where(
                        (ptag == tag_b) | (ptag == tag_i),
                        (tag == tag_b) | (tag == tag_s),
                        (ptag == tag_e) | (ptag == tag_s)))))

    begin = chunk_begin(prev_tag, prev_typ, tag, typ) & valid
    # end_at[i]: position i is the last token of a chunk
    nxt_tag = jnp.concatenate([tag[:, 1:],
                               jnp.full((b_, 1), -1, tag.dtype)], axis=1)
    nxt_typ = jnp.concatenate([typ[:, 1:],
                               jnp.full((b_, 1), other, typ.dtype)], axis=1)
    end_at = (typ != other) & chunk_end(tag, typ, nxt_tag, nxt_typ) & valid

    # next_end[i] = first j >= i with end_at[j] (reverse cumulative min)
    idx = jnp.arange(t_, dtype=jnp.int32)[None, :]
    cand = jnp.where(end_at, idx, t_ + 1)
    next_end = lax.cummin(cand[:, ::-1], axis=1)[:, ::-1]
    return begin, next_end, typ


@register("chunk_eval")
def _chunk_eval(ctx, ins, attrs):
    inference = _squeeze_label(single(ins, "Inference"))  # [B, T]
    label = _squeeze_label(single(ins, "Label"))
    xlen = single(ins, "XLen").astype(jnp.int32)
    num_chunk_types = int(attrs["num_chunk_types"])
    scheme = attrs.get("chunk_scheme", "IOB")
    excluded = list(attrs.get("excluded_chunk_types", []) or [])
    t_ = label.shape[1]
    valid = (jnp.arange(t_, dtype=jnp.int32)[None, :] < xlen[:, None])

    beg_l, end_l, typ_l = _chunk_flags(label, valid, num_chunk_types, scheme)
    beg_i, end_i, typ_i = _chunk_flags(inference, valid, num_chunk_types,
                                       scheme)

    def included(typ):
        inc = jnp.ones(typ.shape, bool)
        for e in excluded:
            inc &= typ != e
        return inc

    n_label = jnp.sum((beg_l & included(typ_l)).astype(_i64()))
    n_infer = jnp.sum((beg_i & included(typ_i)).astype(_i64()))
    correct = (beg_l & beg_i & (typ_l == typ_i) & (end_l == end_i) &
               included(typ_l))
    n_correct = jnp.sum(correct.astype(_i64()))

    nc = n_correct.astype(jnp.float32)
    precision = jnp.where(n_infer > 0, nc / n_infer, 0.0)
    recall = jnp.where(n_label > 0, nc / n_label, 0.0)
    f1 = jnp.where(n_correct > 0,
                   2 * precision * recall / (precision + recall), 0.0)
    return {"Precision": [precision.reshape(1)],
            "Recall": [recall.reshape(1)],
            "F1-Score": [f1.reshape(1)],
            "NumInferChunks": [n_infer.reshape(1)],
            "NumLabelChunks": [n_label.reshape(1)],
            "NumCorrectChunks": [n_correct.reshape(1)]}
