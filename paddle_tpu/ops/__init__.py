"""Op lowering rules. Importing this package registers all ops."""
from . import basic      # noqa: F401
from . import nn_ops     # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import sequence_ops   # noqa: F401
from . import control_ops    # noqa: F401
from . import crf_ops        # noqa: F401
from . import ctc_ops        # noqa: F401
from . import detection_ops  # noqa: F401
from . import parallel_ops   # noqa: F401
from . import tail_ops       # noqa: F401
from . import volumetric_ops  # noqa: F401
from . import guard_ops      # noqa: F401
from . import quant_ops      # noqa: F401
