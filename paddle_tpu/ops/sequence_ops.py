"""Sequence / LoD op lowerings (filled out with the sequence milestone).

Parity: paddle/fluid/operators/sequence_*.cc, gru_op.cc, lstm_op.cc.
"""
