"""Sequence / recurrent op lowerings over the padded-dense layout.

Parity: paddle/fluid/operators/{sequence_pool_op,sequence_softmax_op,
sequence_conv_op,sequence_expand_op,sequence_reshape_op,lod_reset_op,
lstm_op,gru_op,row_conv_op}.{cc,cu,h}.

Layout contract (SURVEY.md §6.3): a lod_level-1 tensor is a padded dense
array X [num_seqs, max_len, *feature] plus XLen int32 [num_seqs] of true
lengths. The reference walks host-side LoD offsets per op; here every op is
a masked/vectorized XLA computation with static shapes. The recurrences
(dynamic_lstm/dynamic_gru) are lax.scan over time with the gate matmuls
batched onto the MXU.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register, single


def _mask(xlen, max_len, dtype=jnp.float32):
    """[B, T] 1/0 validity mask from lengths."""
    t = jnp.arange(max_len, dtype=jnp.int32)
    return (t[None, :] < xlen.astype(jnp.int32)[:, None]).astype(dtype)


def _seq_pallas_on(op):
    """Pallas fast-path gate for the sequence ops (kernel_config owns
    the flag parse; the kernels need the pallas TPU package importable
    even for interpret mode)."""
    from . import pallas_kernels as pk
    from .kernel_config import pallas_on
    return pk.attention_available() and pallas_on(op)


def _feat_mask(x, xlen):
    """mask broadcastable over x's feature dims."""
    m = _mask(xlen, x.shape[1], x.dtype)
    return m.reshape(m.shape + (1,) * (x.ndim - 2))


@register("sequence_pool")
def _sequence_pool(ctx, ins, attrs):
    x = single(ins, "X")          # [B, T, ...]
    xlen = single(ins, "XLen")    # [B]
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    # fused path gates on f32 like the LSTM kernel: the kernel computes
    # in f32, so an int accumulation (exact in the dense path) or a
    # bf16 input must not silently change numerics under the flag
    if ptype in ("SUM", "AVERAGE", "SQRT") and x.ndim >= 2 \
            and x.dtype == jnp.float32 and _seq_pallas_on("seq"):
        # fused masked pool: one VMEM pass builds the @SEQLEN mask and
        # reduces (linear pools only — MAX/LAST/FIRST keep the dense
        # path). Feature dims flatten to one trailing axis.
        from . import pallas_kernels as pk
        from .kernel_config import tiles_for
        b, t = x.shape[:2]
        feat = x.shape[2:]
        f = int(np.prod(feat)) if feat else 1
        out = pk.masked_pool(
            x.reshape(b, t, f), xlen, ptype=ptype,
            block_n=tiles_for("seq", t)["block_n"]).reshape((b,) + feat)
        return {"Out": [out.astype(x.dtype)]}
    m = _feat_mask(x, xlen)
    denom = jnp.maximum(xlen.astype(x.dtype), 1).reshape(
        (-1,) + (1,) * (x.ndim - 2))
    if ptype == "SUM":
        out = jnp.sum(x * m, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(x * m, axis=1) / denom
    elif ptype == "SQRT":
        out = jnp.sum(x * m, axis=1) / jnp.sqrt(denom)
    elif ptype == "MAX":
        neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
        out = jnp.max(jnp.where(m > 0, x, neg), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(xlen.astype(jnp.int32) - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
        ).squeeze(1)
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError("unknown pooltype %r" % ptype)
    # MaxIndex output (reference) only needed for MAX grad — vjp handles it
    return {"Out": [out]}


@register("sequence_last_step")
def _sequence_last_step(ctx, ins, attrs):
    return _sequence_pool(ctx, ins, dict(attrs, pooltype="LAST"))


@register("sequence_first_step")
def _sequence_first_step(ctx, ins, attrs):
    return _sequence_pool(ctx, ins, dict(attrs, pooltype="FIRST"))


@register("sequence_softmax")
def _sequence_softmax(ctx, ins, attrs):
    x = single(ins, "X")        # [B, T] or [B, T, 1]
    xlen = single(ins, "XLen")
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    logits = x.reshape(x.shape[0], x.shape[1]) if squeeze else x
    if logits.ndim == 2 and logits.dtype == jnp.float32 \
            and _seq_pallas_on("seq"):
        # fused masked softmax: mask + online max + normalize in one
        # VMEM pass per row block (bit-exact vs the where-mask path:
        # masked lanes underflow exp to exactly 0 either way)
        from . import pallas_kernels as pk
        from .kernel_config import tiles_for
        out = pk.masked_softmax(
            logits, xlen,
            block_n=tiles_for("seq", logits.shape[1])["block_n"])
        if squeeze:
            out = out.reshape(x.shape)
        return {"Out": [out.astype(x.dtype)]}
    m = _mask(xlen, logits.shape[1], logits.dtype)
    neg = jnp.asarray(-1e30, logits.dtype)
    out = jax.nn.softmax(jnp.where(m > 0, logits, neg), axis=1) * m
    if squeeze:
        out = out.reshape(x.shape)
    return {"Out": [out]}


@register("sequence_conv")
def _sequence_conv(ctx, ins, attrs):
    """Context-window conv over time (reference: sequence_conv_op).

    Filter [ctx_len * D, F]; context window centered per contextStart.
    """
    x = single(ins, "X")         # [B, T, D]
    w = single(ins, "Filter")    # [ctx_len*D, F]
    xlen = single(ins, "XLen")
    ctx_len = attrs.get("contextLength", 3)
    ctx_start = attrs.get("contextStart", -(ctx_len // 2))
    b, t, d = x.shape
    xm = x * _feat_mask(x, xlen)
    cols = []
    for k in range(ctx_len):
        off = ctx_start + k
        shifted = jnp.roll(xm, -off, axis=1)
        if off > 0:    # rolled forward: zero the tail
            valid = jnp.arange(t) < (t - off)
        elif off < 0:  # rolled backward: zero the head
            valid = jnp.arange(t) >= (-off)
        else:
            valid = jnp.ones(t, bool)
        cols.append(shifted * valid[None, :, None].astype(x.dtype))
    ctx_mat = jnp.concatenate(cols, axis=-1)        # [B, T, ctx_len*D]
    out = jnp.einsum("btc,cf->btf", ctx_mat, w)
    out = out * _feat_mask(out, xlen)
    return {"Out": [out]}


@register("sequence_reshape")
def _sequence_reshape(ctx, ins, attrs):
    """Repack row data to width new_dim (reference: sequence_reshape_op.cc).

    Padded-dense: each row's valid data is a contiguous prefix of the
    flattened [T*D] row, so reshaping to [T*D/new_dim, new_dim] keeps it a
    contiguous prefix; only the lengths rescale (exact integer math). T is
    zero-padded up when T*D doesn't divide new_dim (bucketed padding)."""
    x = single(ins, "X")        # [B, T, D]
    xlen = single(ins, "XLen")  # [B]
    new_dim = int(attrs["new_dim"])
    b, t, d = x.shape
    # smallest pad with (t+pad)*d % new_dim == 0: t+pad ≡ 0 (mod nd/gcd)
    import math
    m = new_dim // math.gcd(d, new_dim)
    pad_t = (-t) % m
    if pad_t:
        x = jnp.pad(x, ((0, 0), (0, pad_t), (0, 0)))
        t += pad_t
    out = x.reshape(b, (t * d) // new_dim, new_dim)
    elems = xlen.astype(jnp.int32) * d
    # reference sequence_reshape_op.cc enforces per-sequence divisibility;
    # a floor here would silently drop the tail of a sequence
    ctx.add_error(
        "sequence_reshape: a sequence's len*dim (%d per step) is not "
        "divisible by new_dim=%d; its tail would be dropped" % (d, new_dim),
        (elems % new_dim != 0).any())
    out_len = elems // new_dim
    return {"Out": [out], "OutLen": [out_len]}


@register("sequence_expand")
def _sequence_expand(ctx, ins, attrs):
    """Expand each row of X to match Y's sequence lengths.

    Padded-layout semantics: X [B, 1-or-T, ...] or [B, ...]; output repeats
    X's per-sequence row across Y's max_len timesteps (masked).
    """
    x = single(ins, "X")
    y = single(ins, "Y")
    ylen = single(ins, "YLen")
    t = y.shape[1]
    if x.ndim == y.ndim:          # padded [B, Tx, ...]: row 0 is the entry
        head = x[:, 0]
    else:                          # [B, ...] per-sequence row
        head = x
    rep = jnp.broadcast_to(head[:, None], (x.shape[0], t) + head.shape[1:])
    return {"Out": [rep * _feat_mask(rep, ylen)]}


@register("lod_reset")
def _lod_reset(ctx, ins, attrs):
    """lod_reset_op.cc: keep the flat data stream, replace the segmentation.

    The reference's row-major [total, D] layout makes this metadata-only;
    the padded-dense layout has to repack rows — flatten X's valid rows to
    a contiguous stream (scatter by old cumulative lengths), then re-split
    per the new lengths (gather by new cumulative lengths). New lengths
    come from attr target_lens (static), YLen (Y's own LoD), or YData
    (Y.data holding offsets, reference doc "attr(target_lod): [0, 4, 6]").
    """
    x = single(ins, "X")
    xlen = single(ins, "XLen")
    ylen = single(ins, "YLen")
    ydata = single(ins, "YData")
    y = single(ins, "Y")
    t_lens = attrs.get("target_lens") or []
    if ylen is None and ydata is None and not t_lens:
        # no target: pass through unchanged (the reference op enforces a
        # target; tolerated here for metadata-only program clones)
        return {"Out": [x]} if xlen is None else \
            {"Out": [x], "OutLen": [xlen]}
    # 1. flatten valid rows into one contiguous stream
    if xlen is not None:
        b, t = x.shape[:2]
        feat = x.shape[2:]
        cap = b * t
        xl = xlen.astype(jnp.int32)
        cum = jnp.cumsum(xl) - xl                       # exclusive prefix
        pos = cum[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
        valid = jnp.arange(t, dtype=jnp.int32)[None, :] < xl[:, None]
        pos = jnp.where(valid, pos, cap)                # park padding rows
        flat = jnp.zeros((cap + 1,) + feat, x.dtype).at[
            pos.reshape(-1)].set(x.reshape((cap,) + feat))[:cap]
    else:                                               # dense X: rows ARE the stream
        feat = x.shape[1:]
        flat = x
        cap = x.shape[0]
    # 2. new segmentation
    if ylen is not None:
        newlen = ylen.astype(jnp.int32)
        b2 = y.shape[0] if y is not None else newlen.shape[0]
        t2 = y.shape[1] if y is not None and len(y.shape) > 1 else cap
    elif ydata is not None:
        off = ydata.reshape(-1).astype(jnp.int32)
        newlen = off[1:] - off[:-1]
        b2, t2 = newlen.shape[0], cap
    else:
        lens = [int(v) for v in t_lens]
        newlen = jnp.asarray(lens, jnp.int32)
        b2, t2 = len(lens), max(lens)
    # reference lod_reset_op.cc enforces an ascending LoD whose last offset
    # equals the data length; a mismatch here would silently duplicate
    # (clip) or drop rows. Non-monotone offsets telescope to a valid sum,
    # so negative lengths must be rejected separately.
    total = jnp.sum(xl) if xlen is not None else cap
    ctx.add_error(
        "lod_reset: target segmentation length sum != data stream length",
        (jnp.sum(newlen) != total) | (newlen < 0).any())
    cum2 = jnp.cumsum(newlen) - newlen
    idx = cum2[:, None] + jnp.arange(t2, dtype=jnp.int32)[None, :]
    valid2 = jnp.arange(t2, dtype=jnp.int32)[None, :] < newlen[:, None]
    out = flat[jnp.clip(idx, 0, cap - 1).reshape(-1)].reshape(
        (b2, t2) + feat)
    out = jnp.where(valid2.reshape((b2, t2) + (1,) * len(feat)), out,
                    jnp.zeros((), x.dtype))
    return {"Out": [out], "OutLen": [newlen]}


@register("row_conv")
def _row_conv(ctx, ins, attrs):
    """Lookahead row convolution (reference: row_conv_op, DeepSpeech2)."""
    x = single(ins, "X")        # [B, T, D]
    w = single(ins, "Filter")   # [future_ctx, D]
    xlen = single(ins, "XLen")
    fut = w.shape[0]
    xm = x * _feat_mask(x, xlen)
    out = jnp.zeros_like(x)
    t = x.shape[1]
    for k in range(fut):
        shifted = jnp.roll(xm, -k, axis=1)
        valid = (jnp.arange(t) < (t - k)).astype(x.dtype)
        out = out + shifted * valid[None, :, None] * w[k][None, None, :]
    return {"Out": [out * _feat_mask(x, xlen)]}


# ---------------------------------------------------------------------------
# recurrences: LSTM / GRU via lax.scan (reference: lstm_op.cc, gru_op.cc —
# there a C++ loop over LoD-sorted batches calling cuBLAS per step; here one
# scan whose per-step gate matmul is a single MXU batched matmul)
# ---------------------------------------------------------------------------

def _lstm_act(name):
    return {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "relu": jax.nn.relu, "identity": lambda v: v}[name]


def _amp_recurrence(ctx, x_dtype):
    """AMP discipline for scan recurrences: the per-step gate matmul rides
    the MXU in bf16 (2x fp32 throughput), but the carried state accumulates
    in f32 — carrying cell state in bf16 loses the long-horizon additions
    that make LSTMs work. Applies when the program is AMP or the input
    already arrived bf16 (from an AMP'd input-projection mul).

    Returns (state_dtype, rmat(h, w)) — shared by _lstm and _gru."""
    bf = getattr(ctx, "amp", False) or x_dtype == jnp.bfloat16
    state_dt = jnp.float32 if x_dtype in (jnp.float32, jnp.bfloat16) \
        else x_dtype

    def rmat(h, wm):
        if bf:
            return jnp.matmul(h.astype(jnp.bfloat16),
                              wm.astype(jnp.bfloat16),
                              preferred_element_type=jnp.float32)
        return h @ wm.astype(state_dt)

    return state_dt, rmat


@register("lstm")
def _lstm(ctx, ins, attrs):
    """dynamic_lstm: input [B, T, 4D] (pre-projected by an fc), weight
    [D, 4D] recurrent, bias [1, 4D] (+[1, 3D] peepholes if use_peepholes).

    Gate order (reference lstm_op.cc:125 {W_ch, W_ih, W_fh, W_oh}):
    candidate, input, forget, output.
    """
    x = single(ins, "Input")       # [B, T, 4D]
    w = single(ins, "Weight")      # [D, 4D]
    bias = single(ins, "Bias")     # [1, 4D(+3D)]
    h0 = single(ins, "H0")
    c0 = single(ins, "C0")
    xlen = single(ins, "XLen")
    d = w.shape[0]
    b, t, _ = x.shape
    use_peep = attrs.get("use_peepholes", False)
    gact = _lstm_act(attrs.get("gate_activation", "sigmoid"))
    # lstm_op.h: act_cand maps the candidate gate, act_cell maps the cell
    # state on its way into the hidden output (h = o * act_cell(c)) —
    # indistinguishable at the tanh/tanh default, distinct otherwise
    cell_act = _lstm_act(attrs.get("cell_activation", "tanh"))
    cand_act = _lstm_act(attrs.get("candidate_activation", "tanh"))
    is_rev = attrs.get("is_reverse", False)

    if (not use_peep and x.dtype == jnp.float32
            and not getattr(ctx, "amp", False)
            and attrs.get("gate_activation", "sigmoid") == "sigmoid"
            and attrs.get("cell_activation", "tanh") == "tanh"
            and attrs.get("candidate_activation", "tanh") == "tanh"
            and _seq_pallas_on("lstm")):
        # fused pallas recurrence (default activations, no peepholes —
        # the long tail keeps the scan): four gates + state update in
        # one VMEM pass per step, carried state resident in VMEM
        from . import pallas_kernels as pk
        from .kernel_config import tiles_for
        hidden, cell = pk.fused_lstm(
            x, w, bias.reshape(-1)[:4 * d], h0, c0, xlen,
            reverse=is_rev, block_b=tiles_for("lstm", d)["block_b"])
        return {"Hidden": [hidden], "Cell": [cell],
                "BatchGate": [x], "BatchCellPreAct": [cell]}

    state_dt, rmat2 = _amp_recurrence(ctx, x.dtype)
    rmat = lambda h: rmat2(h, w)

    bias = bias.reshape(-1).astype(state_dt)
    gate_bias = bias[:4 * d]
    if use_peep:
        w_ic, w_fc, w_oc = (bias[4 * d:5 * d], bias[5 * d:6 * d],
                            bias[6 * d:7 * d])
    h_prev = h0.astype(state_dt) if h0 is not None \
        else jnp.zeros((b, d), state_dt)
    c_prev = c0.astype(state_dt) if c0 is not None \
        else jnp.zeros((b, d), state_dt)

    m = _mask(xlen, t, state_dt)                    # [B, T]
    xs = jnp.swapaxes(x, 0, 1).astype(state_dt)     # [T, B, 4D]
    ms = m.T[:, :, None]                            # [T, B, 1]
    if is_rev:
        xs = xs[::-1]
        ms = ms[::-1]

    def step(carry, inp):
        h_prev, c_prev = carry
        xt, mt = inp
        gates = xt + rmat(h_prev) + gate_bias       # [B, 4D]
        # reference weight layout lstm_op.cc:125 "{W_ch, W_ih, W_fh,
        # W_oh}" — CANDIDATE block first (kernel order in, ig, fg, og)
        gc, gi, gf, go = jnp.split(gates, 4, axis=-1)
        if use_peep:
            gi = gi + c_prev * w_ic
            gf = gf + c_prev * w_fc
        i = gact(gi)
        f = gact(gf)
        c_new = f * c_prev + i * cand_act(gc)
        if use_peep:
            go = go + c_new * w_oc
        o = gact(go)
        h_new = o * cell_act(c_new)
        # masked carry: padding steps keep previous state
        h = mt * h_new + (1 - mt) * h_prev
        c = mt * c_new + (1 - mt) * c_prev
        return (h, c), (h, c)

    (hT, cT), (hs, cs) = lax.scan(step, (h_prev, c_prev), (xs, ms))
    if is_rev:
        hs, cs = hs[::-1], cs[::-1]
    hidden = jnp.swapaxes(hs, 0, 1).astype(x.dtype)  # [B, T, D]
    cell = jnp.swapaxes(cs, 0, 1).astype(x.dtype)
    return {"Hidden": [hidden], "Cell": [cell],
            "BatchGate": [x], "BatchCellPreAct": [cell]}


@register("lstmp")
def _lstmp(ctx, ins, attrs):
    """lstmp_op.cc — LSTM with recurrent projection: the [B, P] PROJECTED
    state (not the [B, D] hidden) feeds the next step's gate matmul
    (lstmp_op.h:161-167), so Weight is [P, 4D] and ProjWeight [D, P];
    r_t = proj_act(h_t @ ProjWeight). H0 [B, D] enters through the same
    projection (lstmp_op.h:174-187). Divergence kept deliberately: the
    reference gates on proj_act but then applies cell_act to the
    projection (lstmp_op.h:201-203, an evident typo since both default to
    tanh); we apply proj_act itself.
    """
    x = single(ins, "Input")            # [B, T, 4D]
    w = single(ins, "Weight")           # [P, 4D]
    w_proj = single(ins, "ProjWeight")  # [D, P]
    bias = single(ins, "Bias")          # [1, 4D(+3D)]
    h0 = single(ins, "H0")
    c0 = single(ins, "C0")
    xlen = single(ins, "XLen")
    d = w_proj.shape[0]
    p = w_proj.shape[1]
    b, t, _ = x.shape
    use_peep = attrs.get("use_peepholes", False)
    gact = _lstm_act(attrs.get("gate_activation", "sigmoid"))
    cell_act = _lstm_act(attrs.get("cell_activation", "tanh"))
    cand_act = _lstm_act(attrs.get("candidate_activation", "tanh"))
    pact = _lstm_act(attrs.get("proj_activation", "tanh"))
    is_rev = attrs.get("is_reverse", False)

    if (not use_peep and x.dtype == jnp.float32
            and not getattr(ctx, "amp", False)
            and attrs.get("gate_activation", "sigmoid") == "sigmoid"
            and attrs.get("cell_activation", "tanh") == "tanh"
            and attrs.get("candidate_activation", "tanh") == "tanh"
            and attrs.get("proj_activation", "tanh") == "tanh"
            and _seq_pallas_on("lstm")):
        from . import pallas_kernels as pk
        from .kernel_config import tiles_for
        if h0 is not None:
            r0 = jnp.tanh(h0.astype(jnp.float32) @
                          w_proj.astype(jnp.float32))
        else:
            r0 = jnp.zeros((b, p), jnp.float32)
        proj, cell = pk.fused_lstmp(
            x, w, w_proj, bias.reshape(-1)[:4 * d], r0, c0, xlen,
            reverse=is_rev, block_b=tiles_for("lstm", d)["block_b"])
        return {"Projection": [proj], "Cell": [cell],
                "BatchGate": [x], "BatchCellPreAct": [cell],
                "BatchHidden": [cell], "OrderedP0": [r0.astype(x.dtype)]}

    state_dt, rmat2 = _amp_recurrence(ctx, x.dtype)

    bias = bias.reshape(-1).astype(state_dt)
    gate_bias = bias[:4 * d]
    if use_peep:
        w_ic, w_fc, w_oc = (bias[4 * d:5 * d], bias[5 * d:6 * d],
                            bias[6 * d:7 * d])
    c_prev = c0.astype(state_dt) if c0 is not None \
        else jnp.zeros((b, d), state_dt)
    if h0 is not None:
        r_prev = pact(rmat2(h0.astype(state_dt), w_proj))
    else:
        r_prev = jnp.zeros((b, p), state_dt)

    m = _mask(xlen, t, state_dt)
    xs = jnp.swapaxes(x, 0, 1).astype(state_dt)     # [T, B, 4D]
    ms = m.T[:, :, None]
    if is_rev:
        xs = xs[::-1]
        ms = ms[::-1]

    def step(carry, inp):
        r_prev, c_prev = carry
        xt, mt = inp
        gates = xt + rmat2(r_prev, w) + gate_bias    # [B, 4D]
        # reference weight layout lstm_op.cc:125 "{W_ch, W_ih, W_fh,
        # W_oh}" — CANDIDATE block first (kernel order in, ig, fg, og)
        gc, gi, gf, go = jnp.split(gates, 4, axis=-1)
        if use_peep:
            gi = gi + c_prev * w_ic
            gf = gf + c_prev * w_fc
        i = gact(gi)
        f = gact(gf)
        c_new = f * c_prev + i * cand_act(gc)
        if use_peep:
            go = go + c_new * w_oc
        o = gact(go)
        h_new = o * cell_act(c_new)
        r_new = pact(rmat2(h_new, w_proj))           # [B, P]
        r = mt * r_new + (1 - mt) * r_prev
        c = mt * c_new + (1 - mt) * c_prev
        return (r, c), (r, c)

    _, (rs, cs) = lax.scan(step, (r_prev, c_prev), (xs, ms))
    if is_rev:
        rs, cs = rs[::-1], cs[::-1]
    proj = jnp.swapaxes(rs, 0, 1).astype(x.dtype)   # [B, T, P]
    cell = jnp.swapaxes(cs, 0, 1).astype(x.dtype)
    return {"Projection": [proj], "Cell": [cell],
            "BatchGate": [x], "BatchCellPreAct": [cell],
            "BatchHidden": [cell], "OrderedP0": [r_prev]}


@register("gru")
def _gru(ctx, ins, attrs):
    """dynamic_gru: input [B, T, 3D] pre-projected, weight packed
    [D, 3D] = [update|reset (2D) ; candidate (D)] as in gru_op.cc.
    """
    x = single(ins, "Input")     # [B, T, 3D]
    w = single(ins, "Weight")    # [D, 3D]
    bias = single(ins, "Bias")   # [1, 3D]
    h0 = single(ins, "H0")
    xlen = single(ins, "XLen")
    d = w.shape[0]
    b, t, _ = x.shape
    gact = _lstm_act(attrs.get("gate_activation", "sigmoid"))
    cact = _lstm_act(attrs.get("activation", "tanh"))
    is_rev = attrs.get("is_reverse", False)

    state_dt, rmat = _amp_recurrence(ctx, x.dtype)

    w_g = w[:, :2 * d]      # update+reset recurrent weights
    w_c = w[:, 2 * d:]      # candidate recurrent weights
    bias = bias.reshape(-1).astype(state_dt) if bias is not None \
        else jnp.zeros(3 * d, state_dt)
    h_prev = h0.astype(state_dt) if h0 is not None \
        else jnp.zeros((b, d), state_dt)

    m = _mask(xlen, t, state_dt)
    xs = jnp.swapaxes(x, 0, 1).astype(state_dt)
    ms = m.T[:, :, None]
    if is_rev:
        xs = xs[::-1]
        ms = ms[::-1]

    def step(h_prev, inp):
        xt, mt = inp
        xu = xt[:, :2 * d] + rmat(h_prev, w_g) + bias[:2 * d]
        u, r = jnp.split(gact(xu), 2, axis=-1)
        c = cact(xt[:, 2 * d:] + rmat(r * h_prev, w_c) + bias[2 * d:])
        # reference gru convention (gru_kernel.h / test_gru_op.py:71):
        # the update gate weights the CANDIDATE, not the carried state
        h_new = u * c + (1 - u) * h_prev
        h = mt * h_new + (1 - mt) * h_prev
        return h, h

    hT, hs = lax.scan(step, h_prev, (xs, ms))
    if is_rev:
        hs = hs[::-1]
    hidden = jnp.swapaxes(hs, 0, 1).astype(x.dtype)
    return {"Hidden": [hidden], "BatchGate": [x],
            "BatchResetHiddenPrev": [hidden], "BatchHidden": [hidden]}


@register("gru_unit")
def _gru_unit(ctx, ins, attrs):
    """Single GRU step (reference: gru_unit_op) — used inside DynamicRNN."""
    x = single(ins, "Input")        # [B, 3D]
    h_prev = single(ins, "HiddenPrev")
    w = single(ins, "Weight")       # [D, 3D]
    bias = single(ins, "Bias")
    d = w.shape[0]
    gact = _lstm_act({1: "sigmoid", 0: "identity", 2: "tanh",
                      3: "relu"}.get(attrs.get("gate_activation", 1),
                                     "sigmoid")
                     if isinstance(attrs.get("gate_activation", 1), int)
                     else attrs.get("gate_activation", "sigmoid"))
    cact = _lstm_act({1: "sigmoid", 0: "identity", 2: "tanh",
                      3: "relu"}.get(attrs.get("activation", 2), "tanh")
                     if isinstance(attrs.get("activation", 2), int)
                     else attrs.get("activation", "tanh"))
    if bias is not None:
        x = x + bias.reshape(-1)
    xu = x[:, :2 * d] + h_prev @ w[:, :2 * d]
    u, r = jnp.split(gact(xu), 2, axis=-1)
    c = cact(x[:, 2 * d:] + (r * h_prev) @ w[:, 2 * d:])
    h = u * c + (1 - u) * h_prev   # gru_unit_op: u weights the candidate
    return {"Hidden": [h], "Gate": [xu], "ResetHiddenPrev": [r * h_prev]}


@register("lstm_unit")
def _lstm_unit(ctx, ins, attrs):
    """Single LSTM step (reference: lstm_unit_op): X [B, 4D] pre-gates."""
    x = single(ins, "X")
    c_prev = single(ins, "C_prev")
    forget_bias = attrs.get("forget_bias", 0.0)
    # reference lstm_unit_op.h packs gates i, f, o, j — candidate LAST
    # (unlike lstm_op's candidate-FIRST {W_ch, W_ih, W_fh, W_oh}) —
    # order matters for loaded weights
    gi, gf, go, gj = jnp.split(x, 4, axis=-1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + forget_bias)
    o = jax.nn.sigmoid(go)
    c = f * c_prev + i * jnp.tanh(gj)
    h = o * jnp.tanh(c)
    return {"C": [c], "H": [h]}


@register("sequence_cache_write")
def _sequence_cache_write(ctx, ins, attrs):
    """Per-row timestep write into a [B, T, ...] cache (TPU-native
    addition): Out[b, Pos[b]] = X[b], every other cell bit-identical to
    Cache.  The KV-cache building block for decode-step programs —
    Cache and Pos are persistable slot state under serving.DecodeEngine,
    so the executor's donation machinery keeps the whole cache
    device-resident and this lowers to one in-place scatter row write
    per step, never a host round-trip or a full-cache copy.  Row b's
    output depends only on row b of every input — the property the
    decode batcher's slot-reuse invariant (ARCHITECTURE §27) leans on."""
    cache = single(ins, "Cache")                      # [B, T, ...]
    x = single(ins, "X")                              # [B, ...]
    pos = single(ins, "Pos").astype(jnp.int32).reshape(-1)   # [B]
    b = cache.shape[0]
    out = cache.at[jnp.arange(b), pos].set(jnp.asarray(x, cache.dtype))
    return {"Out": [out]}


@register("sequence_mask")
def _sequence_mask(ctx, ins, attrs):
    """lengths [N] -> [N, maxlen] mask. Parity: sequence_mask_op.h."""
    x = single(ins, "X").astype(jnp.int32)
    ref = single(ins, "MaxLenRef")
    maxlen = ref.shape[1] if ref is not None else int(attrs["maxlen"])
    t = jnp.arange(maxlen, dtype=jnp.int32)
    mask = (t[None, :] < x[:, None])
    return {"Y": [mask.astype(np.dtype(attrs.get("out_dtype", "int64")))]}
