"""CTC family: warpctc loss, ctc_align (greedy-decode merge), edit_distance,
sequence_erase.

Parity: paddle/fluid/operators/{warpctc_op,ctc_align_op,edit_distance_op,
sequence_erase_op}.{h,cc,cu}. The reference offloads the CTC loss to the
warp-ctc CUDA library and walks sequences host-side for align/erase/edit
distance; here each is a batched XLA computation over the padded-dense
layout:

- warpctc: log-space alpha recursion over the 2U+1 extended label states,
  one lax.scan over time for the whole batch (warp-ctc's softmax is
  included: input is unnormalized logits). Gradient falls out of jax.vjp
  of the scan, replacing the library's hand-computed WarpCTCGrad.
- edit_distance: Levenshtein DP, scanned over hypothesis positions with
  the insertion recurrence closed into a cumulative min (d[i][j] =
  min_k<=j(cand[k] + j - k) = cummin(cand[k]-k)+j), so the inner loop is
  a vector op, not a scan.
- ctc_align / sequence_erase: keep-mask + stable-argsort compaction
  (kept tokens move to the front, new lengths = mask sum).
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import (register, single, int_dtype as _i64,
                             squeeze_label as _squeeze2d)

_NEG = -1e30


@register("warpctc")
def _warpctc(ctx, ins, attrs):
    logits = single(ins, "Logits")                  # [B, T, C]
    label = _squeeze2d(single(ins, "Label"))  # [B, U] int32
    xlen = single(ins, "XLen").astype(jnp.int32)    # [B]
    llen = single(ins, "LabelLen").astype(jnp.int32)
    blank = int(attrs.get("blank", 0))
    norm_by_times = bool(attrs.get("norm_by_times", False))

    b_, t_, c = logits.shape
    u = label.shape[1]
    s = 2 * u + 1
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

    # extended label sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.full((b_, s), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(label)
    # skip transition s-2 -> s allowed for non-blank states with
    # ext[s] != ext[s-2]
    skip_ok = jnp.zeros((b_, s), bool)
    if u > 1:
        skip_ok = skip_ok.at[:, 3::2].set(label[:, 1:] != label[:, :-1])
    # states beyond 2*llen never feed the final selection (transitions only
    # move forward), so padded label content is harmless.

    lp_ext = jnp.take_along_axis(
        lp, jnp.broadcast_to(ext[:, None, :], (b_, t_, s)), axis=2)

    alpha0 = jnp.full((b_, s), _NEG)
    alpha0 = alpha0.at[:, 0].set(lp_ext[:, 0, 0])
    if s > 1:
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(llen > 0, lp_ext[:, 0, 1], _NEG))

    def shift(a, k):
        return jnp.concatenate(
            [jnp.full((b_, k), _NEG, a.dtype), a[:, :-k]], axis=1)

    def step(alpha, inp):
        lp_t, valid = inp                            # [B, S], [B]
        stay = alpha
        diag = shift(alpha, 1)
        skip = jnp.where(skip_ok, shift(alpha, 2), _NEG)
        m = jnp.maximum(jnp.maximum(stay, diag), skip)
        tot = m + jnp.log(jnp.exp(stay - m) + jnp.exp(diag - m) +
                          jnp.exp(skip - m))
        new = tot + lp_t
        return jnp.where(valid[:, None], new, alpha), None

    if t_ > 1:
        tmask = (jnp.arange(1, t_, dtype=jnp.int32)[:, None] <
                 xlen[None, :])                      # [T-1, B]
        alpha, _ = lax.scan(step, alpha0,
                            (jnp.moveaxis(lp_ext[:, 1:], 1, 0), tmask))
    else:
        alpha = alpha0

    # final: states 2*llen (trailing blank) and 2*llen-1 (last label)
    f_blank = jnp.take_along_axis(alpha, (2 * llen)[:, None], axis=1)[:, 0]
    lbl_idx = jnp.maximum(2 * llen - 1, 0)
    f_label = jnp.where(
        llen > 0,
        jnp.take_along_axis(alpha, lbl_idx[:, None], axis=1)[:, 0], _NEG)
    m = jnp.maximum(f_blank, f_label)
    ll = m + jnp.log(jnp.exp(f_blank - m) + jnp.exp(f_label - m))
    loss = -ll
    if norm_by_times:
        # reference semantics (warpctc_op.h WarpCTCGradKernel): the LOSS
        # value stays raw; only the gradient is normalized by the number of
        # timesteps. value == loss, d(value) == d(loss)/T:
        t_norm = jnp.maximum(xlen, 1).astype(loss.dtype)
        scaled = loss / t_norm
        loss = lax.stop_gradient(loss - scaled) + scaled
    loss = loss[:, None].astype(logits.dtype)
    return {"Loss": [loss], "WarpCTCGrad": [jnp.zeros_like(logits)]}


def _compact(x, keep, pad_value=0):
    """Move kept tokens to the front of each row, pad the rest."""
    order = jnp.argsort(~keep, axis=1, stable=True)
    out = jnp.take_along_axis(x, order, axis=1)
    kept = jnp.take_along_axis(keep, order, axis=1)
    new_len = jnp.sum(keep, axis=1).astype(jnp.int32)
    return jnp.where(kept, out, pad_value), new_len


@register("ctc_align")
def _ctc_align(ctx, ins, attrs):
    x = _squeeze2d(single(ins, "Input"))  # [B, T] int32
    xlen = single(ins, "XLen").astype(jnp.int32)
    blank = int(attrs.get("blank", 0))
    merge = bool(attrs.get("merge_repeated", True))
    b_, t_ = x.shape
    valid = (jnp.arange(t_, dtype=jnp.int32)[None, :] < xlen[:, None])
    prev = jnp.concatenate([jnp.full((b_, 1), -1, x.dtype), x[:, :-1]],
                           axis=1)
    keep = (x != blank) & valid
    if merge:
        keep &= (x != prev)
    out, new_len = _compact(x, keep)
    return {"Output": [out.astype(_i64())], "OutLen": [new_len]}


@register("sequence_erase")
def _sequence_erase(ctx, ins, attrs):
    x = _squeeze2d(single(ins, "X"))
    xlen = single(ins, "XLen").astype(jnp.int32)
    tokens = list(attrs.get("tokens", []) or [])
    b_, t_ = x.shape
    valid = (jnp.arange(t_, dtype=jnp.int32)[None, :] < xlen[:, None])
    keep = valid
    for tok in tokens:
        keep &= (x != int(tok))
    out, new_len = _compact(x, keep)
    return {"Out": [out.astype(_i64())], "OutLen": [new_len]}


@register("edit_distance")
def _edit_distance(ctx, ins, attrs):
    hyp = _squeeze2d(single(ins, "Hyps"))   # [B, U1] int32
    ref = _squeeze2d(single(ins, "Refs"))   # [B, U2] int32
    hlen = single(ins, "HypsLen").astype(jnp.int32)
    rlen = single(ins, "RefsLen").astype(jnp.int32)
    normalized = bool(attrs.get("normalized", True))
    b_, u1 = hyp.shape
    u2 = ref.shape[1]

    jcol = jnp.arange(u2 + 1, dtype=jnp.float32)[None, :]     # [1, U2+1]
    row0 = jnp.broadcast_to(jcol, (b_, u2 + 1))               # d[0][j] = j

    def step(prev, hyp_i):
        # prev: d[i-1][*] [B, U2+1]; hyp_i: [B]
        cost = (hyp_i[:, None] != ref).astype(jnp.float32)    # [B, U2]
        # substitute/match (diagonal) vs delete-from-hyp (above)
        cand = jnp.minimum(prev[:, :-1] + cost, prev[:, 1:] + 1.0)
        cand = jnp.concatenate([prev[:, :1] + 1.0, cand], axis=1)
        # insertions: row[j] = min_{k<=j}(cand[k] + j - k)
        row = lax.cummin(cand - jcol, axis=1) + jcol
        return row, row

    if u1 > 0:
        _, rows = lax.scan(step, row0, jnp.moveaxis(hyp, 1, 0))
        table = jnp.concatenate([row0[None], rows], axis=0)   # [U1+1, B, U2+1]
    else:
        table = row0[None]
    # pick d[hlen][rlen] per row
    d_h = jnp.take_along_axis(
        jnp.moveaxis(table, 0, 1),                            # [B, U1+1, U2+1]
        hlen[:, None, None].astype(jnp.int32), axis=1)[:, 0]  # [B, U2+1]
    dist = jnp.take_along_axis(d_h, rlen[:, None], axis=1)[:, 0]
    if normalized:
        dist = dist / jnp.maximum(rlen, 1).astype(dist.dtype)
    seq_num = jnp.asarray([b_], _i64())
    return {"Out": [dist[:, None].astype(jnp.float32)],
            "SequenceNum": [seq_num]}
