"""Pallas TPU kernels for the fused hot ops (SURVEY.md §3: "pallas reserved
for fused softmax-xent, LN, and flash/ring attention").

flash_attention — blockwise online-softmax attention. The [T, T] score
matrix never hits HBM: each q-block holds running (max, denom, acc) in VMEM
while k/v blocks stream past, so peak memory is O(T·D) instead of O(T²) and
the two matmuls per block ride the MXU back to back. Backward is the
standard flash recompute from the saved logsumexp, also as pallas kernels
(a dK/dV kernel over k-blocks + a dQ kernel over q-blocks, both with
causal block skipping), differentiable via custom_vjp.

softmax_xent — fused log-softmax + label pick over the vocab dim: one VMEM
pass computes the loss and the logsumexp residual; the probability matrix is
only formed in the backward (where it is the gradient anyway).

Both run as real pallas kernels on TPU and fall back to interpret mode on
CPU (the unit tests exercise the same kernel code path everywhere).

Parity note: the reference has no fused attention (its transformer builds
q@k^T + softmax + @v from separate ops, paddle/fluid/operators/matmul_op.cc
+ softmax_op.cc); these kernels are the TPU-native upgrade path behind the
same layer APIs.
"""
import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
try:
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover - pallas tpu backend unavailable
    pltpu = None
    _VMEM = None

__all__ = ["flash_attention", "softmax_xent", "layer_norm",
           "attention_available"]

_NEG = -1e30


def _interpret_default():
    return jax.default_backend() != "tpu"


def attention_available():
    return pltpu is not None


def _vmem_spec(*args, **kwargs):
    if _VMEM is not None:
        kwargs.setdefault("memory_space", _VMEM)
    return pl.BlockSpec(*args, **kwargs)


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, len_ref, o_ref, lse_ref, *,
                      scale, causal, block_q, block_k, t_pad):
    qb = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale                 # [bq, d]
    bq, d = q.shape
    qpos = qb * block_q + lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    # whole [BH, 1] array lives in SMEM (a (1,1)-blocked spec violates
    # Mosaic's (8,128) block rule — caught on first real-TPU run, round 4)
    kv_len = len_ref[pl.program_id(0), 0]                    # this row's T

    nk = t_pad // block_k
    if causal:
        # only k blocks up to this q block's causal frontier do any work —
        # skipping the rest halves the attention FLOPs for causal decode
        nk_dyn = jnp.minimum(nk, ((qb + 1) * block_q + block_k - 1)
                             // block_k)
    else:
        nk_dyn = nk
    # key-padding early exit: blocks entirely past this row's length
    nk_dyn = jnp.minimum(nk_dyn, (kv_len + block_k - 1) // block_k)

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
        kpos = kb * block_k + lax.broadcasted_iota(jnp.int32, (1, block_k),
                                                   1)
        valid = kpos < kv_len
        if causal:
            valid = valid & (qpos >= kpos)
        s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(valid, p, 0.0)                         # masked -> 0
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(p, v,
                                   preferred_element_type=jnp.float32)
        return m_new, l, acc

    m, l, acc = lax.fori_loop(
        0, nk_dyn, body,
        (jnp.full((bq, 1), _NEG, jnp.float32),
         jnp.zeros((bq, 1), jnp.float32),
         jnp.zeros((bq, d), jnp.float32)))

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)                         # [bq, 1]


def _flash_fwd(q, k, v, kv_len, scale, causal, block_q, block_k, interpret):
    """q,k,v: [BH, T, D]; kv_len: [BH] int32 (true key length per row)
    -> (out [BH, T, D], lse [BH, T])."""
    bh, t, d = q.shape
    # pad T so BOTH the q grid and the k loop divide exactly (mismatched
    # block sizes otherwise drop tail k blocks / leave q rows unwritten)
    blk = int(np.lcm(block_q, block_k))
    t_pad = int(-(-t // blk) * blk)
    if t_pad != t:
        pad = [(0, 0), (0, t_pad - t), (0, 0)]
        q, k, v = (jnp.pad(a, pad) for a in (q, k, v))
    lens = kv_len.reshape(bh, 1).astype(jnp.int32)
    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, t_pad=t_pad)
    # lens: whole array in SMEM (no blocking); lse: [BH, T, 1] so the
    # block's trailing dims are (block_q, 1) — Mosaic requires last-two
    # block dims divisible by (8, 128) or equal to the array's
    smem = {} if pltpu is None else {"memory_space": pltpu.SMEM}
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, t_pad // block_q),
        in_specs=[
            _vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),
            _vmem_spec((1, t_pad, d), lambda b, i: (b, 0, 0)),
            _vmem_spec((1, t_pad, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec(**smem),
        ],
        out_specs=[
            _vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),
            _vmem_spec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_pad, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t_pad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, lens)
    return out[:, :t], lse[:, :t, 0]


def _flash_bwd_dkdv_kernel(q_ref, g_ref, k_ref, v_ref, lse_ref, delta_ref,
                           len_ref, dk_ref, dv_ref, *, scale, causal,
                           block_q, block_k, t_pad):
    """One k-block's dK/dV: stream q-blocks past it, starting at the
    causal frontier (q blocks strictly before this k block contribute
    nothing — the same 2x FLOP skip the forward kernel does)."""
    kb = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                     # [bk, d]
    v = v_ref[0].astype(jnp.float32)
    bk, d = k.shape
    kpos = kb * block_k + lax.broadcasted_iota(jnp.int32, (1, bk), 1)
    kv_len = len_ref[pl.program_id(0), 0]
    nq = t_pad // block_q
    qb0 = (kb * block_k) // block_q if causal else 0
    # key-padding early exit (mirror of the forward's): a k block entirely
    # past this row's length contributes nothing — skip its q loop
    qb0 = jnp.where(kb * block_k >= kv_len, nq, qb0)

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        g = g_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * block_q, block_q), :]     # [bq, 1] f32
        delta = delta_ref[0, pl.ds(qb * block_q, block_q), :]
        qpos = qb * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        valid = kpos < kv_len
        if causal:
            valid = valid & (qpos >= kpos)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)           # [bq, bk]
        dv = dv + jnp.dot(p.T, g, preferred_element_type=jnp.float32)
        dp = jnp.dot(g, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = lax.fori_loop(qb0, nq, body,
                           (jnp.zeros((bk, d), jnp.float32),
                            jnp.zeros((bk, d), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, g_ref, k_ref, v_ref, lse_ref, delta_ref,
                         len_ref, dq_ref, *, scale, causal, block_q,
                         block_k, t_pad):
    """One q-block's dQ: stream k-blocks up to the causal / key-length
    frontier (mirror of the forward loop)."""
    qb = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                     # [bq, d]
    g = g_ref[0].astype(jnp.float32)
    lse = lse_ref[0]                                     # [bq, 1] f32
    delta = delta_ref[0]
    bq, d = q.shape
    qpos = qb * block_q + lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    kv_len = len_ref[pl.program_id(0), 0]
    nk = t_pad // block_k
    if causal:
        nk_dyn = jnp.minimum(nk, ((qb + 1) * block_q + block_k - 1)
                             // block_k)
    else:
        nk_dyn = nk
    nk_dyn = jnp.minimum(nk_dyn, (kv_len + block_k - 1) // block_k)

    def body(kb, dq):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        kpos = kb * block_k + lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        valid = kpos < kv_len
        if causal:
            valid = valid & (qpos >= kpos)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)
        dp = jnp.dot(g, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq = lax.fori_loop(0, nk_dyn, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    """Flash backward as two pallas kernels (standard flash-attention
    recompute from the saved logsumexp — the [T, T] matrix never exists):
    a dK/dV kernel gridded over k-blocks and a dQ kernel gridded over
    q-blocks, both with causal block skipping. Replaces the r4 plain-lax
    scan, which the microbench measured at 0.75x XLA's dense backward
    (no causal skip, no VMEM residency control).

    VMEM budget (ADVICE r4 #3): each kernel pins one full [t_pad, d]
    operand pair in VMEM per grid step (q+g for dK/dV, k+v for dQ) —
    2*t_pad*d*2B bf16 ≈ 0.5 MB at t=2048, d=64, comfortably inside the
    ~16 MB/core budget up to t≈32k. Streaming that pair through a second
    grid axis (double-buffered) is the follow-up if longer single-core
    sequences are ever benched; ring/Ulysses SP is the intended path for
    those lengths (parallel/ring_attention.py)."""
    q, k, v, kv_len, out, lse = res
    bh, t, d = q.shape
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                               # [BH, T]
    blk = int(np.lcm(block_q, block_k))
    t_pad = int(-(-t // blk) * blk)
    if t_pad != t:
        pad3 = [(0, 0), (0, t_pad - t), (0, 0)]
        q, k, v, g = (jnp.pad(a, pad3) for a in (q, k, v, g))
        lse = jnp.pad(lse, [(0, 0), (0, t_pad - t)])
        delta = jnp.pad(delta, [(0, 0), (0, t_pad - t)])
    lse3 = lse[..., None].astype(jnp.float32)
    delta3 = delta[..., None].astype(jnp.float32)
    lens = kv_len.reshape(bh, 1).astype(jnp.int32)
    smem = {} if pltpu is None else {"memory_space": pltpu.SMEM}

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkdv_kernel, scale=scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          t_pad=t_pad),
        grid=(bh, t_pad // block_k),
        in_specs=[
            _vmem_spec((1, t_pad, d), lambda b, j: (b, 0, 0)),     # q
            _vmem_spec((1, t_pad, d), lambda b, j: (b, 0, 0)),     # g
            _vmem_spec((1, block_k, d), lambda b, j: (b, j, 0)),   # k
            _vmem_spec((1, block_k, d), lambda b, j: (b, j, 0)),   # v
            _vmem_spec((1, t_pad, 1), lambda b, j: (b, 0, 0)),     # lse
            _vmem_spec((1, t_pad, 1), lambda b, j: (b, 0, 0)),     # delta
            pl.BlockSpec(**smem),
        ],
        out_specs=[
            _vmem_spec((1, block_k, d), lambda b, j: (b, j, 0)),
            _vmem_spec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_pad, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t_pad, d), v.dtype),
        ],
        interpret=interpret,
    )(q, g, k, v, lse3, delta3, lens)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, t_pad=t_pad),
        grid=(bh, t_pad // block_q),
        in_specs=[
            _vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),   # q
            _vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),   # g
            _vmem_spec((1, t_pad, d), lambda b, i: (b, 0, 0)),     # k
            _vmem_spec((1, t_pad, d), lambda b, i: (b, 0, 0)),     # v
            _vmem_spec((1, block_q, 1), lambda b, i: (b, i, 0)),   # lse
            _vmem_spec((1, block_q, 1), lambda b, i: (b, i, 0)),   # delta
            pl.BlockSpec(**smem),
        ],
        out_specs=_vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t_pad, d), q.dtype),
        interpret=interpret,
    )(q, g, k, v, lse3, delta3, lens)
    return dq[:, :t], dk[:, :t], dv[:, :t]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_core(q, k, v, kv_len, scale, causal, block_q, block_k,
                interpret):
    out, _ = _flash_fwd(q, k, v, kv_len, scale, causal, block_q, block_k,
                        interpret)
    return out


def _flash_core_fwd(q, k, v, kv_len, scale, causal, block_q, block_k,
                    interpret):
    out, lse = _flash_fwd(q, k, v, kv_len, scale, causal, block_q, block_k,
                          interpret)
    return out, (q, k, v, kv_len, out, lse)


def _flash_core_bwd(scale, causal, block_q, block_k, interpret, res, g):
    dq, dk, dv = _flash_bwd(scale, causal, block_q, block_k, interpret,
                            res, g)
    return dq, dk, dv, None


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q, k, v, causal=False, scale=None, kv_len=None,
                    block_q=128, block_k=128, interpret=None):
    """Exact attention, flash-style. q,k,v: [B, T, H, D] (BTHD, the layout
    ring_attention uses); returns [B, T, H, D].

    kv_len: optional [B] int true key lengths — keys at position >= kv_len
    are masked out AND their blocks skipped entirely (the padded-batch
    regime every fluid sequence model runs in). Differentiable; matches
    attention_reference to fp32 tolerance. On TPU the forward runs as a
    pallas kernel (online softmax in VMEM); off-TPU it runs the same
    kernel in interpret mode.
    """
    if interpret is None:
        interpret = _interpret_default()
    b, t, h, d = q.shape
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    block_q = max(8, min(block_q, int(-(-t // 8) * 8)))
    block_k = max(8, min(block_k, int(-(-t // 8) * 8)))
    if kv_len is None:
        lens = jnp.full((b * h,), t, jnp.int32)
    else:
        lens = jnp.repeat(jnp.asarray(kv_len, jnp.int32).reshape(b), h)

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    out = _flash_core(to_bh(q), to_bh(k), to_bh(v), lens, float(scale),
                      bool(causal), int(block_q), int(block_k),
                      bool(interpret))
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# fused softmax + cross-entropy
# ---------------------------------------------------------------------------

def _xent_kernel(logits_ref, labels_ref, loss_ref, lse_ref):
    x = logits_ref[:].astype(jnp.float32)                    # [bn, V]
    lab = labels_ref[:]                                      # [bn, 1] int32
    m = jnp.max(x, axis=-1, keepdims=True)
    lse = m + jnp.log(jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True))
    cols = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    picked = jnp.sum(jnp.where(cols == lab, x, 0.0), axis=-1,
                     keepdims=True)
    loss_ref[:] = lse - picked
    lse_ref[:] = lse


def _xent_fwd_call(logits, labels, block_n, interpret):
    n, v = logits.shape
    n_pad = int(-(-n // block_n) * block_n)
    lp = jnp.pad(logits, [(0, n_pad - n), (0, 0)]) if n_pad != n else logits
    lb = labels.reshape(-1, 1).astype(jnp.int32)
    lb = jnp.pad(lb, [(0, n_pad - n), (0, 0)]) if n_pad != n else lb
    loss, lse = pl.pallas_call(
        _xent_kernel,
        grid=(n_pad // block_n,),
        in_specs=[
            _vmem_spec((block_n, v), lambda i: (i, 0)),
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(lp, lb)
    return loss[:n], lse[:n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _xent_core(logits, labels, block_n, interpret):
    loss, _ = _xent_fwd_call(logits, labels, block_n, interpret)
    return loss


def _xent_core_fwd(logits, labels, block_n, interpret):
    loss, lse = _xent_fwd_call(logits, labels, block_n, interpret)
    return loss, (logits, labels, lse)


def _xent_core_bwd(block_n, interpret, res, g):
    logits, labels, lse = res
    p = jnp.exp(logits.astype(jnp.float32) - lse)            # softmax
    onehot = jax.nn.one_hot(labels.reshape(-1), logits.shape[-1],
                            dtype=jnp.float32)
    dlogits = (p - onehot) * g.reshape(-1, 1)
    return dlogits.astype(logits.dtype), None


_xent_core.defvjp(_xent_core_fwd, _xent_core_bwd)


def softmax_xent(logits, labels, block_n=8, interpret=None):
    """Fused log-softmax + NLL. logits [N, V], labels [N] (or [N,1]) int.
    Returns loss [N, 1] float32. Differentiable (custom_vjp)."""
    if interpret is None:
        interpret = _interpret_default()
    return _xent_core(logits, labels.reshape(-1), int(block_n),
                      bool(interpret))


# ---------------------------------------------------------------------------
# fused layer norm
# ---------------------------------------------------------------------------

def _ln_kernel(x_ref, scale_ref, bias_ref, y_ref, mean_ref, rstd_ref, *,
               eps):
    x = x_ref[:].astype(jnp.float32)                         # [bn, D]
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    y = (x - mu) * rstd * scale_ref[:].astype(jnp.float32) \
        + bias_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mu
    rstd_ref[:] = rstd


def _ln_fwd_call(x, scale, bias, eps, block_n, interpret):
    n, d = x.shape
    n_pad = int(-(-n // block_n) * block_n)
    xp = jnp.pad(x, [(0, n_pad - n), (0, 0)]) if n_pad != n else x
    y, mean, rstd = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=(n_pad // block_n,),
        in_specs=[
            _vmem_spec((block_n, d), lambda i: (i, 0)),
            _vmem_spec((1, d), lambda i: (0, 0)),
            _vmem_spec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            _vmem_spec((block_n, d), lambda i: (i, 0)),
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, d), x.dtype),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(xp, scale.reshape(1, d), bias.reshape(1, d))
    return y[:n], mean[:n], rstd[:n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ln_core(x, scale, bias, eps, block_n, interpret):
    y, _, _ = _ln_fwd_call(x, scale, bias, eps, block_n, interpret)
    return y


def _ln_core_fwd(x, scale, bias, eps, block_n, interpret):
    y, mean, rstd = _ln_fwd_call(x, scale, bias, eps, block_n, interpret)
    # residuals must be jax values: a 0-size sentinel carries bias's dtype
    return y, (x, scale, jnp.zeros((0,), bias.dtype), mean, rstd)


def _ln_core_bwd(eps, block_n, interpret, res, g):
    x, scale, bias_like, mean, rstd = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    xhat = (xf - mean) * rstd                                # [N, D]
    gs = gf * scale.reshape(1, -1).astype(jnp.float32)
    dx = rstd * (gs - jnp.mean(gs, axis=-1, keepdims=True)
                 - xhat * jnp.mean(gs * xhat, axis=-1, keepdims=True))
    dscale = jnp.sum(gf * xhat, axis=0)
    dbias = jnp.sum(gf, axis=0)
    return (dx.astype(x.dtype), dscale.astype(scale.dtype),
            dbias.astype(bias_like.dtype))


_ln_core.defvjp(_ln_core_fwd, _ln_core_bwd)


def layer_norm(x, scale, bias, eps=1e-5, block_n=8, interpret=None):
    """Fused layer norm over the trailing dim of 2D x [N, D]; one VMEM pass
    computes y + the (mean, rstd) backward residuals. Differentiable
    (custom_vjp; dense backward — the fwd is the HBM-bound pass worth
    fusing). Returns (y, mean [N], variance [N]) matching the layer_norm
    op's output contract; the fetchable mean/variance are plain reductions
    XLA DCEs when (as usual) nothing consumes them."""
    if interpret is None:
        interpret = _interpret_default()
    y = _ln_core(x, scale, bias, float(eps), int(block_n), bool(interpret))
    xf = x.astype(jnp.float32)
    return y, jnp.mean(xf, axis=-1), jnp.var(xf, axis=-1)
