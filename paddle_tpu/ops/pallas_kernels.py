"""Pallas TPU kernels for the fused hot ops (SURVEY.md §3: "pallas reserved
for fused softmax-xent, LN, and flash/ring attention").

flash_attention — blockwise online-softmax attention. The [T, T] score
matrix never hits HBM: each q-block holds running (max, denom, acc) in VMEM
while k/v blocks stream past, so peak memory is O(T·D) instead of O(T²) and
the two matmuls per block ride the MXU back to back. Backward is the
standard flash recompute from the saved logsumexp, also as pallas kernels
(a dK/dV kernel over k-blocks + a dQ kernel over q-blocks, both with
causal block skipping), differentiable via custom_vjp.

softmax_xent — fused log-softmax + label pick over the vocab dim: one VMEM
pass computes the loss and the logsumexp residual; the probability matrix is
only formed in the backward (where it is the gradient anyway).

Both run as real pallas kernels on TPU and fall back to interpret mode on
CPU (the unit tests exercise the same kernel code path everywhere).

Parity note: the reference has no fused attention (its transformer builds
q@k^T + softmax + @v from separate ops, paddle/fluid/operators/matmul_op.cc
+ softmax_op.cc); these kernels are the TPU-native upgrade path behind the
same layer APIs.
"""
import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
try:
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover - pallas tpu backend unavailable
    pltpu = None
    _VMEM = None

__all__ = ["flash_attention", "softmax_xent", "layer_norm",
           "fused_lstm", "fused_lstmp", "masked_softmax", "masked_pool",
           "attention_available"]

_NEG = -1e30


def _interpret_default():
    return jax.default_backend() != "tpu"


def attention_available():
    return pltpu is not None


def _vmem_spec(*args, **kwargs):
    if _VMEM is not None:
        kwargs.setdefault("memory_space", _VMEM)
    return pl.BlockSpec(*args, **kwargs)


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, len_ref, o_ref, lse_ref, *,
                      scale, causal, block_q, block_k, t_pad):
    qb = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale                 # [bq, d]
    bq, d = q.shape
    qpos = qb * block_q + lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    # whole [BH, 1] array lives in SMEM (a (1,1)-blocked spec violates
    # Mosaic's (8,128) block rule — caught on first real-TPU run, round 4)
    kv_len = len_ref[pl.program_id(0), 0]                    # this row's T

    nk = t_pad // block_k
    if causal:
        # only k blocks up to this q block's causal frontier do any work —
        # skipping the rest halves the attention FLOPs for causal decode
        nk_dyn = jnp.minimum(nk, ((qb + 1) * block_q + block_k - 1)
                             // block_k)
    else:
        nk_dyn = nk
    # key-padding early exit: blocks entirely past this row's length
    nk_dyn = jnp.minimum(nk_dyn, (kv_len + block_k - 1) // block_k)

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
        kpos = kb * block_k + lax.broadcasted_iota(jnp.int32, (1, block_k),
                                                   1)
        valid = kpos < kv_len
        if causal:
            valid = valid & (qpos >= kpos)
        s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(valid, p, 0.0)                         # masked -> 0
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(p, v,
                                   preferred_element_type=jnp.float32)
        return m_new, l, acc

    m, l, acc = lax.fori_loop(
        0, nk_dyn, body,
        (jnp.full((bq, 1), _NEG, jnp.float32),
         jnp.zeros((bq, 1), jnp.float32),
         jnp.zeros((bq, d), jnp.float32)))

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)                         # [bq, 1]


def _flash_fwd(q, k, v, kv_len, scale, causal, block_q, block_k, interpret):
    """q,k,v: [BH, T, D]; kv_len: [BH] int32 (true key length per row)
    -> (out [BH, T, D], lse [BH, T])."""
    bh, t, d = q.shape
    # pad T so BOTH the q grid and the k loop divide exactly (mismatched
    # block sizes otherwise drop tail k blocks / leave q rows unwritten)
    blk = int(np.lcm(block_q, block_k))
    t_pad = int(-(-t // blk) * blk)
    if t_pad != t:
        pad = [(0, 0), (0, t_pad - t), (0, 0)]
        q, k, v = (jnp.pad(a, pad) for a in (q, k, v))
    lens = kv_len.reshape(bh, 1).astype(jnp.int32)
    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, t_pad=t_pad)
    # lens: whole array in SMEM (no blocking); lse: [BH, T, 1] so the
    # block's trailing dims are (block_q, 1) — Mosaic requires last-two
    # block dims divisible by (8, 128) or equal to the array's
    smem = {} if pltpu is None else {"memory_space": pltpu.SMEM}
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, t_pad // block_q),
        in_specs=[
            _vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),
            _vmem_spec((1, t_pad, d), lambda b, i: (b, 0, 0)),
            _vmem_spec((1, t_pad, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec(**smem),
        ],
        out_specs=[
            _vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),
            _vmem_spec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_pad, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t_pad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, lens)
    return out[:, :t], lse[:, :t, 0]


def _flash_bwd_dkdv_kernel(q_ref, g_ref, k_ref, v_ref, lse_ref, delta_ref,
                           len_ref, dk_ref, dv_ref, *, scale, causal,
                           block_q, block_k, t_pad):
    """One k-block's dK/dV: stream q-blocks past it, starting at the
    causal frontier (q blocks strictly before this k block contribute
    nothing — the same 2x FLOP skip the forward kernel does)."""
    kb = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                     # [bk, d]
    v = v_ref[0].astype(jnp.float32)
    bk, d = k.shape
    kpos = kb * block_k + lax.broadcasted_iota(jnp.int32, (1, bk), 1)
    kv_len = len_ref[pl.program_id(0), 0]
    nq = t_pad // block_q
    qb0 = (kb * block_k) // block_q if causal else 0
    # key-padding early exit (mirror of the forward's): a k block entirely
    # past this row's length contributes nothing — skip its q loop
    qb0 = jnp.where(kb * block_k >= kv_len, nq, qb0)

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        g = g_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * block_q, block_q), :]     # [bq, 1] f32
        delta = delta_ref[0, pl.ds(qb * block_q, block_q), :]
        qpos = qb * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        valid = kpos < kv_len
        if causal:
            valid = valid & (qpos >= kpos)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)           # [bq, bk]
        dv = dv + jnp.dot(p.T, g, preferred_element_type=jnp.float32)
        dp = jnp.dot(g, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = lax.fori_loop(qb0, nq, body,
                           (jnp.zeros((bk, d), jnp.float32),
                            jnp.zeros((bk, d), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, g_ref, k_ref, v_ref, lse_ref, delta_ref,
                         len_ref, dq_ref, *, scale, causal, block_q,
                         block_k, t_pad):
    """One q-block's dQ: stream k-blocks up to the causal / key-length
    frontier (mirror of the forward loop)."""
    qb = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                     # [bq, d]
    g = g_ref[0].astype(jnp.float32)
    lse = lse_ref[0]                                     # [bq, 1] f32
    delta = delta_ref[0]
    bq, d = q.shape
    qpos = qb * block_q + lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    kv_len = len_ref[pl.program_id(0), 0]
    nk = t_pad // block_k
    if causal:
        nk_dyn = jnp.minimum(nk, ((qb + 1) * block_q + block_k - 1)
                             // block_k)
    else:
        nk_dyn = nk
    nk_dyn = jnp.minimum(nk_dyn, (kv_len + block_k - 1) // block_k)

    def body(kb, dq):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        kpos = kb * block_k + lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        valid = kpos < kv_len
        if causal:
            valid = valid & (qpos >= kpos)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)
        dp = jnp.dot(g, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq = lax.fori_loop(0, nk_dyn, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    """Flash backward as two pallas kernels (standard flash-attention
    recompute from the saved logsumexp — the [T, T] matrix never exists):
    a dK/dV kernel gridded over k-blocks and a dQ kernel gridded over
    q-blocks, both with causal block skipping. Replaces the r4 plain-lax
    scan, which the microbench measured at 0.75x XLA's dense backward
    (no causal skip, no VMEM residency control).

    VMEM budget (ADVICE r4 #3): each kernel pins one full [t_pad, d]
    operand pair in VMEM per grid step (q+g for dK/dV, k+v for dQ) —
    2*t_pad*d*2B bf16 ≈ 0.5 MB at t=2048, d=64, comfortably inside the
    ~16 MB/core budget up to t≈32k. Streaming that pair through a second
    grid axis (double-buffered) is the follow-up if longer single-core
    sequences are ever benched; ring/Ulysses SP is the intended path for
    those lengths (parallel/ring_attention.py)."""
    q, k, v, kv_len, out, lse = res
    bh, t, d = q.shape
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                               # [BH, T]
    blk = int(np.lcm(block_q, block_k))
    t_pad = int(-(-t // blk) * blk)
    if t_pad != t:
        pad3 = [(0, 0), (0, t_pad - t), (0, 0)]
        q, k, v, g = (jnp.pad(a, pad3) for a in (q, k, v, g))
        lse = jnp.pad(lse, [(0, 0), (0, t_pad - t)])
        delta = jnp.pad(delta, [(0, 0), (0, t_pad - t)])
    lse3 = lse[..., None].astype(jnp.float32)
    delta3 = delta[..., None].astype(jnp.float32)
    lens = kv_len.reshape(bh, 1).astype(jnp.int32)
    smem = {} if pltpu is None else {"memory_space": pltpu.SMEM}

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkdv_kernel, scale=scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          t_pad=t_pad),
        grid=(bh, t_pad // block_k),
        in_specs=[
            _vmem_spec((1, t_pad, d), lambda b, j: (b, 0, 0)),     # q
            _vmem_spec((1, t_pad, d), lambda b, j: (b, 0, 0)),     # g
            _vmem_spec((1, block_k, d), lambda b, j: (b, j, 0)),   # k
            _vmem_spec((1, block_k, d), lambda b, j: (b, j, 0)),   # v
            _vmem_spec((1, t_pad, 1), lambda b, j: (b, 0, 0)),     # lse
            _vmem_spec((1, t_pad, 1), lambda b, j: (b, 0, 0)),     # delta
            pl.BlockSpec(**smem),
        ],
        out_specs=[
            _vmem_spec((1, block_k, d), lambda b, j: (b, j, 0)),
            _vmem_spec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_pad, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t_pad, d), v.dtype),
        ],
        interpret=interpret,
    )(q, g, k, v, lse3, delta3, lens)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, t_pad=t_pad),
        grid=(bh, t_pad // block_q),
        in_specs=[
            _vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),   # q
            _vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),   # g
            _vmem_spec((1, t_pad, d), lambda b, i: (b, 0, 0)),     # k
            _vmem_spec((1, t_pad, d), lambda b, i: (b, 0, 0)),     # v
            _vmem_spec((1, block_q, 1), lambda b, i: (b, i, 0)),   # lse
            _vmem_spec((1, block_q, 1), lambda b, i: (b, i, 0)),   # delta
            pl.BlockSpec(**smem),
        ],
        out_specs=_vmem_spec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t_pad, d), q.dtype),
        interpret=interpret,
    )(q, g, k, v, lse3, delta3, lens)
    return dq[:, :t], dk[:, :t], dv[:, :t]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_core(q, k, v, kv_len, scale, causal, block_q, block_k,
                interpret):
    out, _ = _flash_fwd(q, k, v, kv_len, scale, causal, block_q, block_k,
                        interpret)
    return out


def _flash_core_fwd(q, k, v, kv_len, scale, causal, block_q, block_k,
                    interpret):
    out, lse = _flash_fwd(q, k, v, kv_len, scale, causal, block_q, block_k,
                          interpret)
    return out, (q, k, v, kv_len, out, lse)


def _flash_core_bwd(scale, causal, block_q, block_k, interpret, res, g):
    dq, dk, dv = _flash_bwd(scale, causal, block_q, block_k, interpret,
                            res, g)
    return dq, dk, dv, None


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q, k, v, causal=False, scale=None, kv_len=None,
                    block_q=128, block_k=128, interpret=None):
    """Exact attention, flash-style. q,k,v: [B, T, H, D] (BTHD, the layout
    ring_attention uses); returns [B, T, H, D].

    kv_len: optional [B] int true key lengths — keys at position >= kv_len
    are masked out AND their blocks skipped entirely (the padded-batch
    regime every fluid sequence model runs in). Differentiable; matches
    attention_reference to fp32 tolerance. On TPU the forward runs as a
    pallas kernel (online softmax in VMEM); off-TPU it runs the same
    kernel in interpret mode.
    """
    if interpret is None:
        interpret = _interpret_default()
    b, t, h, d = q.shape
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    block_q = max(8, min(block_q, int(-(-t // 8) * 8)))
    block_k = max(8, min(block_k, int(-(-t // 8) * 8)))
    if kv_len is None:
        lens = jnp.full((b * h,), t, jnp.int32)
    else:
        lens = jnp.repeat(jnp.asarray(kv_len, jnp.int32).reshape(b), h)

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    out = _flash_core(to_bh(q), to_bh(k), to_bh(v), lens, float(scale),
                      bool(causal), int(block_q), int(block_k),
                      bool(interpret))
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# fused softmax + cross-entropy
# ---------------------------------------------------------------------------

def _xent_kernel(logits_ref, labels_ref, loss_ref, lse_ref):
    x = logits_ref[:].astype(jnp.float32)                    # [bn, V]
    lab = labels_ref[:]                                      # [bn, 1] int32
    m = jnp.max(x, axis=-1, keepdims=True)
    lse = m + jnp.log(jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True))
    cols = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    picked = jnp.sum(jnp.where(cols == lab, x, 0.0), axis=-1,
                     keepdims=True)
    loss_ref[:] = lse - picked
    lse_ref[:] = lse


def _xent_fwd_call(logits, labels, block_n, interpret):
    n, v = logits.shape
    n_pad = int(-(-n // block_n) * block_n)
    lp = jnp.pad(logits, [(0, n_pad - n), (0, 0)]) if n_pad != n else logits
    lb = labels.reshape(-1, 1).astype(jnp.int32)
    lb = jnp.pad(lb, [(0, n_pad - n), (0, 0)]) if n_pad != n else lb
    loss, lse = pl.pallas_call(
        _xent_kernel,
        grid=(n_pad // block_n,),
        in_specs=[
            _vmem_spec((block_n, v), lambda i: (i, 0)),
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(lp, lb)
    return loss[:n], lse[:n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _xent_core(logits, labels, block_n, interpret):
    loss, _ = _xent_fwd_call(logits, labels, block_n, interpret)
    return loss


def _xent_core_fwd(logits, labels, block_n, interpret):
    loss, lse = _xent_fwd_call(logits, labels, block_n, interpret)
    return loss, (logits, labels, lse)


def _xent_core_bwd(block_n, interpret, res, g):
    logits, labels, lse = res
    p = jnp.exp(logits.astype(jnp.float32) - lse)            # softmax
    onehot = jax.nn.one_hot(labels.reshape(-1), logits.shape[-1],
                            dtype=jnp.float32)
    dlogits = (p - onehot) * g.reshape(-1, 1)
    return dlogits.astype(logits.dtype), None


_xent_core.defvjp(_xent_core_fwd, _xent_core_bwd)


def softmax_xent(logits, labels, block_n=8, interpret=None):
    """Fused log-softmax + NLL. logits [N, V], labels [N] (or [N,1]) int.
    Returns loss [N, 1] float32. Differentiable (custom_vjp)."""
    if interpret is None:
        interpret = _interpret_default()
    return _xent_core(logits, labels.reshape(-1), int(block_n),
                      bool(interpret))


# ---------------------------------------------------------------------------
# fused layer norm
# ---------------------------------------------------------------------------

def _ln_kernel(x_ref, scale_ref, bias_ref, y_ref, mean_ref, rstd_ref, *,
               eps):
    x = x_ref[:].astype(jnp.float32)                         # [bn, D]
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    y = (x - mu) * rstd * scale_ref[:].astype(jnp.float32) \
        + bias_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mu
    rstd_ref[:] = rstd


def _ln_fwd_call(x, scale, bias, eps, block_n, interpret):
    n, d = x.shape
    n_pad = int(-(-n // block_n) * block_n)
    xp = jnp.pad(x, [(0, n_pad - n), (0, 0)]) if n_pad != n else x
    y, mean, rstd = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=(n_pad // block_n,),
        in_specs=[
            _vmem_spec((block_n, d), lambda i: (i, 0)),
            _vmem_spec((1, d), lambda i: (0, 0)),
            _vmem_spec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            _vmem_spec((block_n, d), lambda i: (i, 0)),
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, d), x.dtype),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(xp, scale.reshape(1, d), bias.reshape(1, d))
    return y[:n], mean[:n], rstd[:n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ln_core(x, scale, bias, eps, block_n, interpret):
    y, _, _ = _ln_fwd_call(x, scale, bias, eps, block_n, interpret)
    return y


def _ln_core_fwd(x, scale, bias, eps, block_n, interpret):
    y, mean, rstd = _ln_fwd_call(x, scale, bias, eps, block_n, interpret)
    # residuals must be jax values: a 0-size sentinel carries bias's dtype
    return y, (x, scale, jnp.zeros((0,), bias.dtype), mean, rstd)


def _ln_core_bwd(eps, block_n, interpret, res, g):
    x, scale, bias_like, mean, rstd = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    xhat = (xf - mean) * rstd                                # [N, D]
    gs = gf * scale.reshape(1, -1).astype(jnp.float32)
    dx = rstd * (gs - jnp.mean(gs, axis=-1, keepdims=True)
                 - xhat * jnp.mean(gs * xhat, axis=-1, keepdims=True))
    dscale = jnp.sum(gf * xhat, axis=0)
    dbias = jnp.sum(gf, axis=0)
    return (dx.astype(x.dtype), dscale.astype(scale.dtype),
            dbias.astype(bias_like.dtype))


_ln_core.defvjp(_ln_core_fwd, _ln_core_bwd)


def _pad_rows(a, rows):
    if a.shape[0] == rows:
        return a
    return jnp.pad(a, [(0, rows - a.shape[0])] + [(0, 0)] * (a.ndim - 1))


def _resolve_block_b(b, block_b):
    """(block, padded_b) for a batch-blocked kernel. block_b=0 (the
    default-table value) = the whole batch in one block; both forms pad
    b up to a multiple of 8 (the f32 sublane tile)."""
    if block_b and int(block_b) > 0:
        blk = max(8, int(block_b))
    else:
        blk = int(-(-b // 8) * 8)
    return blk, int(-(-b // blk) * blk)


# ---------------------------------------------------------------------------
# fused LSTM recurrence (reference: lstm_op.cc / lstmp_op.cc — a host loop
# calling cuBLAS per step; here ONE pallas kernel walks the whole sequence:
# grid (batch-block, T), carried (h, c) state resident in VMEM scratch, the
# four gates + state update one VMEM pass per step, @SEQLEN-masked carries)
# ---------------------------------------------------------------------------

def _lstm_seq_kernel(x_ref, m_ref, w_ref, b_ref, h0_ref, c0_ref,
                     h_out, c_out, h_scr, c_scr, *, d):
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    h_prev = h_scr[:]
    c_prev = c_scr[:]
    xt = x_ref[0].astype(jnp.float32)                       # [bb, 4D]
    gates = xt + jnp.dot(h_prev, w_ref[:],
                         preferred_element_type=jnp.float32) + b_ref[0]
    # reference gate order lstm_op.cc:125 {W_ch, W_ih, W_fh, W_oh}:
    # candidate block FIRST
    z = jnp.tanh(gates[:, :d])
    i = jax.nn.sigmoid(gates[:, d:2 * d])
    f = jax.nn.sigmoid(gates[:, 2 * d:3 * d])
    o = jax.nn.sigmoid(gates[:, 3 * d:])
    c_new = f * c_prev + i * z
    h_new = o * jnp.tanh(c_new)
    mt = m_ref[0]                                           # [bb, 1]
    h = mt * h_new + (1 - mt) * h_prev
    c = mt * c_new + (1 - mt) * c_prev
    h_scr[:] = h
    c_scr[:] = c
    h_out[0] = h.astype(h_out.dtype)
    c_out[0] = c.astype(c_out.dtype)


def _lstm_fwd_call(xs, ms, w, b, h0, c0, block_b, interpret):
    """xs [T, B, 4D] f32, ms [T, B, 1], w [D, 4D], b [4D], h0/c0 [B, D]
    -> (hs, cs) [T, B, D]."""
    if pltpu is None:  # pragma: no cover - VMEM scratch needs the backend
        raise RuntimeError("fused_lstm needs the pallas TPU backend "
                           "(guard dispatch on attention_available())")
    t, bsz, four_d = xs.shape
    d = four_d // 4
    blk, b_pad = _resolve_block_b(bsz, block_b)
    if b_pad != bsz:
        xs = jnp.pad(xs, [(0, 0), (0, b_pad - bsz), (0, 0)])
        ms = jnp.pad(ms, [(0, 0), (0, b_pad - bsz), (0, 0)])
        h0 = _pad_rows(h0, b_pad)
        c0 = _pad_rows(c0, b_pad)
    hs, cs = pl.pallas_call(
        functools.partial(_lstm_seq_kernel, d=d),
        # batch blocks on the MAJOR grid axis: each block walks its
        # full time loop before the next block reuses the state scratch
        grid=(b_pad // blk, t),
        in_specs=[
            _vmem_spec((1, blk, four_d), lambda bb, i: (i, bb, 0)),
            _vmem_spec((1, blk, 1), lambda bb, i: (i, bb, 0)),
            _vmem_spec((d, four_d), lambda bb, i: (0, 0)),
            _vmem_spec((1, four_d), lambda bb, i: (0, 0)),
            _vmem_spec((blk, d), lambda bb, i: (bb, 0)),
            _vmem_spec((blk, d), lambda bb, i: (bb, 0)),
        ],
        out_specs=[
            _vmem_spec((1, blk, d), lambda bb, i: (i, bb, 0)),
            _vmem_spec((1, blk, d), lambda bb, i: (i, bb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((t, b_pad, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk, d), jnp.float32),
            pltpu.VMEM((blk, d), jnp.float32),
        ],
        interpret=interpret,
    )(xs, ms, w, b.reshape(1, -1), h0, c0)
    return hs[:, :bsz], cs[:, :bsz]


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def _lstm_seq_core(xs, ms, w, b, h0, c0, block_b, interpret):
    hs, cs = _lstm_fwd_call(xs, ms, w, b, h0, c0, block_b, interpret)
    return hs, cs


def _lstm_seq_core_fwd(xs, ms, w, b, h0, c0, block_b, interpret):
    hs, cs = _lstm_fwd_call(xs, ms, w, b, h0, c0, block_b, interpret)
    return (hs, cs), (xs, ms, w, b, h0, c0, hs, cs)


def _lstm_seq_core_bwd(block_b, interpret, res, g):
    """Exact reverse-mode through the recurrence from the SAVED states
    (no forward recompute): one reverse scan, each step re-deriving the
    gates from (h_{t-1}, c_{t-1}) with one matmul, then the standard
    LSTM chain rule. Matches jax.grad of the unfused lax.scan path
    (regression-tested)."""
    xs, ms, w, b, h0, c0, hs, cs = res
    ghs, gcs = g
    d = w.shape[0]
    h_prevs = jnp.concatenate([h0[None], hs[:-1]], axis=0)   # [T, B, D]
    c_prevs = jnp.concatenate([c0[None], cs[:-1]], axis=0)

    def step(carry, inp):
        dh_c, dc_c, dw, db = carry
        xt, mt, h_prev, c_prev, gh, gc_out = inp
        dh = dh_c + gh
        dc = dc_c + gc_out
        gates = xt + h_prev @ w + b
        z = jnp.tanh(gates[:, :d])
        i = jax.nn.sigmoid(gates[:, d:2 * d])
        f = jax.nn.sigmoid(gates[:, 2 * d:3 * d])
        o = jax.nn.sigmoid(gates[:, 3 * d:])
        c_new = f * c_prev + i * z
        tc = jnp.tanh(c_new)
        dh_new = dh * mt
        dc_new = dc * mt + dh_new * o * (1 - tc * tc)
        dgo = dh_new * tc * o * (1 - o)
        dgf = dc_new * c_prev * f * (1 - f)
        dgi = dc_new * z * i * (1 - i)
        dgc = dc_new * i * (1 - z * z)
        dg = jnp.concatenate([dgc, dgi, dgf, dgo], axis=-1)  # [B, 4D]
        dw = dw + h_prev.T @ dg
        db = db + jnp.sum(dg, axis=0)
        dh_prev = dg @ w.T + dh * (1 - mt)
        dc_prev = dc_new * f + dc * (1 - mt)
        return (dh_prev, dc_prev, dw, db), dg

    init = (jnp.zeros_like(h0), jnp.zeros_like(c0),
            jnp.zeros_like(w), jnp.zeros_like(b))
    (dh0, dc0, dw, db), dxs = lax.scan(
        step, init, (xs, ms, h_prevs, c_prevs, ghs, gcs), reverse=True)
    return dxs, jnp.zeros_like(ms), dw, db, dh0, dc0


_lstm_seq_core.defvjp(_lstm_seq_core_fwd, _lstm_seq_core_bwd)


def fused_lstm(x, w, gate_bias, h0, c0, xlen, reverse=False, block_b=0,
               interpret=None):
    """Fused-gate dynamic LSTM over the padded-dense layout: x [B, T, 4D]
    (pre-projected gate inputs), w [D, 4D] recurrent weight, gate_bias
    [4D]; returns (hidden, cell) [B, T, D] in x's dtype. Default
    activations only (sigmoid gates, tanh candidate/cell — the
    dispatching op falls back to the lax.scan path otherwise), @SEQLEN
    masking via xlen [B] (padding steps carry state through),
    differentiable (custom_vjp; saved-state reverse scan backward), and
    runs the same kernel in interpret mode off-TPU."""
    if interpret is None:
        interpret = _interpret_default()
    b, t, four_d = x.shape
    d = four_d // 4
    xs = jnp.swapaxes(x, 0, 1).astype(jnp.float32)           # [T, B, 4D]
    lens = jnp.asarray(xlen, jnp.int32)
    mask = (lax.broadcasted_iota(jnp.int32, (t, b), 0)
            < lens[None, :]).astype(jnp.float32)[:, :, None]  # [T, B, 1]
    if reverse:
        xs = xs[::-1]
        mask = mask[::-1]
    h0 = jnp.zeros((b, d), jnp.float32) if h0 is None \
        else h0.astype(jnp.float32)
    c0 = jnp.zeros((b, d), jnp.float32) if c0 is None \
        else c0.astype(jnp.float32)
    hs, cs = _lstm_seq_core(xs, mask, w.astype(jnp.float32),
                            gate_bias.reshape(-1).astype(jnp.float32),
                            h0, c0, int(block_b), bool(interpret))
    if reverse:
        hs, cs = hs[::-1], cs[::-1]
    return (jnp.swapaxes(hs, 0, 1).astype(x.dtype),
            jnp.swapaxes(cs, 0, 1).astype(x.dtype))


# --- lstmp: recurrent projection (the [B, P] projected state feeds the
# next step's gate matmul; see ops/sequence_ops._lstmp for the layout) ---

def _lstmp_seq_kernel(x_ref, m_ref, w_ref, wp_ref, b_ref, r0_ref, c0_ref,
                      r_out, c_out, r_scr, c_scr, *, d):
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        r_scr[:] = r0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    r_prev = r_scr[:]
    c_prev = c_scr[:]
    xt = x_ref[0].astype(jnp.float32)                       # [bb, 4D]
    gates = xt + jnp.dot(r_prev, w_ref[:],
                         preferred_element_type=jnp.float32) + b_ref[0]
    z = jnp.tanh(gates[:, :d])
    i = jax.nn.sigmoid(gates[:, d:2 * d])
    f = jax.nn.sigmoid(gates[:, 2 * d:3 * d])
    o = jax.nn.sigmoid(gates[:, 3 * d:])
    c_new = f * c_prev + i * z
    h_new = o * jnp.tanh(c_new)
    r_new = jnp.tanh(jnp.dot(h_new, wp_ref[:],
                             preferred_element_type=jnp.float32))
    mt = m_ref[0]
    r = mt * r_new + (1 - mt) * r_prev
    c = mt * c_new + (1 - mt) * c_prev
    r_scr[:] = r
    c_scr[:] = c
    r_out[0] = r.astype(r_out.dtype)
    c_out[0] = c.astype(c_out.dtype)


def _lstmp_fwd_call(xs, ms, w, w_proj, b, r0, c0, block_b, interpret):
    if pltpu is None:  # pragma: no cover - VMEM scratch needs the backend
        raise RuntimeError("fused_lstmp needs the pallas TPU backend "
                           "(guard dispatch on attention_available())")
    t, bsz, four_d = xs.shape
    d = four_d // 4
    p = w_proj.shape[1]
    blk, b_pad = _resolve_block_b(bsz, block_b)
    if b_pad != bsz:
        xs = jnp.pad(xs, [(0, 0), (0, b_pad - bsz), (0, 0)])
        ms = jnp.pad(ms, [(0, 0), (0, b_pad - bsz), (0, 0)])
        r0 = _pad_rows(r0, b_pad)
        c0 = _pad_rows(c0, b_pad)
    rs, cs = pl.pallas_call(
        functools.partial(_lstmp_seq_kernel, d=d),
        grid=(b_pad // blk, t),
        in_specs=[
            _vmem_spec((1, blk, four_d), lambda bb, i: (i, bb, 0)),
            _vmem_spec((1, blk, 1), lambda bb, i: (i, bb, 0)),
            _vmem_spec((p, four_d), lambda bb, i: (0, 0)),
            _vmem_spec((d, p), lambda bb, i: (0, 0)),
            _vmem_spec((1, four_d), lambda bb, i: (0, 0)),
            _vmem_spec((blk, p), lambda bb, i: (bb, 0)),
            _vmem_spec((blk, d), lambda bb, i: (bb, 0)),
        ],
        out_specs=[
            _vmem_spec((1, blk, p), lambda bb, i: (i, bb, 0)),
            _vmem_spec((1, blk, d), lambda bb, i: (i, bb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, b_pad, p), jnp.float32),
            jax.ShapeDtypeStruct((t, b_pad, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk, p), jnp.float32),
            pltpu.VMEM((blk, d), jnp.float32),
        ],
        interpret=interpret,
    )(xs, ms, w, w_proj, b.reshape(1, -1), r0, c0)
    return rs[:, :bsz], cs[:, :bsz]


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def _lstmp_seq_core(xs, ms, w, w_proj, b, r0, c0, block_b, interpret):
    return _lstmp_fwd_call(xs, ms, w, w_proj, b, r0, c0, block_b,
                           interpret)


def _lstmp_seq_core_fwd(xs, ms, w, w_proj, b, r0, c0, block_b, interpret):
    rs, cs = _lstmp_fwd_call(xs, ms, w, w_proj, b, r0, c0, block_b,
                             interpret)
    return (rs, cs), (xs, ms, w, w_proj, b, r0, c0, rs, cs)


def _lstmp_seq_core_bwd(block_b, interpret, res, g):
    xs, ms, w, w_proj, b, r0, c0, rs, cs = res
    grs, gcs = g
    d = w_proj.shape[0]
    r_prevs = jnp.concatenate([r0[None], rs[:-1]], axis=0)
    c_prevs = jnp.concatenate([c0[None], cs[:-1]], axis=0)

    def step(carry, inp):
        dr_c, dc_c, dw, dwp, db = carry
        xt, mt, r_prev, c_prev, gr, gc_out = inp
        dr = dr_c + gr
        dc = dc_c + gc_out
        gates = xt + r_prev @ w + b
        z = jnp.tanh(gates[:, :d])
        i = jax.nn.sigmoid(gates[:, d:2 * d])
        f = jax.nn.sigmoid(gates[:, 2 * d:3 * d])
        o = jax.nn.sigmoid(gates[:, 3 * d:])
        c_new = f * c_prev + i * z
        tc = jnp.tanh(c_new)
        h_new = o * tc
        r_new = jnp.tanh(h_new @ w_proj)
        dr_new = dr * mt
        dproj = dr_new * (1 - r_new * r_new)                 # [B, P]
        dh_new = dproj @ w_proj.T
        dwp = dwp + h_new.T @ dproj
        dc_new = dc * mt + dh_new * o * (1 - tc * tc)
        dgo = dh_new * tc * o * (1 - o)
        dgf = dc_new * c_prev * f * (1 - f)
        dgi = dc_new * z * i * (1 - i)
        dgc = dc_new * i * (1 - z * z)
        dg = jnp.concatenate([dgc, dgi, dgf, dgo], axis=-1)
        dw = dw + r_prev.T @ dg
        db = db + jnp.sum(dg, axis=0)
        dr_prev = dg @ w.T + dr * (1 - mt)
        dc_prev = dc_new * f + dc * (1 - mt)
        return (dr_prev, dc_prev, dw, dwp, db), dg

    init = (jnp.zeros_like(r0), jnp.zeros_like(c0), jnp.zeros_like(w),
            jnp.zeros_like(w_proj), jnp.zeros_like(b))
    (dr0, dc0, dw, dwp, db), dxs = lax.scan(
        step, init, (xs, ms, r_prevs, c_prevs, grs, gcs), reverse=True)
    return dxs, jnp.zeros_like(ms), dw, dwp, db, dr0, dc0


_lstmp_seq_core.defvjp(_lstmp_seq_core_fwd, _lstmp_seq_core_bwd)


def fused_lstmp(x, w, w_proj, gate_bias, r0, c0, xlen, reverse=False,
                block_b=0, interpret=None):
    """Fused LSTMP (recurrent projection): x [B, T, 4D], w [P, 4D],
    w_proj [D, P], r0 [B, P] the PROJECTED initial state (the caller
    projects h0 — its grads flow through that projection's own vjp),
    c0 [B, D]. Returns (projection, cell) = ([B, T, P], [B, T, D]).
    Default activations only, like fused_lstm."""
    if interpret is None:
        interpret = _interpret_default()
    b, t, four_d = x.shape
    d = w_proj.shape[0]
    xs = jnp.swapaxes(x, 0, 1).astype(jnp.float32)
    lens = jnp.asarray(xlen, jnp.int32)
    mask = (lax.broadcasted_iota(jnp.int32, (t, b), 0)
            < lens[None, :]).astype(jnp.float32)[:, :, None]
    if reverse:
        xs = xs[::-1]
        mask = mask[::-1]
    c0 = jnp.zeros((b, d), jnp.float32) if c0 is None \
        else c0.astype(jnp.float32)
    rs, cs = _lstmp_seq_core(xs, mask, w.astype(jnp.float32),
                             w_proj.astype(jnp.float32),
                             gate_bias.reshape(-1).astype(jnp.float32),
                             r0.astype(jnp.float32), c0, int(block_b),
                             bool(interpret))
    if reverse:
        rs, cs = rs[::-1], cs[::-1]
    return (jnp.swapaxes(rs, 0, 1).astype(x.dtype),
            jnp.swapaxes(cs, 0, 1).astype(x.dtype))


# ---------------------------------------------------------------------------
# masked sequence softmax / pool (the @SEQLEN-dominated sequence ops: one
# VMEM pass computes mask + reduce + normalize per row block, instead of
# the where/softmax/mul chain XLA materializes between HBM round-trips)
# ---------------------------------------------------------------------------

def _masked_softmax_kernel(x_ref, len_ref, y_ref):
    x = x_ref[:].astype(jnp.float32)                         # [bn, T]
    lens = len_ref[:]                                        # [bn, 1] int32
    cols = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    valid = cols < lens
    s = jnp.where(valid, x, _NEG)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.where(valid, jnp.exp(s - m), 0.0)
    denom = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    y_ref[:] = (p / denom).astype(y_ref.dtype)


def _masked_softmax_call(x, lens, block_n, interpret):
    n, t = x.shape
    n_pad = int(-(-n // block_n) * block_n)
    xp = _pad_rows(x, n_pad)
    lp = _pad_rows(lens.reshape(-1, 1).astype(jnp.int32), n_pad)
    y = pl.pallas_call(
        _masked_softmax_kernel,
        grid=(n_pad // block_n,),
        in_specs=[
            _vmem_spec((block_n, t), lambda i: (i, 0)),
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
        ],
        out_specs=_vmem_spec((block_n, t), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, t), x.dtype),
        interpret=interpret,
    )(xp, lp)
    return y[:n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _masked_softmax_core(x, lens, block_n, interpret):
    return _masked_softmax_call(x, lens, block_n, interpret)


def _masked_softmax_core_fwd(x, lens, block_n, interpret):
    y = _masked_softmax_call(x, lens, block_n, interpret)
    return y, y


def _masked_softmax_core_bwd(block_n, interpret, y, g):
    yf = y.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    dx = yf * (gf - jnp.sum(gf * yf, axis=-1, keepdims=True))
    return dx.astype(y.dtype), None


_masked_softmax_core.defvjp(_masked_softmax_core_fwd,
                            _masked_softmax_core_bwd)


def masked_softmax(x, xlen, block_n=8, interpret=None):
    """Sequence softmax over the time dim of x [B, T] with true lengths
    xlen [B]: positions >= xlen contribute nothing and get 0. One VMEM
    pass per row block; differentiable (custom_vjp from the saved
    output — masked positions have y == 0, so their grads vanish
    exactly like the unfused where-mask path)."""
    if interpret is None:
        interpret = _interpret_default()
    return _masked_softmax_core(x, jnp.asarray(xlen, jnp.int32),
                                int(block_n), bool(interpret))


def _masked_pool_kernel(x_ref, len_ref, o_ref, *, ptype):
    x = x_ref[:].astype(jnp.float32)                         # [bn, T, F]
    lens = len_ref[:]                                        # [bn, 1]
    cols = lax.broadcasted_iota(jnp.int32, x.shape[:2], 1)
    m = (cols < lens).astype(jnp.float32)[:, :, None]        # [bn, T, 1]
    s = jnp.sum(x * m, axis=1)                               # [bn, F]
    denom = jnp.maximum(lens.astype(jnp.float32), 1.0)       # [bn, 1]
    if ptype == "AVERAGE":
        s = s / denom
    elif ptype == "SQRT":
        s = s / jnp.sqrt(denom)
    o_ref[:] = s.astype(o_ref.dtype)


def _masked_pool_call(x, lens, ptype, block_n, interpret):
    n, t, f = x.shape
    n_pad = int(-(-n // block_n) * block_n)
    xp = _pad_rows(x, n_pad)
    lp = _pad_rows(lens.reshape(-1, 1).astype(jnp.int32), n_pad)
    out = pl.pallas_call(
        functools.partial(_masked_pool_kernel, ptype=ptype),
        grid=(n_pad // block_n,),
        in_specs=[
            _vmem_spec((block_n, t, f), lambda i: (i, 0, 0)),
            _vmem_spec((block_n, 1), lambda i: (i, 0)),
        ],
        out_specs=_vmem_spec((block_n, f), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, f), x.dtype),
        interpret=interpret,
    )(xp, lp)
    return out[:n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _masked_pool_core(x, lens, ptype, block_n, interpret):
    return _masked_pool_call(x, lens, ptype, block_n, interpret)


def _masked_pool_core_fwd(x, lens, ptype, block_n, interpret):
    out = _masked_pool_call(x, lens, ptype, block_n, interpret)
    # residuals must be jax values: a 0-size sentinel carries x's
    # shape[1:]/dtype (the layer_norm kernel's bias trick)
    return out, (lens, jnp.zeros((0,) + x.shape[1:], x.dtype))


def _masked_pool_core_bwd(ptype, block_n, interpret, res, g):
    lens, x_like = res
    t = x_like.shape[1]
    n = lens.shape[0]
    x_dtype = x_like.dtype
    m = (lax.broadcasted_iota(jnp.int32, (n, t), 1)
         < lens.reshape(-1, 1)).astype(jnp.float32)[:, :, None]
    gf = g.astype(jnp.float32)[:, None, :]                   # [N, 1, F]
    if ptype == "AVERAGE":
        gf = gf / jnp.maximum(lens.astype(jnp.float32), 1.0
                              ).reshape(-1, 1, 1)
    elif ptype == "SQRT":
        gf = gf / jnp.sqrt(jnp.maximum(lens.astype(jnp.float32), 1.0)
                           ).reshape(-1, 1, 1)
    return (gf * m).astype(x_dtype), None


_masked_pool_core.defvjp(_masked_pool_core_fwd, _masked_pool_core_bwd)


def masked_pool(x, xlen, ptype="AVERAGE", block_n=8, interpret=None):
    """Masked sequence pool over the time dim of x [B, T, F]:
    SUM / AVERAGE / SQRT (the linear pools — MAX/LAST/FIRST keep the
    dense path, their grads are selection-shaped). Returns [B, F];
    differentiable (custom_vjp, exact: the pools are linear in x)."""
    if ptype not in ("SUM", "AVERAGE", "SQRT"):
        raise ValueError("masked_pool handles SUM/AVERAGE/SQRT, got %r"
                         % (ptype,))
    if interpret is None:
        interpret = _interpret_default()
    return _masked_pool_core(x, jnp.asarray(xlen, jnp.int32), str(ptype),
                             int(block_n), bool(interpret))


def layer_norm(x, scale, bias, eps=1e-5, block_n=8, interpret=None):
    """Fused layer norm over the trailing dim of 2D x [N, D]; one VMEM pass
    computes y + the (mean, rstd) backward residuals. Differentiable
    (custom_vjp; dense backward — the fwd is the HBM-bound pass worth
    fusing). Returns (y, mean [N], variance [N]) matching the layer_norm
    op's output contract; the fetchable mean/variance are plain reductions
    XLA DCEs when (as usual) nothing consumes them."""
    if interpret is None:
        interpret = _interpret_default()
    y = _ln_core(x, scale, bias, float(eps), int(block_n), bool(interpret))
    xf = x.astype(jnp.float32)
    return y, jnp.mean(xf, axis=-1), jnp.var(xf, axis=-1)
