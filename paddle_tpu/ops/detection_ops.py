"""SSD detection ops: prior_box, iou_similarity, box_coder, bipartite_match,
target_assign, mine_hard_examples, multiclass_nms, fused ssd_loss.

Parity: paddle/fluid/operators/{prior_box_op,iou_similarity_op,box_coder_op,
bipartite_match_op,target_assign_op,mine_hard_examples_op,multiclass_nms_op}
.{h,cc} and the ssd_loss layer composition in
python/paddle/fluid/layers/detection.py:348.

Layout: ground-truth boxes/labels are padded dense [B, G, ...] + GtLen
(the reference walks LoD offsets host-side). The greedy bipartite match
and NMS become fixed-trip-count lax loops over the small G / keep_top_k
dims, batched over B — device-resident instead of the reference's
CPU-only kernels.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register, single

_EPS = 1e-6


# ---------------------------------------------------------------------------
# geometry helpers (shared by op lowerings and the fused ssd_loss)
# ---------------------------------------------------------------------------

def iou_matrix(x, y):
    """x [..., N, 4], y [..., M, 4] -> IoU [..., N, M] (corner encoding).

    Matches iou_similarity_op.h IOUSimilarityFunctor (no +1 pixel; plain
    normalized coordinates)."""
    x1, y1, x2, y2 = [x[..., :, None, i] for i in range(4)]
    a1, b1, a2, b2 = [y[..., None, :, i] for i in range(4)]
    iw = jnp.maximum(jnp.minimum(x2, a2) - jnp.maximum(x1, a1), 0.0)
    ih = jnp.maximum(jnp.minimum(y2, b2) - jnp.maximum(y1, b1), 0.0)
    inter = iw * ih
    area_x = jnp.maximum(x2 - x1, 0.0) * jnp.maximum(y2 - y1, 0.0)
    area_y = jnp.maximum(a2 - a1, 0.0) * jnp.maximum(b2 - b1, 0.0)
    union = area_x + area_y - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _center_size(box):
    w = box[..., 2] - box[..., 0]
    h = box[..., 3] - box[..., 1]
    cx = (box[..., 2] + box[..., 0]) / 2
    cy = (box[..., 3] + box[..., 1]) / 2
    return cx, cy, w, h


def encode_center_size(target, prior, prior_var):
    """target [..., 4] vs prior [..., 4] (broadcastable) -> offsets.

    box_coder_op.h EncodeCenterSize."""
    pcx, pcy, pw, ph = _center_size(prior)
    tcx, tcy, tw, th = _center_size(target)
    out = jnp.stack([
        (tcx - pcx) / pw / prior_var[..., 0],
        (tcy - pcy) / ph / prior_var[..., 1],
        jnp.log(jnp.abs(tw / pw)) / prior_var[..., 2],
        jnp.log(jnp.abs(th / ph)) / prior_var[..., 3],
    ], axis=-1)
    return out


def decode_center_size(target, prior, prior_var):
    """box_coder_op.h DecodeCenterSize: target offsets -> corner boxes."""
    pcx, pcy, pw, ph = _center_size(prior)
    cx = prior_var[..., 0] * target[..., 0] * pw + pcx
    cy = prior_var[..., 1] * target[..., 1] * ph + pcy
    w = jnp.exp(prior_var[..., 2] * target[..., 2]) * pw
    h = jnp.exp(prior_var[..., 3] * target[..., 3]) * ph
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


def bipartite_match_batch(dist, gt_valid):
    """Greedy global-max matching per batch row.

    dist [B, G, M], gt_valid [B, G] -> (match_idx [B, M] int32 (-1 = none),
    match_dist [B, M]). Mirrors BipartiteMatchKernel::BipartiteMatch: repeat
    G times: take the global (row, col) argmax over unmatched rows/cols with
    dist >= EPS; assign col -> row."""
    b, g, m = dist.shape
    dist = jnp.where(gt_valid[:, :, None], dist, 0.0)

    def body(_, carry):
        match_idx, match_dist, row_free = carry
        cand = jnp.where(row_free[:, :, None] & (match_idx == -1)[:, None, :],
                         dist, -1.0)
        flat = cand.reshape(b, g * m)
        best = jnp.argmax(flat, axis=1)
        best_val = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        r, c = best // m, best % m
        ok = best_val >= _EPS
        match_idx = jnp.where(
            ok[:, None] & (jnp.arange(m)[None, :] == c[:, None]),
            r[:, None].astype(jnp.int32), match_idx)
        match_dist = jnp.where(
            ok[:, None] & (jnp.arange(m)[None, :] == c[:, None]),
            best_val[:, None], match_dist)
        row_free = row_free & ~(ok[:, None] &
                                (jnp.arange(g)[None, :] == r[:, None]))
        return match_idx, match_dist, row_free

    init = (jnp.full((b, m), -1, jnp.int32), jnp.zeros((b, m)),
            gt_valid)
    match_idx, match_dist, _ = lax.fori_loop(0, g, body, init)
    return match_idx, match_dist


def argmax_match_fill(dist, gt_valid, match_idx, match_dist, threshold):
    """ArgMaxMatch (match_type='per_prediction'): for still-unmatched
    columns, match to the argmax row if dist >= threshold."""
    masked = jnp.where(gt_valid[:, :, None], dist, -1.0)
    best_r = jnp.argmax(masked, axis=1).astype(jnp.int32)     # [B, M]
    best_v = jnp.max(masked, axis=1)
    fill = (match_idx == -1) & (best_v >= threshold)
    return (jnp.where(fill, best_r, match_idx),
            jnp.where(fill, best_v, match_dist))


# ---------------------------------------------------------------------------
# op lowerings
# ---------------------------------------------------------------------------

def _expand_aspect_ratios(aspect_ratios, flip):
    out = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - o) < 1e-6 for o in out):
            continue
        out.append(ar)
        if flip:
            out.append(1.0 / ar)
    return out


@register("prior_box")
def _prior_box(ctx, ins, attrs):
    x = single(ins, "Input")    # feature map [B, C, fh, fw]
    img = single(ins, "Image")  # [B, C, ih, iw]
    min_sizes = [float(v) for v in attrs["min_sizes"]]
    max_sizes = [float(v) for v in attrs.get("max_sizes", []) or []]
    ars = _expand_aspect_ratios(
        [float(v) for v in attrs.get("aspect_ratios", [1.0])],
        bool(attrs.get("flip", False)))
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    clip = bool(attrs.get("clip", False))
    offset = float(attrs.get("offset", 0.5))
    fh, fw = x.shape[2], x.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    step_w = float(attrs.get("step_w", 0) or 0) or iw / fw
    step_h = float(attrs.get("step_h", 0) or 0) or ih / fh

    # per-cell half-sizes, in reference order: [min, (max,) ar!=1...] per s
    half = []
    for s, ms in enumerate(min_sizes):
        half.append((ms / 2.0, ms / 2.0))
        if max_sizes:
            mx = np.sqrt(ms * max_sizes[s]) / 2.0
            half.append((mx, mx))
        for ar in ars:
            if abs(ar - 1.0) < 1e-6:
                continue
            half.append((ms * np.sqrt(ar) / 2.0, ms / np.sqrt(ar) / 2.0))
    half = np.asarray(half, np.float32)              # [P, 2] (w, h)
    p = half.shape[0]

    cx = (np.arange(fw) + offset) * step_w           # [fw]
    cy = (np.arange(fh) + offset) * step_h           # [fh]
    cxg, cyg = np.meshgrid(cx, cy)                   # [fh, fw]
    boxes = np.stack([
        (cxg[:, :, None] - half[None, None, :, 0]) / iw,
        (cyg[:, :, None] - half[None, None, :, 1]) / ih,
        (cxg[:, :, None] + half[None, None, :, 0]) / iw,
        (cyg[:, :, None] + half[None, None, :, 1]) / ih,
    ], axis=-1).astype(np.float32)                   # [fh, fw, P, 4]
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variances, np.float32),
                          (fh, fw, p, 4)).copy()
    return {"Boxes": [jnp.asarray(boxes)], "Variances": [jnp.asarray(var)]}


@register("iou_similarity")
def _iou_similarity(ctx, ins, attrs):
    x = single(ins, "X")
    y = single(ins, "Y")
    return {"Out": [iou_matrix(x, y)]}


@register("box_coder")
def _box_coder(ctx, ins, attrs):
    prior = single(ins, "PriorBox")        # [M, 4]
    prior_var = single(ins, "PriorBoxVar")  # [M, 4]
    target = single(ins, "TargetBox")
    code_type = attrs.get("code_type", "encode_center_size")
    if code_type == "encode_center_size":
        # target [N, 4] x prior [M, 4] -> [N, M, 4]
        out = encode_center_size(target[:, None, :], prior[None, :, :],
                                 prior_var[None, :, :])
    else:
        # target [N, M, 4] offsets vs prior [M, 4] -> [N, M, 4]
        out = decode_center_size(target, prior[None, :, :],
                                 prior_var[None, :, :])
    return {"OutputBox": [out]}


@register("bipartite_match")
def _bipartite_match(ctx, ins, attrs):
    dist = single(ins, "DistMat")          # [B, G, M]
    glen = single(ins, "GtLen").astype(jnp.int32)
    g = dist.shape[1]
    gt_valid = jnp.arange(g, dtype=jnp.int32)[None, :] < glen[:, None]
    midx, mdist = bipartite_match_batch(dist, gt_valid)
    mtype = attrs.get("match_type", "bipartite")
    if mtype == "per_prediction":
        midx, mdist = argmax_match_fill(
            dist, gt_valid, midx, mdist,
            float(attrs.get("dist_threshold", 0.5)))
    return {"ColToRowMatchIndices": [midx], "ColToRowMatchDist": [mdist]}


@register("target_assign")
def _target_assign(ctx, ins, attrs):
    """Gather per-prior targets by match indices (target_assign_op.h).

    X [B, G, K] (gt feature), MatchIndices [B, M] -> Out [B, M, K];
    unmatched (= -1) get mismatch_value and weight 0."""
    x = single(ins, "X")
    midx = single(ins, "MatchIndices")
    mismatch = attrs.get("mismatch_value", 0)
    safe = jnp.maximum(midx, 0)
    out = jnp.take_along_axis(x, safe[:, :, None], axis=1)
    matched = (midx >= 0)[:, :, None]
    out = jnp.where(matched, out, jnp.asarray(mismatch, x.dtype))
    w = matched.astype(jnp.float32)
    return {"Out": [out], "OutWeight": [w]}


@register("mine_hard_examples")
def _mine_hard_examples(ctx, ins, attrs):
    """max_negative mining: among unmatched priors, keep the
    neg_pos_ratio * num_pos with highest conf loss (mine_hard_examples_op.cc).
    Output NegMask [B, M] (1 = selected negative) — dense stand-in for the
    reference's LoD NegIndices."""
    cls_loss = single(ins, "ClsLoss")        # [B, M]
    midx = single(ins, "MatchIndices")       # [B, M]
    mdist = single(ins, "MatchDist")
    ratio = float(attrs.get("neg_pos_ratio", 3.0))
    thresh = float(attrs.get("neg_dist_threshold", 0.5))
    b, m = cls_loss.shape
    eligible = (midx == -1) & (mdist < thresh)
    num_pos = jnp.sum((midx != -1).astype(jnp.int32), axis=1)
    neg_sel = jnp.minimum((num_pos.astype(jnp.float32) * ratio)
                          .astype(jnp.int32),
                          jnp.sum(eligible.astype(jnp.int32), axis=1))
    loss = jnp.where(eligible, cls_loss, -jnp.inf)
    order = jnp.argsort(-loss, axis=1)                  # descending
    rank_of = jnp.argsort(order, axis=1)                # rank per prior
    neg_mask = eligible & (rank_of < neg_sel[:, None])
    return {"NegMask": [neg_mask.astype(jnp.float32)]}


def _nms_single(boxes, scores, score_threshold, nms_threshold, nms_top_k,
                eta):
    """Greedy NMS for one class: returns keep mask [M] (multiclass_nms_op.cc
    NMSFast)."""
    m = boxes.shape[0]
    valid = scores > score_threshold
    neg = jnp.asarray(-jnp.inf, scores.dtype)
    s = jnp.where(valid, scores, neg)
    order = jnp.argsort(-s)
    if nms_top_k > 0 and nms_top_k < m:
        order = order[:nms_top_k]
    sboxes = boxes[order]
    svalid = valid[order]
    n = order.shape[0]
    ious = iou_matrix(sboxes, sboxes)                   # [n, n]

    def body(i, carry):
        # keep i if no higher-ranked kept box overlaps > adaptive threshold
        keep, thresh = carry
        over = (ious[i] > thresh) & keep & (jnp.arange(n) < i)
        ki = svalid[i] & ~jnp.any(over)
        # NMSFast: adaptive threshold decays after each kept box (eta < 1)
        thresh = jnp.where(ki & (eta < 1.0) & (thresh > 0.5), thresh * eta,
                           thresh)
        return keep.at[i].set(ki), thresh

    keep_sorted, _ = lax.fori_loop(
        0, n, body, (jnp.zeros((n,), bool), jnp.asarray(nms_threshold)))
    keep = jnp.zeros((m,), bool).at[order].set(keep_sorted)
    return keep


@register("multiclass_nms")
def _multiclass_nms(ctx, ins, attrs):
    """BBoxes [B, M, 4], Scores [B, C, M] -> Out [B, keep_top_k, 6]
    ([label, score, xmin, ymin, xmax, ymax], -1-padded) + OutLen."""
    bboxes = single(ins, "BBoxes")
    scores = single(ins, "Scores")
    background = int(attrs.get("background_label", 0))
    score_threshold = float(attrs.get("score_threshold", 0.01))
    nms_top_k = int(attrs.get("nms_top_k", 400))
    nms_threshold = float(attrs.get("nms_threshold", 0.45))
    keep_top_k = int(attrs.get("keep_top_k", 200))
    eta = float(attrs.get("nms_eta", 1.0))
    b, m = bboxes.shape[0], bboxes.shape[1]
    c = scores.shape[1]

    def per_image(boxes, sc):
        # keep mask per class
        keeps = []
        for cls in range(c):
            if cls == background:
                keeps.append(jnp.zeros((m,), bool))
                continue
            keeps.append(_nms_single(boxes, sc[cls], score_threshold,
                                     nms_threshold, nms_top_k, eta))
        keep = jnp.stack(keeps)                          # [C, M]
        flat_score = jnp.where(keep, sc, -jnp.inf).reshape(-1)  # [C*M]
        k = min(keep_top_k, c * m)
        top_s, top_i = lax.top_k(flat_score, k)
        cls_i = (top_i // m).astype(jnp.float32)
        box_i = top_i % m
        sel = jnp.take(boxes, box_i, axis=0)             # [k, 4]
        good = top_s > -jnp.inf
        out = jnp.concatenate([
            jnp.where(good, cls_i, -1.0)[:, None],
            jnp.where(good, top_s, -1.0)[:, None],
            jnp.where(good[:, None], sel, -1.0)], axis=1)
        return out, jnp.sum(good.astype(jnp.int32))

    outs, lens = jax.vmap(per_image)(bboxes, scores)
    return {"Out": [outs], "OutLen": [lens]}


@register("ssd_loss")
def _ssd_loss(ctx, ins, attrs):
    """Fused SSD loss (detection.py:348 ssd_loss layer composition):
    iou -> bipartite(+per_prediction) match -> encode loc targets ->
    smooth_l1 loc loss + softmax conf loss -> hard negative mining ->
    normalized weighted sum. One op instead of ~10, same math."""
    loc = single(ins, "Location")        # [B, M, 4]
    conf = single(ins, "Confidence")     # [B, M, C]
    gt_box = single(ins, "GtBox")        # [B, G, 4]
    gt_label = single(ins, "GtLabel")    # [B, G] or [B, G, 1]
    glen = single(ins, "GtLen").astype(jnp.int32)
    prior = single(ins, "PriorBox")      # [M, 4]
    prior_var = single(ins, "PriorBoxVar")  # [M, 4] (optional; default 1s)
    if prior_var is None:
        prior_var = jnp.ones_like(prior)
    if gt_label.ndim == 3:
        gt_label = gt_label[:, :, 0]
    gt_label = gt_label.astype(jnp.int32)
    background = int(attrs.get("background_label", 0))
    overlap_threshold = float(attrs.get("overlap_threshold", 0.5))
    neg_overlap = float(attrs.get("neg_overlap", 0.5))
    neg_pos_ratio = float(attrs.get("neg_pos_ratio", 3.0))
    loc_w = float(attrs.get("loc_loss_weight", 1.0))
    conf_w = float(attrs.get("conf_loss_weight", 1.0))
    mtype = attrs.get("match_type", "per_prediction")

    b, m = loc.shape[0], loc.shape[1]
    g = gt_box.shape[1]
    gt_valid = jnp.arange(g, dtype=jnp.int32)[None, :] < glen[:, None]

    iou = iou_matrix(gt_box, prior[None])               # [B, G, M]
    midx, mdist = bipartite_match_batch(iou, gt_valid)
    if mtype == "per_prediction":
        midx, mdist = argmax_match_fill(iou, gt_valid, midx, mdist,
                                        overlap_threshold)

    matched = midx >= 0                                  # [B, M]
    safe = jnp.maximum(midx, 0)
    # conf target: matched -> gt label, else background
    tgt_label = jnp.take_along_axis(gt_label, safe, axis=1)
    tgt_label = jnp.where(matched, tgt_label, background)
    # loc target: encoded offsets of matched gt vs prior
    tgt_box = jnp.take_along_axis(gt_box, safe[:, :, None], axis=1)
    loc_tgt = encode_center_size(tgt_box, prior[None], prior_var[None])

    # conf loss: softmax cross entropy per prior
    lp = jax.nn.log_softmax(conf, axis=-1)
    conf_loss = -jnp.take_along_axis(lp, tgt_label[:, :, None],
                                     axis=2)[:, :, 0]   # [B, M]

    # hard negative mining (max_negative)
    eligible = (~matched) & (mdist < neg_overlap)
    num_pos = jnp.sum(matched.astype(jnp.int32), axis=1)
    neg_sel = jnp.minimum((num_pos.astype(jnp.float32) * neg_pos_ratio)
                          .astype(jnp.int32),
                          jnp.sum(eligible.astype(jnp.int32), axis=1))
    neg_loss = jnp.where(eligible, conf_loss, -jnp.inf)
    order = jnp.argsort(-neg_loss, axis=1)
    rank_of = jnp.argsort(order, axis=1)
    neg_mask = eligible & (rank_of < neg_sel[:, None])

    conf_weight = matched.astype(jnp.float32) + neg_mask.astype(jnp.float32)

    # loc loss: smooth l1 on matched priors
    diff = loc - lax.stop_gradient(loc_tgt)
    ad = jnp.abs(diff)
    smooth = jnp.where(ad < 1.0, 0.5 * ad * ad, ad - 0.5)
    loc_loss = jnp.sum(smooth, axis=2) * matched.astype(jnp.float32)

    # per-image sum over priors -> [B, 1]; normalize by the total matched
    # count = reduce_sum(target_loc_weight) (detection.py:556-560)
    loss = (conf_w * conf_loss * lax.stop_gradient(conf_weight) +
            loc_w * loc_loss)                            # [B, M]
    loss = jnp.sum(loss, axis=1, keepdims=True)          # [B, 1]
    if bool(attrs.get("normalize", True)):
        normalizer = jnp.maximum(
            lax.stop_gradient(jnp.sum(num_pos).astype(jnp.float32)), 1.0)
        loss = loss / normalizer
    return {"Loss": [loss]}


@register("detection_map")
def _detection_map(ctx, ins, attrs):
    """Batch mAP via host callback to metrics.DetectionMAP (reference
    detection_map_op.h ran on CPU inside the executor; jax.pure_callback
    is the same host round-trip under whole-program jit)."""
    det = single(ins, "DetectRes")        # [B, K, 6], -1 padded
    det_len = single(ins, "DetectLen")    # [B]
    label = single(ins, "Label")          # [B, G, 5|6]
    label_len = single(ins, "LabelLen")   # [B]
    thr = attrs.get("overlap_threshold", 0.5)
    ap = attrs.get("ap_version", "integral")
    eval_difficult = attrs.get("evaluate_difficult", True)
    background = attrs.get("background_label", None)

    def host_map(det, det_len, label, label_len):
        from ..metrics import DetectionMAP
        det = np.asarray(det)
        det_len = np.ravel(np.asarray(det_len)).astype(np.int64)
        label = np.asarray(label)
        label_len = np.ravel(np.asarray(label_len)).astype(np.int64)
        has_difficult = label.shape[-1] == 6
        box_start = 2 if has_difficult else 1
        m = DetectionMAP(overlap_threshold=thr, ap_version=ap,
                         evaluate_difficult=eval_difficult,
                         background_label=background)
        gt_boxes, gt_labels, gt_diff = [], [], []
        for i in range(label.shape[0]):
            rows = label[i, :label_len[i]]
            gt_labels.append(rows[:, 0])
            gt_boxes.append(rows[:, box_start:box_start + 4])
            gt_diff.append(rows[:, 1] if has_difficult
                           else np.zeros(len(rows)))
        m.update(det, det_len, gt_boxes, gt_labels, gt_difficult=gt_diff)
        return np.asarray([m.eval()], np.float32)

    out = jax.pure_callback(
        host_map, jax.ShapeDtypeStruct((1,), jnp.float32),
        det, det_len, label, label_len)
    return {"Out": [out]}
