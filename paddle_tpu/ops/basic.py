"""Elementwise / math / tensor op lowerings.

Parity: paddle/fluid/operators/{activation_op,elementwise_*,mul_op,matmul_op,
mean_op,scale_op,sum_op,cast_op,concat_op,reshape_op,transpose_op,split_op,
reduce_op,fill_*,uniform_random_op,gaussian_random_op,clip_op,compare_op,
logical_op,cumsum_op,scatter_op,gather_op,topk_op,one_hot_op,...}.{cc,cu}.
Each CUDA kernel there becomes one jnp/lax expression here; gradients are
derived automatically via jax.vjp of these rules (no *_grad lowerings).
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register, single


def _out(x):
    return {"Out": [x]}


# ---------------------------------------------------------------------------
# activations (reference: activation_op.cc ~27 kernels)
# ---------------------------------------------------------------------------

def _act(name, fn):
    register(name)(lambda ctx, ins, attrs, fn=fn: _out(fn(single(ins, "X"), attrs)))


_act("sigmoid", lambda x, a: jax.nn.sigmoid(x))
_act("logsigmoid", lambda x, a: jax.nn.log_sigmoid(x))
_act("exp", lambda x, a: jnp.exp(x))
_act("relu", lambda x, a: jax.nn.relu(x))
_act("tanh", lambda x, a: jnp.tanh(x))
_act("tanh_shrink", lambda x, a: x - jnp.tanh(x))
_act("softshrink", lambda x, a: jnp.where(x > a.get("lambda", 0.5), x - a.get("lambda", 0.5),
                                          jnp.where(x < -a.get("lambda", 0.5), x + a.get("lambda", 0.5), 0.0)))
_act("sqrt", lambda x, a: jnp.sqrt(x))
_act("abs", lambda x, a: jnp.abs(x))
_act("ceil", lambda x, a: jnp.ceil(x))
_act("floor", lambda x, a: jnp.floor(x))
_act("cos", lambda x, a: jnp.cos(x))
_act("sin", lambda x, a: jnp.sin(x))
_act("round", lambda x, a: jnp.round(x))
_act("reciprocal", lambda x, a: 1.0 / x)
_act("log", lambda x, a: jnp.log(x))
_act("square", lambda x, a: jnp.square(x))
_act("softplus", lambda x, a: jax.nn.softplus(x))
_act("softsign", lambda x, a: x / (1 + jnp.abs(x)))
_act("brelu", lambda x, a: jnp.clip(x, a.get("t_min", 0.0), a.get("t_max", 24.0)))
_act("leaky_relu", lambda x, a: jax.nn.leaky_relu(x, a.get("alpha", 0.02)))
_act("soft_relu", lambda x, a: jnp.log1p(jnp.exp(jnp.clip(x, -a.get("threshold", 40.0), a.get("threshold", 40.0)))))
_act("elu", lambda x, a: jax.nn.elu(x, a.get("alpha", 1.0)))
_act("relu6", lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0)))
_act("pow", lambda x, a: jnp.power(x, a.get("factor", 1.0)))
_act("stanh", lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(a.get("scale_a", 2.0 / 3.0) * x))
_act("hard_shrink", lambda x, a: jnp.where(jnp.abs(x) > a.get("threshold", 0.5), x, 0.0))
_act("thresholded_relu", lambda x, a: jnp.where(x > a.get("threshold", 1.0), x, 0.0))
_act("hard_sigmoid", lambda x, a: jnp.clip(a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0))
_act("swish", lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x))


# ---------------------------------------------------------------------------
# elementwise binary ops with fluid's axis-broadcast semantics
# (reference: elementwise_op_function.h)
# ---------------------------------------------------------------------------

def _bcast_y(x, y, axis):
    """Fluid broadcast: Y's shape must match a contiguous run of X's dims
    starting at `axis` (axis=-1 => trailing alignment, numpy-style)."""
    if x.ndim == y.ndim:
        return y
    if axis == -1 or axis is None:
        axis = x.ndim - y.ndim
    new_shape = (1,) * axis + y.shape + (1,) * (x.ndim - axis - y.ndim)
    return y.reshape(new_shape)


def _elementwise(name, fn):
    def lower(ctx, ins, attrs):
        x, y = single(ins, "X"), single(ins, "Y")
        y = _bcast_y(x, y, attrs.get("axis", -1))
        return _out(fn(x, y))
    register(name)(lower)


_elementwise("elementwise_add", lambda x, y: x + y)
_elementwise("elementwise_sub", lambda x, y: x - y)
_elementwise("elementwise_mul", lambda x, y: x * y)
_elementwise("elementwise_div", lambda x, y: x / y)
_elementwise("elementwise_max", jnp.maximum)
_elementwise("elementwise_min", jnp.minimum)
_elementwise("elementwise_pow", jnp.power)


@register("minus")
def _minus(ctx, ins, attrs):
    return _out(single(ins, "X") - single(ins, "Y"))


# ---------------------------------------------------------------------------
# mul / matmul (reference: mul_op.cc, matmul_op.cc) — MXU path
# ---------------------------------------------------------------------------

def _flatten2d(x, num_col_dims):
    lead = int(np.prod(x.shape[:num_col_dims])) if num_col_dims > 0 else 1
    return x.reshape(lead, -1)


@register("mul")
def _mul(ctx, ins, attrs):
    x, y = single(ins, "X"), single(ins, "Y")
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    x2 = _flatten2d(x, xn)
    y2 = y.reshape(int(np.prod(y.shape[:yn])), -1)
    out = jnp.matmul(x2, y2, preferred_element_type=jnp.float32).astype(x.dtype) \
        if x.dtype == jnp.bfloat16 else x2 @ y2
    out_shape = x.shape[:xn] + y.shape[yn:]
    return _out(out.reshape(out_shape))


@register("matmul")
def _matmul(ctx, ins, attrs):
    x, y = single(ins, "X"), single(ins, "Y")
    if attrs.get("transpose_X"):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if attrs.get("transpose_Y"):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    if x.dtype == jnp.bfloat16 or y.dtype == jnp.bfloat16:
        out = jnp.matmul(x, y, preferred_element_type=jnp.float32) \
            .astype(jnp.promote_types(x.dtype, y.dtype))
    else:
        out = jnp.matmul(x, y)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    return _out(out)


# ---------------------------------------------------------------------------
# shape / dtype manipulation
# ---------------------------------------------------------------------------

@register("mean")
def _mean(ctx, ins, attrs):
    return _out(jnp.mean(single(ins, "X")).reshape(1))


@register("scale")
def _scale(ctx, ins, attrs):
    x = single(ins, "X")
    out = x * attrs.get("scale", 1.0)
    bias = attrs.get("bias", 0.0)
    if bias:
        if attrs.get("bias_after_scale", True):
            out = out + bias
        else:
            out = (x + bias) * attrs.get("scale", 1.0)
    return _out(out)


@register("cast")
def _cast(ctx, ins, attrs):
    return _out(single(ins, "X").astype(np.dtype(attrs["out_dtype"])))


@register("sum")
def _sum(ctx, ins, attrs):
    xs = ins.get("X", [])
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return _out(out)


@register("concat")
def _concat(ctx, ins, attrs):
    return _out(jnp.concatenate(ins["X"], axis=attrs.get("axis", 0)))


@register("split")
def _split(ctx, ins, attrs):
    x = single(ins, "X")
    axis = attrs.get("axis", 0)
    sections = attrs.get("sections")
    if sections:
        idx = np.cumsum(sections)[:-1].tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, attrs.get("num", 1), axis=axis)
    return {"Out": list(outs)}


@register("reshape")
def _reshape(ctx, ins, attrs):
    x = single(ins, "X")
    shape = list(attrs["shape"])
    # fluid semantics: 0 means copy dim from input, -1 infers
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)] \
        if any(s == 0 for s in shape) else shape
    return _out(x.reshape(shape))


@register("squeeze")
def _squeeze(ctx, ins, attrs):
    x = single(ins, "X")
    axes = attrs.get("axes") or [i for i, d in enumerate(x.shape) if d == 1]
    return _out(jnp.squeeze(x, axis=tuple(axes)))


@register("unsqueeze")
def _unsqueeze(ctx, ins, attrs):
    x = single(ins, "X")
    for a in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, a)
    return _out(x)


@register("transpose")
def _transpose(ctx, ins, attrs):
    return _out(jnp.transpose(single(ins, "X"), attrs["axis"]))


@register("expand")
def _expand(ctx, ins, attrs):
    x = single(ins, "X")
    times = attrs["expand_times"]
    return _out(jnp.tile(x, times))


@register("assign")
def _assign(ctx, ins, attrs):
    return _out(single(ins, "X"))


@register("print")
def _print(ctx, ins, attrs):
    """Identity + debug callback print (reference print_op.cc). Works under
    jit and inside lax control flow; the runtime prints when the step runs.
    first_n is honored per compiled entry via a host-side counter in the
    callback closure (a re-trace starts a fresh count)."""
    x = single(ins, "In")
    msg = attrs.get("message") or ""
    parts = []
    if attrs.get("print_tensor_name", True):
        parts.append(attrs.get("var_name", ""))
    if attrs.get("print_tensor_type", True):
        parts.append(str(x.dtype))
    if attrs.get("print_tensor_shape", True):
        parts.append(str(tuple(x.shape)))
    header = " ".join(p for p in [msg] + parts if p)
    summarize = attrs.get("summarize", -1)
    first_n = attrs.get("first_n", -1)
    shown = x.reshape(-1)
    if summarize and summarize > 0:
        shown = shown[:summarize]
    state = {"n": 0}

    def _emit(v):
        if first_n < 0 or state["n"] < first_n:
            state["n"] += 1
            print(header, np.asarray(v))

    jax.debug.callback(_emit, shown)
    return _out(x)


@register("clip")
def _clip(ctx, ins, attrs):
    return _out(jnp.clip(single(ins, "X"), attrs["min"], attrs["max"]))


@register("clip_by_norm")
def _clip_by_norm(ctx, ins, attrs):
    x = single(ins, "X")
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return _out(x * scale)


# ---------------------------------------------------------------------------
# reductions (reference: reduce_op.cc family)
# ---------------------------------------------------------------------------

def _reduce(name, fn):
    def lower(ctx, ins, attrs):
        x = single(ins, "X")
        if attrs.get("reduce_all"):
            dim = None
        else:
            dim = attrs.get("dim", 0)
            if isinstance(dim, (list, tuple)):
                dim = tuple(dim)
        keep = attrs.get("keep_dim", False)
        out = fn(x, axis=dim, keepdims=keep)
        if dim is None and not keep:
            out = out.reshape(1)
        return _out(out)
    register(name)(lower)


_reduce("reduce_sum", jnp.sum)
_reduce("reduce_mean", jnp.mean)
_reduce("reduce_max", jnp.max)
_reduce("reduce_min", jnp.min)
_reduce("reduce_prod", jnp.prod)


# ---------------------------------------------------------------------------
# fills / random (reference: fill_constant_op.cc, uniform_random_op.cc, ...)
# ---------------------------------------------------------------------------

def _resolve_bsl_shape(ref, attrs):
    """*_batch_size_like shape: copy batch dim from a reference input."""
    shape = list(attrs["shape"])
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    return shape


@register("fill_constant")
def _fill_constant(ctx, ins, attrs):
    dtype = np.dtype(attrs.get("dtype", "float32"))
    shape = [1 if s == -1 else s for s in attrs.get("shape", [1])]
    return _out(jnp.full(shape, attrs.get("value", 0.0), dtype=dtype))


@register("fill_constant_batch_size_like")
def _fill_cbsl(ctx, ins, attrs):
    ref = single(ins, "Input")
    shape = _resolve_bsl_shape(ref, attrs)
    return _out(jnp.full(shape, attrs.get("value", 0.0),
                         dtype=np.dtype(attrs.get("dtype", "float32"))))


@register("fill_zeros_like")
def _fill_zeros_like(ctx, ins, attrs):
    return _out(jnp.zeros_like(single(ins, "X")))


@register("fill")
def _fill(ctx, ins, attrs):
    """fill_op.cc: fill Out with the row-major `value` float list, reshaped
    to `shape`, cast to `dtype` (force_cpu is a placement no-op here)."""
    arr = np.asarray(attrs["value"], dtype=np.float32)
    arr = arr.reshape(attrs["shape"]).astype(
        np.dtype(attrs.get("dtype", "float32")))
    return _out(jnp.asarray(arr))


def _attr_np_dtype(attrs, default="float32"):
    """Resolve a "dtype" attr that may be a numpy-style string (our
    layers) OR the era framework.proto VarType enum int (era descs and
    reference OpTest configs encode dtype as e.g. 5=FP32, 2=INT32)."""
    v = attrs.get("dtype", default)
    if isinstance(v, (int, np.integer)):
        table = {0: "bool", 1: "int16", 2: "int32", 3: "int64",
                 4: "float16", 5: "float32", 6: "float64"}
        v = table.get(int(v), default)
    return np.dtype(v)


@register("assign_value")
def _assign_value(ctx, ins, attrs):
    """assign_value_op.cc:55 stores the payload in a dtype-SUFFIXED attr
    (fp32_values / int32_values, selected in assign_value_op.h:34) —
    accept those wire names (era descs / OpTest configs, where dtype is
    the VarType enum int) alongside the layer's own "values"."""
    dtype = _attr_np_dtype(attrs)
    if "values" in attrs:
        vals = attrs["values"]
    elif dtype == np.int32 and "int32_values" in attrs:
        vals = attrs["int32_values"]
    elif "fp32_values" in attrs:
        vals = attrs["fp32_values"]
    else:
        raise KeyError(
            "assign_value: none of values/fp32_values/int32_values in "
            "attrs %r" % sorted(attrs))
    arr = np.asarray(vals, dtype=dtype)
    return _out(jnp.asarray(arr.reshape(attrs["shape"])))


@register("shape")
def _shape(ctx, ins, attrs):
    x = single(ins, "Input")
    return _out(jnp.asarray(x.shape, dtype=jnp.int32))


@register("uniform_random", uses_rng=True)
def _uniform_random(ctx, ins, attrs):
    dtype = np.dtype(attrs.get("dtype", "float32"))
    shape = [1 if s == -1 else s for s in attrs["shape"]]
    out = jax.random.uniform(ctx.rng(seed=attrs.get("seed", 0)), shape, dtype=dtype,
                             minval=attrs.get("min", -1.0),
                             maxval=attrs.get("max", 1.0))
    return _out(out)


@register("uniform_random_batch_size_like", uses_rng=True)
def _uniform_random_bsl(ctx, ins, attrs):
    ref = single(ins, "Input")
    shape = _resolve_bsl_shape(ref, attrs)
    return _out(jax.random.uniform(ctx.rng(seed=attrs.get("seed", 0)), shape,
                                   dtype=np.dtype(attrs.get("dtype", "float32")),
                                   minval=attrs.get("min", -1.0),
                                   maxval=attrs.get("max", 1.0)))


@register("gaussian_random", uses_rng=True)
def _gaussian_random(ctx, ins, attrs):
    dtype = np.dtype(attrs.get("dtype", "float32"))
    shape = [1 if s == -1 else s for s in attrs["shape"]]
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * \
        jax.random.normal(ctx.rng(seed=attrs.get("seed", 0)), shape, dtype=dtype)
    return _out(out)


@register("gaussian_random_batch_size_like", uses_rng=True)
def _gaussian_random_bsl(ctx, ins, attrs):
    ref = single(ins, "Input")
    shape = _resolve_bsl_shape(ref, attrs)
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * \
        jax.random.normal(ctx.rng(seed=attrs.get("seed", 0)), shape,
                          dtype=np.dtype(attrs.get("dtype", "float32")))
    return _out(out)


@register("truncated_gaussian_random", uses_rng=True)
def _truncated_gaussian_random(ctx, ins, attrs):
    dtype = np.dtype(attrs.get("dtype", "float32"))
    shape = [1 if s == -1 else s for s in attrs["shape"]]
    std = attrs.get("std", 1.0)
    out = attrs.get("mean", 0.0) + std * jax.random.truncated_normal(
        ctx.rng(seed=attrs.get("seed", 0)), -2.0, 2.0, shape, dtype=dtype)
    return _out(out)


# ---------------------------------------------------------------------------
# comparison / logical (reference: compare_op.cc, logical_op.cc)
# ---------------------------------------------------------------------------

def _compare(name, fn):
    def lower(ctx, ins, attrs):
        return _out(fn(single(ins, "X"), single(ins, "Y")))
    register(name)(lower)


_compare("less_than", lambda x, y: x < y)
_compare("less_equal", lambda x, y: x <= y)
_compare("greater_than", lambda x, y: x > y)
_compare("greater_equal", lambda x, y: x >= y)
_compare("equal", lambda x, y: x == y)
_compare("not_equal", lambda x, y: x != y)
_compare("logical_and", jnp.logical_and)
_compare("logical_or", jnp.logical_or)
_compare("logical_xor", jnp.logical_xor)


@register("logical_not")
def _logical_not(ctx, ins, attrs):
    return _out(jnp.logical_not(single(ins, "X")))


# ---------------------------------------------------------------------------
# indexing / misc
# ---------------------------------------------------------------------------

@register("sign")
def _sign(ctx, ins, attrs):
    return _out(jnp.sign(single(ins, "X")))


@register("reduce_sum_square")
def _reduce_sum_square(ctx, ins, attrs):
    return _out(jnp.sum(jnp.square(single(ins, "X"))).reshape(1))


@register("global_norm_scale")
def _global_norm_scale(ctx, ins, attrs):
    total_sq = single(ins, "X").reshape(())
    clip = attrs["clip_norm"]
    norm = jnp.sqrt(total_sq)
    return _out(jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12)).reshape(1))


@register("cumsum")
def _cumsum(ctx, ins, attrs):
    x = single(ins, "X")
    axis = attrs.get("axis", -1)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive"):
        out = out - x
    if attrs.get("reverse"):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
        if attrs.get("exclusive"):
            out = out - x
    return _out(out)


@register("gather")
def _gather(ctx, ins, attrs):
    x, idx = single(ins, "X"), single(ins, "Index")
    return _out(jnp.take(x, idx.reshape(-1).astype(jnp.int32), axis=0))


@register("scatter")
def _scatter(ctx, ins, attrs):
    x, idx, upd = single(ins, "X"), single(ins, "Ids"), single(ins, "Updates")
    idx = idx.reshape(-1).astype(jnp.int32)
    return _out(x.at[idx].set(upd))


@register("topk")
def _topk(ctx, ins, attrs):
    x = single(ins, "X")
    k = attrs.get("k", 1)
    vals, idx = lax.top_k(x, k)
    return {"Out": [vals], "Indices": [idx.astype(jnp.int64)]}


@register("arg_max")
def _arg_max(ctx, ins, attrs):
    return _out(jnp.argmax(single(ins, "X"), axis=attrs.get("axis", -1))
                .astype(jnp.int64))


@register("one_hot")
def _one_hot(ctx, ins, attrs):
    x = single(ins, "X")
    depth = attrs["depth"]
    idx = x.reshape(x.shape[:-1] if x.shape and x.shape[-1] == 1 else x.shape)
    return _out(jax.nn.one_hot(idx.astype(jnp.int32), depth, dtype=jnp.float32))


@register("increment")
def _increment(ctx, ins, attrs):
    x = single(ins, "X")
    return _out(x + jnp.asarray(attrs.get("step", 1.0), dtype=x.dtype))


@register("is_empty")
def _is_empty(ctx, ins, attrs):
    x = single(ins, "X")
    return _out(jnp.asarray(x.size == 0))


@register("multiplex")
def _multiplex(ctx, ins, attrs):
    ids = single(ins, "Ids").reshape(-1).astype(jnp.int32)
    xs = jnp.stack(ins["X"], axis=0)  # [n_candidates, batch, ...]
    rows = jnp.arange(ids.shape[0])
    return _out(xs[ids, rows])


@register("cos_sim")
def _cos_sim(ctx, ins, attrs):
    x, y = single(ins, "X"), single(ins, "Y")
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / jnp.maximum(xn * yn, 1e-12)
    return {"Out": [out], "XNorm": [xn], "YNorm": [yn]}


@register("l2_normalize_raw")
def _l2_normalize(ctx, ins, attrs):
    x = single(ins, "X")
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-12)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": [x / norm], "Norm": [norm]}


def _wn_axes(x, dim):
    return tuple(i for i in range(x.ndim) if i != dim) if dim is not None \
        else tuple(range(x.ndim))


@register("wn_norm")
def _wn_norm(ctx, ins, attrs):
    """||X|| over every axis except attr dim (weight-norm g init)."""
    x = single(ins, "X")
    dim = attrs.get("dim")
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=_wn_axes(x, dim)))
    return _out(n.reshape(-1))


@register("weight_norm")
def _weight_norm(ctx, ins, attrs):
    """W = G * V / ||V|| (parity: layer_helper.py __weight_normalize —
    there a 9-op sub-graph; here one op whose jax.vjp yields the G and V
    gradients)."""
    g = single(ins, "G")
    v = single(ins, "V")
    dim = attrs.get("dim")
    axes = _wn_axes(v, dim)
    norm = jnp.sqrt(jnp.sum(jnp.square(v), axis=axes, keepdims=True))
    scale = g.reshape([v.shape[dim] if (dim is not None and i == dim) else 1
                       for i in range(v.ndim)]) if dim is not None \
        else g.reshape((1,) * v.ndim)
    return _out(v * (scale / jnp.maximum(norm, 1e-12)))
