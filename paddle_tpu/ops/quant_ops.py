"""Quantization ops (TPU-native addition — the 2018 reference served
fp32 only; this is the weight-only quantized serving path behind
`InferenceEngine(weights_dtype=...)`, see serving/quantize.py).
"""
import jax.numpy as jnp

from ..core.registry import register, single

# input-slot storage dtypes of dequantize_channel — the static half of
# the int8 contract. analysis.dtype_flow verifies saved programs against
# THIS table, so a storage-format change here is a lint-rule change too.
DEQUANTIZE_SLOTS = {"X": "int8", "Scale": "float32"}


@register("dequantize_channel")
def _dequantize_channel(ctx, ins, attrs):
    """int8 per-channel weight dequantize: Out = X.astype(f32) * Scale
    broadcast along `axis`. Inserted by serving.quantize in front of
    each quantized matmul/conv param; XLA fuses the multiply into the
    consumer, so the weight lives in HBM at 1/4 size and is widened
    on the way into the MXU. The op is the whole runtime contract of
    int8 serving: compute stays f32, only the weight's storage (and
    its rounding, bounded by the per-channel scale) changes."""
    q = single(ins, "X")          # int8 [param shape]
    scale = single(ins, "Scale")  # f32 [C]
    axis = attrs.get("axis", -1)
    if axis < 0:
        axis += q.ndim
    bshape = [1] * q.ndim
    bshape[axis] = q.shape[axis]
    out = q.astype(jnp.float32) * scale.reshape(bshape)
    return {"Out": [out]}
