"""Volumetric (3-D) conv/pool lowerings.

Parity: the reference registers these from the SAME .cc files as the 2-D
family — conv_op.cc:340 (conv3d), conv_transpose_op.cc (conv3d_transpose),
pool_op.cc (pool3d), pool_with_index_op.cc (max_pool3d_with_index) — which
is why the file-level op audit alone missed them (a name-level audit now
exists in tests/unittests/test_reference_op_files_audit.py).

TPU notes: 3-D convs lower to one lax.conv_general_dilated over NCDHW —
XLA tiles the contraction onto the MXU exactly as for 2-D (the extra
spatial dim just joins the window). Pooling is lax.reduce_window over a
5-D operand. The with-index variant gathers explicit windows (indices are
a data output, which reduce_window cannot produce).
"""
import jax.numpy as jnp
from jax import lax

from ..core.registry import register, single


def _triple(v):
    if isinstance(v, (list, tuple)):
        if len(v) == 1:
            return (int(v[0]),) * 3
        return tuple(int(x) for x in v)
    return (int(v),) * 3


def _out(x):
    return {"Out": [x]}


@register("conv3d")
def _conv3d(ctx, ins, attrs):
    x = single(ins, "Input")    # NCDHW
    w = single(ins, "Filter")   # OIDHW (I = C/groups)
    strides = _triple(attrs.get("strides", [1, 1, 1]))
    pads = _triple(attrs.get("paddings", [0, 0, 0]))
    dil = _triple(attrs.get("dilations", [1, 1, 1]))
    groups = attrs.get("groups", 1) or 1
    out = lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=[(p, p) for p in pads],
        rhs_dilation=dil,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=groups)
    return {"Output": [out.astype(x.dtype)]}


@register("conv3d_transpose")
def _conv3d_transpose(ctx, ins, attrs):
    x = single(ins, "Input")    # NCDHW
    w = single(ins, "Filter")   # fluid layout [C_in, C_out, kd, kh, kw]
    strides = _triple(attrs.get("strides", [1, 1, 1]))
    pads = _triple(attrs.get("paddings", [0, 0, 0]))
    dil = _triple(attrs.get("dilations", [1, 1, 1]))
    # Same contract as the 2-D lowering (ops/nn_ops.py _conv2d_transpose):
    # fluid's filter is the OIDHW filter of the forward conv this op is the
    # input-gradient of; transpose_kernel swaps I/O and flips taps, and the
    # gradient conv pads (effective_k - 1 - pad) per side so the output is
    # (D-1)*stride + k - 2*pad.
    eff = [(w.shape[2 + i] - 1) * dil[i] + 1 for i in range(3)]
    out = lax.conv_transpose(
        x, w,
        strides=strides,
        padding=[(eff[i] - 1 - pads[i], eff[i] - 1 - pads[i])
                 for i in range(3)],
        rhs_dilation=dil,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        transpose_kernel=True)
    return {"Output": [out.astype(x.dtype)]}


@register("pool3d")
def _pool3d(ctx, ins, attrs):
    x = single(ins, "X")  # NCDHW
    ptype = attrs.get("pooling_type", "max")
    ksize = _triple(attrs.get("ksize", [2, 2, 2]))
    strides = _triple(attrs.get("strides", [1, 1, 1]))
    pads = _triple(attrs.get("paddings", [0, 0, 0]))
    if attrs.get("global_pooling"):
        ksize = x.shape[2:5]
        pads = (0, 0, 0)
        strides = (1, 1, 1)
    # ceil_mode as trailing padding, mirroring pool2d (pool_op.cc attr)
    extra = [0, 0, 0]
    if attrs.get("ceil_mode", False):
        for d in range(3):
            span = x.shape[2 + d] - ksize[d] + 2 * pads[d]
            out_ceil = -(-span // strides[d]) + 1
            extra[d] = max(0, (out_ceil - 1) * strides[d] - span)
    window = (1, 1) + tuple(ksize)
    strides5 = (1, 1) + tuple(strides)
    padding = ((0, 0), (0, 0)) + tuple(
        (pads[d], pads[d] + extra[d]) for d in range(3))
    if ptype == "max":
        out = lax.reduce_window(x, -jnp.inf, lax.max, window, strides5,
                                padding)
    else:
        s = lax.reduce_window(x, 0.0, lax.add, window, strides5, padding)
        if attrs.get("exclusive", True) and any(
                pads[d] or extra[d] for d in range(3)):
            cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window,
                                    strides5, padding)
            # a ceil-mode window can sit fully inside padding (count 0);
            # emit 0 there, not 0/0
            out = s / jnp.maximum(cnt, 1.0)
        else:
            out = s / float(ksize[0] * ksize[1] * ksize[2])
    return _out(out.astype(x.dtype))


@register("max_pool3d_with_index")
def _max_pool3d_with_index(ctx, ins, attrs):
    """pool_with_index_op.cc (3-D registration): max pool + Mask of the
    in-volume flat index d*(H*W) + h*W + w of each window max."""
    x = single(ins, "X")  # [N, C, D, H, W]
    ksize = [int(k) for k in attrs["ksize"]]
    if attrs.get("global_pooling", False):
        ksize = list(x.shape[2:5])
    strides = [int(s) for s in attrs.get("strides", [1, 1, 1])]
    pads = [int(p) for p in attrs.get("paddings", [0, 0, 0])]
    n, c = x.shape[:2]
    dims = x.shape[2:5]
    outdims = [(dims[i] - ksize[i] + 2 * pads[i]) // strides[i] + 1
               for i in range(3)]
    # per-axis tap index tables [Oi, ki] + validity, as in _pool_windows
    idx, valid = [], []
    for i in range(3):
        t = (jnp.arange(outdims[i]) * strides[i] - pads[i])[:, None] \
            + jnp.arange(ksize[i])[None, :]
        idx.append(t)
        valid.append((t >= 0) & (t < dims[i]))
    # gather windows axis by axis: -> [N, C, Od, kd, Oh, kh, Ow, kw]
    v = x
    for i in range(3):
        axis = 2 + 2 * i
        v = jnp.take(v, jnp.clip(idx[i], 0, dims[i] - 1).reshape(-1),
                     axis=axis)
        v = v.reshape(v.shape[:axis] + (outdims[i], ksize[i])
                      + v.shape[axis + 1:])
    v = v.transpose(0, 1, 2, 4, 6, 3, 5, 7)  # [N,C,Od,Oh,Ow,kd,kh,kw]
    ok = (valid[0][:, None, None, :, None, None]
          & valid[1][None, :, None, None, :, None]
          & valid[2][None, None, :, None, None, :])  # [Od,Oh,Ow,kd,kh,kw]
    neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
    masked = jnp.where(ok[None, None], v, neg)
    flat = masked.reshape((n, c) + tuple(outdims) + (-1,))
    amax = flat.argmax(axis=-1)
    out = flat.max(axis=-1)
    kd, kh, kw = ksize
    ld = amax // (kh * kw)
    lh = (amax // kw) % kh
    lw = amax % kw
    def pick(table, local, bcast):
        # table [Oi, ki] -> value at each output position's local argmax
        t = table.astype(jnp.int32).reshape(bcast)
        return jnp.take_along_axis(
            jnp.broadcast_to(t, local.shape + (t.shape[-1],)),
            local[..., None].astype(jnp.int32), axis=-1).squeeze(-1)
    gd = pick(idx[0], ld, (1, 1, outdims[0], 1, 1, kd))
    gh = pick(idx[1], lh, (1, 1, 1, outdims[1], 1, kh))
    gw = pick(idx[2], lw, (1, 1, 1, 1, outdims[2], kw))
    mask = (gd * (dims[1] * dims[2]) + gh * dims[2] + gw).astype(jnp.int32)
    return {"Out": [out], "Mask": [mask]}
