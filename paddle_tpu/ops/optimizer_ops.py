"""Optimizer update-rule op lowerings.

Parity: paddle/fluid/operators/{sgd_op,momentum_op,adam_op,adagrad_op,
adamax_op,decayed_adagrad_op,adadelta_op,rmsprop_op,ftrl_op}.{cc,cu,h}.
Each writes ParamOut (and accumulator outs) under the SAME var name as the
input, so the executor's state write-back gives in-place-update semantics
without aliasing machinery. All accumulator math in f32 even when params are
bf16 (accumulators are created f32 by the Optimizer classes).
"""
import jax.numpy as jnp
from jax import lax

from ..core.registry import register, single


@register("sgd")
def _sgd(ctx, ins, attrs):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    lr = single(ins, "LearningRate").reshape(())
    return {"ParamOut": [(p - lr * g).astype(p.dtype)]}


@register("momentum")
def _momentum(ctx, ins, attrs):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    v = single(ins, "Velocity")
    lr = single(ins, "LearningRate").reshape(())
    mu = attrs["mu"]
    v_out = mu * v + g
    if attrs.get("use_nesterov", False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": [p_out.astype(p.dtype)], "VelocityOut": [v_out]}


@register("adam")
def _adam(ctx, ins, attrs):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    m = single(ins, "Moment1")
    v = single(ins, "Moment2")
    lr = single(ins, "LearningRate").reshape(())
    b1p = single(ins, "Beta1Pow").reshape(())
    b2p = single(ins, "Beta2Pow").reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    gf = g.astype(jnp.float32)
    m_out = b1 * m + (1 - b1) * gf
    v_out = b2 * v + (1 - b2) * jnp.square(gf)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p_out = p - lr_t * m_out / (jnp.sqrt(v_out) + eps)
    return {"ParamOut": [p_out.astype(p.dtype)],
            "Moment1Out": [m_out], "Moment2Out": [v_out]}


@register("adam_beta_pow_update")
def _adam_beta_pow(ctx, ins, attrs):
    b1p = single(ins, "Beta1Pow")
    b2p = single(ins, "Beta2Pow")
    return {"Beta1PowOut": [b1p * attrs.get("beta1", 0.9)],
            "Beta2PowOut": [b2p * attrs.get("beta2", 0.999)]}


@register("adagrad")
def _adagrad(ctx, ins, attrs):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    mom = single(ins, "Moment")
    lr = single(ins, "LearningRate").reshape(())
    eps = attrs.get("epsilon", 1e-6)
    m_out = mom + jnp.square(g)
    p_out = p - lr * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": [p_out.astype(p.dtype)], "MomentOut": [m_out]}


@register("adamax")
def _adamax(ctx, ins, attrs):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    m = single(ins, "Moment")
    inf_norm = single(ins, "InfNorm")
    lr = single(ins, "LearningRate").reshape(())
    b1p = single(ins, "Beta1Pow").reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_out = b1 * m + (1 - b1) * g
    n_out = jnp.maximum(b2 * inf_norm, jnp.abs(g) + eps)
    p_out = p - (lr / (1 - b1p)) * (m_out / n_out)
    return {"ParamOut": [p_out.astype(p.dtype)],
            "MomentOut": [m_out], "InfNormOut": [n_out]}


@register("decayed_adagrad")
def _decayed_adagrad(ctx, ins, attrs):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    mom = single(ins, "Moment")
    lr = single(ins, "LearningRate").reshape(())
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    m_out = decay * mom + (1 - decay) * jnp.square(g)
    p_out = p - lr * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": [p_out.astype(p.dtype)], "MomentOut": [m_out]}


@register("adadelta")
def _adadelta(ctx, ins, attrs):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    avg_sq_g = single(ins, "AvgSquaredGrad")
    avg_sq_u = single(ins, "AvgSquaredUpdate")
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g2 = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_u + eps) / (g2 + eps)) * g
    u2 = rho * avg_sq_u + (1 - rho) * jnp.square(update)
    return {"ParamOut": [(p + update).astype(p.dtype)],
            "AvgSquaredGradOut": [g2], "AvgSquaredUpdateOut": [u2]}


@register("rmsprop")
def _rmsprop(ctx, ins, attrs):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    ms = single(ins, "MeanSquare")
    mom = single(ins, "Moment")
    lr = single(ins, "LearningRate").reshape(())
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    momentum = attrs.get("momentum", 0.0)
    ms_out = rho * ms + (1 - rho) * jnp.square(g)
    mom_out = momentum * mom + lr * g / jnp.sqrt(ms_out + eps)
    return {"ParamOut": [(p - mom_out).astype(p.dtype)],
            "MeanSquareOut": [ms_out], "MomentOut": [mom_out]}


@register("ftrl")
def _ftrl(ctx, ins, attrs):
    p = single(ins, "Param")
    g = single(ins, "Grad")
    sq_acc = single(ins, "SquaredAccumulator")
    lin_acc = single(ins, "LinearAccumulator")
    lr = single(ins, "LearningRate").reshape(())
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    power = attrs.get("lr_power", -0.5)
    new_sq = sq_acc + jnp.square(g)
    if power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq_acc)) / lr
    else:
        sigma = (jnp.power(new_sq, -power) - jnp.power(sq_acc, -power)) / lr
    new_lin = lin_acc + g - sigma * p
    if power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = jnp.power(new_sq, -power) / lr + 2 * l2
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    p_out = pre / denom
    return {"ParamOut": [p_out.astype(p.dtype)],
            "SquaredAccumOut": [new_sq], "LinearAccumOut": [new_lin]}
