"""Numerical-guard op lowerings (paddle_tpu.resilience.guards).

Three tiny graph ops let `install_numeric_guards` turn a training program
into a self-protecting one without touching any optimizer rule:

  * `check_finite_guard` — all-finite checks over the watched vars
    (loss, param grads, optionally params). Emits a [1] bool "all
    finite" flag AND sticky in-graph assertion flags via
    `ctx.add_error` — the PR-1 checkify channel, so the host pays ONE
    fetch (the combined `__any__` scalar) per run, the flags OR across
    a `steps=K` scan, and `_raise_program_errors` raises a typed
    `NumericalGuardError` naming the non-finite var(s).
  * `guard_backup` — identity alias of a state var's pre-step value
    (free under tracing: no copy is emitted, the env just keeps the
    input tracer alive until the select).
  * `guard_select_all` — ONE lax.cond choosing updated-vs-backup for
    the whole state set: the update gate. A step that tripped the
    guard leaves EVERY gated persistable bit-identical to not having
    run.
"""
import jax.numpy as jnp

from ..core.lowering import GUARD_STAT_PREFIX
from ..core.registry import register, single

# stat-channel key for the sentinel's global gradient norm (see
# resilience/sentinel.py): a float scalar riding the guard error
# channel, peeled into Executor.last_stats after dispatch
GRAD_NORM_STAT = GUARD_STAT_PREFIX + "grad_norm"


@register("check_finite_guard")
def _check_finite_guard(ctx, ins, attrs):
    names = attrs.get("var_names") or []
    vals = ins.get("X", [])
    floats = [(n, v) for n, v in zip(names, vals)
              if jnp.issubdtype(jnp.result_type(v), jnp.floating)]
    if attrs.get("grad_norm_vars"):
        # sentinel tap: ONE f32 global L2 norm over the named subset
        # (the param grads), emitted on the stat channel — it shares
        # the existing fetch of the error dict, so the sentinel's
        # grad-norm watch costs zero additional host syncs. f32
        # accumulation so bf16 grads don't overflow the square.
        watch = frozenset(attrs["grad_norm_vars"])
        sq = [jnp.sum(jnp.square(v.astype(jnp.float32)))
              for n, v in floats if n in watch]
        if sq:
            gn = jnp.sqrt(sum(sq[1:], sq[0]))
            ctx.add_error(GRAD_NORM_STAT, gn)
    if not floats:
        return {"Out": [jnp.ones((1,), jnp.bool_)]}
    if attrs.get("granular", True):
        # default: per-var flags — the trip names exactly which var
        # went bad, and each small reduction fuses into the fusion that
        # PRODUCES its var (no extra materialization). Packed as ONE
        # [N] vector under ONE \x00-joined message key — N+1 scalar jit
        # outputs would cost real per-dispatch marshalling time (see
        # core/lowering.py on vector flags).
        msgs = ["numerical guard: non-finite value detected in %r "
                "(this step's state updates were skipped in-graph)" % n
                for n, _ in floats]
        vec = jnp.stack([~jnp.isfinite(v).all() for _, v in floats])
        ctx.add_error("\x00".join(msgs), vec)
        return {"Out": [jnp.reshape(~vec.any(), (1,))]}
    # granular=False: ONE reduction over the concatenation of every
    # watched value, one combined message. The concat forces the grads
    # to materialize, so this only wins when the watched set is so
    # large that per-var flag plumbing dominates. Concat at the WIDEST
    # watched dtype: downcasting f64 to f32 would map large-but-finite
    # values to inf and trip the guard on healthy steps.
    common = jnp.result_type(*(v.dtype for _, v in floats))
    flat = [v.reshape(-1).astype(common) for _, v in floats]
    combined = flat[0] if len(flat) == 1 else jnp.concatenate(flat)
    ok = jnp.isfinite(combined).all()
    ctx.add_error(
        "numerical guard: non-finite value detected among %s (this "
        "step's state updates were skipped in-graph)"
        % [n for n, _ in floats], ~ok)
    return {"Out": [jnp.reshape(ok, (1,))]}


@register("guard_backup")
def _guard_backup(ctx, ins, attrs):
    return {"Out": [single(ins, "X")]}


@register("guard_select_all")
def _guard_select_all(ctx, ins, attrs):
    """Gate the WHOLE state set through one lax.cond with identity
    branches, instead of N per-var selects: N wheres shatter XLA:CPU's
    update mega-fusion into N tiny select kernels (measured 2x step
    time on the dispatch-bound bench model), while one conditional
    keeps the update fusions intact and adds a single thunk. (Running
    the update ops INSIDE the cond was measured too, and is worse: the
    branch boundary forces every gradient to materialize instead of
    fusing into its update expression.)"""
    import jax
    cond = single(ins, "Cond").reshape(())
    xs = tuple(ins["X"])
    ys = tuple(ins["Y"])
    outs = jax.lax.cond(cond, lambda a, b: a, lambda a, b: b, xs, ys)
    return {"Out": list(outs)}
