"""One owner of the kernel-layer dispatch configuration.

Every pallas fast path used to read its own env flag and run at
hard-coded block sizes (`block_q=128` literals in nn_ops, `block_n=8`
in pallas_kernels) with a single measured-once crossover
(FLAGS_flash_min_seq).  This module centralizes all three surfaces:

* **Gating** — `pallas_explicit()` / `pallas_on(op)` parse
  PADDLE_TPU_PALLAS once, in one place.  Accepted forms:
    - unset/""          : per-op default (TPU backend on, CPU off)
    - "0"/"false"       : every pallas path off
    - "1"/"true"        : every pallas path on (interpret mode on CPU)
    - "attn,xent"       : allowlist — exactly the named ops on, the
                          rest off.  Unknown names raise LOUDLY (the
                          FLAGS_conv_layout discipline: a typo must not
                          silently run the other configuration).
  Op names: attn, xent, ln, lstm, seq (KERNEL_OPS).  Exception: for
  'attn' the flag is an opt-OUT only — fused_attention's positive
  dispatch is always the flash_min_seq() crossover (enabling 'attn'
  does not force flash below the crossover; pin FLAGS_flash_min_seq=0
  for that, as the kernel-coverage tests do).

* **Default tiles** — DEFAULT_TILES is the one shared table the
  per-shape candidate grids are built from; the old literals live here
  and ONLY here.

* **Tuned tiles** — `tiles_for(op, dim)` consults the TuningStore for
  a per-(op, shape-bucket, device_kind) entry recorded by
  `tuning.tune_kernels(...)` and overlays it on the defaults.  Lookups
  happen at TRACE time (inside the op lowering), so a store entry
  changes the traced computation: `kernel_env_key()` — a digest of
  every kernel:* store entry in effect — joins
  `core.lowering.trace_env_key()`, which both executors' jit caches and
  the AOT compile cache key on.  Writing a tuned entry therefore
  re-keys the compiled artifacts instead of silently serving the old
  tiles (regression-tested in test_kernel_tuning.py).

* **Crossover** — `flash_min_seq()` resolves the flash-vs-dense
  attention dispatch point: FLAGS_flash_min_seq when set (0 forces
  flash always), else a tuned `flash_min_seq` knob recorded under the
  CROSSOVER_SIGNATURE store entry for this device, else the measured
  v5e default (1024).
"""
import hashlib
import os

import jax

__all__ = [
    "KERNEL_OPS", "DEFAULT_TILES", "DEFAULT_FLASH_MIN_SEQ",
    "CROSSOVER_SIGNATURE", "pallas_explicit", "pallas_on",
    "flash_min_seq", "flash_at", "shape_bucket", "kernel_signature",
    "tiles_for", "kernel_env_key", "local_device_key",
]

# the one shared default table — the pre-tuning literals.  Keys are the
# knob names the TuningStore accepts (store.KNOWN_KNOBS); values are
# what every dispatch uses when no tuned entry exists for its
# (op, shape-bucket, device_kind).  block_b=0 means "the whole batch in
# one block" (the fused LSTM kernel's pre-knob behavior).
DEFAULT_TILES = {
    "attn": {"block_q": 128, "block_k": 128},
    "xent": {"block_n": 8},
    "ln": {"block_n": 8},
    "lstm": {"block_b": 0},
    "seq": {"block_n": 8},
}
KERNEL_OPS = frozenset(DEFAULT_TILES)
DEFAULT_FLASH_MIN_SEQ = 1024
# store signature for the per-device flash-vs-dense crossover knob
# (shape-independent: it IS the shape rule)
CROSSOVER_SIGNATURE = "kernel:flash_crossover"


def pallas_explicit(op):
    """The explicit PADDLE_TPU_PALLAS setting for `op`: True / False,
    or None when the flag is unset (callers apply their own default).
    Single owner of the flag parse."""
    flag = os.environ.get("PADDLE_TPU_PALLAS", "")
    if flag == "":
        return None
    if flag in ("0", "false", "False"):
        return False
    if flag in ("1", "true", "True"):
        return True
    allow = set(p.strip() for p in flag.split(",") if p.strip())
    bad = sorted(allow - KERNEL_OPS)
    if bad:
        raise ValueError(
            "PADDLE_TPU_PALLAS=%r: unknown op name(s) %r; expected 0, 1 "
            "or a comma list of %s (a typo here would silently run the "
            "wrong kernel path)" % (flag, bad, sorted(KERNEL_OPS)))
    return op in allow


def pallas_on(op):
    """Is the pallas fast path enabled for `op`?  Explicit flag wins;
    default is on exactly on real TPU (interpret-mode kernels on CPU
    are a test/debug path, not a default).  `fused_attention` is the
    one exception: its default dispatch is the flash_min_seq() shape
    rule, so it consults pallas_explicit('attn') directly and treats
    None as 'apply the crossover'."""
    explicit = pallas_explicit(op)
    if explicit is not None:
        return explicit
    return jax.default_backend() == "tpu"


def shape_bucket(dim):
    """Power-of-two bucket (>= 8) of an op's VMEM-pressure dimension —
    T for attention and sequence ops, the row width (vocab / feature
    dim) for xent/ln, the hidden size for the LSTM kernel.  Tuned
    entries are recorded and looked up per bucket so one sweep covers a
    band of real shapes without an entry per literal dim."""
    dim = max(8, int(dim))
    b = 8
    while b < dim:
        b *= 2
    return b


def kernel_signature(op, bucket):
    """TuningStore signature for a kernel-knob entry."""
    return "kernel:%s/b%d" % (op, int(bucket))


def local_device_key():
    """The store device key for the process's devices (tuned tiles are
    per device generation; a process's visible devices are one kind).

    CAREFUL: this sits on trace-time paths (tiles_for, flash_min_seq →
    trace_env_key), and bare jax.devices() INITIALIZES the default
    backend — on a TPU host that dials the tunnel and takes the
    exclusive client lock from a pure-CPU run (the exact hazard
    trace_env_key's PADDLE_TPU_PALLAS comment documents). A
    JAX_PLATFORMS=cpu process therefore resolves the cpu backend
    explicitly and never touches the accelerator."""
    from ..tpu_guard import cpu_only_env
    from ..tuning.store import device_key
    if cpu_only_env():
        return device_key(jax.devices("cpu")[0])
    return device_key(jax.devices()[0])


def _store():
    from ..tuning.store import TuningStore
    return TuningStore()


def tiles_for(op, dim):
    """Resolved block knobs for `op` at VMEM-pressure dimension `dim`:
    DEFAULT_TILES overlaid with the tuned entry for
    (kernel:<op>/b<bucket>, device_kind), if recorded.  Called at trace
    time only — one store read per compiled shape, not per dispatch."""
    if op not in DEFAULT_TILES:
        raise KeyError("unknown kernel op %r (known: %s)"
                       % (op, sorted(DEFAULT_TILES)))
    knobs = dict(DEFAULT_TILES[op])
    st = _store()
    if st.root is not None:
        entry = st.get(kernel_signature(op, shape_bucket(dim)),
                       local_device_key())
        if entry is not None:
            for k in knobs:
                if k in entry["knobs"]:
                    knobs[k] = int(entry["knobs"][k])
    return knobs


_crossover_cache = {}  # root -> (dir_mtime_ns, resolved value)


def flash_min_seq():
    """Flash-vs-dense attention dispatch crossover.  Resolution order:
    FLAGS_flash_min_seq (explicit env pin; 0 forces flash always) ->
    tuned `flash_min_seq` knob for this device (CROSSOVER_SIGNATURE)
    -> 1024 (the round-4 v5e measurement: dense wins at 256, flash at
    2048).  Single owner of the read: the fused_attention dispatch and
    trace_env_key() both resolve through here.  The store lookup sits
    on trace_env_key()'s per-run path, so it caches on the store dir's
    mtime_ns like kernel_env_key (one os.stat per run, not a JSON
    parse)."""
    env = os.environ.get("FLAGS_flash_min_seq", "")
    if env:
        try:
            return int(env)
        except ValueError:
            return DEFAULT_FLASH_MIN_SEQ
    st = _store()
    if st.root is None or not os.path.isdir(st.root):
        return DEFAULT_FLASH_MIN_SEQ
    try:
        stamp = os.stat(st.root).st_mtime_ns
    except OSError:
        return DEFAULT_FLASH_MIN_SEQ
    cached = _crossover_cache.get(st.root)
    if cached is not None and cached[0] == stamp:
        return cached[1]
    value = DEFAULT_FLASH_MIN_SEQ
    entry = st.get(CROSSOVER_SIGNATURE, local_device_key())
    if entry is not None and "flash_min_seq" in entry["knobs"]:
        value = int(entry["knobs"]["flash_min_seq"])
    _crossover_cache[st.root] = (stamp, value)
    return value


def flash_at(q_len):
    """The one flash-vs-dense decision for fused_attention at query
    length `q_len` (the traced q.shape[1]; None when symbolic).

    Decode-shaped dispatch is STRUCTURAL, not a crossover knob:
    at q_len <= 1 (one query row per step — the decode-serving shape)
    the flash kernel's block_q tiling is wrong by construction (a
    128-row q block for a 1-row query; the kernel grid degenerates and
    the crossover knob was never measured there), so the dense path is
    taken unconditionally — EVEN when FLAGS_flash_min_seq=0 pins
    "flash always" for the coverage tests.  Above that:

      * explicit PADDLE_TPU_PALLAS opt-out (=0 or allowlist without
        'attn') -> dense, regardless of length;
      * q_len >= flash_min_seq() -> flash;
      * otherwise dense.

    q_len=None (symbolic trace dim) keeps the historical behavior:
    not decode-shaped, crossover can't be evaluated, flash unless
    explicitly opted out."""
    if q_len is not None and q_len <= 1:
        return False
    if pallas_explicit("attn") is False:
        return False
    if q_len is None:
        return True
    return q_len >= flash_min_seq()


# ---------------------------------------------------------------------------
# trace-env keying: tuned tiles are trace-time state
# ---------------------------------------------------------------------------

_digest_cache = {}  # (root) -> (dir_mtime_ns, digest)


def kernel_env_key():
    """Digest of every kernel:* TuningStore entry in effect — joined
    into core.lowering.trace_env_key() so the jit caches AND the AOT
    compile cache re-key when a tuned tile changes.  Cached on the
    store directory's mtime_ns: steady state costs one os.stat per
    executor run; a put() (atomic os.replace into the dir) bumps the
    mtime and invalidates."""
    from ..tuning.store import resolve_store_dir
    root = resolve_store_dir()
    if not root or not os.path.isdir(root):
        return ""
    try:
        stamp = os.stat(root).st_mtime_ns
    except OSError:
        return ""
    cached = _digest_cache.get(root)
    if cached is not None and cached[0] == stamp:
        return cached[1]
    h = hashlib.sha256()
    st = _store()
    for record in st.entries():
        sig = record.get("signature", "")
        if not isinstance(sig, str) or not sig.startswith("kernel:"):
            continue
        h.update(repr((sig, record.get("device_key"),
                       sorted((record.get("knobs") or {}).items())))
                 .encode("utf-8"))
    digest = h.hexdigest()[:16]
    _digest_cache[root] = (stamp, digest)
    return digest
