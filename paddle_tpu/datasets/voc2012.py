"""PASCAL VOC2012 segmentation.

Parity: python/paddle/v2/dataset/voc2012.py — train()/test()/val() yield
(image float32[3,H,W], segmentation mask int32[H,W] with 21 classes).
Synthetic fallback: random rectangles of uniform class over a background.
"""
import numpy as np

from . import common

__all__ = ["train", "test", "val"]

_CLASSES = 21
_H = _W = 64  # synthetic resolution (real data varies per image)
_TRAIN_N, _TEST_N = common.synthetic_size(48, 12)


def _creator(split_name, n):
    def reader():
        rng = common.synthetic_rng("voc2012", split_name)
        for _ in range(n):
            img = rng.rand(3, _H, _W).astype(np.float32)
            mask = np.zeros((_H, _W), dtype=np.int32)
            for _ in range(int(rng.randint(1, 4))):
                c = int(rng.randint(1, _CLASSES))
                y0, x0 = rng.randint(0, _H // 2), rng.randint(0, _W // 2)
                h, w = rng.randint(8, _H // 2), rng.randint(8, _W // 2)
                mask[y0:y0 + h, x0:x0 + w] = c
                img[:, y0:y0 + h, x0:x0 + w] += c / float(_CLASSES)
            yield np.clip(img, 0, 1.5), mask
    return reader


def train():
    return _creator("train", _TRAIN_N)


def test():
    return _creator("test", _TEST_N)


def val():
    return _creator("val", _TEST_N)
