"""Oxford 102 Flowers.

Parity: python/paddle/v2/dataset/flowers.py — train()/test()/valid() yield
(float32[3*224*224] image in [0,1], label 0..101); mapper/use_xmap kwargs
accepted (mapper applied per sample).
"""
import numpy as np

from . import common
from .. import reader as reader_mod

__all__ = ["train", "test", "valid"]

_CLASSES = 102
_SHAPE = (3, 224, 224)
_TRAIN_N, _TEST_N = common.synthetic_size(64, 16)


def _creator(split_name, n, mapper=None, buffered_size=1024, use_xmap=False):
    def reader():
        tmpl_rng = common.synthetic_rng("flowers", "templates")
        # low-res per-class template upsampled: learnable + cheap to store
        tmpl = tmpl_rng.rand(_CLASSES, 3, 8, 8).astype(np.float32)
        rng = common.synthetic_rng("flowers", split_name)
        for _ in range(n):
            lab = int(rng.randint(0, _CLASSES))
            img = np.kron(tmpl[lab], np.ones((28, 28), dtype=np.float32))
            img = img + rng.randn(*_SHAPE).astype(np.float32) * 0.15
            sample = (np.clip(img, 0.0, 1.0).reshape(-1), lab)
            yield sample
    if mapper is None:
        return reader
    if use_xmap:
        return reader_mod.xmap_readers(mapper, reader, 2, buffered_size)
    return reader_mod.map_readers(mapper, reader)


def train(mapper=None, buffered_size=1024, use_xmap=False):
    return _creator("train", _TRAIN_N, mapper, buffered_size, use_xmap)


def test(mapper=None, buffered_size=1024, use_xmap=False):
    return _creator("test", _TEST_N, mapper, buffered_size, use_xmap)


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    return _creator("valid", _TEST_N, mapper, buffered_size, use_xmap)
