"""CoNLL-2005 semantic role labeling.

Parity: python/paddle/v2/dataset/conll05.py — get_dict() returns
(word_dict, verb_dict, label_dict); test() yields 9 aligned sequences:
(word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_ids, mark, labels)
where ctx_* are the predicate-window words broadcast over the sentence and
mark flags the predicate span. Synthetic fallback keeps exactly that record
shape with a learnable word→label correlation.
"""
import numpy as np

from . import common

__all__ = ["get_dict", "get_embedding", "test", "convert"]

_WORD_VOCAB = 4000
_VERB_VOCAB = 300
_NUM_LABELS = 59  # BIO tags over 29 roles, reference label_dict size era
_TEST_N = common.synthetic_size(200, 200)[1]


def get_dict():
    word_dict = common.word_dict(_WORD_VOCAB)
    verb_dict = common.word_dict(_VERB_VOCAB)
    label_dict = {"label%d" % i: i for i in range(_NUM_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Pretrained word embeddings (reference: emb file). Synthetic:
    deterministic gaussian table [word_vocab, 32]."""
    rng = common.synthetic_rng("conll05", "embedding")
    return rng.randn(_WORD_VOCAB, 32).astype(np.float32)


def _reader_creator(split_name, n):
    def reader():
        lab_rng = common.synthetic_rng("conll05", "labelmap")
        word2label = lab_rng.randint(0, _NUM_LABELS, _WORD_VOCAB)
        rng = common.synthetic_rng("conll05", split_name)
        for _ in range(n):
            length = int(rng.randint(5, 30))
            words = rng.randint(0, _WORD_VOCAB, length).astype(np.int64)
            pred_pos = int(rng.randint(0, length))
            verb = int(rng.randint(0, _VERB_VOCAB))

            def ctx(offset):
                i = min(max(pred_pos + offset, 0), length - 1)
                return np.full(length, words[i], dtype=np.int64)

            mark = np.zeros(length, dtype=np.int64)
            mark[pred_pos] = 1
            labels = word2label[words].astype(np.int64)
            yield (words.tolist(), ctx(-2).tolist(), ctx(-1).tolist(),
                   ctx(0).tolist(), ctx(1).tolist(), ctx(2).tolist(),
                   [verb] * length, mark.tolist(), labels.tolist())
    return reader


def test():
    return _reader_creator("test", _TEST_N)


def train():
    """Synthetic extension: the reference ships only test() publicly (the
    train corpus is licensed); our synthetic fallback can provide both."""
    return _reader_creator("train", _TEST_N * 4)


def convert(path):
    common.convert(path, test(), 1000, "conll05_test")
