"""CIFAR-10 / CIFAR-100.

Parity: python/paddle/v2/dataset/cifar.py — train10/test10/train100/test100
yield (float32[3072] in [0,1], int label). The real
`cifar-10-python.tar.gz` / `cifar-100-python.tar.gz` under DATA_HOME/cifar
is read when present (pickle batch members, exactly the reference's
tarfile walk); synthetic fallback: per-class color-texture templates +
noise (CHW layout like the real pickles).
"""
import os
import pickle
import tarfile

import numpy as np

from . import common

__all__ = ["train10", "test10", "train100", "test100", "convert"]

_TRAIN_N, _TEST_N = common.synthetic_size(1024, 256)
_TARS = {10: "cifar-10-python.tar.gz", 100: "cifar-100-python.tar.gz"}


def _real_reader(split_name, num_classes):
    """Yield from the pickle batches inside the official tar (reference
    cifar.py reader_creator: members filtered by sub_name)."""
    sub_name = ("train" if num_classes == 100 else "data_batch") \
        if split_name == "train" else "test"
    label_key = b"fine_labels" if num_classes == 100 else b"labels"
    path = os.path.join(common.DATA_HOME, "cifar", _TARS[num_classes])

    def reader():
        with tarfile.open(path, mode="r") as tar:
            names = [m for m in tar.getmembers() if sub_name in m.name]
            for m in names:
                batch = pickle.load(tar.extractfile(m), encoding="bytes")
                for img, lab in zip(batch[b"data"], batch[label_key]):
                    yield img.astype(np.float32) / 255.0, int(lab)
    return reader


def _reader_creator(split_name, n, num_classes):
    tag = "cifar%d" % num_classes
    if common.have_real_data("cifar", _TARS[num_classes]):
        return _real_reader(split_name, num_classes)

    def reader():
        tmpl_rng = common.synthetic_rng(tag, "templates")
        templates = tmpl_rng.rand(num_classes, 3072).astype(np.float32)
        rng = common.synthetic_rng(tag, split_name)
        labels = rng.randint(0, num_classes, n)
        for lab in labels:
            img = templates[lab] + rng.randn(3072).astype(np.float32) * 0.25
            yield np.clip(img, 0.0, 1.0), int(lab)
    return reader


def train10():
    return _reader_creator("train", _TRAIN_N, 10)


def test10():
    return _reader_creator("test", _TEST_N, 10)


def train100():
    return _reader_creator("train", _TRAIN_N, 100)


def test100():
    return _reader_creator("test", _TEST_N, 100)


def convert(path):
    common.convert(path, train10(), 1000, "cifar_train10")
    common.convert(path, test10(), 1000, "cifar_test10")
