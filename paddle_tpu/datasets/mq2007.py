"""MQ2007 learning-to-rank dataset.

Parity: python/paddle/v2/dataset/mq2007.py — train/test with format
'pointwise' ((relevance, feature[46])), 'pairwise' ((label, d_high, d_low)),
'listwise' ((relevance_list, feature_list)). Synthetic fallback: a hidden
linear relevance model over 46 features.
"""
import numpy as np

from . import common

__all__ = ["train", "test", "fetch"]

FEATURE_DIM = 46
_TRAIN_Q, _TEST_Q = common.synthetic_size(120, 30)
_DOCS_PER_QUERY = 8


def _queries(split_name, nq):
    model_rng = common.synthetic_rng("mq2007", "model")
    w = model_rng.randn(FEATURE_DIM).astype(np.float32)
    rng = common.synthetic_rng("mq2007", split_name)
    for qid in range(nq):
        feats = rng.randn(_DOCS_PER_QUERY, FEATURE_DIM).astype(np.float32)
        scores = feats @ w + rng.randn(_DOCS_PER_QUERY) * 0.1
        # bucket into relevance 0..2
        rel = np.digitize(scores, np.percentile(scores, [50, 80]))
        yield qid, rel.astype(np.int64), feats


def _reader_creator(split_name, nq, format):
    def pointwise():
        for qid, rel, feats in _queries(split_name, nq):
            for r, f in zip(rel, feats):
                yield int(r), f

    def pairwise():
        rng = common.synthetic_rng("mq2007", split_name + "_pairs")
        for qid, rel, feats in _queries(split_name, nq):
            for i in range(len(rel)):
                for j in range(len(rel)):
                    if rel[i] > rel[j]:
                        yield np.array([1.0], dtype=np.float32), \
                            feats[i], feats[j]

    def listwise():
        for qid, rel, feats in _queries(split_name, nq):
            yield rel.astype(np.float32), feats

    return {"pointwise": pointwise, "pairwise": pairwise,
            "listwise": listwise}[format]


def train(format="pairwise"):
    return _reader_creator("train", _TRAIN_Q, format)


def test(format="pairwise"):
    return _reader_creator("test", _TEST_Q, format)


def fetch():
    raise IOError("zero-egress build: place MQ2007 files under DATA_HOME")
