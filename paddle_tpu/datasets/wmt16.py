"""WMT16 German↔English translation (BPE-era, separate vocab sizes).

Parity: python/paddle/v2/dataset/wmt16.py — train/test/validation take
(src_dict_size, trg_dict_size, src_lang) and yield (src_ids, trg_ids,
trg_ids_next); get_dict(lang, dict_size, reverse) returns the vocab.
"""
from . import common
from . import wmt14 as _w

__all__ = ["train", "test", "validation", "get_dict", "fetch", "convert"]

_TRAIN_N, _TEST_N = common.synthetic_size(600, 150)


def get_dict(lang, dict_size, reverse=False):
    d = common.word_dict(dict_size, extra=("<s>", "<e>", "<unk>"))
    if reverse:
        d = {v: k for k, v in d.items()}
    return d


def _creator(split_name, n, src_dict_size, trg_dict_size, src_lang):
    # reuse the learnable-mapping generator; vocab = min of both sizes so
    # every id is valid in either language's table
    size = min(src_dict_size, trg_dict_size)
    return _w._reader_creator(split_name, n, size, tag="wmt16_" + src_lang)


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _creator("train", _TRAIN_N, src_dict_size, trg_dict_size, src_lang)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _creator("test", _TEST_N, src_dict_size, trg_dict_size, src_lang)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _creator("val", _TEST_N, src_dict_size, trg_dict_size, src_lang)


def fetch():
    raise IOError("zero-egress build: place WMT16 files under DATA_HOME")


def convert(path, src_dict_size=1000, trg_dict_size=1000, src_lang="en"):
    common.convert(path, train(src_dict_size, trg_dict_size, src_lang),
                   1000, "wmt16_train")
