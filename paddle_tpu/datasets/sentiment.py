"""NLTK movie-reviews sentiment corpus.

Parity: python/paddle/v2/dataset/sentiment.py — get_word_dict(),
train()/test() yield (word-id sequence, 0/1). Synthetic fallback mirrors
imdb's generator with this corpus's vocab size.
"""
from . import common
from . import imdb as _imdb

__all__ = ["train", "test", "get_word_dict", "NUM_TRAINING_INSTANCES",
           "NUM_TOTAL_INSTANCES"]

_VOCAB = 2048
NUM_TOTAL_INSTANCES = 2000
NUM_TRAINING_INSTANCES = 1600


def get_word_dict():
    """Sorted-by-frequency word dict (reference builds from nltk corpus)."""
    return common.word_dict(_VOCAB)


def _creator(split_name, n):
    word_idx = get_word_dict()

    def reader():
        # same sentiment-biased generator family as imdb, distinct stream
        inner = _imdb._reader_creator("sentiment_" + split_name, n, word_idx)
        for doc, label in inner():
            yield doc, label
    return reader


def train():
    return _creator("train", NUM_TRAINING_INSTANCES)


def test():
    return _creator("test", NUM_TOTAL_INSTANCES - NUM_TRAINING_INSTANCES)
