"""imikolov (PTB-style) language-model dataset.

Parity: python/paddle/v2/dataset/imikolov.py — build_dict, train/test with
DataType.NGRAM ((w0..wn-1) tuples) or DataType.SEQ ((src, trg) shifted
sequences). Real `ptb.train.txt` / `ptb.valid.txt` under DATA_HOME/imikolov
are read when present (one sentence per line, the Mikolov simple-examples
layout); synthetic fallback: a fixed random bigram chain, so N-gram and
RNN LMs genuinely reduce perplexity.
"""
import collections
import os

import numpy as np

from . import common

__all__ = ["build_dict", "train", "test", "DataType", "convert"]

_TRAIN_N, _TEST_N = common.synthetic_size(800, 200)
_FILES = {"train": "ptb.train.txt", "test": "ptb.valid.txt"}


class DataType(object):
    NGRAM = 1
    SEQ = 2


def _real_lines(split_name):
    path = os.path.join(common.DATA_HOME, "imikolov", _FILES[split_name])
    with open(path) as f:
        for line in f:
            words = line.strip().split()
            if words:
                yield words


def build_dict(min_word_freq=50):
    """word -> id, reference imikolov.py:49 exactly: counts over
    train+valid with '<s>'/'<e>' counted once PER SENTENCE, '<unk>'
    removed then re-added last, strict `> min_word_freq` pruning,
    frequency-ranked ids (ties alphabetical)."""
    if common.have_real_data("imikolov", _FILES["train"]):
        counts = collections.Counter()
        for split in ("train", "test"):
            if not common.have_real_data("imikolov", _FILES[split]):
                continue
            for words in _real_lines(split):
                counts.update(words)
                counts.update(("<s>", "<e>"))
        counts.pop("<unk>", None)
        kept = sorted(
            ((w, c) for w, c in counts.items() if c > min_word_freq),
            key=lambda x: (-x[1], x[0]))
        d = {w: i for i, (w, c) in enumerate(kept)}
        d["<unk>"] = len(d)
        for extra in ("<s>", "<e>"):  # tiny corpora can prune them
            d.setdefault(extra, len(d))
        return d
    d = common.word_dict(2072, extra=("<s>", "<e>", "<unk>"))
    return d


def _sentences(split_name, n, vocab):
    """Markov-chain sentences: next word depends on current (learnable)."""
    chain_rng = common.synthetic_rng("imikolov", "chain")
    # each word has a small successor set
    succ = chain_rng.randint(3, vocab, size=(vocab, 4))
    rng = common.synthetic_rng("imikolov", split_name)
    for _ in range(n):
        length = int(rng.randint(5, 20))
        w = int(rng.randint(3, vocab))
        sent = [w]
        for _ in range(length - 1):
            w = int(succ[w, rng.randint(0, 4)])
            sent.append(w)
        yield sent


def _reader_creator(split_name, n, word_idx, ngram_n, data_type):
    vocab = len(word_idx)
    # real mode requires the TRAIN file (the vocabulary source): a stray
    # valid-only DATA_HOME must not mix a synthetic vocab with real text
    real = common.have_real_data("imikolov", _FILES["train"]) and \
        common.have_real_data("imikolov", _FILES[split_name])

    def sentences():
        if real:
            unk = word_idx["<unk>"]
            for words in _real_lines(split_name):
                yield [word_idx.get(w, unk) for w in words]
        else:
            for sent in _sentences(split_name, n, vocab):
                yield sent

    def reader():
        start, end = word_idx["<s>"], word_idx["<e>"]
        for sent in sentences():
            if data_type == DataType.NGRAM:
                s = [start] + sent + [end]
                if len(s) >= ngram_n:
                    s = np.asarray(s, dtype=np.int64)
                    for i in range(ngram_n, len(s) + 1):
                        yield tuple(s[i - ngram_n:i])
            elif data_type == DataType.SEQ:
                s = [start] + sent + [end]
                # reference: n bounds the src length for SEQ readers
                # (imikolov.py reader_creator: skip if len(src) > n > 0)
                if ngram_n > 0 and len(s) - 1 > ngram_n:
                    continue
                yield s[:-1], s[1:]
            else:
                raise ValueError("Unknown data type %r" % data_type)
    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return _reader_creator("train", _TRAIN_N, word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    return _reader_creator("test", _TEST_N, word_idx, n, data_type)


def convert(path):
    w = build_dict()
    common.convert(path, train(w, 5), 1000, "imikolov_train")
    common.convert(path, test(w, 5), 1000, "imikolov_test")
