"""imikolov (PTB-style) language-model dataset.

Parity: python/paddle/v2/dataset/imikolov.py — build_dict, train/test with
DataType.NGRAM ((w0..wn-1) tuples) or DataType.SEQ ((src, trg) shifted
sequences). Synthetic fallback: a fixed random bigram chain, so N-gram and
RNN LMs genuinely reduce perplexity.
"""
import numpy as np

from . import common

__all__ = ["build_dict", "train", "test", "DataType", "convert"]

_TRAIN_N, _TEST_N = common.synthetic_size(800, 200)


class DataType(object):
    NGRAM = 1
    SEQ = 2


def build_dict(min_word_freq=50):
    """word -> id; '<s>', '<e>', '<unk>' included (reference semantics)."""
    d = common.word_dict(2072, extra=("<s>", "<e>", "<unk>"))
    return d


def _sentences(split_name, n, vocab):
    """Markov-chain sentences: next word depends on current (learnable)."""
    chain_rng = common.synthetic_rng("imikolov", "chain")
    # each word has a small successor set
    succ = chain_rng.randint(3, vocab, size=(vocab, 4))
    rng = common.synthetic_rng("imikolov", split_name)
    for _ in range(n):
        length = int(rng.randint(5, 20))
        w = int(rng.randint(3, vocab))
        sent = [w]
        for _ in range(length - 1):
            w = int(succ[w, rng.randint(0, 4)])
            sent.append(w)
        yield sent


def _reader_creator(split_name, n, word_idx, ngram_n, data_type):
    vocab = len(word_idx)

    def reader():
        start, end = word_idx["<s>"], word_idx["<e>"]
        for sent in _sentences(split_name, n, vocab):
            if data_type == DataType.NGRAM:
                s = [start] + sent + [end]
                if len(s) >= ngram_n:
                    s = np.asarray(s, dtype=np.int64)
                    for i in range(ngram_n, len(s) + 1):
                        yield tuple(s[i - ngram_n:i])
            elif data_type == DataType.SEQ:
                s = [start] + sent + [end]
                yield s[:-1], s[1:]
            else:
                raise ValueError("Unknown data type %r" % data_type)
    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return _reader_creator("train", _TRAIN_N, word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    return _reader_creator("test", _TEST_N, word_idx, n, data_type)


def convert(path):
    w = build_dict()
    common.convert(path, train(w, 5), 1000, "imikolov_train")
    common.convert(path, test(w, 5), 1000, "imikolov_test")
