"""Datasets with the paddle.v2.dataset surface (SURVEY.md §2 Data).

Zero-egress: every module is backed by a deterministic synthetic generator
with the real data's record shapes and vocabularies; real files under
common.DATA_HOME are used where a loader exists (mnist). See common.py.
"""
from . import common
from . import uci_housing
from . import mnist
from . import cifar
from . import imdb
from . import imikolov
from . import movielens
from . import conll05
from . import wmt14
from . import wmt16
from . import mq2007
from . import sentiment
from . import flowers
from . import voc2012

__all__ = ["common", "uci_housing", "mnist", "cifar", "imdb", "imikolov",
           "movielens", "conll05", "wmt14", "wmt16", "mq2007", "sentiment",
           "flowers", "voc2012"]
