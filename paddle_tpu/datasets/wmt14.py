"""WMT14 French→English translation.

Parity: python/paddle/v2/dataset/wmt14.py — train(dict_size)/test(dict_size)
yield (src_ids, trg_ids, trg_ids_next) where trg has <s> prepended and
trg_next is shifted by one ending in <e>; get_dict(dict_size) returns
(src_dict, trg_dict). Special ids: <s>=0, <e>=1, <unk>=2.
"""
import numpy as np

from . import common

__all__ = ["train", "test", "get_dict", "convert"]

_TRAIN_N, _TEST_N = common.synthetic_size(600, 150)


def get_dict(dict_size, reverse=True):
    d = common.word_dict(dict_size, extra=("<s>", "<e>", "<unk>"))
    src = dict(d)
    trg = dict(d)
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg


def _reader_creator(split_name, n, dict_size, tag="wmt14"):
    def reader():
        # a fixed random word-to-word mapping: translation is learnable
        map_rng = common.synthetic_rng(tag, "mapping")
        trans = map_rng.permutation(dict_size)
        trans[:3] = [0, 1, 2]
        rng = common.synthetic_rng(tag, split_name)
        for _ in range(n):
            length = int(rng.randint(3, 12))
            src = rng.randint(3, dict_size, length).astype(np.int64)
            trg = trans[src]
            src_ids = src.tolist()
            trg_ids = [0] + trg.tolist()           # <s> + target
            trg_next = trg.tolist() + [1]          # target + <e>
            yield src_ids, trg_ids, trg_next
    return reader


def train(dict_size):
    return _reader_creator("train", _TRAIN_N, dict_size)


def test(dict_size):
    return _reader_creator("test", _TEST_N, dict_size)


def convert(path):
    common.convert(path, train(1000), 1000, "wmt14_train")
    common.convert(path, test(1000), 1000, "wmt14_test")
