"""Dataset infrastructure.

Parity: python/paddle/v2/dataset/common.py (DATA_HOME, download/md5 cache,
split/cluster_files_reader, convert-to-recordio). This build runs zero-egress:
`download` never touches the network — it returns the cached file when one is
already present under DATA_HOME and raises otherwise. Every dataset module
therefore ships a *deterministic synthetic fallback* with the exact record
types/shapes/vocabularies of the real data, so models, tests and benchmarks
run identically; drop the real files into DATA_HOME to train on them.
"""
import hashlib
import os

import numpy as np

__all__ = ["DATA_HOME", "download", "md5file", "split",
           "cluster_files_reader", "convert", "synthetic_rng",
           "synthetic_size", "have_real_data"]

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def _data_path(module_name, filename):
    return os.path.join(DATA_HOME, module_name, filename)


def have_real_data(module_name, filename):
    return os.path.exists(_data_path(module_name, filename))


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum=None, save_name=None):
    """Zero-egress 'download': resolve to the local cache or fail loudly."""
    filename = save_name or url.split("/")[-1]
    path = _data_path(module_name, filename)
    if os.path.exists(path):
        if md5sum and md5file(path) != md5sum:
            raise IOError("cached file %s fails md5 check" % path)
        return path
    raise IOError(
        "no network egress and %s not cached; place the file at %s or use "
        "the synthetic fallback readers (the default)" % (url, path))


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    """Split reader samples into chunked pickle files (reference parity)."""
    import pickle
    dumper = dumper or pickle.dump
    indx_f = 0
    batched = []
    out_files = []

    def _flush():
        nonlocal indx_f, batched
        if not batched:
            return
        name = suffix % indx_f
        with open(name, "wb") as f:
            dumper(batched, f)
        out_files.append(name)
        batched = []
        indx_f += 1

    for sample in reader():
        batched.append(sample)
        if len(batched) == line_count:
            _flush()
    _flush()
    return out_files


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """Read the shard of chunked files belonging to this trainer."""
    import glob
    import pickle
    loader = loader or pickle.load

    def reader():
        flist = sorted(glob.glob(files_pattern))
        for i, fn in enumerate(flist):
            if i % trainer_count == trainer_id:
                with open(fn, "rb") as f:
                    for sample in loader(f):
                        yield sample
    return reader


def convert(output_path, reader, line_count, name_prefix):
    """Serialize reader samples into recordio shards (reference parity,
    backed by our native recordio writer)."""
    from .. import recordio_writer
    indx_f = 0
    count = 0
    buffered = []

    def _flush():
        nonlocal indx_f, buffered
        if not buffered:
            return
        path = os.path.join(output_path,
                            "%s-%05d.recordio" % (name_prefix, indx_f))
        recordio_writer.convert_reader_to_recordio_file(
            path, lambda: iter(buffered))
        buffered = []
        indx_f += 1

    for sample in reader():
        buffered.append(sample)
        count += 1
        if len(buffered) == line_count:
            _flush()
    _flush()
    return count


# ---------------------------------------------------------------- synthetic

def synthetic_rng(module_name, split_name, salt=0):
    """Deterministic per-(dataset, split) RandomState — same records every
    run, every process (seed is a stable hash, not builtin hash())."""
    key = "%s/%s/%d" % (module_name, split_name, salt)
    seed = int(hashlib.md5(key.encode()).hexdigest()[:8], 16)
    return np.random.RandomState(seed)


def synthetic_size(default_train, default_test):
    """Synthetic dataset sizes, shrinkable for tests via env var
    PADDLE_TPU_SYNTH_SCALE (a float multiplier)."""
    scale = float(os.environ.get("PADDLE_TPU_SYNTH_SCALE", "1.0"))
    return max(8, int(default_train * scale)), max(4, int(default_test * scale))


def word_dict(size, extra=()):
    """Synthetic vocabulary 'w0'..'wN' (+ special tokens at the front)."""
    d = {}
    for i, tok in enumerate(extra):
        d[tok] = i
    for i in range(size - len(extra)):
        d["w%d" % i] = i + len(extra)
    return d
