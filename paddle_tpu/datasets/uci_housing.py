"""UCI housing regression dataset (506 samples, 13 features).

Parity: python/paddle/v2/dataset/uci_housing.py — train()/test() yield
(feature_vector[13] float32, [price] float32), features normalized. Synthetic
fallback: a fixed random linear model + noise, so fit_a_line genuinely
converges on it.
"""
import numpy as np

from . import common

__all__ = ["train", "test", "feature_num", "convert"]

feature_num = 13
_TRAIN_N, _TEST_N = 404, 102  # the real 80/20 split of 506


def _make(split_name, n):
    rng = common.synthetic_rng("uci_housing", "model")  # shared true model
    w = rng.randn(feature_num).astype(np.float32)
    b = np.float32(rng.randn() * 2)
    rng = common.synthetic_rng("uci_housing", split_name)
    xs = rng.randn(n, feature_num).astype(np.float32)
    ys = xs @ w + b + rng.randn(n).astype(np.float32) * 0.1
    return xs, ys.astype(np.float32)


def _reader_creator(split_name, n):
    def reader():
        xs, ys = _make(split_name, n)
        for x, y in zip(xs, ys):
            yield x, np.array([y], dtype=np.float32)
    return reader


def train():
    return _reader_creator("train", _TRAIN_N)


def test():
    return _reader_creator("test", _TEST_N)


def convert(path):
    common.convert(path, train(), 1000, "uci_housing_train")
    common.convert(path, test(), 1000, "uci_housing_test")
