"""UCI housing regression dataset (506 samples, 13 features).

Parity: python/paddle/v2/dataset/uci_housing.py — train()/test() yield
(feature_vector[13] float32, [price] float32), features min-max normalized
like the reference's feature_range scaling. Real `housing.data` under
DATA_HOME/uci_housing is used when present (whitespace table, 14 columns,
80/20 split like the reference); otherwise a fixed random linear model +
noise, so fit_a_line genuinely converges on it.
"""
import os

import numpy as np

from . import common

__all__ = ["train", "test", "feature_num", "convert"]

feature_num = 13
_TRAIN_N, _TEST_N = 404, 102  # the real 80/20 split of 506


def _load_real():
    """Parse housing.data (whitespace floats, 14 cols) and normalize each
    feature as (x - mean) / (max - min) — reference uci_housing.py:67-71
    (load_data loop); the label column stays raw."""
    path = os.path.join(common.DATA_HOME, "uci_housing", "housing.data")
    data = np.loadtxt(path, dtype=np.float32).reshape(-1, feature_num + 1)
    feats = data[:, :feature_num]
    lo, hi = feats.min(axis=0), feats.max(axis=0)
    avg = feats.mean(axis=0)
    span = np.where(hi > lo, hi - lo, 1.0)
    data[:, :feature_num] = (feats - avg) / span
    n_train = int(len(data) * 0.8)
    return data[:n_train], data[n_train:]


def _make(split_name, n):
    rng = common.synthetic_rng("uci_housing", "model")  # shared true model
    w = rng.randn(feature_num).astype(np.float32)
    b = np.float32(rng.randn() * 2)
    rng = common.synthetic_rng("uci_housing", split_name)
    xs = rng.randn(n, feature_num).astype(np.float32)
    ys = xs @ w + b + rng.randn(n).astype(np.float32) * 0.1
    return xs, ys.astype(np.float32)


def _reader_creator(split_name, n):
    # creator-time decision + parse (like the sibling loaders and the
    # reference's load_data): epochs re-yield from memory, not the file
    if common.have_real_data("uci_housing", "housing.data"):
        tr, te = _load_real()
        rows = tr if split_name == "train" else te

        def real_reader():
            for row in rows:
                yield row[:feature_num], row[feature_num:]
        return real_reader

    def reader():
        xs, ys = _make(split_name, n)
        for x, y in zip(xs, ys):
            yield x, np.array([y], dtype=np.float32)
    return reader


def train():
    return _reader_creator("train", _TRAIN_N)


def test():
    return _reader_creator("test", _TEST_N)


def convert(path):
    common.convert(path, train(), 1000, "uci_housing_train")
    common.convert(path, test(), 1000, "uci_housing_test")
