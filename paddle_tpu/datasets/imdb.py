"""IMDB movie-review sentiment.

Parity: python/paddle/v2/dataset/imdb.py — build_dict, word_dict,
train(word_idx)/test(word_idx) yield (word-id sequence, 0/1 label). The
real `aclImdb_v1.tar.gz` under DATA_HOME/imdb is read when present
(reference tokenize(): tar members matched by train/pos etc., lowercased,
punctuation stripped); synthetic fallback: two sentiment-biased unigram
distributions over the vocabulary, so an LSTM/conv classifier genuinely
separates them.
"""
import collections
import os
import re
import string
import tarfile

import numpy as np

from . import common

__all__ = ["build_dict", "word_dict", "train", "test", "convert"]

_VOCAB = 5148  # matches the book chapter's cutoff-150 dict size era
_TRAIN_N, _TEST_N = common.synthetic_size(600, 200)
_TAR = "aclImdb_v1.tar.gz"


def _tokenize(pattern):
    """Yield token lists for tar members matching `pattern` (reference
    imdb.py tokenize: lowercase, strip punctuation, split)."""
    path = os.path.join(common.DATA_HOME, "imdb", _TAR)
    trans = str.maketrans("", "", string.punctuation)
    with tarfile.open(path) as tar:
        for m in tar.getmembers():
            if bool(pattern.match(m.name)):
                doc = tar.extractfile(m).read().decode("latin-1")
                yield doc.lower().translate(trans).split()


def build_dict(pattern=None, cutoff=150):
    """Vocabulary dict word -> id; ids are frequency-ranked (ties broken
    alphabetically), strict `> cutoff` pruning, '<unk>' last — exactly the
    reference build_dict (imdb.py:85), defaulting to the labeled
    train+test corpus the reference book's word_dict used."""
    if common.have_real_data("imdb", _TAR):
        pattern = pattern or re.compile(
            r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$")
        if isinstance(pattern, str):
            pattern = re.compile(pattern)
        counts = collections.Counter()
        for words in _tokenize(pattern):
            counts.update(words)
        kept = sorted(((w, c) for w, c in counts.items() if c > cutoff),
                      key=lambda x: (-x[1], x[0]))
        d = {w: i for i, (w, c) in enumerate(kept)}
        d["<unk>"] = len(d)
        return d
    d = common.word_dict(_VOCAB - 1)
    d["<unk>"] = len(d)
    return d


def word_dict():
    return build_dict()


def _reader_creator(split_name, n, word_idx):
    vocab = len(word_idx)

    if common.have_real_data("imdb", _TAR):
        unk = word_idx["<unk>"]
        # one tar pass for both labels, docs cached like the reference's
        # INS list (reference reader_creator loads at creation time)
        pos_pat = re.compile(r"aclImdb/%s/pos/.*\.txt$" % split_name)
        neg_pat = re.compile(r"aclImdb/%s/neg/.*\.txt$" % split_name)
        both = re.compile(r"aclImdb/%s/((pos)|(neg))/.*\.txt$" % split_name)
        path = os.path.join(common.DATA_HOME, "imdb", _TAR)
        pos_docs, neg_docs = [], []
        with tarfile.open(path) as tar:
            trans = str.maketrans("", "", string.punctuation)
            for m in tar.getmembers():
                if not both.match(m.name):
                    continue
                doc = tar.extractfile(m).read().decode("latin-1")
                ids = [word_idx.get(w, unk)
                       for w in doc.lower().translate(trans).split()]
                (pos_docs if pos_pat.match(m.name) else neg_docs).append(ids)
        ins = [(d, 0) for d in pos_docs] + [(d, 1) for d in neg_docs]

        def real_reader():
            # reference order: all pos docs (label 0) then all neg (label 1)
            for doc, label in ins:
                yield doc, label
        return real_reader

    def reader():
        rng = common.synthetic_rng("imdb", split_name)
        # positive reviews draw from the front of the vocab, negative from
        # the back; overlap keeps the task non-trivial
        for i in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 64))
            if label:
                ids = rng.randint(0, int(vocab * 0.6), length)
            else:
                ids = rng.randint(int(vocab * 0.4), vocab, length)
            yield ids.astype(np.int64).tolist(), label
    return reader


def train(word_idx):
    return _reader_creator("train", _TRAIN_N, word_idx)


def test(word_idx):
    return _reader_creator("test", _TEST_N, word_idx)


def convert(path):
    w = word_dict()
    common.convert(path, train(w), 1000, "imdb_train")
    common.convert(path, test(w), 1000, "imdb_test")
