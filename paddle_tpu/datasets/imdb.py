"""IMDB movie-review sentiment.

Parity: python/paddle/v2/dataset/imdb.py — build_dict, word_dict,
train(word_idx)/test(word_idx) yield (word-id sequence, 0/1 label).
Synthetic fallback: two sentiment-biased unigram distributions over the
vocabulary, so an LSTM/conv classifier genuinely separates them.
"""
import numpy as np

from . import common

__all__ = ["build_dict", "word_dict", "train", "test", "convert"]

_VOCAB = 5148  # matches the book chapter's cutoff-150 dict size era
_TRAIN_N, _TEST_N = common.synthetic_size(600, 200)


def build_dict(pattern=None, cutoff=150):
    """Vocabulary dict word -> id; '<unk>' is the last id (reference puts
    <unk> at len(dict))."""
    d = common.word_dict(_VOCAB - 1)
    d["<unk>"] = len(d)
    return d


def word_dict():
    return build_dict()


def _reader_creator(split_name, n, word_idx):
    vocab = len(word_idx)

    def reader():
        rng = common.synthetic_rng("imdb", split_name)
        # positive reviews draw from the front of the vocab, negative from
        # the back; overlap keeps the task non-trivial
        for i in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 64))
            if label:
                ids = rng.randint(0, int(vocab * 0.6), length)
            else:
                ids = rng.randint(int(vocab * 0.4), vocab, length)
            yield ids.astype(np.int64).tolist(), label
    return reader


def train(word_idx):
    return _reader_creator("train", _TRAIN_N, word_idx)


def test(word_idx):
    return _reader_creator("test", _TEST_N, word_idx)


def convert(path):
    w = word_dict()
    common.convert(path, train(w), 1000, "imdb_train")
    common.convert(path, test(w), 1000, "imdb_test")
