"""MovieLens-1M recommender dataset.

Parity: python/paddle/v2/dataset/movielens.py — train()/test() yield
(user_id, gender_id, age_id, job_id, movie_id, category_ids, title_ids,
[rating]); plus max_user_id/max_movie_id/max_job_id/age_table and the
MovieInfo/UserInfo tables. The real `ml-1m.zip` under DATA_HOME/movielens
is parsed when present ('::'-separated movies/users/ratings.dat, title
year stripped, rating scaled x2-5, random.Random(0) 10% test split —
reference movielens.py:101-160 exactly, with the title/category dicts
built in sorted order for determinism). Synthetic fallback: latent-factor
ratings (user·movie affinity), so the recommender model genuinely learns.
"""
import os
import random
import re
import zipfile

import numpy as np

from . import common

__all__ = ["train", "test", "get_movie_title_dict", "max_movie_id",
           "max_user_id", "max_job_id", "age_table", "movie_categories",
           "convert", "MovieInfo", "UserInfo"]

age_table = [1, 18, 25, 35, 45, 50, 56]

_N_USERS = 944       # ml-100k-scale ids, 1-based like the real data
_N_MOVIES = 1683
_N_JOBS = 21
_N_CATEGORIES = 18
_TITLE_VOCAB = 1024
_TRAIN_N, _TEST_N = common.synthetic_size(2000, 400)


class MovieInfo(object):
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [self.index, [c for c in self.categories],
                [t for t in self.title]]


class UserInfo(object):
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]


def max_user_id():
    if _have_real():
        return max(_real_meta()[0])
    return _N_USERS - 1


def max_movie_id():
    if _have_real():
        return max(_real_meta()[1])
    return _N_MOVIES - 1


def max_job_id():
    if _have_real():
        return max(u.job_id for u in _real_meta()[0].values())
    return _N_JOBS - 1


def movie_categories():
    if _have_real():
        return dict(_real_meta()[3])
    return {"cat%d" % i: i for i in range(_N_CATEGORIES)}


def get_movie_title_dict():
    if _have_real():
        return dict(_real_meta()[2])
    return common.word_dict(_TITLE_VOCAB)


_REAL_CACHE = None


def _have_real():
    return common.have_real_data("movielens", "ml-1m.zip")


def _real_meta():
    """Parse ml-1m.zip into (users, movies, title_dict, cat_dict) with
    MovieInfo values pre-resolved to id lists."""
    global _REAL_CACHE
    if _REAL_CACHE is not None:
        return _REAL_CACHE
    path = os.path.join(common.DATA_HOME, "movielens", "ml-1m.zip")
    pattern = re.compile(r"^(.*)\((\d+)\)$")
    raw_movies = {}
    title_words, cat_names = set(), set()
    with zipfile.ZipFile(path) as z:
        with z.open("ml-1m/movies.dat") as f:
            for line in f:
                mid, title, cats = \
                    line.decode("latin-1").strip().split("::")
                cats = cats.split("|")
                cat_names.update(cats)
                m = pattern.match(title)
                title = m.group(1) if m else title
                raw_movies[int(mid)] = (title, cats)
                title_words.update(w.lower() for w in title.split())
        users = {}
        with z.open("ml-1m/users.dat") as f:
            for line in f:
                uid, gender, age, job = \
                    line.decode("latin-1").strip().split("::")[:4]
                users[int(uid)] = UserInfo(uid, gender, age, job)
    title_dict = {w: i for i, w in enumerate(sorted(title_words))}
    cat_dict = {c: i for i, c in enumerate(sorted(cat_names))}
    movies = {}
    for mid, (title, cats) in raw_movies.items():
        movies[mid] = MovieInfo(
            mid, [cat_dict[c] for c in cats],
            [title_dict[w.lower()] for w in title.split()])
    _REAL_CACHE = (users, movies, title_dict, cat_dict)
    return _REAL_CACHE


def _real_reader(is_test, rand_seed=0, test_ratio=0.1):
    users, movies, _, _ = _real_meta()
    path = os.path.join(common.DATA_HOME, "movielens", "ml-1m.zip")

    def reader():
        rand = random.Random(x=rand_seed)
        with zipfile.ZipFile(path) as z:
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (rand.random() < test_ratio) != is_test:
                        continue
                    uid, mid, rating, _ts = \
                        line.decode("latin-1").strip().split("::")
                    rating = float(rating) * 2 - 5.0  # reference scaling
                    yield tuple(users[int(uid)].value() +
                                movies[int(mid)].value() + [[rating]])
    return reader


_TABLES_CACHE = None


def _tables():
    # memoized like the reference's module-global MOVIE_INFO/USER_INFO
    # (python/paddle/v2/dataset/movielens.py __initialize_meta_info__)
    global _TABLES_CACHE
    if _TABLES_CACHE is not None:
        return _TABLES_CACHE
    rng = common.synthetic_rng("movielens", "tables")
    movies = {}
    for mid in range(1, _N_MOVIES):
        ncat = int(rng.randint(1, 4))
        cats = rng.choice(_N_CATEGORIES, ncat, replace=False).tolist()
        tlen = int(rng.randint(1, 6))
        title = rng.randint(0, _TITLE_VOCAB, tlen).tolist()
        movies[mid] = MovieInfo(mid, cats, title)
    users = {}
    for uid in range(1, _N_USERS):
        users[uid] = UserInfo(
            uid, "M" if rng.rand() < 0.5 else "F",
            age_table[int(rng.randint(0, len(age_table)))],
            int(rng.randint(0, _N_JOBS)))
    # latent factors driving ratings
    uf = rng.randn(_N_USERS, 8).astype(np.float32)
    mf = rng.randn(_N_MOVIES, 8).astype(np.float32)
    _TABLES_CACHE = (users, movies, uf, mf)
    return _TABLES_CACHE


def movie_info():
    return _real_meta()[1] if _have_real() else _tables()[1]


def user_info():
    return _real_meta()[0] if _have_real() else _tables()[0]


def _reader_creator(split_name, n):
    if _have_real():
        return _real_reader(is_test=(split_name == "test"))
    def reader():
        users, movies, uf, mf = _tables()
        rng = common.synthetic_rng("movielens", split_name)
        for _ in range(n):
            uid = int(rng.randint(1, _N_USERS))
            mid = int(rng.randint(1, _N_MOVIES))
            raw = float(uf[uid] @ mf[mid]) / 4.0 + rng.randn() * 0.2
            rating = float(np.clip(np.round(raw + 3.0), 1, 5))
            yield tuple(users[uid].value() + movies[mid].value() + [[rating]])
    return reader


def train():
    return _reader_creator("train", _TRAIN_N)


def test():
    return _reader_creator("test", _TEST_N)


def convert(path):
    common.convert(path, train(), 1000, "movielens_train")
    common.convert(path, test(), 1000, "movielens_test")
