"""MNIST digits.

Parity: python/paddle/v2/dataset/mnist.py — train()/test() yield
(image float32[784] in [-1, 1], label int). Real idx-format files under
DATA_HOME/mnist are used when present; otherwise a deterministic synthetic
set of blurred class-template digits that a LeNet genuinely learns.
"""
import gzip
import os
import struct

import numpy as np

from . import common

__all__ = ["train", "test", "convert"]

_TRAIN_N, _TEST_N = common.synthetic_size(2048, 512)
_CLASSES = 10


def _synthetic(split_name, n):
    tmpl_rng = common.synthetic_rng("mnist", "templates")
    templates = tmpl_rng.rand(_CLASSES, 784).astype(np.float32)
    rng = common.synthetic_rng("mnist", split_name)
    labels = rng.randint(0, _CLASSES, n)
    imgs = templates[labels] + rng.randn(n, 784).astype(np.float32) * 0.35
    imgs = np.clip(imgs, 0.0, 1.0) * 2.0 - 1.0  # reference scales to [-1,1]
    return imgs.astype(np.float32), labels.astype(np.int64)


def _read_idx(image_path, label_path):
    with gzip.open(label_path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)
    with gzip.open(image_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
    images = images.astype(np.float32) / 255.0 * 2.0 - 1.0
    return images, labels


def _reader_creator(split_name, n, image_file, label_file):
    def reader():
        if common.have_real_data("mnist", image_file) and \
                common.have_real_data("mnist", label_file):
            imgs, labels = _read_idx(
                os.path.join(common.DATA_HOME, "mnist", image_file),
                os.path.join(common.DATA_HOME, "mnist", label_file))
        else:
            imgs, labels = _synthetic(split_name, n)
        for img, lab in zip(imgs, labels):
            yield img, int(lab)
    return reader


def train():
    return _reader_creator("train", _TRAIN_N,
                           "train-images-idx3-ubyte.gz",
                           "train-labels-idx1-ubyte.gz")


def test():
    return _reader_creator("test", _TEST_N,
                           "t10k-images-idx3-ubyte.gz",
                           "t10k-labels-idx1-ubyte.gz")


def convert(path):
    common.convert(path, train(), 1000, "mnist_train")
    common.convert(path, test(), 1000, "mnist_test")
