"""v2 minibatch (python/paddle/v2/minibatch.py): group a sample reader's
output into lists of batch_size samples."""
from ..reader import batch

__all__ = ["batch"]
