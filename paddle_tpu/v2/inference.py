"""v2 inference (python/paddle/v2/inference.py).

Inference(output_layer, parameters) prunes the captured main program to the
output layer's forward subgraph (Program.prune + clone(for_test)), so
optimizer/backward ops appended by a trainer never run — then executes it
batch by batch in the Parameters' scope.
"""
import numpy as np

import paddle_tpu as fluid

__all__ = ["infer", "Inference"]


class Inference(object):
    def __init__(self, output_layer, parameters):
        outputs = output_layer if isinstance(output_layer, (list, tuple)) \
            else [output_layer]
        self.output_names = [o.name for o in outputs]
        self.__parameters__ = parameters
        topo = parameters.topology
        self.__program__ = topo.main_program.prune(outputs, for_test=True)
        self._exe = fluid.Executor(fluid.CPUPlace())

    def _feeder(self, feeding):
        from .topology import make_feeder
        # feed only data layers the pruned graph still reads — but resolve
        # column positions against the FULL feeding order, so a pruned-away
        # layer (e.g. the label) skips its input column instead of shifting
        # the remaining ones onto wrong columns
        keep = set(self.__program__.global_block().vars)
        return make_feeder(self.__parameters__.topology, feeding,
                           keep_names=keep)

    def iter_infer_field(self, field, **kwargs):
        for result in self.iter_infer(**kwargs):
            yield result

    def iter_infer(self, input, feeding=None):
        feeder = self._feeder(feeding)
        with fluid.scope_guard(self.__parameters__.scope):
            self.__parameters__._materialize()
            outs = self._exe.run(self.__program__,
                                 feed=feeder.feed(input),
                                 fetch_list=self.output_names)
        yield [np.asarray(o) for o in outs]

    def infer(self, input, field="value", feeding=None, **kwargs):
        rets = []
        for outs in self.iter_infer(input=input, feeding=feeding):
            rets.extend(outs)
        if len(rets) == 1:
            return rets[0]
        return rets


def infer(output_layer, parameters, input, feeding=None, field="value"):
    """paddle.infer(...): one-shot inference over a minibatch
    (reference: inference.py:32's module-level helper)."""
    return Inference(output_layer=output_layer,
                     parameters=parameters).infer(input=input,
                                                  feeding=feeding,
                                                  field=field)
