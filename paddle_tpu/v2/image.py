"""Image IO + augmentation utilities.

Parity: python/paddle/v2/image.py — load_image / resize_short / to_chw /
center_crop / random_crop / left_right_flip / simple_transform /
load_and_transform / batch_images_from_tar, the preprocessing pipeline the
image datasets (flowers, imagenet-style folders) feed through.

TPU-era notes: the reference resized through cv2 bicubic; this rebuild is
numpy-native (bilinear resize implemented here) so the data path has no
mandatory cv2/PIL dependency — file DECODING still needs one of them and
raises a clear error if neither is importable, but every array→array
transform below runs on plain ndarrays. Deterministic augmentation: pass
`rng` (numpy Generator/RandomState) to the random ops instead of relying on
the global seed.
"""
import io as _io
import os
import tarfile

import numpy as np

__all__ = [
    "load_image_bytes", "load_image", "resize_short", "to_chw",
    "center_crop", "random_crop", "left_right_flip", "simple_transform",
    "load_and_transform", "batch_images_from_tar", "load_image_batch",
]


_DECODER = None  # probed once: failed imports re-scan sys.path every call


def _decoder():
    global _DECODER
    if _DECODER is None:
        try:
            import cv2
            _DECODER = ("cv2", cv2)
        except ImportError:
            try:
                from PIL import Image
                _DECODER = ("pil", Image)
            except ImportError:
                _DECODER = (None, None)
    return _DECODER


def load_image_bytes(bytes_, is_color=True):
    """Decode an encoded image buffer to an HWC (or HW gray) uint8 array.

    Channel order is BGR — the cv2 convention the reference pipelines (and
    their per-channel mean constants) were built on — REGARDLESS of which
    decoder is installed, so models don't silently change behavior when
    the environment swaps cv2 for PIL."""
    kind, mod = _decoder()
    if kind == "cv2":
        flag = mod.IMREAD_COLOR if is_color else mod.IMREAD_GRAYSCALE
        img = mod.imdecode(np.frombuffer(bytes_, dtype="uint8"), flag)
        if img is None:
            raise ValueError("could not decode image buffer")
        return img  # cv2 decodes BGR natively
    if kind == "pil":
        img = mod.open(_io.BytesIO(bytes_))
        if is_color:
            return np.asarray(img.convert("RGB"))[:, :, ::-1]  # -> BGR
        return np.asarray(img.convert("L"))
    raise RuntimeError(
        "decoding image files needs cv2 or PIL; neither is importable. "
        "The array transforms (resize_short/crops/simple_transform) work "
        "without them — decode upstream and pass ndarrays.")


def load_image(file, is_color=True):
    """Load an image file to an HWC (or HW) uint8 array."""
    with open(file, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def _resize_bilinear(im, h_new, w_new):
    """Pure-numpy bilinear resize of an HWC/HW array (align_corners=False
    sampling, the cv2/PIL convention)."""
    h, w = im.shape[:2]
    if (h, w) == (h_new, w_new):
        return im
    ys = (np.arange(h_new) + 0.5) * (h / h_new) - 0.5
    xs = (np.arange(w_new) + 0.5) * (w / w_new) - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, w - 1)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)
    wx = np.clip(xs - x0, 0.0, 1.0)

    imf = im.astype(np.float32)
    rows0 = imf[y0]                      # [h_new, w, ...]
    rows1 = imf[y1]
    if im.ndim == 3:
        wy_ = wy[:, None, None]
        wx_ = wx[None, :, None]
    else:
        wy_ = wy[:, None]
        wx_ = wx[None, :]
    top = rows0[:, x0] * (1 - wx_) + rows0[:, x1] * wx_
    bot = rows1[:, x0] * (1 - wx_) + rows1[:, x1] * wx_
    out = top * (1 - wy_) + bot * wy_
    if np.issubdtype(im.dtype, np.integer):
        out = np.clip(np.rint(out), np.iinfo(im.dtype).min,
                      np.iinfo(im.dtype).max)
    return out.astype(im.dtype)


def resize_short(im, size):
    """Resize so the SHORTER edge equals `size`, keeping aspect ratio
    (reference image.py:163; bilinear here — see module docstring)."""
    h, w = im.shape[:2]
    if h > w:
        h_new, w_new = int(round(size * h / w)), size
    else:
        h_new, w_new = size, int(round(size * w / h))
    return _resize_bilinear(im, h_new, w_new)


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW (or any permutation)."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    return im[h_start:h_start + size, w_start:w_start + size]


def _randint(rng, lo, hi):
    """One draw in [lo, hi) from either rng flavor (Generator has
    .integers, RandomState has .randint)."""
    fn = getattr(rng, "integers", None) or rng.randint
    return int(fn(lo, hi))


def random_crop(im, size, is_color=True, rng=None):
    rng = rng or np.random
    h, w = im.shape[:2]
    h_start = _randint(rng, 0, h - size + 1)
    w_start = _randint(rng, 0, w - size + 1)
    return im[h_start:h_start + size, w_start:w_start + size]


def left_right_flip(im, is_color=True):
    if len(im.shape) == 3 and is_color:
        return im[:, ::-1, :]
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None, rng=None):
    """resize_short -> (random crop + 50% flip | center crop) -> CHW ->
    float32 - mean. Parity: reference image.py:291."""
    rng = rng or np.random
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color=is_color, rng=rng)
        if _randint(rng, 0, 2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color=is_color)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype("float32")
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and is_color:
            mean = mean[:, np.newaxis, np.newaxis]
        im = im - mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None, rng=None):
    im = load_image(filename, is_color)
    return simple_transform(im, resize_size, crop_size, is_train, is_color,
                            mean, rng=rng)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """Decode a tar of image files into fixed-size .npz batches next to it
    (reference image.py:48 wrote pickled batch files; npz is the
    version-stable equivalent). Returns the meta-file path listing the
    batch files, one per line."""
    out_path = "%s_%s" % (data_file, dataset_name)
    meta_file = os.path.join(out_path, "batch_meta")
    if os.path.exists(meta_file):
        return meta_file
    os.makedirs(out_path, exist_ok=True)
    data, labels, batch_files, n = [], [], [], 0

    def flush():
        # pickle-free layout: one concatenated byte buffer + offsets
        fname = os.path.join(out_path, "batch_%05d.npz" % n)
        buf = np.frombuffer(b"".join(data), dtype=np.uint8)
        offsets = np.cumsum([0] + [len(d) for d in data]).astype(np.int64)
        np.savez(fname, buffer=buf, offsets=offsets,
                 label=np.asarray(labels, dtype=np.int64))
        batch_files.append(fname)

    with tarfile.open(data_file) as tar:
        for member in tar.getmembers():
            if member.name not in img2label:
                continue
            data.append(tar.extractfile(member).read())
            labels.append(img2label[member.name])
            if len(data) == num_per_batch:
                flush()
                data, labels = [], []
                n += 1
        if data:
            flush()
    with open(meta_file, "w") as f:
        f.write("\n".join(batch_files))
    return meta_file


def load_image_batch(batch_file):
    """Read one batch written by batch_images_from_tar: returns
    (list of encoded-image bytes, labels int64 array)."""
    with np.load(batch_file) as z:
        buf = z["buffer"].tobytes()
        offsets = z["offsets"]
        labels = z["label"]
    images = [buf[offsets[i]:offsets[i + 1]]
              for i in range(len(offsets) - 1)]
    return images, labels
