"""v2 composite networks (python/paddle/v2/networks.py) over fluid.nets."""
import paddle_tpu as fluid
from .layer import _act_name

__all__ = ["simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
           "simple_lstm"]


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride, act=None, **kwargs):
    return fluid.nets.simple_img_conv_pool(
        input=input, filter_size=filter_size, num_filters=num_filters,
        pool_size=pool_size, pool_stride=pool_stride, act=_act_name(act))


def img_conv_group(input, conv_num_filter, pool_size, conv_filter_size=3,
                   conv_act=None, conv_with_batchnorm=False, pool_stride=1,
                   pool_type="max", **kwargs):
    return fluid.nets.img_conv_group(
        input=input, conv_num_filter=conv_num_filter, pool_size=pool_size,
        conv_filter_size=conv_filter_size, conv_act=_act_name(conv_act),
        conv_with_batchnorm=conv_with_batchnorm, pool_stride=pool_stride,
        pool_type=pool_type)


def sequence_conv_pool(input, num_filters, filter_size, act=None,
                       pool_type="max", **kwargs):
    return fluid.nets.sequence_conv_pool(
        input=input, num_filters=num_filters, filter_size=filter_size,
        act=_act_name(act), pool_type=pool_type)


def simple_lstm(input, size, **kwargs):
    fc = fluid.layers.fc(input=input, size=size * 4)
    h, c = fluid.layers.dynamic_lstm(input=fc, size=size * 4)
    return h
