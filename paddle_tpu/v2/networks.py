"""v2 composite networks (python/paddle/v2/networks.py) over fluid.nets."""
import paddle_tpu as fluid
from .layer import _act_name

__all__ = ["simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
           "simple_lstm", "simple_gru", "bidirectional_lstm",
           "bidirectional_gru"]


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride, act=None, **kwargs):
    return fluid.nets.simple_img_conv_pool(
        input=input, filter_size=filter_size, num_filters=num_filters,
        pool_size=pool_size, pool_stride=pool_stride, act=_act_name(act))


def img_conv_group(input, conv_num_filter, pool_size, conv_filter_size=3,
                   conv_act=None, conv_with_batchnorm=False, pool_stride=1,
                   pool_type="max", **kwargs):
    return fluid.nets.img_conv_group(
        input=input, conv_num_filter=conv_num_filter, pool_size=pool_size,
        conv_filter_size=conv_filter_size, conv_act=_act_name(conv_act),
        conv_with_batchnorm=conv_with_batchnorm, pool_stride=pool_stride,
        pool_type=pool_type)


def sequence_conv_pool(input, num_filters, filter_size, act=None,
                       pool_type="max", **kwargs):
    return fluid.nets.sequence_conv_pool(
        input=input, num_filters=num_filters, filter_size=filter_size,
        act=_act_name(act), pool_type=pool_type)


def simple_lstm(input, size, **kwargs):
    fc = fluid.layers.fc(input=input, size=size * 4)
    h, c = fluid.layers.dynamic_lstm(input=fc, size=size * 4)
    return h


def simple_gru(input, size, **kwargs):
    """Parity: trainer_config_helpers/networks.py simple_gru (fc + gru)."""
    fc = fluid.layers.fc(input=input, size=size * 3)
    return fluid.layers.dynamic_gru(input=fc, size=size)


def bidirectional_lstm(input, size, return_seq=False, **kwargs):
    """Parity: networks.py bidirectional_lstm — fwd + bwd lstm, concat.
    return_seq=False returns the concat of each direction's last step."""
    fwd_in = fluid.layers.fc(input=input, size=size * 4)
    fwd, _ = fluid.layers.dynamic_lstm(input=fwd_in, size=size * 4)
    bwd_in = fluid.layers.fc(input=input, size=size * 4)
    bwd, _ = fluid.layers.dynamic_lstm(input=bwd_in, size=size * 4,
                                       is_reverse=True)
    if return_seq:
        return fluid.layers.concat(input=[fwd, bwd], axis=-1)
    # the reverse scan's full-context state sits at the FIRST original
    # position (it processed T-1..0), so the backward summary is first_seq
    # — the reference networks.py does the same
    return fluid.layers.concat(
        input=[fluid.layers.sequence_last_step(input=fwd),
               fluid.layers.sequence_first_step(input=bwd)], axis=-1)


def bidirectional_gru(input, size, return_seq=False, **kwargs):
    """Parity: networks.py bidirectional_gru."""
    fwd_in = fluid.layers.fc(input=input, size=size * 3)
    fwd = fluid.layers.dynamic_gru(input=fwd_in, size=size)
    bwd_in = fluid.layers.fc(input=input, size=size * 3)
    bwd = fluid.layers.dynamic_gru(input=bwd_in, size=size,
                                   is_reverse=True)
    if return_seq:
        return fluid.layers.concat(input=[fwd, bwd], axis=-1)
    return fluid.layers.concat(
        input=[fluid.layers.sequence_last_step(input=fwd),
               fluid.layers.sequence_first_step(input=bwd)], axis=-1)
