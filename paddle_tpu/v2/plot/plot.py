"""Training-curve plotter (API parity: python/paddle/v2/plot/plot.py).

Same public contract — ``Ploter(*titles)``, ``append(title, step, value)``,
``plot(path=None)``, ``reset()``, honoring ``DISABLE_PLOT=True`` — but
built headless-first for TPU workers: points are kept as (step, value)
pairs regardless of environment, and the matplotlib/IPython display stack
is a lazy optional import instead of a hard dependency, so the same
training script runs in a notebook (live-refreshing figure) and on a pod
worker (data collection only) without edits.
"""
import os


class PlotData(object):
    """One curve. Exposes mutable .step / .value lists (reference
    contract: user code may append to or reassign them directly)."""

    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []

    def __len__(self):
        return len(self.step)


def _display_stack():
    """(pyplot, display) when a drawing environment exists, else None."""
    if os.environ.get("DISABLE_PLOT") == "True":
        return None
    try:
        import matplotlib.pyplot as plt
        from IPython import display
    except Exception:
        return None
    return plt, display


class Ploter(object):
    def __init__(self, *args):
        self.__args__ = args
        self.__plot_data__ = {}
        for title in args:
            self.__plot_data__[title] = PlotData()
        self.__disable_plot__ = os.environ.get("DISABLE_PLOT")
        stack = _display_stack()
        self.plt = stack[0] if stack else None
        self.display = stack[1] if stack else None

    def __plot_is_disabled__(self):
        return self.__disable_plot__ == "True"

    def append(self, title, step, value):
        if title not in self.__plot_data__:
            raise AssertionError("unknown curve title %r (have %s)"
                                 % (title, list(self.__plot_data__)))
        self.__plot_data__[title].append(step, value)

    def plot(self, path=None):
        if self.plt is None:
            return  # headless / disabled: keep collecting, draw nothing
        drawn = [t for t in self.__args__ if len(self.__plot_data__[t])]
        for title in drawn:
            curve = self.__plot_data__[title]
            self.plt.plot(curve.step, curve.value)
        self.plt.legend(drawn, loc="upper left")
        if path is not None:
            self.plt.savefig(path)
        else:
            self.display.clear_output(wait=True)
            self.display.display(self.plt.gcf())
        self.plt.gcf().clear()

    def reset(self):
        for curve in self.__plot_data__.values():
            curve.reset()
