"""Training-curve plotter (parity: python/paddle/v2/plot/plot.py).

The reference imports matplotlib + IPython eagerly unless
DISABLE_PLOT=True; here the imports are lazy AND optional, so the shim is
usable on headless TPU workers: data is always collected, drawing happens
only when a display stack exists.
"""
import os


class PlotData(object):
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter(object):
    def __init__(self, *args):
        self.__args__ = args
        self.__plot_data__ = {title: PlotData() for title in args}
        self.__disable_plot__ = os.environ.get("DISABLE_PLOT")
        self.plt = None
        self.display = None
        if not self.__plot_is_disabled__():
            try:
                import matplotlib.pyplot as plt
                from IPython import display
                self.plt = plt
                self.display = display
            except Exception:
                pass  # headless: collect data, skip drawing

    def __plot_is_disabled__(self):
        return self.__disable_plot__ == "True"

    def append(self, title, step, value):
        assert isinstance(title, str)
        assert title in self.__plot_data__
        self.__plot_data__[title].append(step, value)

    def plot(self, path=None):
        if self.__plot_is_disabled__() or self.plt is None:
            return
        titles = []
        for title in self.__args__:
            data = self.__plot_data__[title]
            if len(data.step) > 0:
                self.plt.plot(data.step, data.value)
                titles.append(title)
        self.plt.legend(titles, loc="upper left")
        if path is None:
            self.display.clear_output(wait=True)
            self.display.display(self.plt.gcf())
        else:
            self.plt.savefig(path)
        self.plt.gcf().clear()

    def reset(self):
        for data in self.__plot_data__.values():
            data.reset()
