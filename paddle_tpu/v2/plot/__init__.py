"""paddle.v2.plot — notebook training-curve plotting.

Parity: python/paddle/v2/plot/{__init__.py,plot.py} (Ploter/PlotData).
Same contract: append (title, step, value) points, .plot() refreshes a
matplotlib figure inside IPython, and DISABLE_PLOT=True (or a headless
environment without matplotlib/IPython — the normal case on a TPU pod
worker) degrades to pure data collection so training scripts keep running.
"""
from .plot import Ploter, PlotData

__all__ = ["Ploter", "PlotData"]
