"""v2 Topology: the captured model graph (python/paddle/v2/topology.py).

The reference serialized a gserver ModelConfig proto from the layer DAG.
Here the v2 layer calls have already built fluid programs, so Topology just
captures the default main/startup programs plus the ordered data layers —
everything the trainer / inference engine needs.
"""
from .. import framework as _fw

__all__ = ["Topology"]


class Topology(object):
    def __init__(self, layers, extra_layers=None):
        if not isinstance(layers, (list, tuple)):
            layers = [layers]
        self.layers = list(layers)
        if extra_layers is not None:
            extra = extra_layers if isinstance(extra_layers, (list, tuple)) \
                else [extra_layers]
            self.layers.extend(extra)
        self.main_program = _fw.default_main_program()
        self.startup_program = _fw.default_startup_program()

    def data_layers(self):
        """Ordered {name: Variable} of data layers (creation order — the
        default reader column order, like the reference's data_type())."""
        out = {}
        for name, var in self.main_program.global_block().vars.items():
            if getattr(var, "is_data", False) and "@SEQLEN" not in name:
                out[name] = var
        return out

    def data_type(self):
        """[(name, v2 InputType-or-dtype)] in data-layer order."""
        return [(name, getattr(var, "v2_type", var.dtype))
                for name, var in self.data_layers().items()]

    def proto(self):
        """The serialized model config (reference: ModelConfig proto); here
        the printable program desc serves the same debugging role."""
        return self.main_program.to_string()


class _ColumnFeeder(object):
    """Projects each input row onto explicit source columns before handing
    it to the (strictly positional) DataFeeder — so a {name: column} feeding
    dict with gaps, or a pruned-away data layer, never shifts the remaining
    names onto wrong columns."""

    def __init__(self, feeder, columns):
        self._feeder = feeder
        self._columns = columns  # source column index per feed name

    def feed(self, data):
        rows = [[row[c] for c in self._columns] for row in data]
        return self._feeder.feed(rows)


def make_feeder(topology, feeding=None, keep_names=None):
    """Resolve the v2 feeding spec into a feeder (shared by trainer.SGD and
    inference.Inference — reference: v2/trainer.py feeding handling).

    feeding: None, a {name: input-row column} dict, or an ordered name list.
    keep_names: names the (possibly pruned) program still reads.

    Column semantics (reference parity): an explicit feeding dict/list pins
    each name to its input-row column — pruned names drop out without
    shifting the others. With feeding=None the input rows are expected to
    contain exactly the KEPT data layers in creation order (a v2 inference
    caller feeds only the columns the pruned topology reads)."""
    from .. import data_feeder as _df
    if feeding is None:
        pairs = list(enumerate(topology.data_layers()))
    elif isinstance(feeding, dict):
        pairs = sorted((c, n) for n, c in feeding.items())
    else:
        pairs = list(enumerate(feeding))
    if keep_names is not None:
        pairs = [(c, n) for c, n in pairs if n in keep_names]
    if feeding is None:
        # no explicit columns: rows contain only the kept layers, in order
        pairs = [(i, n) for i, (_, n) in enumerate(pairs)]
    names = [n for _, n in pairs]
    feeder = _df.DataFeeder(feed_list=names, program=topology.main_program)
    return _ColumnFeeder(feeder, [c for c, _ in pairs])
