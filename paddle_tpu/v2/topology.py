"""v2 Topology: the captured model graph (python/paddle/v2/topology.py).

The reference serialized a gserver ModelConfig proto from the layer DAG.
Here the v2 layer calls have already built fluid programs, so Topology just
captures the default main/startup programs plus the ordered data layers —
everything the trainer / inference engine needs.
"""
from .. import framework as _fw

__all__ = ["Topology"]


class Topology(object):
    def __init__(self, layers, extra_layers=None):
        if not isinstance(layers, (list, tuple)):
            layers = [layers]
        self.layers = list(layers)
        if extra_layers is not None:
            extra = extra_layers if isinstance(extra_layers, (list, tuple)) \
                else [extra_layers]
            self.layers.extend(extra)
        self.main_program = _fw.default_main_program()
        self.startup_program = _fw.default_startup_program()

    def data_layers(self):
        """Ordered {name: Variable} of data layers (creation order — the
        default reader column order, like the reference's data_type())."""
        out = {}
        for name, var in self.main_program.global_block().vars.items():
            if getattr(var, "is_data", False) and "@SEQLEN" not in name:
                out[name] = var
        return out

    def data_type(self):
        """[(name, v2 InputType-or-dtype)] in data-layer order."""
        return [(name, getattr(var, "v2_type", var.dtype))
                for name, var in self.data_layers().items()]

    def proto(self):
        """The serialized model config (reference: ModelConfig proto); here
        the printable program desc serves the same debugging role."""
        return self.main_program.to_string()
