"""v2 evaluator shim (parity: python/paddle/v2/evaluator.py).

The reference auto-generated its names from trainer_config_helpers
evaluators (classification_error, auc, ctc_error, ...) — a stack subsumed
by fluid (SURVEY.md §2 "Legacy v2 API"). The evaluators with fluid-era
equivalents are re-exported here from the fluid metrics/evaluator modules
so v2-style code finds them under the old names; the rest of the legacy
generator has no fluid counterpart and is out of scope.
"""
from ..evaluator import Accuracy, ChunkEvaluator, EditDistance  # noqa: F401

__all__ = ["classification_error", "Accuracy", "ChunkEvaluator",
           "EditDistance"]


def classification_error(input, label, **kwargs):
    """reference classification_error_evaluator ~ 1 - accuracy: returns the
    fluid accuracy layer's complement."""
    from .. import layers
    acc = layers.accuracy(input=input, label=label,
                          k=kwargs.get("top_k", 1))
    one = layers.fill_constant(shape=[1], dtype="float32", value=1.0)
    return layers.elementwise_sub(one, acc)
