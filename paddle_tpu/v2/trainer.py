"""v2 SGD trainer (python/paddle/v2/trainer.py:37).

The reference combined a GradientMachine, a ParameterUpdater and a
DataFeeder into the classic train loop with BeginPass/BeginIteration/
EndIteration/EndPass events. Here the loop drives the fluid Executor over
the captured topology: the update_equation's fluid optimizer is appended to
the captured main program once, and each batch is one jitted
forward+backward+update step on the accelerator.
"""
import numpy as np

import paddle_tpu as fluid
from . import event as v2_event
from . import optimizer as v2_optimizer
from .parameters import Parameters
from .topology import Topology

__all__ = ["SGD"]


def default_event_handler(event):
    pass


class SGD(object):
    """SGD(cost, parameters, update_equation).train(reader, num_passes,
    event_handler, feeding) — the full legacy surface; is_local/pserver_spec
    accepted for parity (distribution is the fluid DistributeTranspiler's
    job in this stack)."""

    def __init__(self, cost, parameters, update_equation, extra_layers=None,
                 is_local=True, pserver_spec=None, use_etcd=True):
        if not isinstance(parameters, Parameters):
            raise TypeError("parameters should be "
                            "paddle.v2.parameters.Parameters")
        if not isinstance(update_equation, v2_optimizer.Optimizer):
            raise TypeError("update equation parameter must be "
                            "paddle.v2.optimizer.Optimizer")
        self.__topology__ = parameters.topology
        if extra_layers is not None:
            extra = extra_layers if isinstance(extra_layers, (list, tuple)) \
                else [extra_layers]
            self.__topology__.layers.extend(extra)
        self.__parameters__ = parameters
        self.__optimizer__ = update_equation
        self.cost = cost if not isinstance(cost, (list, tuple)) else cost[0]
        self._exe = fluid.Executor(fluid.CPUPlace())
        # forward-only clone BEFORE optimizer ops, for test()/metrics
        self.__test_program__ = \
            self.__topology__.main_program.clone(for_test=True)
        with fluid.program_guard(self.__topology__.main_program,
                                 self.__topology__.startup_program):
            update_equation.fluid_opt.minimize(self.cost)

    def _feeder(self, feeding):
        from .topology import make_feeder
        return make_feeder(self.__topology__, feeding)

    def train(self, reader, num_passes=1, event_handler=None, feeding=None):
        if event_handler is None:
            event_handler = default_event_handler
        self.__parameters__._materialize()  # params + optimizer accumulators
        feeder = self._feeder(feeding)
        main = self.__topology__.main_program
        with fluid.scope_guard(self.__parameters__.scope):
            for pass_id in range(num_passes):
                event_handler(v2_event.BeginPass(pass_id))
                pass_costs = []
                for batch_id, data_batch in enumerate(reader()):
                    event_handler(v2_event.BeginIteration(pass_id, batch_id))
                    cost, = self._exe.run(main,
                                          feed=feeder.feed(data_batch),
                                          fetch_list=[self.cost])
                    cost = float(np.asarray(cost).reshape(-1)[0])
                    pass_costs.append(cost)
                    event_handler(v2_event.EndForwardBackward(
                        pass_id=pass_id, batch_id=batch_id, gm=None))
                    event_handler(v2_event.EndIteration(
                        pass_id=pass_id, batch_id=batch_id, cost=cost,
                        evaluator={"cost": cost}, gm=None))
                event_handler(v2_event.EndPass(
                    pass_id,
                    evaluator={"cost": float(np.mean(pass_costs))
                               if pass_costs else float("nan")},
                    gm=None))

    def test(self, reader, feeding=None):
        """Mean cost over the reader on the forward-only (is_test) graph."""
        feeder = self._feeder(feeding)
        total, n = 0.0, 0
        with fluid.scope_guard(self.__parameters__.scope):
            self.__parameters__._materialize()
            for data_batch in reader():
                cost, = self._exe.run(self.__test_program__,
                                      feed=feeder.feed(data_batch),
                                      fetch_list=[self.cost.name])
                total += float(np.asarray(cost).reshape(-1)[0]) \
                    * len(data_batch)
                n += len(data_batch)
        mean = total / max(n, 1)
        return v2_event.TestResult(evaluator={"cost": mean}, cost=mean)

    def save_parameter_to_tar(self, f):
        self.__parameters__.to_tar(f)
