"""v2 optimizers (python/paddle/v2/optimizer.py) -> fluid optimizers."""
import paddle_tpu as fluid

__all__ = ["Momentum", "Adam", "Adamax", "AdaGrad", "DecayedAdaGrad",
           "AdaDelta", "RMSProp", "ModelAverage", "L2Regularization"]


class Optimizer(object):
    def __init__(self, fluid_opt):
        self.fluid_opt = fluid_opt


def Momentum(momentum=None, learning_rate=1e-3, sparse=False, **kwargs):
    return Optimizer(fluid.optimizer.Momentum(
        learning_rate=learning_rate, momentum=momentum or 0.0))


def Adam(beta1=0.9, beta2=0.999, epsilon=1e-8, learning_rate=1e-3, **kw):
    return Optimizer(fluid.optimizer.Adam(
        learning_rate=learning_rate, beta1=beta1, beta2=beta2,
        epsilon=epsilon))


def Adamax(beta1=0.9, beta2=0.999, learning_rate=1e-3, **kwargs):
    return Optimizer(fluid.optimizer.Adamax(
        learning_rate=learning_rate, beta1=beta1, beta2=beta2))


def AdaGrad(learning_rate=1e-3, **kwargs):
    return Optimizer(fluid.optimizer.Adagrad(learning_rate=learning_rate))


def DecayedAdaGrad(rho=0.95, epsilon=1e-6, learning_rate=1e-3, **kwargs):
    return Optimizer(fluid.optimizer.DecayedAdagrad(
        learning_rate=learning_rate, decay=rho, epsilon=epsilon))


def AdaDelta(rho=0.95, epsilon=1e-6, learning_rate=1e-3, **kwargs):
    return Optimizer(fluid.optimizer.Adadelta(
        learning_rate=learning_rate, rho=rho, epsilon=epsilon))


def RMSProp(rho=0.95, epsilon=1e-6, learning_rate=1e-3, **kwargs):
    return Optimizer(fluid.optimizer.RMSProp(
        learning_rate=learning_rate, rho=rho, epsilon=epsilon))


def ModelAverage(average_window=0.5, **kwargs):
    return Optimizer(fluid.optimizer.ModelAverage(
        average_window_rate=average_window))


def L2Regularization(rate):
    from ..regularizer import L2Decay
    return L2Decay(rate)
