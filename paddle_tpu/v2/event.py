"""v2 training events (python/paddle/v2/event.py).

The trainer invokes event_handler with these before/after every pass and
iteration; `with_metric` carries the evaluator metrics of the span.
"""
__all__ = ["BeginPass", "EndPass", "BeginIteration", "EndIteration",
           "EndForwardBackward", "TestResult"]


class WithMetric(object):
    def __init__(self, evaluator):
        self.evaluator = evaluator

    @property
    def metrics(self):
        return dict(self.evaluator or {})


class TestResult(WithMetric):
    """Result of trainer.test: mean cost + metrics over the test reader."""

    def __init__(self, evaluator, cost):
        super(TestResult, self).__init__(evaluator)
        self.cost = cost


class BeginPass(object):
    def __init__(self, pass_id):
        self.pass_id = pass_id


class EndPass(WithMetric):
    def __init__(self, pass_id, evaluator=None, gm=None):
        super(EndPass, self).__init__(evaluator)
        self.pass_id = pass_id
        self.gm = gm


class BeginIteration(object):
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndForwardBackward(object):
    def __init__(self, pass_id, batch_id, gm=None):
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.gm = gm


class EndIteration(WithMetric):
    def __init__(self, pass_id, batch_id, cost, evaluator=None, gm=None):
        super(EndIteration, self).__init__(evaluator)
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
        self.gm = gm
