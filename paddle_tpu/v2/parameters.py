"""v2 Parameters (python/paddle/v2/parameters.py).

Dict-like view of the model's trainable parameters. The reference wrapped
GradientMachine parameter buffers; here Parameters owns the fluid Scope the
trainer/inferencer run in, materializing it from the startup program on
first use (a temp-scope run that only fills names still missing, so a
later-appended optimizer's accumulators initialize without resetting
already-trained weights). to_tar/from_tar round-trip values as a tar of
.npy members, like the reference's tar checkpoints.
"""
import io as _io
import tarfile

import numpy as np

import paddle_tpu as fluid
from .topology import Topology

__all__ = ["Parameters", "create"]


class Parameters(object):
    def __init__(self, topology):
        self.topology = topology
        self.scope = fluid.Scope()
        self._exe = fluid.Executor(fluid.CPUPlace())

    # -- materialization ----------------------------------------------------
    def _param_names(self):
        return [p.name for p in
                self.topology.main_program.global_block().all_parameters()]

    def _materialize(self):
        """Run the startup program for any persistable var not yet present
        (first call fills everything; later calls only fill vars appended
        since — e.g. optimizer accumulators — keeping trained values).
        No-op while the startup program is unchanged, so per-batch get()
        calls don't re-execute initialization."""
        version = self.topology.startup_program._version
        if getattr(self, "_materialized_version", None) == version:
            return
        temp = fluid.Scope()
        with fluid.scope_guard(temp):
            self._exe.run(self.topology.startup_program)
        for name in temp.names():
            if not self.scope.has(name):
                self.scope.set(name, temp.get(name))
        self._materialized_version = version

    # -- dict-like surface --------------------------------------------------
    def names(self):
        return self._param_names()

    def keys(self):
        return self.names()

    def has_key(self, key):
        return key in self.names()

    def __iter__(self):
        return iter(self.names())

    def __contains__(self, key):
        return self.has_key(key)

    def __len__(self):
        return len(self.names())

    def get(self, name):
        self._materialize()
        val = self.scope.get(name)
        if val is None:
            raise KeyError("no parameter %r" % name)
        return np.asarray(val)

    __getitem__ = get

    def set(self, name, value):
        self._materialize()
        if not self.scope.has(name):
            raise KeyError("no parameter %r" % name)
        cur = self.scope.get(name)
        value = np.asarray(value)
        if cur is not None and tuple(np.shape(cur)) != value.shape:
            # reference Parameters.__setitem__ raises on mismatch — a silent
            # reshape would scramble e.g. a transposed weight matrix
            raise ValueError(
                "parameter %r has shape %s, cannot set value of shape %s"
                % (name, tuple(np.shape(cur)), value.shape))
        self.scope.set(name, value)

    __setitem__ = set

    def get_shape(self, name):
        v = self.topology.main_program.global_block().vars.get(name)
        if v is None or v.shape is None:
            return tuple(np.shape(self.get(name)))
        return tuple(v.shape)

    # -- tar serialization (reference: Parameters.to_tar/from_tar) ----------
    def to_tar(self, f):
        self._materialize()
        with tarfile.open(fileobj=f, mode="w") as tar:
            for name in self.names():
                buf = _io.BytesIO()
                np.save(buf, self.get(name), allow_pickle=False)
                data = buf.getvalue()
                info = tarfile.TarInfo(name=name + ".npy")
                info.size = len(data)
                tar.addfile(info, _io.BytesIO(data))

    def from_tar(self, f):
        self._materialize()
        with tarfile.open(fileobj=f, mode="r") as tar:
            for member in tar.getmembers():
                name = member.name[:-4] if member.name.endswith(".npy") \
                    else member.name
                arr = np.load(_io.BytesIO(tar.extractfile(member).read()),
                              allow_pickle=False)
                self.set(name, arr)
        return self

    @staticmethod
    def from_tar_file(f):
        raise NotImplementedError(
            "standalone tar loading needs a topology; build the model and "
            "use parameters.create(cost).from_tar(f)")


def create(layers):
    """paddle.parameters.create(cost): capture the current default programs
    and return the Parameters handle the trainer/inferencer will run in."""
    topo = layers if isinstance(layers, Topology) else Topology(layers)
    return Parameters(topo)
