"""v2 activation objects (python/paddle/v2/activation.py)."""


class BaseActivation(object):
    name = None

    def __repr__(self):
        return "Activation(%s)" % self.name


def _make(name, fluid_name):
    cls = type(name, (BaseActivation,), {"name": fluid_name})
    return cls


Linear = _make("Linear", None)
Relu = _make("Relu", "relu")
Tanh = _make("Tanh", "tanh")
Sigmoid = _make("Sigmoid", "sigmoid")
Softmax = _make("Softmax", "softmax")
Exp = _make("Exp", "exp")
Log = _make("Log", "log")
Square = _make("Square", "square")
Sqrt = _make("Sqrt", "sqrt")
Abs = _make("Abs", "abs")
SoftRelu = _make("SoftRelu", "softplus")
BRelu = _make("BRelu", "brelu")
STanh = _make("STanh", "stanh")
