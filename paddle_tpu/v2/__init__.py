"""paddle.v2 compatibility shim (legacy trainer API, tier 3).

Parity: python/paddle/v2/__init__.py surface — layer/activation/data_type/
attr/pooling/networks/optimizer/parameters/trainer/event/inference/
minibatch/dataset/reader — implemented as a thin eager layer over the
paddle_tpu fluid core (SURVEY.md §2 "Legacy v2 API"): every v2 layer call
appends ops to the default fluid program; trainer.SGD drives the fluid
Executor. The gserver/trainer_config_helpers machinery the reference
wraps is subsumed by the fluid op set.
"""
from .. import datasets as dataset          # noqa: F401
from .. import reader                       # noqa: F401
from ..reader import batch                  # noqa: F401
from . import activation                    # noqa: F401
from . import attr                          # noqa: F401
from . import data_type                     # noqa: F401
from . import pooling                       # noqa: F401
from . import layer                         # noqa: F401
from . import networks                      # noqa: F401
from . import optimizer                     # noqa: F401
from . import parameters                    # noqa: F401
from . import trainer                       # noqa: F401
from . import event                         # noqa: F401
from . import inference                     # noqa: F401
from .inference import infer                # noqa: F401
from . import topology                      # noqa: F401
from . import minibatch                     # noqa: F401
from . import image                         # noqa: F401
from . import data_feeder                   # noqa: F401
from . import evaluator                     # noqa: F401
from . import plot                          # noqa: F401
from . import op                            # noqa: F401

__all__ = ["init", "dataset", "reader", "batch", "layer", "activation",
           "data_type", "attr", "pooling", "networks", "optimizer",
           "parameters", "trainer", "event", "inference", "infer",
           "topology", "minibatch", "image"]


def init(**kwargs):
    """paddle.v2.init(use_gpu=..., trainer_count=...): device selection is
    jax-managed; accepted for compatibility."""
    return None
