"""v2 DataFeeder (parity: python/paddle/v2/data_feeder.py).

The reference converted reader minibatches into C++ `Arguments` via
PyDataProvider2 scanners; the TPU-native equivalent converts them into the
fluid feed dict consumed by the whole-program XLA executor. Constructed
from `data_types` ([(name, paddle.v2.data_type.InputType)]) and an optional
`feeding` map of name -> input-row column, exactly like the reference; the
result of `feeder(minibatch)` is directly usable as `Executor.run(feed=...)`.
"""
import numpy as np

from . import data_type as _data_type
from ..core.lod import LoDTensor

__all__ = ["DataFeeder"]


def default_feeding_map(data_types):
    return {name: i for i, (name, _) in enumerate(data_types)}


class DataFeeder(object):
    def __init__(self, data_types, feeding=None):
        self.data_types = list(data_types)
        if feeding is None:
            feeding = default_feeding_map(self.data_types)
        elif not isinstance(feeding, dict):
            feeding = {name: i for i, name in enumerate(feeding)}
        self.feeding = feeding

    def __call__(self, dat, argument=None):
        """Convert one minibatch (list of per-sample rows) into a feed dict.
        Scalar/int types get a trailing [batch, 1] axis; seq_type>0 columns
        become LoDTensors (padded dense + lengths downstream)."""
        feed = {}
        for name, tp in self.data_types:
            col = self.feeding[name]
            column = [row[col] for row in dat]
            if isinstance(tp, _data_type.InputType) and tp.seq_type:
                seqs = [np.asarray(s, dtype=tp.dtype) for s in column]
                # integer sequences carry a feature dim of 1 downstream
                if seqs and seqs[0].ndim == 1 and tp.dtype.startswith("int"):
                    seqs = [s.reshape(-1, 1) for s in seqs]
                feed[name] = LoDTensor.from_sequences(seqs)
            else:
                arr = np.asarray(column,
                                 dtype=getattr(tp, "dtype", "float32"))
                if arr.ndim == 1:
                    arr = arr.reshape(-1, 1)
                feed[name] = arr
        return feed

    # reference spelling: feeder.convert(minibatch)
    convert = __call__
