"""v2 layers: eager shims over fluid layers (python/paddle/v2/layer.py).

Each call appends ops to the default fluid programs; the returned fluid
Variable doubles as the v2 "layer output" handle (it carries .name for
feeding, which is all the v2 trainer needs).
"""
import paddle_tpu as fluid
from .activation import BaseActivation
from . import data_type as _dt

__all__ = ["data", "fc", "embedding", "classification_cost",
           "cross_entropy_cost", "square_error_cost", "lstmemory",
           "max_id", "concat", "pool", "dropout"]


def _act_name(act):
    if act is None:
        return None
    if isinstance(act, type) and issubclass(act, BaseActivation):
        act = act()
    return act.name


def data(name, type):
    lod = 1 if type.seq_type else 0
    shape = [1] if type.dtype == "int64" else [type.dim]
    v = fluid.layers.data(name=name, shape=shape, dtype=type.dtype,
                          lod_level=lod)
    v.v2_type = type
    return v


def fc(input, size, act=None, param_attr=None, bias_attr=None, name=None):
    # fluid fc accepts a Variable or a list of Variables directly
    return fluid.layers.fc(input=input, size=size, act=_act_name(act),
                           param_attr=param_attr, bias_attr=bias_attr,
                           name=name)


def embedding(input, size, param_attr=None):
    v2_type = getattr(input, "v2_type", None)
    if v2_type is None or not getattr(v2_type, "dim", None) \
            or v2_type.dtype != "int64":
        raise ValueError(
            "v2 embedding needs its input to be a paddle.layer.data of "
            "integer_value/integer_value_sequence type (the vocabulary size "
            "comes from the data type's dim)")
    return fluid.layers.embedding(input=input, size=[v2_type.dim, size],
                                  param_attr=param_attr)


def classification_cost(input, label):
    cost = fluid.layers.cross_entropy(input=input, label=label)
    return fluid.layers.mean(x=cost)


cross_entropy_cost = classification_cost


def square_error_cost(input, label):
    cost = fluid.layers.square_error_cost(input=input, label=label)
    return fluid.layers.mean(x=cost)


def lstmemory(input, size=None, reverse=False, act=None, **kwargs):
    hidden = size or input.shape[-1] // 4
    h, c = fluid.layers.dynamic_lstm(
        input=input, size=hidden * 4, is_reverse=reverse,
        candidate_activation=_act_name(act) or "tanh")
    return h


def max_id(input):
    return fluid.layers.argmax(input, axis=-1)


def concat(input, axis=1):
    return fluid.layers.concat(input=list(input), axis=axis)


def pool(input, pooling_type=None):
    name = pooling_type.name if pooling_type else "max"
    return fluid.layers.sequence_pool(input=input, pool_type=name)


def dropout(input, dropout_rate):
    return fluid.layers.dropout(x=input, dropout_prob=dropout_rate)
