"""v2 layers: eager shims over fluid layers (python/paddle/v2/layer.py).

Each call appends ops to the default fluid programs; the returned fluid
Variable doubles as the v2 "layer output" handle (it carries .name for
feeding, which is all the v2 trainer needs).
"""
import paddle_tpu as fluid
from .activation import BaseActivation
from . import data_type as _dt

__all__ = ["data", "fc", "embedding", "classification_cost",
           "cross_entropy_cost", "square_error_cost", "mse_cost",
           "lstmemory", "grumemory", "max_id", "concat", "pool", "dropout",
           "img_conv", "img_pool", "batch_norm", "cos_sim", "first_seq",
           "last_seq", "addto", "seq_reshape", "scaling", "trans",
           "sum_cost", "huber_regression_cost", "crf", "crf_decoding"]


def _act_name(act):
    if act is None:
        return None
    if isinstance(act, type) and issubclass(act, BaseActivation):
        act = act()
    return act.name


def data(name, type):
    lod = 1 if type.seq_type else 0
    shape = [1] if type.dtype == "int64" else [type.dim]
    v = fluid.layers.data(name=name, shape=shape, dtype=type.dtype,
                          lod_level=lod)
    v.v2_type = type
    return v


def fc(input, size, act=None, param_attr=None, bias_attr=None, name=None):
    # fluid fc accepts a Variable or a list of Variables directly
    return fluid.layers.fc(input=input, size=size, act=_act_name(act),
                           param_attr=param_attr, bias_attr=bias_attr,
                           name=name)


def embedding(input, size, param_attr=None):
    v2_type = getattr(input, "v2_type", None)
    if v2_type is None or not getattr(v2_type, "dim", None) \
            or v2_type.dtype != "int64":
        raise ValueError(
            "v2 embedding needs its input to be a paddle.layer.data of "
            "integer_value/integer_value_sequence type (the vocabulary size "
            "comes from the data type's dim)")
    return fluid.layers.embedding(input=input, size=[v2_type.dim, size],
                                  param_attr=param_attr)


def classification_cost(input, label):
    cost = fluid.layers.cross_entropy(input=input, label=label)
    return fluid.layers.mean(x=cost)


cross_entropy_cost = classification_cost


def square_error_cost(input, label):
    cost = fluid.layers.square_error_cost(input=input, label=label)
    return fluid.layers.mean(x=cost)


def lstmemory(input, size=None, reverse=False, act=None, **kwargs):
    hidden = size or input.shape[-1] // 4
    h, c = fluid.layers.dynamic_lstm(
        input=input, size=hidden * 4, is_reverse=reverse,
        candidate_activation=_act_name(act) or "tanh")
    return h


def max_id(input):
    return fluid.layers.argmax(input, axis=-1)


def concat(input, axis=1):
    return fluid.layers.concat(input=list(input), axis=axis)


def pool(input, pooling_type=None):
    name = pooling_type.name if pooling_type else "max"
    return fluid.layers.sequence_pool(input=input, pool_type=name)


def dropout(input, dropout_rate):
    return fluid.layers.dropout(x=input, dropout_prob=dropout_rate)


mse_cost = square_error_cost


def grumemory(input, size=None, reverse=False, act=None, **kwargs):
    hidden = size or input.shape[-1] // 3
    return fluid.layers.dynamic_gru(
        input=input, size=hidden, is_reverse=reverse,
        candidate_activation=_act_name(act) or "tanh")


def img_conv(input, filter_size, num_filters, num_channels=None, stride=1,
             padding=0, groups=1, act=None, param_attr=None,
             bias_attr=None, **kwargs):
    return fluid.layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=padding, groups=groups,
        act=_act_name(act), param_attr=param_attr, bias_attr=bias_attr)


def img_pool(input, pool_size, pool_type=None, stride=1, padding=0,
             **kwargs):
    name = pool_type.name if pool_type is not None else "max"
    if name == "average":
        name = "avg"
    return fluid.layers.pool2d(
        input=input, pool_size=pool_size, pool_type=name,
        pool_stride=stride, pool_padding=padding)


def batch_norm(input, act=None, **kwargs):
    return fluid.layers.batch_norm(input=input, act=_act_name(act))


def cos_sim(a, b, scale=1, **kwargs):
    out = fluid.layers.cos_sim(X=a, Y=b)
    return out if scale == 1 else fluid.layers.scale(x=out,
                                                     scale=float(scale))


def first_seq(input, **kwargs):
    return fluid.layers.sequence_first_step(input=input)


def last_seq(input, **kwargs):
    return fluid.layers.sequence_last_step(input=input)


def addto(input, act=None, bias_attr=None, **kwargs):
    vals = list(input) if isinstance(input, (list, tuple)) else [input]
    out = vals[0]
    for v in vals[1:]:
        out = fluid.layers.elementwise_add(x=out, y=v)
    if bias_attr not in (None, False):
        bias = fluid.layers.create_parameter(
            shape=[out.shape[-1]], dtype=out.dtype,
            attr=None if bias_attr is True else bias_attr, is_bias=True)
        out = fluid.layers.elementwise_add(x=out, y=bias,
                                           axis=len(out.shape) - 1)
    a = _act_name(act)
    if a:
        out = getattr(fluid.layers, a)(out)
    return out


def seq_reshape(input, reshape_size, **kwargs):
    return fluid.layers.sequence_reshape(input=input,
                                         new_dim=reshape_size)


def scaling(input, weight, **kwargs):
    return fluid.layers.elementwise_mul(x=input, y=weight, axis=0)


def trans(input, **kwargs):
    return fluid.layers.transpose(input, perm=[1, 0])


def sum_cost(input, **kwargs):
    return fluid.layers.reduce_sum(input)


def huber_regression_cost(input, label, delta=1.0, **kwargs):
    # Huber(delta) in terms of smooth_l1(sigma): with sigma = delta**-0.5
    # the threshold is 1/sigma^2 = delta, and scaling the result by delta
    # gives quadratic 0.5*d^2 and linear delta*(|d| - delta/2) exactly.
    delta = float(delta)
    return fluid.layers.scale(
        fluid.layers.mean(
            fluid.layers.smooth_l1(x=input, y=label,
                                   sigma=delta ** -0.5)),
        scale=delta)


def crf(input, label, param_attr=None, **kwargs):
    return fluid.layers.linear_chain_crf(input=input, label=label,
                                         param_attr=param_attr)


def crf_decoding(input, param_attr=None, **kwargs):
    return fluid.layers.crf_decoding(input=input, param_attr=param_attr)
