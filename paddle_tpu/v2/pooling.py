"""v2 pooling types (python/paddle/v2/pooling.py)."""


class BasePoolingType(object):
    name = None


class Max(BasePoolingType):
    name = "max"


class Avg(BasePoolingType):
    name = "average"


class Sum(BasePoolingType):
    name = "sum"


class SquareRootN(BasePoolingType):
    name = "sqrt"
