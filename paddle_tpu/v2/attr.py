"""v2 parameter attributes (python/paddle/v2/attr.py)."""
from ..core.param_attr import ParamAttr


def Param(name=None, is_static=False, initial_std=None, initial_mean=None,
          l2_rate=None, learning_rate=None, **kwargs):
    from ..core.initializer import NormalInitializer
    from ..regularizer import L2Decay
    init = None
    if initial_std is not None or initial_mean is not None:
        init = NormalInitializer(initial_mean or 0.0, initial_std or 1.0)
    reg = L2Decay(l2_rate) if l2_rate else None
    return ParamAttr(name=name, initializer=init, regularizer=reg,
                     learning_rate=learning_rate or 1.0,
                     trainable=not is_static)


ParameterAttribute = Param
Extra = dict
