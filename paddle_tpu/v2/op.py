"""paddle.v2.op — unary math functions + arithmetic operators on layers.

Parity: python/paddle/v2/op.py. There each op builds a mixed/projection
sub-network through trainer_config_helpers; here a v2 "layer" IS a fluid
Variable (see v2/layer.py), so the math ops delegate straight to the
fluid op set and the +,-,* operator sugar is already provided by fluid's
math_op_patch on every Variable — only the named functions need shims.
"""
import paddle_tpu as fluid

__all__ = ["exp", "log", "abs", "sigmoid", "tanh", "square", "relu",
           "sqrt", "reciprocal", "softmax"]


def _unary(op_name):
    def op(input, name=None):
        return getattr(fluid.layers, op_name)(input)
    op.__name__ = op_name
    return op


exp = _unary("exp")
log = _unary("log")
abs = _unary("abs")
sigmoid = _unary("sigmoid")
tanh = _unary("tanh")
square = _unary("square")
relu = _unary("relu")
sqrt = _unary("sqrt")
reciprocal = _unary("reciprocal")


def softmax(input, name=None):
    return fluid.layers.softmax(input)
