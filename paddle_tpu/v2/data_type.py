"""v2 input type declarations (python/paddle/v2/data_type.py)."""


class InputType(object):
    def __init__(self, dim, seq_type, dtype):
        self.dim = dim
        self.seq_type = seq_type  # 0 = no sequence, 1 = sequence
        self.dtype = dtype


def dense_vector(dim, seq_type=0):
    return InputType(dim, seq_type, "float32")


def dense_vector_sequence(dim):
    return dense_vector(dim, 1)


def integer_value(value_range, seq_type=0):
    return InputType(value_range, seq_type, "int64")


def integer_value_sequence(value_range):
    return integer_value(value_range, 1)


def sparse_binary_vector(dim, seq_type=0):
    return InputType(dim, seq_type, "int64")


def sparse_float_vector(dim, seq_type=0):
    return InputType(dim, seq_type, "float32")
