"""Host-side streaming metrics.

Parity: python/paddle/fluid/metrics.py + evaluator.py (Accuracy, ChunkEvaluator,
EditDistance, DetectionMAP are graph-side; these accumulate across batches).
"""
import numpy as np

__all__ = ["MetricBase", "CompositeMetric", "Accuracy", "ChunkEvaluator",
           "EditDistance", "Auc"]


class MetricBase(object):
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for attr, value in self.__dict__.items():
            if attr.startswith("_"):
                continue
            if isinstance(value, (int, float)):
                setattr(self, attr, 0)
            elif isinstance(value, np.ndarray):
                setattr(self, attr, np.zeros_like(value))

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super(CompositeMetric, self).__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, *args, **kwargs):
        for m in self._metrics:
            m.update(*args, **kwargs)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super(Accuracy, self).__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no batches accumulated")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super(ChunkEvaluator, self).__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).sum())
        self.num_label_chunks += int(np.asarray(num_label_chunks).sum())
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).sum())

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0)
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super(EditDistance, self).__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        d = np.asarray(distances)
        self.total_distance += float(d.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((d > 0).sum())

    def eval(self):
        avg = self.total_distance / max(self.seq_num, 1)
        err_rate = self.instance_error / max(self.seq_num, 1)
        return avg, err_rate


class Auc(MetricBase):
    def __init__(self, name=None, num_thresholds=200):
        super(Auc, self).__init__(name)
        self._num_thresholds = num_thresholds
        self.tp = np.zeros(num_thresholds, dtype=np.int64)
        self.fp = np.zeros(num_thresholds, dtype=np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_score = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 \
            else preds.reshape(-1)
        bucket = np.clip((pos_score * self._num_thresholds).astype(int),
                         0, self._num_thresholds - 1)
        for b, l in zip(bucket, labels):
            if l > 0:
                self.tp[b] += 1
            else:
                self.fp[b] += 1

    def eval(self):
        tp_c = np.cumsum(self.tp[::-1])[::-1].astype(float)
        fp_c = np.cumsum(self.fp[::-1])[::-1].astype(float)
        tpr = tp_c / max(tp_c[0], 1)
        fpr = fp_c / max(fp_c[0], 1)
        return float(-np.trapezoid(tpr, fpr))


class DetectionMAP(MetricBase):
    """Mean average precision over accumulated detections.

    Parity: paddle/fluid/operators/detection_map_op.h semantics (score-sorted
    greedy TP/FP assignment at an IoU threshold, 11point or integral AP),
    computed host-side from fetched numpy results instead of an in-graph
    CPU-only accumulator op.

    update(nmsed_out [B, K, 6] (-1 padded), nmsed_lens [B],
           gt_boxes: list of [Gi, 4], gt_labels: list of [Gi]) per batch.
    """

    def __init__(self, overlap_threshold=0.5, ap_version="integral",
                 evaluate_difficult=True, background_label=None,
                 name=None):
        super(DetectionMAP, self).__init__(name)
        self.overlap_threshold = overlap_threshold
        self.ap_version = ap_version
        self.evaluate_difficult = evaluate_difficult
        self.background_label = background_label
        self.reset()

    def reset(self):
        self._dets = []   # (class, score, box, image_id)
        self._gts = []    # (class, box, image_id, difficult)
        self._img = 0

    def update(self, nmsed_out, nmsed_lens, gt_boxes, gt_labels,
               gt_difficult=None):
        nmsed_out = np.asarray(nmsed_out)
        nmsed_lens = np.ravel(np.asarray(nmsed_lens))
        for i in range(nmsed_out.shape[0]):
            img = self._img + i
            for j in range(int(nmsed_lens[i])):
                lab, score = nmsed_out[i, j, 0], nmsed_out[i, j, 1]
                self._dets.append((int(lab), float(score),
                                   nmsed_out[i, j, 2:6].copy(), img))
            gb = np.asarray(gt_boxes[i]).reshape(-1, 4)
            gl = np.ravel(np.asarray(gt_labels[i]))
            gd = np.ravel(np.asarray(gt_difficult[i])) \
                if gt_difficult is not None else np.zeros(len(gl))
            for g in range(gb.shape[0]):
                self._gts.append((int(gl[g]), gb[g].copy(), img,
                                  bool(gd[g])))
        self._img += nmsed_out.shape[0]

    @staticmethod
    def _iou(a, b):
        iw = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        ih = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = iw * ih
        ua = max(0.0, a[2] - a[0]) * max(0.0, a[3] - a[1]) + \
            max(0.0, b[2] - b[0]) * max(0.0, b[3] - b[1]) - inter
        return inter / ua if ua > 0 else 0.0

    def _ap(self, recall, precision):
        if self.ap_version == "11point":
            ap = 0.0
            for t in np.arange(0.0, 1.1, 0.1):
                p = np.max(precision[recall >= t]) if \
                    np.any(recall >= t) else 0.0
                ap += p / 11.0
            return ap
        # integral
        ap = 0.0
        prev_r = 0.0
        for r, p in zip(recall, precision):
            ap += p * (r - prev_r)
            prev_r = r
        return ap

    def eval(self):
        classes = sorted({c for c, _, _, _ in self._gts
                          if c != self.background_label})
        aps = []
        for cls in classes:
            gts = [(b, i, d) for c, b, i, d in self._gts if c == cls]
            # difficult gts don't count as positives when excluded
            npos = sum(1 for _, _, d in gts
                       if self.evaluate_difficult or not d)
            dets = sorted((d for d in self._dets if d[0] == cls),
                          key=lambda d: -d[1])
            used = set()
            tp = np.zeros(len(dets))
            fp = np.zeros(len(dets))
            for k, (_, score, box, img) in enumerate(dets):
                # reference protocol (detection_map_op.h / VOC): argmax over
                # ALL gts of the image; a detection whose best gt is already
                # claimed counts FP (no re-matching to the second-best gt)
                best, best_g = 0.0, -1
                for gi, (gb, gimg, _) in enumerate(gts):
                    if gimg != img:
                        continue
                    ov = self._iou(box, gb)
                    if ov > best:
                        best, best_g = ov, gi
                if best >= self.overlap_threshold and best_g >= 0:
                    if not self.evaluate_difficult and gts[best_g][2]:
                        continue  # matched a difficult gt: neither TP nor FP
                    if best_g not in used:
                        tp[k] = 1
                        used.add(best_g)
                    else:
                        fp[k] = 1
                else:
                    fp[k] = 1
            if npos == 0:
                continue
            tp_c = np.cumsum(tp)
            fp_c = np.cumsum(fp)
            recall = tp_c / npos
            precision = tp_c / np.maximum(tp_c + fp_c, 1e-9)
            aps.append(self._ap(recall, precision))
        return float(np.mean(aps)) if aps else 0.0
