"""Host-side streaming metrics.

Parity: python/paddle/fluid/metrics.py + evaluator.py (Accuracy, ChunkEvaluator,
EditDistance, DetectionMAP are graph-side; these accumulate across batches).
"""
import numpy as np

__all__ = ["MetricBase", "CompositeMetric", "Accuracy", "ChunkEvaluator",
           "EditDistance", "Auc"]


class MetricBase(object):
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for attr, value in self.__dict__.items():
            if attr.startswith("_"):
                continue
            if isinstance(value, (int, float)):
                setattr(self, attr, 0)
            elif isinstance(value, np.ndarray):
                setattr(self, attr, np.zeros_like(value))

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super(CompositeMetric, self).__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, *args, **kwargs):
        for m in self._metrics:
            m.update(*args, **kwargs)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super(Accuracy, self).__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no batches accumulated")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super(ChunkEvaluator, self).__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).sum())
        self.num_label_chunks += int(np.asarray(num_label_chunks).sum())
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).sum())

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0)
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super(EditDistance, self).__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        d = np.asarray(distances)
        self.total_distance += float(d.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((d > 0).sum())

    def eval(self):
        avg = self.total_distance / max(self.seq_num, 1)
        err_rate = self.instance_error / max(self.seq_num, 1)
        return avg, err_rate


class Auc(MetricBase):
    def __init__(self, name=None, num_thresholds=200):
        super(Auc, self).__init__(name)
        self._num_thresholds = num_thresholds
        self.tp = np.zeros(num_thresholds, dtype=np.int64)
        self.fp = np.zeros(num_thresholds, dtype=np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_score = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 \
            else preds.reshape(-1)
        bucket = np.clip((pos_score * self._num_thresholds).astype(int),
                         0, self._num_thresholds - 1)
        for b, l in zip(bucket, labels):
            if l > 0:
                self.tp[b] += 1
            else:
                self.fp[b] += 1

    def eval(self):
        tp_c = np.cumsum(self.tp[::-1])[::-1].astype(float)
        fp_c = np.cumsum(self.fp[::-1])[::-1].astype(float)
        tpr = tp_c / max(tp_c[0], 1)
        fpr = fp_c / max(fp_c[0], 1)
        return float(-np.trapezoid(tpr, fpr))
