"""Convert python readers to recordio files and back.

Parity: python/paddle/fluid/recordio_writer.py
(convert_reader_to_recordio_file). The reference serializes each sample as
feeded LoDTensor protos; here a sample (a tuple of arrays/scalars) is
serialized as a small self-describing binary record (count + per-field numpy
.npy payloads), which round-trips exactly and needs no proto dependency.
"""
import io

import numpy as np

from . import recordio

__all__ = ["convert_reader_to_recordio_file", "recordio_reader"]


def _serialize_sample(sample):
    buf = io.BytesIO()
    fields = sample if isinstance(sample, (tuple, list)) else (sample,)
    buf.write(np.uint32(len(fields)).tobytes())
    for f in fields:
        fbuf = io.BytesIO()
        np.save(fbuf, np.asarray(f), allow_pickle=False)
        raw = fbuf.getvalue()
        buf.write(np.uint32(len(raw)).tobytes())
        buf.write(raw)
    return buf.getvalue()


def _deserialize_sample(record):
    buf = io.BytesIO(record)
    (n,) = np.frombuffer(buf.read(4), dtype=np.uint32)
    fields = []
    for _ in range(int(n)):
        (sz,) = np.frombuffer(buf.read(4), dtype=np.uint32)
        fields.append(np.load(io.BytesIO(buf.read(int(sz))),
                              allow_pickle=False))
    return tuple(fields)


def convert_reader_to_recordio_file(
        filename, reader_creator, feeder=None,
        compressor=recordio.Compressor.Gzip, max_num_records=1000,
        feed_order=None):
    """Write every sample of reader_creator() into `filename`. Returns the
    record count.

    With a `feeder` (DataFeeder), each item from the reader is a minibatch
    (the reference's convert pattern: a paddle.batch-ed reader) and is run
    through feeder.feed() so every record holds one batched array per feed
    var, ordered by `feed_order` (defaults to the feeder's feed list). Dense
    vars only — sequence (lod_level>0) vars have no recordio layout here.
    Without a feeder, samples are serialized directly."""
    count = 0
    if feeder is not None and feed_order is None:
        feed_order = feeder.feed_names
    with recordio.Writer(filename, compressor=compressor,
                         max_num_records=max_num_records) as w:
        for sample in reader_creator():
            if feeder is not None:
                d = feeder.feed(sample)
                fields = []
                for name in feed_order:
                    val = d[name]
                    if not isinstance(val, np.ndarray):
                        raise NotImplementedError(
                            "recordio conversion supports dense feed vars "
                            "only; %r is a sequence (lod_level>0)" % name)
                    fields.append(val)
                sample = tuple(fields)
            w.write(_serialize_sample(sample))
            count += 1
    return count


def recordio_reader(filename):
    """A reader creator over a recordio file written by
    convert_reader_to_recordio_file (the open_recordio_file op equivalent;
    reference: operators/reader/create_recordio_file_reader_op.cc)."""
    def reader():
        with recordio.Scanner(filename) as s:
            for record in s:
                yield _deserialize_sample(record)
    return reader
