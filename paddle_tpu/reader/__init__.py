"""Reader decorators: composable python data pipelines.

Parity: python/paddle/v2/reader/decorator.py (map_readers, shuffle, chain,
compose, buffered, firstn, xmap_readers) + python/paddle/v2/minibatch.py
(batch). A *reader creator* is a zero-arg callable returning an iterable of
samples; decorators wrap creators into new creators. On TPU the pipeline's
job is to keep batches of fixed shape flowing to the host staging buffer —
`batch` + `buffered` give the double-buffering the reference's C++ readers
implemented natively.
"""
import itertools
import queue as _queue
import random
import threading

__all__ = ["map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "xmap_readers", "batch", "skip",
           "ComposeNotAligned"]


class ComposeNotAligned(ValueError):
    pass


class _ReaderError(object):
    """Exception captured on a worker thread, re-raised in the consumer."""

    def __init__(self, exc):
        self.exc = exc


def map_readers(func, *readers):
    """Creator yielding func(s1, s2, ...) over zipped samples of readers."""
    def reader():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)
    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer of buf_size samples."""
    def data_reader():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b
    return data_reader


def chain(*readers):
    """Concatenate readers back to back."""
    def reader():
        for r in readers:
            for s in r():
                yield s
    return reader


def compose(*readers, **kwargs):
    """Zip readers into flat tuples: (a, (b, c)) -> (a, b, c).

    check_alignment (default True): raise ComposeNotAligned if the readers
    end at different lengths.
    """
    check_alignment = kwargs.pop("check_alignment", True)
    if kwargs:
        raise TypeError("unexpected kwargs %r" % list(kwargs))

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())
            return
        for outputs in itertools.zip_longest(*rs):
            if any(o is None for o in outputs):
                raise ComposeNotAligned(
                    "outputs of readers are not aligned")
            yield sum((make_tuple(o) for o in outputs), ())
    return reader


def buffered(reader, size):
    """Prefetch up to `size` samples on a background thread (the host-side
    half of input/compute overlap; device double-buffering is in
    DataFeeder)."""
    end = object()

    def read_worker(r, q):
        try:
            for d in r:
                q.put(d)
            q.put(end)
        except BaseException as e:  # propagate to the consumer, don't truncate
            q.put(_ReaderError(e))

    def data_reader():
        r = reader()
        q = _queue.Queue(maxsize=size)
        t = threading.Thread(target=read_worker, args=(r, q))
        t.daemon = True
        t.start()
        e = q.get()
        while e is not end:
            if isinstance(e, _ReaderError):
                raise e.exc
            yield e
            e = q.get()
    return data_reader


def firstn(reader, n):
    """Limit to the first n samples."""
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item
    return firstn_reader


def skip(reader, n):
    """Drop the first n samples of the FIRST iteration only — the
    host-pipeline half of checkpoint resume. In-graph readers restore
    their position via `ReaderBase.load_state_dict` (deterministic
    replay); a host feeding loop resumes the same way by wrapping its
    creator in `skip(creator, batches_consumed)` so the post-resume
    stream starts exactly where the checkpointed run stopped.
    Deterministic creators (seeded shuffle, file readers) replay
    bit-identically. Later iterations (the NEXT epochs of a multi-pass
    loop) yield the full stream — only the resume epoch is partial."""
    state = {"pending": int(n)}

    def skip_reader():
        it = reader()
        pending, state["pending"] = state["pending"], 0
        for _ in range(pending):
            try:
                next(it)
            except StopIteration:
                return
        for item in it:
            yield item
    return skip_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Apply mapper over samples with process_num worker threads.

    order=True preserves input order (reference: order_read_worker path).
    """
    in_end = object()
    out_end = object()

    def read_worker(q):
        try:
            for i, s in enumerate(reader()):
                q.put((i, s))
        except BaseException as e:
            q.put(_ReaderError(e))
        finally:
            for _ in range(process_num):
                q.put(in_end)

    def handle_worker(in_q, out_q):
        try:
            item = in_q.get()
            while item is not in_end and not isinstance(item, _ReaderError):
                i, s = item
                out_q.put((i, mapper(s)))
                item = in_q.get()
            if isinstance(item, _ReaderError):
                out_q.put(item)
        except BaseException as e:
            out_q.put(_ReaderError(e))
        finally:
            out_q.put(out_end)

    def xreader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)
        t = threading.Thread(target=read_worker, args=(in_q,))
        t.daemon = True
        t.start()
        workers = []
        for _ in range(process_num):
            w = threading.Thread(target=handle_worker, args=(in_q, out_q))
            w.daemon = True
            w.start()
            workers.append(w)
        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is out_end:
                finished += 1
                continue
            if isinstance(item, _ReaderError):
                raise item.exc
            i, mapped = item
            if not order:
                yield mapped
                continue
            pending[i] = mapped
            while next_idx in pending:
                yield pending.pop(next_idx)
                next_idx += 1
        # drain any stragglers kept for ordering
        for i in sorted(pending):
            yield pending[i]
    return xreader


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of batch_size (paddle.batch parity)."""
    def batch_reader():
        b = []
        for s in reader():
            b.append(s)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return batch_reader
