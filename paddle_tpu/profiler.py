"""Profiler.

Parity: python/paddle/fluid/profiler.py (cuda_profiler/profiler context
managers over platform::Profiler, whose report printed an Event table sorted
by `sorted_key` in {calls,total,max,min,ave}). TPU-native: one jitted XLA
computation replaces the reference's per-op kernel stream, so the profiled
unit is the jit entry — per (program, feed-signature) call counts, compile
time, and blocked run times — plus a jax.profiler trace (TensorBoard/XProf)
for intra-computation detail.
"""
import contextlib
import threading
import time

import jax

__all__ = ["profiler", "start_profiler", "stop_profiler", "reset_profiler",
           "profile_report", "record_event", "cache_stats", "note_sync",
           "sync_stats", "dispatch_path", "record_idle", "snapshot"]

_active = False
_trace_dir = None
_span = [None, None]
_entries = {}  # tag -> {"calls", "runs", "total", "max", "min",
#                        "compiles", "compile_s", "aot_hits", "saved_s",
#                        "idle_s", "gaps"}  (see record_run/record_idle)
_syncs = {}    # tag -> host-sync count (see note_sync)
_syncs_on_dispatch = 0  # syncs observed on a marked dispatch-path thread
_sync_lock = threading.Lock()  # note_sync is called from dispatch
# workers, completion threads and clients at once — an unlocked
# read-modify-write could lose exactly the dispatch-path increment the
# no-premature-sync regression tests exist to catch
_tls = threading.local()  # .dispatch_path: this thread IS a hot
# dispatch loop (serving batcher worker, training step loop) — any
# note_sync here is a premature sync the pipeline regression test fails


def is_active():
    return _active


def note_sync(tag):
    """Count one host<->device synchronization point (block_until_ready,
    np.asarray of a device array, watchdog completion wait). Every sync
    site on the runtime's dispatch paths calls this, tagged by WHY it
    synced — so "the device pipeline never stalls on the host" is a
    testable property (`sync_stats`), not a code-review hope. Counting
    is ALWAYS on (a dict increment at a site already paying a
    millisecond-class device wait — unlike timing, it needs no extra
    sync of its own, so it must not require the profiler's
    sync-everything mode). Syncs observed on a thread inside a
    `dispatch_path()` region additionally count as on-dispatch-path:
    the pipelined batcher/trainer regression tests assert that number
    stays zero."""
    global _syncs_on_dispatch
    with _sync_lock:
        _syncs[tag] = _syncs.get(tag, 0) + 1
        if getattr(_tls, "dispatch_path", False):
            _syncs_on_dispatch += 1


@contextlib.contextmanager
def dispatch_path():
    """Mark the current thread as a hot dispatch loop for the duration:
    any note_sync inside is a premature host sync (it stalls the next
    dispatch behind a D2H wait). The serving batcher's dispatch worker
    wraps each dispatch in this; tests wrap training step loops."""
    prev = getattr(_tls, "dispatch_path", False)
    _tls.dispatch_path = True
    try:
        yield
    finally:
        _tls.dispatch_path = prev


def sync_stats():
    """{"by_tag": {tag: count}, "total", "on_dispatch_path"} since the
    last reset_profiler(). Counting is always-on (see note_sync), so
    counts accumulate from process start across unprofiled traffic —
    call reset_profiler() to scope a measurement window."""
    with _sync_lock:
        return {"by_tag": dict(_syncs),
                "total": sum(_syncs.values()),
                "on_dispatch_path": _syncs_on_dispatch}


def record_idle(tag, idle_s):
    """Account `idle_s` seconds the device spent with no dispatch queued
    under `tag` (between one dispatch's completion and the next
    dispatch's enqueue). The serving InflightWindow's completion thread
    and the executors' profiling path report through here; the report's
    Idle(s)/Util% columns render it."""
    e = _entries.setdefault(tag, _fresh_entry())
    e["idle_s"] += idle_s
    e["gaps"] += 1


def _fresh_entry():
    return {"calls": 0, "runs": 0, "total": 0.0, "max": 0.0,
            "min": float("inf"), "compiles": 0, "compile_s": 0.0,
            "aot_hits": 0, "saved_s": 0.0, "idle_s": 0.0, "gaps": 0}


def record_run(tag, seconds, compiled=False, aot_hit=False, saved_s=0.0,
               idle_s=None):
    """Executor hook: one jitted dispatch of `tag` took `seconds` (blocked).
    Calls that traced+compiled are counted separately (Compiles/Compile(s))
    so Total/Max/Min/Ave stay honest cache-hit execution times.

    aot_hit=True marks a call whose executable came from the persistent
    AOT artifact cache (core/compile_cache.py) instead of a fresh
    compile — still an execution call (the deserialize happens before
    the timed dispatch), but counted in its own column with `saved_s`,
    the compile seconds the recording process paid minus the load time,
    so warm-vs-cold process starts are visible per tag in one report.

    idle_s: seconds the device sat with nothing queued before this
    dispatch was enqueued (None = previous completion unknown or the
    device still had work) — feeds the Idle(s)/Util% columns."""
    e = _entries.setdefault(tag, _fresh_entry())
    e["calls"] += 1
    if idle_s is not None:
        e["idle_s"] += idle_s
        e["gaps"] += 1
    if aot_hit:
        e["aot_hits"] += 1
        e["saved_s"] += saved_s
    if compiled:
        e["compiles"] += 1
        e["compile_s"] += seconds
    else:
        e["runs"] += 1
        e["total"] += seconds
        e["max"] = max(e["max"], seconds)
        e["min"] = min(e["min"], seconds)


def cache_stats():
    """Aggregate compile-cache accounting over every profiled tag:
    {"compiles", "aot_hits", "warm_calls", "saved_s"} — compiles are
    fresh trace+compile calls, aot_hits replaced a compile with a disk
    load, warm_calls hit the in-process jit cache, saved_s totals the
    recorded compile time avoided. The cross-process cache tests assert
    "zero new compiles" on exactly this counter."""
    compiles = sum(e["compiles"] for e in _entries.values())
    aot_hits = sum(e.get("aot_hits", 0) for e in _entries.values())
    calls = sum(e["calls"] for e in _entries.values())
    return {"compiles": compiles, "aot_hits": aot_hits,
            "warm_calls": calls - compiles - aot_hits,
            "saved_s": sum(e.get("saved_s", 0.0)
                           for e in _entries.values())}


def record_event(tag, seconds=0.0):
    """Count a discrete runtime event into the Event table — the
    resilience supervisor tags every recovery action this way
    (`resilience/<fault>:<action>` rows), so one profile_report() shows
    training dispatches and fault handling side by side. `seconds` is
    the time the handler spent (0 for pure bookkeeping events)."""
    record_run(tag, seconds, compiled=False)


def snapshot():
    """Machine-readable export of everything the profiler tracks, in one
    dict: {"entries": {tag: {calls, runs, total, max, min, ave,
    compiles, compile_s, aot_hits, saved_s, idle_s, gaps}},
    "sync_stats": sync_stats(), "cache_stats": cache_stats()}. This is
    the PUBLIC surface for bench.py / the observability registry / CI
    gates — nothing should read the private `_entries` dict (its
    "min" sentinel and optional keys are internal). Values are plain
    numbers (JSON-safe); `min` reads 0.0 for entries with no exec
    calls, matching the report."""
    entries = {}
    for tag, e in list(_entries.items()):
        d = {"calls": e["calls"], "runs": e["runs"],
             "total": e["total"], "max": e["max"],
             "min": 0.0 if e["min"] == float("inf") else e["min"],
             "ave": e["total"] / max(e["runs"], 1),
             "compiles": e["compiles"], "compile_s": e["compile_s"],
             "aot_hits": e.get("aot_hits", 0),
             "saved_s": e.get("saved_s", 0.0),
             "idle_s": e.get("idle_s", 0.0), "gaps": e.get("gaps", 0)}
        entries[tag] = d
    return {"entries": entries, "sync_stats": sync_stats(),
            "cache_stats": cache_stats()}


_SORT_KEYS = ("calls", "total", "max", "min", "ave")


def _check_sorted_key(sorted_key):
    if sorted_key is not None and sorted_key not in _SORT_KEYS:
        raise ValueError("sorted_key must be one of %s, got %r"
                         % (list(_SORT_KEYS), sorted_key))


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    """Parity: fluid.profiler.profiler context manager. state accepted for
    API compatibility (CPU/GPU/All — one device stream on TPU)."""
    _check_sorted_key(sorted_key)  # fail before the workload, not after
    start_profiler(state, profile_path)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


def start_profiler(state="All", profile_path="/tmp/profile"):
    global _trace_dir, _active
    _active = True
    _trace_dir = profile_path
    try:
        jax.profiler.start_trace(profile_path)
    except Exception:
        _trace_dir = None
    _span[0] = time.time()


def profile_report(sorted_key=None, json=False):
    """The Event-table equivalent: one row per jitted program entry.

    sorted_key: None (insertion order) | 'calls' | 'total' | 'max' | 'min'
    | 'ave' (reference profiler.py sorted_key contract).

    json=True returns the `snapshot()` dict instead of the rendered
    table — the machine-readable contract bench.py and the
    observability registry consume (sorted_key is still validated but
    irrelevant: consumers sort their own views)."""
    _check_sorted_key(sorted_key)
    if json:
        return snapshot()
    rows = []
    for tag, e in _entries.items():
        total = e["total"]
        idle = e.get("idle_s", 0.0)
        # device utilization under this tag between first and last
        # dispatch: busy time over busy+observed idle gaps. Only
        # meaningful where completion times were observed (profiling
        # executors, the serving in-flight window) — tags with no idle
        # observations render "-".
        util = (100.0 * total / (total + idle)
                if (total + idle) > 0 and e.get("gaps", 0) else None)
        rows.append((tag, e["calls"], total, e["max"],
                     0.0 if e["min"] == float("inf") else e["min"],
                     total / max(e["runs"], 1),  # mean over EXEC calls
                     e["compiles"], e["compile_s"],
                     e.get("aot_hits", 0), e.get("saved_s", 0.0),
                     idle, util))
    keyidx = {"calls": 1, "total": 2, "max": 3, "min": 4, "ave": 5}
    if sorted_key is not None:
        rows.sort(key=lambda r: r[keyidx[sorted_key]], reverse=True)
    lines = ["%-40s %8s %10s %10s %10s %10s %9s %10s %7s %9s %8s %6s" %
             ("Entry", "Calls", "Total(s)", "Max(s)", "Min(s)", "Ave(s)",
              "Compiles", "Compile(s)", "AOTHit", "Saved(s)", "Idle(s)",
              "Util%")]
    for (tag, calls, total, mx, mn, ave, ncomp, comp, ahit,
         saved, idle, util) in rows:
        lines.append("%-40s %8d %10.4f %10.4f %10.4f %10.4f %9d %10.4f "
                     "%7d %9.4f %8.4f %6s"
                     % (tag[:40], calls, total, mx, mn, ave, ncomp, comp,
                        ahit, saved, idle,
                        "-" if util is None else "%.1f" % util))
    if rows:
        cs = cache_stats()
        lines.append(
            "compile cache: %d compiles, %d AOT hits, %d warm calls, "
            "%.4fs compile time saved"
            % (cs["compiles"], cs["aot_hits"], cs["warm_calls"],
               cs["saved_s"]))
        ss = sync_stats()
        if ss["total"]:
            lines.append(
                "host syncs: %d total (%d on a dispatch path): %s"
                % (ss["total"], ss["on_dispatch_path"],
                   ", ".join("%s=%d" % kv
                             for kv in sorted(ss["by_tag"].items()))))
    return "\n".join(lines)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    global _trace_dir, _active
    _active = False
    if _trace_dir is not None:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        _trace_dir = None
    _span[1] = time.time()
    if _span[0] is not None:
        print("[paddle_tpu.profiler] profiled %.3fs; XLA trace at %s"
              % (_span[1] - _span[0], profile_path))
    if _entries:
        print(profile_report(sorted_key))


def reset_profiler():
    global _syncs_on_dispatch
    _entries.clear()
    with _sync_lock:
        _syncs.clear()
        _syncs_on_dispatch = 0
    _span[0] = _span[1] = None


@contextlib.contextmanager
def cuda_profiler(*args, **kwargs):
    """Reference API kept for script compatibility; profiles the TPU."""
    with profiler():
        yield
