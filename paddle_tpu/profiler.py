"""Profiler.

Parity: python/paddle/fluid/profiler.py (cuda_profiler/profiler context
managers over platform::Profiler, whose report printed an Event table sorted
by `sorted_key` in {calls,total,max,min,ave}). TPU-native: one jitted XLA
computation replaces the reference's per-op kernel stream, so the profiled
unit is the jit entry — per (program, feed-signature) call counts, compile
time, and blocked run times — plus a jax.profiler trace (TensorBoard/XProf)
for intra-computation detail.
"""
import contextlib
import time

import jax

__all__ = ["profiler", "start_profiler", "stop_profiler", "reset_profiler",
           "profile_report", "record_event"]

_active = False
_trace_dir = None
_span = [None, None]
_entries = {}  # tag -> {"calls", "runs", "total", "max", "min",
#                        "compiles", "compile_s"} (see record_run)


def is_active():
    return _active


def record_run(tag, seconds, compiled=False):
    """Executor hook: one jitted dispatch of `tag` took `seconds` (blocked).
    Calls that traced+compiled are counted separately (Compiles/Compile(s))
    so Total/Max/Min/Ave stay honest cache-hit execution times."""
    e = _entries.setdefault(tag, {"calls": 0, "runs": 0, "total": 0.0,
                                  "max": 0.0, "min": float("inf"),
                                  "compiles": 0, "compile_s": 0.0})
    e["calls"] += 1
    if compiled:
        e["compiles"] += 1
        e["compile_s"] += seconds
    else:
        e["runs"] += 1
        e["total"] += seconds
        e["max"] = max(e["max"], seconds)
        e["min"] = min(e["min"], seconds)


def record_event(tag, seconds=0.0):
    """Count a discrete runtime event into the Event table — the
    resilience supervisor tags every recovery action this way
    (`resilience/<fault>:<action>` rows), so one profile_report() shows
    training dispatches and fault handling side by side. `seconds` is
    the time the handler spent (0 for pure bookkeeping events)."""
    record_run(tag, seconds, compiled=False)


_SORT_KEYS = ("calls", "total", "max", "min", "ave")


def _check_sorted_key(sorted_key):
    if sorted_key is not None and sorted_key not in _SORT_KEYS:
        raise ValueError("sorted_key must be one of %s, got %r"
                         % (list(_SORT_KEYS), sorted_key))


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    """Parity: fluid.profiler.profiler context manager. state accepted for
    API compatibility (CPU/GPU/All — one device stream on TPU)."""
    _check_sorted_key(sorted_key)  # fail before the workload, not after
    start_profiler(state, profile_path)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


def start_profiler(state="All", profile_path="/tmp/profile"):
    global _trace_dir, _active
    _active = True
    _trace_dir = profile_path
    try:
        jax.profiler.start_trace(profile_path)
    except Exception:
        _trace_dir = None
    _span[0] = time.time()


def profile_report(sorted_key=None):
    """The Event-table equivalent: one row per jitted program entry.

    sorted_key: None (insertion order) | 'calls' | 'total' | 'max' | 'min'
    | 'ave' (reference profiler.py sorted_key contract)."""
    _check_sorted_key(sorted_key)
    rows = [(tag, e["calls"], e["total"], e["max"],
             0.0 if e["min"] == float("inf") else e["min"],
             e["total"] / max(e["runs"], 1),  # mean over EXEC calls only
             e["compiles"], e["compile_s"])
            for tag, e in _entries.items()]
    keyidx = {"calls": 1, "total": 2, "max": 3, "min": 4, "ave": 5}
    if sorted_key is not None:
        rows.sort(key=lambda r: r[keyidx[sorted_key]], reverse=True)
    lines = ["%-40s %8s %10s %10s %10s %10s %9s %10s" %
             ("Entry", "Calls", "Total(s)", "Max(s)", "Min(s)", "Ave(s)",
              "Compiles", "Compile(s)")]
    for tag, calls, total, mx, mn, ave, ncomp, comp in rows:
        lines.append("%-40s %8d %10.4f %10.4f %10.4f %10.4f %9d %10.4f"
                     % (tag[:40], calls, total, mx, mn, ave, ncomp, comp))
    return "\n".join(lines)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    global _trace_dir, _active
    _active = False
    if _trace_dir is not None:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        _trace_dir = None
    _span[1] = time.time()
    if _span[0] is not None:
        print("[paddle_tpu.profiler] profiled %.3fs; XLA trace at %s"
              % (_span[1] - _span[0], profile_path))
    if _entries:
        print(profile_report(sorted_key))


def reset_profiler():
    _entries.clear()
    _span[0] = _span[1] = None


@contextlib.contextmanager
def cuda_profiler(*args, **kwargs):
    """Reference API kept for script compatibility; profiles the TPU."""
    with profiler():
        yield
