"""Profiler.

Parity: python/paddle/fluid/profiler.py (cuda_profiler/profiler context
managers over platform::Profiler). TPU-native: wraps jax.profiler traces
(viewable in TensorBoard/XProf) and reports per-run wall times + compile
cache statistics, which replace the reference's per-op CPU/GPU timeline.
"""
import contextlib
import time

import jax

__all__ = ["profiler", "start_profiler", "stop_profiler", "reset_profiler"]

_records = []
_trace_dir = None


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    """Parity: fluid.profiler.profiler context manager."""
    start_profiler(state, profile_path)
    yield
    stop_profiler(sorted_key, profile_path)


def start_profiler(state="All", profile_path="/tmp/profile"):
    global _trace_dir
    _trace_dir = profile_path
    try:
        jax.profiler.start_trace(profile_path)
    except Exception:
        _trace_dir = None
    _records.append(("start", time.time()))


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    global _trace_dir
    if _trace_dir is not None:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        _trace_dir = None
    _records.append(("stop", time.time()))
    starts = [t for k, t in _records if k == "start"]
    stops = [t for k, t in _records if k == "stop"]
    if starts and stops:
        print("[paddle_tpu.profiler] profiled %.3fs; XLA trace at %s"
              % (stops[-1] - starts[-1], profile_path))


def reset_profiler():
    del _records[:]


@contextlib.contextmanager
def cuda_profiler(*args, **kwargs):
    """Reference API kept for script compatibility; profiles the TPU."""
    with profiler():
        yield
