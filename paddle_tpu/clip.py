"""Gradient / error clipping.

Parity: python/paddle/fluid/clip.py — GradientClipByValue/Norm/GlobalNorm,
set_gradient_clip, ErrorClipByValue.
"""
from .core.framework import default_main_program

__all__ = ["ErrorClipByValue", "GradientClipByValue", "GradientClipByNorm",
           "GradientClipByGlobalNorm", "set_gradient_clip",
           "append_gradient_clip_ops", "error_clip_callback"]


class BaseErrorClipAttr(object):
    pass


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max


def error_clip_callback(block, context):
    """Parity: reference clip.py:62 — called per appended grad op with the
    grad_to_var map; clips @GRAD outputs whose forward var carries an
    error_clip attr. core/backward.py applies the same policy inline for
    the built-in append_backward; this callback is the hook for custom
    backward builders."""
    grad_to_var = context
    if not block.ops:
        return
    op = block.ops[-1]
    for grad_n in (n for ns in op.outputs.values() for n in ns
                   if n in grad_to_var):
        fwd_var = block.var_recursive(grad_to_var[grad_n])
        error_clip = getattr(fwd_var, "error_clip", None)
        if error_clip is None:
            continue
        if not isinstance(error_clip, BaseErrorClipAttr):
            raise TypeError("Variable's error_clip should be an instance "
                            "of BaseErrorClipAttr or None")
        block.append_op(
            type="clip", inputs={"X": [grad_n]}, outputs={"Out": [grad_n]},
            attrs={"min": error_clip.min, "max": error_clip.max},
            infer_shape=False)


class BaseGradientClipAttr(object):
    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def _create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def _create_operators(self, param, grad):
        block = grad.block
        out = block.create_var(dtype=grad.dtype, shape=grad.shape,
                               name=grad.name + "@CLIP")
        block.append_op(type="clip", inputs={"X": [grad]},
                        outputs={"Out": [out]},
                        attrs={"min": self.min, "max": self.max},
                        infer_shape=False)
        return param, out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _create_operators(self, param, grad):
        block = grad.block
        out = block.create_var(dtype=grad.dtype, shape=grad.shape,
                               name=grad.name + "@CLIP")
        block.append_op(type="clip_by_norm", inputs={"X": [grad]},
                        outputs={"Out": [out]},
                        attrs={"max_norm": self.clip_norm},
                        infer_shape=False)
        return param, out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process_context(self, context, param, grad):
        if self.group_name not in context:
            context[self.group_name] = []
            context[self.group_name + "_clip_value"] = self.clip_norm
        elif context[self.group_name + "_clip_value"] != self.clip_norm:
            raise ValueError("all parameters in a group should share clip_norm")
        context[self.group_name].append((param, grad))
        self.context = context

    def _create_operators(self, param, grad):
        # one fused global-norm clip per group (lowered as a single XLA
        # fusion; parity with the reference's square_sum + scale pipeline)
        group = self.context[self.group_name]
        if group[0][0] is not param:
            # operators are created when the first param of the group comes
            # through; cached scale var reused for the rest
            pass
        block = grad.block
        scale_name = self.group_name + "@CLIP_SCALE"
        if not block.has_var(scale_name):
            sums = []
            for _, g in group:
                sq = block.create_var(dtype=g.dtype, shape=(1,))
                block.append_op(type="reduce_sum_square", inputs={"X": [g]},
                                outputs={"Out": [sq]}, infer_shape=False)
                sums.append(sq)
            total = block.create_var(dtype=grad.dtype, shape=(1,),
                                     name=self.group_name + "@GLOBAL_NORM_SQ")
            block.append_op(type="sum", inputs={"X": sums},
                            outputs={"Out": [total]}, infer_shape=False)
            scale = block.create_var(dtype=grad.dtype, shape=(1,),
                                     name=scale_name)
            block.append_op(type="global_norm_scale", inputs={"X": [total]},
                            outputs={"Out": [scale]},
                            attrs={"clip_norm": self.clip_norm},
                            infer_shape=False)
        scale_var = block.var(scale_name)
        out = block.create_var(dtype=grad.dtype, shape=grad.shape,
                               name=grad.name + "@CLIP")
        block.append_op(type="elementwise_mul",
                        inputs={"X": [grad], "Y": [scale_var]},
                        outputs={"Out": [out]}, attrs={"axis": -1},
                        infer_shape=False)
        return param, out


def set_gradient_clip(clip, param_list=None, program=None):
    if not isinstance(clip, BaseGradientClipAttr):
        raise TypeError("clip should be an instance of BaseGradientClipAttr")
    if program is None:
        program = default_main_program()
    if param_list is None:
        param_list = program.global_block().all_parameters()
    if all(isinstance(elem, str) for elem in param_list):
        param_list = [program.global_block().var(name) for name in param_list]
    for param in param_list:
        param.gradient_clip_attr = clip


def append_gradient_clip_ops(param_grad):
    context = {}
    for p, g in param_grad:
        clip_attr = p.gradient_clip_attr or NullGradientClipAttr()
        clip_attr._process_context(context=context, param=p, grad=g)
    res = []
    for p, g in param_grad:
        clip_attr = p.gradient_clip_attr or NullGradientClipAttr()
        res.append(clip_attr._create_operators(param=p, grad=g))
    return res
