"""Structural passes: op-registry coverage, reader placement, feed/fetch
carrier well-formedness.

These unify validation that previously lived scattered across runtime
paths: `registry.get`'s NotImplementedError (now caught before lowering),
`run_host_io_prepass`'s "main block refuses steps>1" refusal, and the
feed/fetch plumbing rules `reference_format.py` enforces on the era wire.
"""
from ..core import registry
from ..core.readers import HOST_IO_OPS
from .pass_base import AnalysisPass, register_pass
from .diagnostics import Diagnostic, ERROR

READER_CREATION_OPS = frozenset(HOST_IO_OPS - {"read"})


def known_op_types():
    """Op types SOME lowering path handles: registered rules, graph-level
    specials (control flow / tensor arrays), host-side io ops, and the
    generic gradient op."""
    from ..core.lowering import _SPECIAL
    return (set(registry._OPS) | set(_SPECIAL) | set(HOST_IO_OPS)
            | {"grad_of"})


@register_pass
class OpRegistryPass(AnalysisPass):
    """Unregistered-op detection: the runtime raises NotImplementedError
    deep inside the jit trace; here it is a pre-lowering error with
    close-name suggestions (registry.suggest) and the creation site."""

    name = "op-registry"

    def run(self, ctx):
        known = known_op_types()
        for block in ctx.program.blocks:
            for i, op in enumerate(block.ops):
                if op.type not in known:
                    close = registry.suggest(op.type)
                    ctx.error(
                        "unregistered-op",
                        "op type %r has no registered TPU lowering"
                        % op.type,
                        block=block, op_idx=i, op=op,
                        hint=("did you mean %s?" %
                              " / ".join(repr(c) for c in close))
                        if close else
                        "register a lowering rule (core/registry.py) or "
                        "remove the op")
                elif op.type == "grad_of":
                    from ..core.lowering import SPECIAL_GRADS
                    fwd = op.attrs.get("fwd_type")
                    if fwd and fwd not in SPECIAL_GRADS \
                            and not registry.is_registered(fwd):
                        ctx.error(
                            "unregistered-op",
                            "grad_of op differentiates forward type %r "
                            "which has no registered lowering" % fwd,
                            block=block, op_idx=i, op=op,
                            var_names=(fwd,))


@register_pass
class ReaderPlacementPass(AnalysisPass):
    """In-graph reader op placement. The io pre-pass
    (executor.run_host_io_prepass) executes host io ops of the GLOBAL
    block only, and with steps>1 refuses reader-creation ops in the run
    program (they would run once per CALL, not once per step) — both are
    runtime failures this pass surfaces before any record is consumed."""

    name = "reader-placement"

    def run(self, ctx):
        has_read = any(op.type == "read"
                       for b in ctx.program.blocks for op in b.ops)
        for block in ctx.program.blocks:
            for i, op in enumerate(block.ops):
                if op.type == "read":
                    if block.idx != 0:
                        ctx.error(
                            "reader-placement",
                            "`read` op in sub-block %d: the io pre-pass "
                            "only executes readers in the global block, "
                            "so this op is silently skipped and its "
                            "outputs are never produced" % block.idx,
                            block=block, op_idx=i, op=op,
                            hint="hoist read_file out of the "
                                 "while/conditional block")
                        continue
                    rnames = op.inputs.get("Reader", [])
                    rvar = ctx.lookup(block, rnames[0]) if rnames else None
                    if not rnames:
                        ctx.error("reader-placement",
                                  "`read` op has no Reader input",
                                  block=block, op_idx=i, op=op)
                    elif rvar is not None and not rvar.persistable:
                        ctx.warning(
                            "reader-placement",
                            "reader variable %r is not persistable; its "
                            "host-side state will not survive in the "
                            "scope between runs" % rnames[0],
                            block=block, op_idx=i, op=op,
                            var_names=(rnames[0],))
                elif op.type in READER_CREATION_OPS:
                    if block.idx != 0:
                        ctx.error(
                            "reader-placement",
                            "reader-creation op %r in sub-block %d is "
                            "never executed by the io pre-pass"
                            % (op.type, block.idx),
                            block=block, op_idx=i, op=op)
                    elif ctx.steps > 1:
                        ctx.error(
                            "reader-placement",
                            "reader-creation op %r in the main block of a "
                            "steps=%d run: it would execute once per CALL "
                            "instead of once per step"
                            % (op.type, ctx.steps),
                            block=block, op_idx=i, op=op,
                            hint="keep reader creation in the startup "
                                 "program (the standard split), or run "
                                 "with steps=1")
                    elif has_read:
                        ctx.warning(
                            "reader-placement",
                            "reader-creation op %r rides in the same "
                            "program as `read` ops: re-running this "
                            "program resets the reader every call"
                            % op.type,
                            block=block, op_idx=i, op=op,
                            hint="keep reader creation in the startup "
                                 "program")


@register_pass
class CarrierPass(AnalysisPass):
    """Feed/fetch carrier well-formedness for the in-memory Program:
    every fetch must be producible at the top level (written by a global
    op or its sub-block carries, persistable, or fed), and sequence feeds
    need their @SEQLEN companion declared. The era-wire (serialized
    protobuf) carrier rules live in `check_wire_carriers` below."""

    name = "carriers"

    def run(self, ctx):
        gblock = ctx.program.global_block()
        producible = set(ctx.feed_names)
        for op in gblock.ops:
            producible.update(n for ns in op.outputs.values()
                              for n in ns if n)
            # sub-block carries are written back into the top-level env
            for key in ("carry_names", "out_names"):
                val = op.attrs.get(key)
                if isinstance(val, (list, tuple)):
                    producible.update(n for n in val if n)
            cond = op.inputs.get("Condition")
            if op.type == "while" and cond:
                producible.add(cond[0])
        for name in ctx.fetch_names:
            if name in producible:
                continue
            v = ctx.lookup(gblock, name)
            if v is not None and v.persistable:
                continue  # scope read (evaluator.eval pattern)
            ctx.error(
                "bad-fetch",
                "fetch target %r is neither produced by the program, "
                "persistable, nor fed" % name,
                var_names=(name,),
                hint="fetch a variable the program writes, or mark it "
                     "persistable so it survives in the scope")
        for name in sorted(ctx.feed_names):
            v = ctx.lookup(gblock, name)
            if v is None:
                if not name.endswith("@SEQLEN"):
                    ctx.warning(
                        "unknown-feed",
                        "fed variable %r is not declared in the program"
                        % name, var_names=(name,))
                continue
            if v.lod_level > 0 and not getattr(v, "seq_len_var", None):
                ctx.warning(
                    "bad-carrier",
                    "sequence feed %r (lod_level=%d) has no @SEQLEN "
                    "lengths companion; only LoDTensor feeds will work"
                    % (name, v.lod_level), var_names=(name,))


def check_wire_carriers(blocks):
    """Era-wire feed/fetch plumbing checks on a parsed ProgramDesc
    (reference_format._parse_blocks output or raw protobuf bytes) —
    the serialized-format half of CarrierPass, run by tools/pplint.py
    BEFORE parse_program_desc strips the plumbing:

      * the 'feed'/'fetch' carrier vars exist and are persistable
        (the era C++ executor creates non-persistable vars in a per-run
        LOCAL scope, so a non-persistable carrier shadows the outer-scope
        one SetFeedVariable filled — reference_format.py's rule);
      * feed/fetch op col attrs are unique and contiguous 0..n-1;
      * every feed Out / fetch X names a declared variable.

    Returns a list of Diagnostics (errors only)."""
    from .. import reference_format as rf
    if isinstance(blocks, (bytes, bytearray)):
        blocks = rf._parse_blocks(blocks)
    diags = []

    def err(msg, var_names=()):
        diags.append(Diagnostic(ERROR, "bad-carrier", msg, block_idx=0,
                                var_names=var_names))

    if not blocks:
        return diags
    _, _, varz, ops = blocks[0]
    var_info = {name: (vtype, persistable)
                for name, vtype, persistable in varz}
    declared = set(var_info)
    plumbing = [(t, ins, outs, attrs) for t, ins, outs, attrs in ops
                if t in ("feed", "fetch")]
    for carrier in ("feed", "fetch"):
        n_ops = sum(1 for t, _, _, _ in plumbing if t == carrier)
        if not n_ops:
            continue
        info = var_info.get(carrier)
        if info is None:
            err("%d %s op(s) but no %r carrier variable is declared"
                % (n_ops, carrier, carrier), (carrier,))
        elif not info[1]:
            err("%r carrier variable is not persistable: the era executor "
                "would shadow it with a per-run local-scope var and "
                "%s data would be lost" % (carrier, carrier), (carrier,))
    for carrier, slot in (("feed", "Out"), ("fetch", "X")):
        cols = []
        for t, ins, outs, attrs in plumbing:
            if t != carrier:
                continue
            cols.append(attrs.get("col", len(cols)))
            names = (outs if carrier == "feed" else ins).get(slot, [])
            if not names:
                err("%s op has no %s slot" % (carrier, slot))
            elif names[0] not in declared:
                err("%s op references undeclared variable %r"
                    % (carrier, names[0]), (names[0],))
        if cols and sorted(cols) != list(range(len(cols))):
            err("%s op col attrs %r are not contiguous 0..%d"
                % (carrier, sorted(cols), len(cols) - 1))
    return diags
