"""Static program verifier: a pass pipeline over the Fluid graph IR,
run BEFORE lowering.

The reference stack validates programs piecemeal at run time (per-op
InferShape inside the executor loop), so a malformed ProgramDesc fails
deep inside op N with no pointer back to the layer call that built it —
and the whole-program XLA rebuild inherits that as opaque trace/XLA
failures after lowering has started. Like TVM's and TensorFlow's
graph-level verification passes, this package checks the Program while
it is still a graph:

    result = analysis.analyze(program, feed_names=[...],
                              fetch_names=[...])
    for d in result:            # structured Diagnostics
        print(d.format())
    result.raise_if_errors()    # ProgramVerificationError

Pipeline (pass_base.PASS_REGISTRY, registration order):
  op-registry       unregistered op types (+ close-name suggestions)
  reader-placement  host-io ops outside the io pre-pass's reach
  carriers          feed/fetch well-formedness, sequence companions
  def-use           use-before-def, cross-block captures, carrier
                    hazards, dead writes/ops/unused vars
  shape-infer       declared vs re-inferred shapes/dtypes (first
                    inconsistent op)

Entry points: `Executor.run(validate=True)` / FLAGS_validate_program=1
(errors raise before any reader record is consumed), `tools/pplint.py`
for saved programs (native desc, pickle, or era-wire protobuf), and the
op_test harness (every op test validates its program for free). See
ARCHITECTURE.md §2c for how to add a pass.
"""
from .diagnostics import (AnalysisResult, Diagnostic, ERROR, WARNING,
                          ProgramVerificationError)
from .pass_base import (AnalysisContext, AnalysisPass, PASS_REGISTRY,
                        default_passes, register_pass)
from . import structural  # registers op-registry/reader-placement/carriers
from . import def_use     # registers def-use
from . import shape_infer  # registers shape-infer
from .structural import check_wire_carriers

__all__ = [
    "analyze", "validate_or_raise", "Diagnostic", "AnalysisResult",
    "AnalysisContext", "AnalysisPass", "ProgramVerificationError",
    "ERROR", "WARNING", "PASS_REGISTRY", "default_passes",
    "register_pass", "check_wire_carriers",
]


def analyze(program, feed_names=None, fetch_names=None, steps=1,
            passes=None):
    """Run the analysis pipeline over `program`; returns AnalysisResult.

    feed_names: names the caller will feed (None = assume every is_data
    var, the layers.data contract). fetch_names: fetch targets (enables
    precise dead-code/fetchability checks). steps: the Executor steps=K
    setting (K>1 arms the multi-step reader-placement rule). passes:
    explicit pass instances (default: the registered pipeline).
    """
    ctx = AnalysisContext(program, feed_names=feed_names,
                          fetch_names=fetch_names, steps=steps)
    for p in (passes if passes is not None else default_passes()):
        p.run(ctx)
    return ctx.result


def validate_or_raise(program, feed_names=None, fetch_names=None, steps=1,
                      passes=None):
    """analyze() + raise ProgramVerificationError on any error-severity
    finding (strict mode). Returns the AnalysisResult when clean."""
    result = analyze(program, feed_names=feed_names,
                     fetch_names=fetch_names, steps=steps, passes=passes)
    result.raise_if_errors()
    return result
