"""Static program verifier: a pass pipeline over the Fluid graph IR,
run BEFORE lowering.

The reference stack validates programs piecemeal at run time (per-op
InferShape inside the executor loop), so a malformed ProgramDesc fails
deep inside op N with no pointer back to the layer call that built it —
and the whole-program XLA rebuild inherits that as opaque trace/XLA
failures after lowering has started. Like TVM's and TensorFlow's
graph-level verification passes, this package checks the Program while
it is still a graph:

    result = analysis.analyze(program, feed_names=[...],
                              fetch_names=[...])
    for d in result:            # structured Diagnostics
        print(d.format())
    result.raise_if_errors()    # ProgramVerificationError

Base pipeline (pass_base.PASS_REGISTRY, registration order):
  op-registry       unregistered op types (+ close-name suggestions)
  reader-placement  host-io ops outside the io pre-pass's reach
  carriers          feed/fetch well-formedness, sequence companions
  def-use           use-before-def, cross-block captures, carrier
                    hazards, dead writes/ops/unused vars
  shape-infer       declared vs re-inferred shapes/dtypes (first
                    inconsistent op)

Deployment tier (deployment.DEPLOYMENT_PASS_REGISTRY) — runs only when
a `DeploymentContext` is supplied, checking the program against how it
will be DEPLOYED rather than against the IR alone:
  row-independence      batch-dim taint: row-sliced fetches depend only
                        on their own row (the batching contract), with
                        per-fetch certificates on the result
  sharding-consistency  ShardingPlan vs program coherence
  dtype-flow            @QVAL/@QSCALE pairing, AMP flags, stray fp64
  decode-invariants     slot write-once/static-shape/aliasing contract
  donation-safety       scope state read after its in-step update

Entry points: `Executor.run(validate=True)` / FLAGS_validate_program=1
(errors raise before any reader record is consumed), engine load
(`InferenceEngine`/`DecodeEngine` run the deployment tier under their
own context before the empirical probes), `ParallelExecutor` plan
arming, `CheckpointManager` save, `tools/pplint.py` for saved programs
(native desc, pickle, or era-wire protobuf; `--deploy` picks the
context), and the op_test harness. See ARCHITECTURE.md §2c.
"""
from .diagnostics import (AnalysisResult, Diagnostic, ERROR, WARNING,
                          ProgramVerificationError)
from .pass_base import (AnalysisContext, AnalysisPass, PASS_REGISTRY,
                        default_passes, register_pass)
from .deployment import (DEPLOYMENT_PASS_REGISTRY, DeploymentContext,
                         DeploymentPass, PlanView, deployment_passes,
                         infer_slot_vars, register_deployment_pass)
from . import structural  # registers op-registry/reader-placement/carriers
from . import def_use     # registers def-use
from . import shape_infer  # registers shape-infer
from . import row_independence      # registers row-independence
from . import sharding_consistency  # registers sharding-consistency
from . import dtype_flow            # registers dtype-flow
from . import decode_invariants     # registers decode-invariants
from . import donation_safety       # registers donation-safety
from .structural import check_wire_carriers

__all__ = [
    "analyze", "analyze_deployment", "validate_or_raise", "Diagnostic",
    "AnalysisResult", "AnalysisContext", "AnalysisPass",
    "ProgramVerificationError", "ERROR", "WARNING", "PASS_REGISTRY",
    "DEPLOYMENT_PASS_REGISTRY", "DeploymentContext", "DeploymentPass",
    "PlanView", "default_passes", "deployment_passes", "infer_slot_vars",
    "register_pass", "register_deployment_pass", "check_wire_carriers",
]


def analyze(program, feed_names=None, fetch_names=None, steps=1,
            passes=None, deploy=None):
    """Run the analysis pipeline over `program`; returns AnalysisResult.

    feed_names: names the caller will feed (None = assume every is_data
    var, the layers.data contract). fetch_names: fetch targets (enables
    precise dead-code/fetchability checks). steps: the Executor steps=K
    setting (K>1 arms the multi-step reader-placement rule). passes:
    explicit pass instances (default: the registered pipeline).
    deploy: a DeploymentContext — appends the applicable deployment
    passes after the base/explicit pipeline.
    """
    ctx = AnalysisContext(program, feed_names=feed_names,
                          fetch_names=fetch_names, steps=steps,
                          deploy=deploy)
    pipeline = list(passes if passes is not None else default_passes())
    if deploy is not None:
        pipeline.extend(deployment_passes(deploy))
    for p in pipeline:
        p.run(ctx)
    return ctx.result


def analyze_deployment(program, deploy, feed_names=None, fetch_names=None,
                       steps=1):
    """Run ONLY the deployment tier under `deploy` — the engines' load
    path, where the base pipeline already ran on the pristine program
    and only the deployment contracts (possibly against a REWRITTEN
    program: int8, bf16) still need proving."""
    ctx = AnalysisContext(program, feed_names=feed_names,
                          fetch_names=fetch_names, steps=steps,
                          deploy=deploy)
    for p in deployment_passes(deploy):
        p.run(ctx)
    return ctx.result


def validate_or_raise(program, feed_names=None, fetch_names=None, steps=1,
                      passes=None, deploy=None):
    """analyze() + raise ProgramVerificationError on any error-severity
    finding (strict mode). Returns the AnalysisResult when clean."""
    result = analyze(program, feed_names=feed_names,
                     fetch_names=fetch_names, steps=steps, passes=passes,
                     deploy=deploy)
    result.raise_if_errors()
    return result
