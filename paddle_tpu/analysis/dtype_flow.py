"""Dtype-flow: quantization pair well-formedness, AMP flags, stray fp64.

The base shape-infer pass already re-derives every op's output dtype
forward through the graph and flags declared-vs-inferred conflicts; this
deployment pass layers the DEPLOYMENT dtype contracts on top:

  quant-pair   the int8 rewrite's structural invariant (PR-13): every
               X@QVAL has an X@QSCALE twin, both persistable with the
               storage dtypes ops/quant_ops.DEQUANTIZE_SLOTS pins
               (int8 values, f32 per-channel scales), the scale length
               matches the quantized axis, exactly one
               dequantize_channel consumes the pair, and the base var
               it reconstitutes is a plain intermediate written by that
               op alone — so every consumer reads the dequantized value,
               never a stale fp32 master shadowing it from the scope
  amp-flag     (WARNING) the deployment says bf16 / AMP but the program
               was built without enable_mixed_precision — weights get
               demoted while every intermediate stays f32, the worst of
               both precisions
  stray-fp64   (WARNING) a declared float64 var: without jax_enable_x64
               it silently truncates to f32; with it, it doubles HBM and
               falls off the fast matmul path on TPU
"""
import collections

from ..ops.quant_ops import DEQUANTIZE_SLOTS
from .deployment import DeploymentPass, register_deployment_pass
from .shape_infer import _canonical

# mirrors serving.quantize.{QVAL,QSCALE}_SUFFIX — NOT imported, because
# analysis loads before the serving package in paddle_tpu/__init__ and
# pulling serving.quantize here would initialize the whole serving stack
# mid-import; test_deployment_analysis pins the two pairs equal
QVAL_SUFFIX = "@QVAL"
QSCALE_SUFFIX = "@QSCALE"


@register_deployment_pass
class DtypeFlowPass(DeploymentPass):
    name = "dtype-flow"

    def run(self, ctx):
        self._check_quant_pairs(ctx)
        self._check_amp(ctx)
        self._check_fp64(ctx)

    # ---- @QVAL/@QSCALE structure -------------------------------------
    def _check_quant_pairs(self, ctx):
        gb = ctx.program.global_block()
        dequants = collections.defaultdict(list)  # qval name -> ops
        writers = collections.defaultdict(list)   # any name -> writer ops
        for block in ctx.program.blocks:
            for op_idx, op in enumerate(block.ops):
                for n in op.all_output_vars():
                    if n:
                        writers[n].append((block, op_idx, op))
                if op.type == "dequantize_channel":
                    for n in op.inputs.get("X", ()):
                        dequants[n].append((block, op_idx, op))

        names = {v.name for v in ctx.program.list_vars()}
        for qv in sorted(n for n in names if n.endswith(QVAL_SUFFIX)):
            base = qv[:-len(QVAL_SUFFIX)]
            self._check_pair(ctx, gb, qv, base, dequants, writers)
        for qs in sorted(n for n in names if n.endswith(QSCALE_SUFFIX)):
            base = qs[:-len(QSCALE_SUFFIX)]
            if base + QVAL_SUFFIX not in names:
                ctx.error(
                    "quant-pair",
                    "scale %r has no %r twin — the dequantize has "
                    "nothing to widen" % (qs, base + QVAL_SUFFIX),
                    var_names=(qs,),
                    hint="re-run the int8 rewrite; a partial rewrite "
                         "artifact was saved")

    def _check_pair(self, ctx, gb, qv, base, dequants, writers):
        qs = base + QSCALE_SUFFIX
        qv_var, qs_var = ctx.lookup(gb, qv), ctx.lookup(gb, qs)
        if qs_var is None:
            ctx.error(
                "quant-pair",
                "quantized values %r have no %r scales — consumers "
                "would read raw int8 codes as if they were weights"
                % (qv, qs),
                var_names=(qv,),
                hint="re-run the int8 rewrite; a partial rewrite "
                     "artifact was saved")
            return
        for name, var, slot in ((qv, qv_var, "X"), (qs, qs_var, "Scale")):
            want = DEQUANTIZE_SLOTS[slot]
            if var.dtype is not None and \
                    _canonical(var.dtype) != _canonical(want):
                ctx.error(
                    "quant-pair",
                    "%r is declared %s but the int8 storage contract "
                    "(dequantize_channel %s slot) is %s"
                    % (name, var.dtype, slot, want), var_names=(name,))
            if not var.persistable:
                ctx.error(
                    "quant-pair",
                    "%r must be persistable — the quantized storage IS "
                    "the scope state int8 serving exists for" % name,
                    var_names=(name,))
        users = dequants.get(qv, ())
        if not users:
            ctx.error(
                "quant-pair",
                "no dequantize_channel consumes %r: the quantized "
                "weight is dead and consumers of %r read something else "
                "entirely" % (qv, base), var_names=(qv, base),
                hint="the rewrite inserts dequantize_channel(X=%s, "
                     "Scale=%s) -> %s in front of the first consumer"
                     % (qv, qs, base))
            return
        block, op_idx, op = users[0]
        if len(users) > 1:
            ctx.warning(
                "quant-pair",
                "%d dequantize_channel ops consume %r — one widen fused "
                "into the consumer is the contract; extras waste HBM "
                "bandwidth" % (len(users), qv),
                block=block, op_idx=op_idx, op=op, var_names=(qv,))
        outs = [n for n in op.all_output_vars() if n]
        scale_shape = tuple(getattr(qs_var, "shape", ()) or ())
        q_shape = tuple(getattr(qv_var, "shape", ()) or ())
        axis = op.attrs.get("axis", -1)
        if q_shape and len(scale_shape) == 1 and scale_shape[0] >= 0:
            chan = q_shape[axis if axis >= 0 else axis + len(q_shape)]
            if chan >= 0 and scale_shape[0] != chan:
                ctx.error(
                    "quant-pair",
                    "%r has %d scales but %r has %d channels along the "
                    "quantized axis %d" % (qs, scale_shape[0], qv, chan,
                                           axis),
                    block=block, op_idx=op_idx, op=op,
                    var_names=(qv, qs))
        for out in outs:
            out_var = ctx.lookup(gb, out)
            if out_var is not None and out_var.persistable:
                ctx.error(
                    "quant-pair",
                    "dequantize_channel writes %r which is still "
                    "persistable: the scope's fp32 master would shadow "
                    "(or be clobbered by) the dequantized value "
                    "depending on donation order" % out,
                    block=block, op_idx=op_idx, op=op, var_names=(out,),
                    hint="the rewrite demotes the base param to a plain "
                         "intermediate; re-run it")
            extra = [w for w in writers.get(out, ()) if w[2] is not op]
            if extra:
                eb, ei, eop = extra[0]
                ctx.error(
                    "quant-pair",
                    "%r is written both by dequantize_channel and by op "
                    "%d (%s) — consumers race between the dequantized "
                    "weight and something else" % (out, ei, eop.type),
                    block=eb, op_idx=ei, op=eop, var_names=(out,))

    # ---- AMP flag vs deployment --------------------------------------
    def _check_amp(self, ctx):
        deploy = ctx.deploy
        program_amp = bool(getattr(ctx.program, "_amp", False))
        wants_amp = deploy.amp if deploy.amp is not None else (
            True if deploy.weights_dtype == "bf16" else None)
        if wants_amp is True and not program_amp:
            ctx.warning(
                "amp-flag",
                "deployment expects bf16/AMP but the program was built "
                "without enable_mixed_precision: weights demote to bf16 "
                "while every intermediate stays f32 — the bandwidth win "
                "without the compute win, plus a cast per weight use",
                hint="build with "
                     "fluid.default_main_program()."
                     "enable_mixed_precision(), or serve f32")
        elif wants_amp is False and program_amp:
            ctx.warning(
                "amp-flag",
                "the program was built WITH enable_mixed_precision but "
                "this deployment pins full f32 — intermediates compute "
                "bf16 against f32 expectations",
                hint="match the deployment's amp flag to the program")

    # ---- stray fp64 ---------------------------------------------------
    def _check_fp64(self, ctx):
        seen = set()
        for v in ctx.program.list_vars():
            if v.name in seen or str(v.dtype) not in ("float64", "double"):
                continue
            seen.add(v.name)
            ctx.warning(
                "stray-fp64",
                "variable %r is declared float64: without jax_enable_x64 "
                "it silently truncates to f32, with it it computes at "
                "1/10th matmul throughput on TPU" % v.name,
                var_names=(v.name,),
                hint="declare f32 (or int64 for ids) explicitly")
