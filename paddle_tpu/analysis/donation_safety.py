"""Donation-safety: scope state read after its in-step update.

The executor donates scope buffers into the step where it can (the
state_rw fast path, decode's slot update): after the update writes a
persistable var, the PRE-update buffer is gone. Inside one traced step
that is fine — dataflow is by value — but an op that reads the var
AFTER its update observes the NEW value, while the same read placed
before it observes the OLD one. Programs that mix the two orderings
around an in-place update are almost always one reorder away from a
silent semantic change (and are exactly the shape that breaks when a
fetch aliases a donated buffer), so this pass flags them:

  read-after-update  (WARNING) persistable var read both BEFORE and
                     AFTER an op updates it in the same step — the two
                     reads observe different values of one name, the
                     pre/post ambiguity donation turns into
                     use-after-free

A var whose every read follows its single write (the lr-decay counter:
increment, then read everywhere) is unambiguous and NOT flagged — only
mixed-order reads are. Exempt: the numeric-guard machinery
(guard_backup/guard_select_all re-read updated params by design — that
is the rollback contract), gradient accumulation, and reads inside the
updating op itself (sgd/adam read-modify-write their param in one op).
"""
from ..core.framework import GRAD_SUFFIX
from .deployment import DeploymentPass, register_deployment_pass

_GUARD_OPS = frozenset({"guard_backup", "guard_select_all"})


@register_deployment_pass
class DonationSafetyPass(DeploymentPass):
    name = "donation-safety"

    def run(self, ctx):
        gb = ctx.program.global_block()
        last_write = {}  # persistable name -> (op_idx, op)
        read_before = set()  # names read before any in-step write
        reported = set()
        for op_idx, op in enumerate(gb.ops):
            if op.type in _GUARD_OPS:
                continue
            reads = [n for n in op.all_input_vars() if n]
            outs = frozenset(n for n in op.all_output_vars() if n)
            for name in reads:
                if name in outs or name in reported:
                    continue  # in-op read-modify-write is one update
                prev = last_write.get(name)
                if prev is None:
                    read_before.add(name)
                    continue
                if name not in read_before:
                    continue  # write-then-read only: unambiguous
                widx, wop = prev
                reported.add(name)
                ctx.warning(
                    "read-after-update",
                    "persistable %r is updated by op %d (%s) and read "
                    "again by op %d (%s) in the same step: the read "
                    "observes the post-update value, and a donated "
                    "buffer makes the pre-update value unrecoverable — "
                    "one reorder (or a fetch of this var) away from a "
                    "silent semantic change"
                    % (name, widx, wop.type, op_idx, op.type),
                    block=gb, op_idx=op_idx, op=op, var_names=(name,),
                    hint="read the var before its update, or route the "
                         "updated value through a fresh intermediate")
            for name in outs:
                if name.endswith(GRAD_SUFFIX) or op.type == "grad_of":
                    continue  # accumulation, not an update
                var = ctx.lookup(gb, name)
                if var is not None and var.persistable:
                    last_write[name] = (op_idx, op)
