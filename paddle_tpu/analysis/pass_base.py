"""Pass registry + shared AnalysisContext for the static program verifier.

A pass is a class with a `name` and `run(ctx)`; `@register_pass` puts it in
the default pipeline in registration order (structural checks first, then
def-use, then shape inference — later passes may assume earlier invariants,
e.g. shape inference skips ops the registry pass already flagged as
unregistered). `analyze()` (package __init__) instantiates the pipeline
fresh per program, so passes may keep per-run state on self.
"""
import collections

from ..core.framework import _sub_block_indices
from .diagnostics import (AnalysisResult, Diagnostic, ERROR, WARNING)

PASS_REGISTRY = collections.OrderedDict()


def register_pass(cls):
    """Class decorator: add an AnalysisPass subclass to the default
    pipeline (keyed by its `name`)."""
    PASS_REGISTRY[cls.name] = cls
    return cls


def default_passes():
    """Fresh instances of every registered pass, pipeline order."""
    return [cls() for cls in PASS_REGISTRY.values()]


class AnalysisContext(object):
    """Everything a pass needs about the program under analysis.

    feed_names=None means "unknown feeds": every is_data Variable (plus
    its @SEQLEN companion) is assumed fed — the layers.data contract.
    When the Executor validates, it passes the REAL feed set; is_data
    vars are still unioned in because in-graph reader (`read` op)
    outputs are injected by the io pre-pass, not listed in `feed`.
    """

    def __init__(self, program, feed_names=None, fetch_names=None, steps=1,
                 deploy=None):
        self.program = program
        self.fetch_names = tuple(
            f if isinstance(f, str) else f.name for f in (fetch_names or ()))
        self.steps = int(steps)
        self.deploy = deploy  # DeploymentContext; None = base tier only
        self.result = AnalysisResult()
        feeds = set(feed_names or ())
        for v in program.list_vars():
            if getattr(v, "is_data", False):
                feeds.add(v.name)
                if getattr(v, "seq_len_var", None):
                    feeds.add(v.seq_len_var)
        self.feed_names = frozenset(feeds)
        self._state = None

    # ---- helpers shared by passes ------------------------------------
    def report(self, severity, code, message, block=None, op_idx=None,
               op=None, var_names=(), hint=None):
        self.result.add(Diagnostic(
            severity, code, message,
            block_idx=block.idx if block is not None else None,
            op_idx=op_idx,
            op_type=op.type if op is not None else None,
            var_names=var_names, hint=hint,
            callstack=getattr(op, "callstack", ()) if op is not None
            else ()))

    def error(self, *args, **kwargs):
        self.report(ERROR, *args, **kwargs)

    def warning(self, *args, **kwargs):
        self.report(WARNING, *args, **kwargs)

    def lookup(self, block, name):
        """Variable for `name` searching block then ancestors (None if
        undeclared anywhere on the chain)."""
        b = block
        while b is not None:
            v = b.vars.get(name)
            if v is not None:
                return v
            b = b.parent_block
        return None

    def state_sets(self):
        """(state_rw, state_ro, state_out) of lowering.analyze_state —
        the executor's own classification of the program's scope state,
        cached per analysis run."""
        if self._state is None:
            from ..core.lowering import analyze_state
            rw, ro, out = analyze_state(
                self.program, sorted(self.feed_names), self.fetch_names)
            self._state = (frozenset(rw), frozenset(ro), frozenset(out))
        return self._state

    def state_in(self):
        """Persistable vars the executor's state analysis would READ from
        the Scope (state_rw + state_ro of lowering.analyze_state) — the
        single source of truth for which read-before-write names are
        legitimately scope-provided."""
        rw, ro, _ = self.state_sets()
        return rw | ro

    def sub_blocks(self, op):
        """Blocks an op's attrs reference (framework._sub_block_indices)."""
        return [self.program.blocks[i] for i in _sub_block_indices(op)
                if 0 <= i < len(self.program.blocks)]


class AnalysisPass(object):
    """Base class; subclasses set `name` and implement run(ctx)."""

    name = "base"

    def run(self, ctx):  # pragma: no cover - interface
        raise NotImplementedError


def attr_referenced_names(op):
    """Var names an op references through ATTRS rather than input slots —
    the same conventions Block.rename_var rewrites (fwd_inputs/fwd_outputs
    maps of grad_of ops, *_name/*_names bindings of control-flow
    lowerings). Used as USES by dead-op/unused-var detection; over-
    approximating (e.g. open_files' file_names) only suppresses warnings,
    never invents one."""
    names = []
    for key, val in op.attrs.items():
        if key in ("fwd_inputs", "fwd_outputs") and isinstance(val, dict):
            for ns in val.values():
                names.extend(n for n in ns if n)
        elif key.endswith("_name") and isinstance(val, str):
            names.append(val)
        elif key.endswith("_names") and isinstance(val, (list, tuple)):
            names.extend(n for n in val if isinstance(n, str) and n)
    return names
