"""Deployment-pass tier: context-parameterized verification.

The base pipeline (structural / def-use / shape-infer) checks a Program
against the IR's own rules. The passes in this tier instead check it
against a DEPLOYMENT — the serving lattice, the decode slot layout, a
ShardingPlan, a weights dtype — captured in a `DeploymentContext`. They
turn contracts that PR-3/9/13/16 could only probe empirically (load-time
row sweeps, bit-exactness checks) into properties proven on the graph:

  row-independence      every row-sliced fetch depends only on its own
                        input row (the Batcher's coalescing contract)
  sharding-consistency  ShardingPlan entries match the program's vars
                        (existence/shape/dtype, grad coverage, int8
                        conflicts, silent replication)
  dtype-flow            @QVAL/@QSCALE pairing + dequantize_channel
                        placement, AMP-flag consistency, stray fp64
  decode-invariants     slot vars written exactly once per step, static
                        slot shapes, fetch/donation aliasing
  donation-safety       scope state read after its in-step update

A deployment pass subclasses DeploymentPass and self-selects on the
context (`applicable(deploy)`), so one pipeline serves all four seams:
InferenceEngine / DecodeEngine load, ParallelExecutor plan arming,
CheckpointManager save, and `tools/pplint.py --deploy ...`. None of
these passes run unless a DeploymentContext is supplied — plain
`analysis.analyze(program)` behavior is unchanged.
"""
import collections

from .pass_base import AnalysisPass

DEPLOYMENT_PASS_REGISTRY = collections.OrderedDict()


def register_deployment_pass(cls):
    """Class decorator: add a DeploymentPass to the deployment tier
    (keyed by `name`, run in registration order after the base tier)."""
    DEPLOYMENT_PASS_REGISTRY[cls.name] = cls
    return cls


def deployment_passes(deploy):
    """Fresh instances of every registered deployment pass that declares
    itself applicable to `deploy`, pipeline order."""
    return [cls() for cls in DEPLOYMENT_PASS_REGISTRY.values()
            if cls.applicable(deploy)]


class DeploymentPass(AnalysisPass):
    """Base for context-parameterized passes; `ctx.deploy` is always a
    DeploymentContext when run() is called."""

    @classmethod
    def applicable(cls, deploy):  # pragma: no cover - interface default
        return True


class DeploymentContext(object):
    """How the program will be DEPLOYED — everything the deployment tier
    checks against that the program desc itself doesn't carry.

    kind           "serving" | "decode" | "training" | "generic"
    row_fetches    fetch names sliced back per request row (the engine's
                   "rows" fetch policy) — MIXED taint here is an ERROR
    whole_fetches  fetches returned whole to every request ("whole" /
                   "dynamic" policy) — MIXED taint is only a WARNING
    row_sources    var names that carry per-row data INTO the step; None
                   means "the feed set" (serving). Decode contexts list
                   the slot-resident state instead.
    slot_vars      persistable slot-major state of a decode step
    max_slots      leading dim of every slot var
    plan           ShardingPlan (or PlanView) the program runs under
    weights_dtype  serving weights dtype ("f32" | "bf16" | "int8")
    amp            expected program AMP flag (None = don't check)
    steps          Executor steps=K setting
    """

    __slots__ = ("kind", "row_fetches", "whole_fetches", "row_sources",
                 "slot_vars", "max_slots", "plan", "weights_dtype", "amp",
                 "steps")

    def __init__(self, kind="generic", row_fetches=(), whole_fetches=(),
                 row_sources=None, slot_vars=(), max_slots=None, plan=None,
                 weights_dtype=None, amp=None, steps=1):
        self.kind = kind
        self.row_fetches = tuple(row_fetches)
        self.whole_fetches = tuple(whole_fetches)
        self.row_sources = (None if row_sources is None
                            else frozenset(row_sources))
        self.slot_vars = frozenset(slot_vars)
        self.max_slots = max_slots
        self.plan = plan
        self.weights_dtype = weights_dtype
        self.amp = amp
        self.steps = int(steps)

    # ---- constructors for the four seams -----------------------------
    @classmethod
    def for_serving(cls, row_fetches, whole_fetches=(), weights_dtype=None,
                    plan=None, amp=None):
        return cls(kind="serving", row_fetches=row_fetches,
                   whole_fetches=whole_fetches, weights_dtype=weights_dtype,
                   plan=plan, amp=amp)

    @classmethod
    def for_decode(cls, slot_vars, max_slots, row_fetches=(),
                   weights_dtype=None):
        return cls(kind="decode", row_fetches=row_fetches,
                   row_sources=slot_vars, slot_vars=slot_vars,
                   max_slots=max_slots, weights_dtype=weights_dtype)

    @classmethod
    def for_training(cls, plan=None, amp=None, steps=1):
        return cls(kind="training", plan=plan, amp=amp, steps=steps)

    @classmethod
    def generic(cls):
        return cls(kind="generic")

    def cache_key(self):
        """Hashable identity for maybe_validate_program's per-program
        validation cache: same program + same deployment = one analysis."""
        plan = self.plan
        plan_key = None
        if plan is not None:
            digest = getattr(plan, "digest", None)
            plan_key = digest() if callable(digest) else id(plan)
        return (self.kind, self.row_fetches, self.whole_fetches,
                self.row_sources, tuple(sorted(self.slot_vars)),
                self.max_slots, plan_key, self.weights_dtype, self.amp,
                self.steps)

    def __repr__(self):
        return "DeploymentContext(%s%s%s)" % (
            self.kind,
            ", plan" if self.plan is not None else "",
            ", %s" % self.weights_dtype if self.weights_dtype else "")


class PlanView(object):
    """Device-free stand-in for a ShardingPlan, for linting a saved plan
    on a machine that cannot build the real mesh (pplint on a 1-CPU box
    checking an 8-chip plan). Carries exactly what sharding-consistency
    reads: entries, mesh axis sizes, and the axis roles. Built from the
    plan's canonical JSON (`ShardingPlan.to_json()`)."""

    def __init__(self, mesh_shape, entries=(), batch_axis=None,
                 shard_axis=None, tp_axis=None, tp_placement="gather"):
        self.mesh_shape = dict(mesh_shape)
        self.batch_axis = batch_axis
        self.shard_axis = shard_axis
        self.tp_axis = tp_axis
        self.tp_placement = tp_placement
        self.entries = {}
        for e in entries:
            self.entries[e.name] = e

    @classmethod
    def from_json(cls, doc):
        from ..parallel.plan import VarPlan, _spec_from_json
        entries = []
        for name in sorted(doc.get("vars", ())):
            d = doc["vars"][name]
            entries.append(VarPlan(
                name, tuple(_spec_from_json(d["spec"])), d["kind"],
                owner=d.get("owner"), override=d.get("override", False),
                reason=d.get("reason", "")))
        return cls(dict(doc.get("mesh_axes", ())), entries,
                   batch_axis=doc.get("batch_axis"),
                   shard_axis=doc.get("shard_axis"),
                   tp_axis=doc.get("tp_axis"),
                   tp_placement=doc.get("tp_placement", "gather"))


def plan_axis_sizes(plan):
    """{axis: size} for a ShardingPlan (real mesh) or PlanView (sizes
    recorded in JSON)."""
    shape = getattr(plan, "mesh_shape", None)
    if shape is None:
        shape = plan.mesh.shape
    return dict(shape)


def infer_slot_vars(program, fetch_names, max_slots):
    """Slot-resident state of a decode step program, by the same rule
    DecodeEngine uses at load: persistable vars the step reads or writes
    whose leading dim is the slot dim (max_slots or -1). Lets pplint
    build a decode context for a saved step program without an engine."""
    from ..core.lowering import analyze_state
    rw, ro, out = analyze_state(program, [], tuple(fetch_names or ()))
    slot = set()
    for name in set(rw) | set(ro) | set(out):
        v = program.global_block().vars.get(name)
        if v is None or not v.persistable:
            continue
        shape = tuple(getattr(v, "shape", ()) or ())
        if shape and shape[0] in (-1, max_slots):
            slot.add(name)
    return slot
