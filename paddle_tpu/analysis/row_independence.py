"""Row-independence: batch-dim dataflow taint over the Program.

The Batcher's whole contract (PR-3) — and DecodeBatcher's slot variant
(PR-16) — is that at a fixed compiled shape, row i of every row-sliced
fetch depends only on row i of the inputs, so requests coalesced into
one device batch cannot observe each other. Until now that was checked
empirically (load-time identity probes). This pass proves it on the
graph with a three-point taint lattice per var name:

    CONST < ROW < MIXED

  CONST  row-constant: params, scope state, fill_constant results —
         identical for every row, so sharing it across rows is safe
  ROW    row-aligned: leading dim is the batch/slot dim and row i is a
         function of row i of the sources only
  MIXED  cross-row-dependent: some row reflects another request's data

Feeds (or the decode slot vars) start ROW; everything else starts
CONST. The default transfer is join (max) over an op's inputs — correct
for every elementwise/rowwise op. A table of explicit rules covers the
ops that genuinely move data across the batch dim: reductions over dim
0, train-mode batch_norm, axis-0 concat/split/stack, batch transposes
and reshapes, cross-row gathers/scatters, and the lod machinery
(beam search, rank-table reordering) whose whole purpose is cross-row
traffic. Sub-blocks are walked inline at their owner's position and the
whole walk iterates to a fixpoint (the lattice is finite and transfers
monotone, so <=3 sweeps).

Every fetch gets a certificate {status: row|const|mixed, cause} on
`AnalysisResult.certificates`; a MIXED row-sliced fetch is an ERROR
naming the mixing op AND the poisoned fetch, a MIXED whole/dynamic
fetch a WARNING. The engine records the certificate and the Batcher
consumes it: an uncertified engine (validate=False on a mixing program)
stops coalescing rows from different requests into one device batch.
"""
from ..core.framework import GRAD_SUFFIX
from ..core.readers import is_host_io_op
from .deployment import (DeploymentPass, register_deployment_pass)

CONST, ROW, MIXED = 0, 1, 2
_STATUS = {CONST: "const", ROW: "row", MIXED: "mixed"}

# ops whose entire job is cross-row traffic: any ROW input poisons
_CROSS_ROW_OPS = frozenset({
    "beam_search", "beam_search_decode", "lod_rank_table",
    "reorder_lod_tensor_by_rank", "shrink_rnn_memory",
    "split_lod_tensor", "merge_lod_tensor", "scatter",
    "sequence_expand", "sequence_reshape", "im2sequence",
})

# ops whose output depends only on input SHAPE (fixed per compiled
# bucket), never on row values
_SHAPE_ONLY_OPS = frozenset({
    "shape", "fill_constant_batch_size_like", "fill_zeros_like",
    "uniform_random_batch_size_like", "gaussian_random_batch_size_like",
})

_REDUCE_OPS = frozenset({"reduce_sum", "reduce_mean", "reduce_max",
                         "reduce_min", "reduce_prod", "reduce_all",
                         "reduce_any"})

# ops with a single `axis` attr that mixes rows iff it names dim 0
# (NOT elementwise_*: their `axis` is a broadcast alignment offset)
_AXIS_OPS = frozenset({"cumsum", "arg_max", "arg_min", "l2_normalize",
                       "norm", "log_softmax"})

_MATMUL_OPS = frozenset({"mul", "matmul"})


@register_deployment_pass
class RowIndependencePass(DeploymentPass):
    name = "row-independence"

    @classmethod
    def applicable(cls, deploy):
        return deploy.kind in ("serving", "decode") and (
            deploy.row_fetches or deploy.whole_fetches)

    def run(self, ctx):
        self.ctx = ctx
        deploy = ctx.deploy
        sources = deploy.row_sources
        if sources is None:
            sources = ctx.feed_names
        # name -> (level, cause); cause = (block, op_idx, op, reason) for
        # the op that first raised the name to MIXED
        self.states = {n: (ROW, None) for n in sources}
        for _ in range(3):  # fixpoint over backward-carried loop state
            before = dict(self.states)
            self._walk(ctx.program.global_block())
            if self.states == before:
                break
        self._certify()

    # ---- lattice plumbing --------------------------------------------
    def _level(self, name):
        return self.states.get(name, (CONST, None))

    def _raise_to(self, name, level, cause):
        cur, cur_cause = self._level(name)
        if level > cur:
            self.states[name] = (level, cause if level == MIXED else None)
        elif level == cur == MIXED and cur_cause is None:
            self.states[name] = (level, cause)

    def _join_inputs(self, op, skip_slots=()):
        level, cause = CONST, None
        for slot, names in op.inputs.items():
            if slot in skip_slots:
                continue
            for n in names:
                if not n:
                    continue
                lv, cs = self._level(n)
                if lv > level:
                    level, cause = lv, cs
        return level, cause

    # ---- walk ---------------------------------------------------------
    def _walk(self, block):
        ctx = self.ctx
        for op_idx, op in enumerate(block.ops):
            if is_host_io_op(op.type):
                for ns in op.outputs.values():
                    for n in ns:
                        if n:
                            self._raise_to(n, ROW, None)
                continue
            for sub in ctx.sub_blocks(op):
                self._walk(sub)
            level, cause = self._transfer(block, op_idx, op)
            for ns in op.outputs.values():
                for n in ns:
                    if n:
                        self._raise_to(n, level, cause)

    def _shape_of(self, block, name):
        v = self.ctx.lookup(block, name)
        return tuple(getattr(v, "shape", ()) or ()) if v is not None else ()

    def _first(self, op, slot):
        names = op.inputs.get(slot) or ()
        return names[0] if names else None

    def _transfer(self, block, op_idx, op):
        """-> (level, cause) of the op's outputs."""
        t = op.type
        join, join_cause = self._join_inputs(op)

        def mixed(reason):
            return MIXED, (block, op_idx, op, reason)

        if t in _SHAPE_ONLY_OPS:
            return CONST, None
        if join == CONST:
            return CONST, None  # no row data flows in at all
        if join == MIXED:
            return MIXED, join_cause
        # join == ROW from here: does THIS op mix rows?
        if t in _CROSS_ROW_OPS:
            return mixed("%s moves data across the batch dim by design"
                         % t)
        if t in _REDUCE_OPS:
            if self._reduces_dim0(block, op):
                return mixed("reduction over dim 0 folds all rows "
                             "together")
            return ROW, None
        if t == "mean":
            return mixed("mean reduces over every dim including the "
                         "batch dim")
        if t == "batch_norm" and not op.attrs.get("is_test", False):
            return mixed("train-mode batch_norm normalizes with "
                         "statistics computed ACROSS the batch")
        if t in ("concat", "stack") and op.attrs.get("axis", 0) == 0:
            return mixed("%s along axis 0 splices rows from different "
                         "inputs" % t)
        if t in ("split", "unstack") and op.attrs.get("axis", 0) == 0:
            return mixed("%s along axis 0 redistributes rows across "
                         "outputs" % t)
        if t in ("transpose", "transpose2"):
            perm = op.attrs.get("axis") or ()
            if tuple(perm[:1]) not in ((), (0,)):
                return mixed("transpose moves the batch dim off axis 0")
        if t in ("reshape", "reshape2"):
            if not self._reshape_keeps_rows(block, op):
                return mixed("reshape regroups the batch dim")
        if t in ("squeeze", "unsqueeze"):
            if 0 in (op.attrs.get("axes") or ()):
                return mixed("%s touches axis 0 (the batch dim)" % t)
        if t == "flatten" and op.attrs.get("axis", 1) == 0:
            return mixed("flatten(axis=0) folds the batch dim into the "
                         "feature dim")
        if t in _AXIS_OPS:
            if self._axis_is_dim0(block, op):
                return mixed("%s over axis 0 couples rows" % t)
        if t == "expand":
            times = op.attrs.get("expand_times") or ()
            if times and times[0] != 1:
                return mixed("expand tiles the batch dim")
        if t == "pad":
            pads = op.attrs.get("paddings") or ()
            if tuple(pads[:2]) not in ((), (0, 0)):
                return mixed("pad shifts rows along the batch dim")
        if t in ("gather", "lookup_table"):
            table = self._first(op, "X" if t == "gather" else "W")
            if table is not None and self._level(table)[0] >= ROW:
                return mixed("%s indexes into row-dependent data — row i "
                             "of the result can read another request's "
                             "row" % t)
            return ROW, None  # CONST table + ROW index: per-row lookup
        if t in _MATMUL_OPS:
            return self._matmul(block, op_idx, op)
        # default: elementwise / rowwise (activations, cast, softmax over
        # the feature axis, sequence ops on the batch-major layout, ...)
        return ROW, None

    def _reduces_dim0(self, block, op):
        if op.attrs.get("reduce_all", False):
            return True
        dims = op.attrs.get("dim", 0)
        if not isinstance(dims, (list, tuple)):
            dims = [dims]
        x = self._first(op, "X")
        rank = len(self._shape_of(block, x)) if x else 0
        return any((d + rank if (d < 0 and rank) else d) == 0
                   for d in dims)

    def _axis_is_dim0(self, block, op):
        axis = op.attrs.get("axis", -1)
        x = self._first(op, "X")
        rank = len(self._shape_of(block, x)) if x else 0
        if axis < 0:
            if not rank:
                return False
            axis += rank
        return axis == 0

    def _reshape_keeps_rows(self, block, op):
        shape = tuple(op.attrs.get("shape") or ())
        if not shape:
            return False
        if shape[0] in (0, -1):
            return True  # leading dim copied / inferred: rows intact
        x = self._first(op, "X")
        in_shape = self._shape_of(block, x) if x else ()
        # concrete-but-equal leading dim (the decode slot case: slot
        # programs reshape [slots] -> [slots, 1] with slots literal)
        return bool(in_shape) and in_shape[0] == shape[0]

    def _matmul(self, block, op_idx, op):
        xl = max([self._level(n)[0] for n in op.inputs.get("X", ()) if n]
                 or [CONST])
        yl = max([self._level(n)[0] for n in op.inputs.get("Y", ()) if n]
                 or [CONST])
        if MIXED in (xl, yl):
            lv, cs = self._join_inputs(op)
            return lv, cs
        if CONST in (xl, yl):
            return max(xl, yl), None  # data x const weights: rowwise
        # both operands row-tainted: only a BATCHED matmul (both rank>=3,
        # contraction inside each row) keeps rows independent
        xr = len(self._shape_of(block, self._first(op, "X")))
        yr = len(self._shape_of(block, self._first(op, "Y")))
        if xr >= 3 and yr >= 3:
            return ROW, None
        return MIXED, (block, op_idx, op,
                       "%s contracts two row-dependent operands over the "
                       "batch dim" % op.type)

    # ---- certificates -------------------------------------------------
    def _certify(self):
        ctx = self.ctx
        deploy = ctx.deploy
        certs = ctx.result.certificates
        gb = ctx.program.global_block()
        for fetch in list(deploy.row_fetches) + list(deploy.whole_fetches):
            level, cause = self._level(fetch)
            cert = {"status": _STATUS[level], "cause": None}
            if level == MIXED:
                row_sliced = fetch in deploy.row_fetches
                if cause is not None:
                    cblock, cop_idx, cop, reason = cause
                else:
                    cblock, cop_idx, cop, reason = gb, None, None, \
                        "cross-row dataflow"
                cert["cause"] = "%s (block %d op %s)" % (
                    reason, cblock.idx,
                    cop_idx if cop_idx is not None else "?")
                report = ctx.error if row_sliced else ctx.warning
                report(
                    "cross-row-mix",
                    "fetch %r is cross-row-dependent: %s — %s"
                    % (fetch, reason,
                       "coalesced requests could observe each other's "
                       "rows, so the batcher contract CANNOT hold"
                       if row_sliced else
                       "it is returned whole to every request, which is "
                       "only safe if callers expect a batch-level value"),
                    block=cblock, op_idx=cop_idx, op=cop,
                    var_names=(fetch,),
                    hint="make the fetch rowwise (reduce over feature "
                         "dims only, is_test batch_norm), or serve it "
                         "with batching disabled")
            certs[fetch] = cert
