"""Whole-program abstract shape/dtype verification.

Re-derives every registered op's output shapes/dtypes with
`registry.abstract_eval` — the same dual-sentinel jax.eval_shape
machinery `append_op` uses at build time, factored read-only — and
compares them against the DECLARED Variable shapes/dtypes. On a program
built through the layers API the two always agree (the declarations came
from this machinery); a conflict means the program was hand-edited,
deserialized from a corrupted/incompatible desc, or a transform broke an
invariant — exactly the class of bug that otherwise surfaces as an
opaque XLA shape error deep inside `Executor.run`.

Reports the FIRST inconsistent op and stops: one bad declaration poisons
every shape downstream, so later findings would be cascades, not causes.
Comparisons are conservative (only both-static dims conflict; -1 against
anything passes) — zero false positives is the contract that lets
FLAGS_validate_program=1 run across the whole test suite.
"""
from ..core import registry
from .pass_base import AnalysisPass, register_pass


def _canonical(dtype_name):
    """Declared dtype as the backend will actually materialize it: without
    jax_enable_x64, 64-bit declarations truncate to 32-bit (int64->int32,
    float64->float32) — the lowering rules produce the truncated dtype, so
    comparing against the raw declaration would flag every int64
    fill_constant in a default-config program."""
    import jax.dtypes
    import numpy as np
    return np.dtype(jax.dtypes.canonicalize_dtype(
        np.dtype(dtype_name))).name


@register_pass
class ShapeInferencePass(AnalysisPass):
    name = "shape-infer"

    def run(self, ctx):
        for block in ctx.program.blocks:
            for op_idx, op in enumerate(block.ops):
                if op.type == "grad_of":
                    continue  # derived via vjp; fwd op already checked
                res = registry.abstract_eval(block, op)
                if res is None:
                    continue  # unregistered/special/custom-infer/bailed
                if self._check_op(ctx, block, op_idx, op, res):
                    return  # first inconsistent op only

    def _check_op(self, ctx, block, op_idx, op, res):
        for slot, entries in res.items():
            names = op.outputs.get(slot, [])
            for name, entry in zip(names, entries):
                if not name or entry is None:
                    continue
                var = ctx.lookup(block, name)
                if var is None:
                    continue
                inferred_shape, _, inferred_dtype = entry
                if var.dtype is not None and \
                        _canonical(var.dtype) != inferred_dtype:
                    ctx.error(
                        "dtype-mismatch",
                        "output %r (slot %s) is declared %s but the "
                        "lowering rule produces %s"
                        % (name, slot, var.dtype, inferred_dtype),
                        block=block, op_idx=op_idx, op=op,
                        var_names=(name,),
                        hint="fix the declared dtype or cast the inputs")
                    return True
                declared = var.shape
                if declared is None:
                    continue
                if len(declared) != len(inferred_shape):
                    ctx.error(
                        "shape-mismatch",
                        "output %r (slot %s) is declared rank %d %r but "
                        "the lowering rule produces rank %d %r"
                        % (name, slot, len(declared), tuple(declared),
                           len(inferred_shape), inferred_shape),
                        block=block, op_idx=op_idx, op=op,
                        var_names=(name,),
                        hint="fix the declared shape (or the op attrs "
                             "that drive it)")
                    return True
                for d, i in zip(declared, inferred_shape):
                    if d >= 0 and i >= 0 and d != i:
                        ctx.error(
                            "shape-mismatch",
                            "output %r (slot %s) is declared %r but the "
                            "lowering rule produces %r"
                            % (name, slot, tuple(declared),
                               inferred_shape),
                            block=block, op_idx=op_idx, op=op,
                            var_names=(name,),
                            hint="fix the declared shape (or the op "
                                 "attrs that drive it)")
                        return True
        return False
