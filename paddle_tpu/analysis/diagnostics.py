"""Structured findings of the static program verifier.

Each analysis pass reports `Diagnostic`s into an `AnalysisResult`; the
Executor's strict mode raises `ProgramVerificationError` carrying the
error-severity subset. Severity contract:

  ERROR   — the program WILL fail (or silently compute garbage) when
            lowered/executed as analyzed: use-before-def, unregistered
            op, declared-vs-inferred shape conflict, carrier hazards.
            Strict mode (`Executor.run(validate=True)` /
            FLAGS_validate_program) raises on these.
  WARNING — legal but suspicious: dead ops, unused vars, dead writes,
            reader creation riding in a compute program. Reported by
            `tools/pplint.py` (non-fatal unless --strict) and available
            programmatically; strict mode does not raise on them.
"""

ERROR = "error"
WARNING = "warning"


class Diagnostic(object):
    """One finding: severity, a stable kebab-case code, where (block/op),
    which vars, a fix hint, and the offending op's Python creation stack
    (Operator.callstack) when available."""

    __slots__ = ("severity", "code", "message", "block_idx", "op_idx",
                 "op_type", "var_names", "hint", "callstack")

    def __init__(self, severity, code, message, block_idx=None, op_idx=None,
                 op_type=None, var_names=(), hint=None, callstack=()):
        self.severity = severity
        self.code = code
        self.message = message
        self.block_idx = block_idx
        self.op_idx = op_idx
        self.op_type = op_type
        self.var_names = tuple(var_names)
        self.hint = hint
        self.callstack = tuple(callstack or ())

    def location(self):
        parts = []
        if self.block_idx is not None:
            parts.append("block %d" % self.block_idx)
        if self.op_idx is not None:
            parts.append("op %d" % self.op_idx)
        if self.op_type:
            parts.append("(%s)" % self.op_type)
        return " ".join(parts)

    def format(self, with_callstack=True):
        loc = self.location()
        lines = ["%s[%s]%s %s" % (self.severity, self.code,
                                  " " + loc + ":" if loc else ":",
                                  self.message)]
        if self.hint:
            lines.append("    fix: %s" % self.hint)
        if with_callstack and self.callstack:
            from ..core.utils import format_callstack
            lines.append("    created at:")
            lines.append(format_callstack(self.callstack, prefix="      "))
        return "\n".join(lines)

    def __repr__(self):
        return "Diagnostic(%s, %s, %r)" % (self.severity, self.code,
                                           self.message)


class AnalysisResult(object):
    """Ordered collection of diagnostics from one analyzer run.

    `certificates` is the deployment tier's per-fetch row-independence
    verdict: {fetch: {"status": "row"|"const"|"mixed", "cause": str}}.
    Empty unless the row-independence pass ran. "row"/"const" is the
    proof the Batcher's coalescing relies on; consumers (engine,
    pplint --json) treat a missing entry as unproven, not safe."""

    def __init__(self, diagnostics=None):
        self.diagnostics = list(diagnostics or [])
        self.certificates = {}

    def add(self, diag):
        self.diagnostics.append(diag)

    def extend(self, diags):
        self.diagnostics.extend(diags)

    @property
    def errors(self):
        return [d for d in self.diagnostics if d.severity == ERROR]

    @property
    def warnings(self):
        return [d for d in self.diagnostics if d.severity == WARNING]

    @property
    def ok(self):
        return not self.errors

    def by_code(self, code):
        return [d for d in self.diagnostics if d.code == code]

    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self):
        return len(self.diagnostics)

    def format(self, with_callstack=True):
        lines = [d.format(with_callstack=with_callstack)
                 for d in self.diagnostics]
        lines.append("%d error(s), %d warning(s)"
                     % (len(self.errors), len(self.warnings)))
        return "\n".join(lines)

    def raise_if_errors(self):
        errs = self.errors
        if errs:
            raise ProgramVerificationError(errs)
        return self


class ProgramVerificationError(RuntimeError):
    """Raised by strict validation when the analyzer finds errors.
    Subclasses RuntimeError so existing broad except clauses keep
    working; `.diagnostics` carries the structured findings."""

    def __init__(self, diagnostics):
        self.diagnostics = list(diagnostics)
        msg = "program verification failed with %d error(s):\n%s" % (
            len(self.diagnostics),
            "\n".join(d.format() for d in self.diagnostics))
        super(ProgramVerificationError, self).__init__(msg)
