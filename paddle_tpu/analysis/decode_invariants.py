"""Decode-invariants: the slot-resident step contract, proven on the IR.

DecodeEngine (PR-16) keeps every stream's state in persistable slot
vars with leading dim max_slots and runs ONE step program for all
slots; between steps it writes admitted rows in place with a donated
slot update. That only works if the step program treats slot state the
way the engine assumes:

  slot-double-write  a slot var written more than once per step: the
                     engine snapshots state_out ONCE after the step, so
                     the first write is at best dead and at worst races
                     the donated update ordering
  slot-shape         a slot var whose leading dim is not the slot dim
                     (max_slots or -1) or with non-static feature dims:
                     the step recompiles per occupancy, or the slot
                     update indexes garbage
  slot-fetch-alias   a fetch that IS an updated slot var: the fetched
                     value aliases a buffer build_slot_update_fn donates,
                     so the caller's array is invalidated by the next
                     admit — the engine must fetch step OUTPUTS (token,
                     finished), never carried state

DecodeEngine enforces some of this dynamically at load; this pass makes
the same contract checkable for a SAVED step program (pplint --deploy
decode) and turns the engine's load failures into named diagnostics.
"""
from .deployment import DeploymentPass, register_deployment_pass


@register_deployment_pass
class DecodeInvariantsPass(DeploymentPass):
    name = "decode-invariants"

    @classmethod
    def applicable(cls, deploy):
        return deploy.kind == "decode" and bool(deploy.slot_vars)

    def run(self, ctx):
        deploy = ctx.deploy
        slot = deploy.slot_vars
        gb = ctx.program.global_block()
        writes = {}
        for block in ctx.program.blocks:
            for op_idx, op in enumerate(block.ops):
                for n in op.all_output_vars():
                    if n in slot:
                        writes.setdefault(n, []).append(
                            (block, op_idx, op))

        for name in sorted(slot):
            self._check_shape(ctx, gb, name, deploy.max_slots)
            ws = writes.get(name, ())
            if len(ws) > 1:
                block, op_idx, op = ws[-1]
                first = ws[0]
                ctx.error(
                    "slot-double-write",
                    "slot var %r is written %d times in one step (ops %s"
                    ") — the engine snapshots carried state once per "
                    "step, so every write but the last is unobservable "
                    "and the donated slot update's ordering is undefined"
                    % (name, len(ws),
                       ", ".join("%d (%s)" % (w[1], w[2].type)
                                 for w in ws)),
                    block=block, op_idx=op_idx, op=op, var_names=(name,),
                    hint="fold the updates into one assign per step "
                         "(first write at op %d (%s))"
                         % (first[1], first[2].type))

        written = frozenset(writes)
        for fetch in ctx.fetch_names:
            if fetch in written:
                block, op_idx, op = writes[fetch][-1]
                ctx.error(
                    "slot-fetch-alias",
                    "fetch %r is an updated slot var: its value aliases "
                    "a buffer the donated slot update invalidates on the "
                    "next admit — the caller would read freed memory "
                    "semantics" % fetch,
                    block=block, op_idx=op_idx, op=op,
                    var_names=(fetch,),
                    hint="fetch a step OUTPUT (assign the slot var to a "
                         "fresh non-persistable fetch var) instead of "
                         "the carried state itself")

    def _check_shape(self, ctx, gb, name, max_slots):
        var = ctx.lookup(gb, name)
        if var is None:
            ctx.error(
                "slot-shape",
                "slot var %r is not declared in the step program" % name,
                var_names=(name,))
            return
        shape = tuple(getattr(var, "shape", ()) or ())
        bad_lead = (not shape or
                    (max_slots is not None and
                     shape[0] not in (-1, max_slots)))
        if not var.persistable:
            ctx.error(
                "slot-shape",
                "slot var %r is not persistable — it cannot carry state "
                "across steps, every step would read zeros" % name,
                var_names=(name,),
                hint="create it with create_global_var(persistable=True)")
        if bad_lead:
            ctx.error(
                "slot-shape",
                "slot var %r has shape %r; its leading dim must be the "
                "slot dim (%r) so every stream owns row i" % (
                    name, shape, max_slots),
                var_names=(name,))
        if any(d < 0 for d in shape[1:]):
            ctx.error(
                "slot-shape",
                "slot var %r has non-static feature dims %r — the step "
                "would recompile per occupancy and the slot update "
                "cannot index a stable row" % (name, shape),
                var_names=(name,),
                hint="pad feature dims to compile-time constants")
