"""Sharding-consistency: prove a ShardingPlan coheres with the program.

PR-9/11 made the distribution plan explicit (VarPlan per persistable,
grad reduce-scatter constraints, tp gather placement) but only the
executor's device_put would notice a plan that no longer matches the
program it was built for — at run time, per var, as an opaque shape
error or (worse) a silently replicated footprint. This pass checks the
whole plan against the graph statically:

  plan-var-missing    a PARAM entry names a var the program doesn't
                      declare (stale plan / renamed param). Gradient /
                      accumulator entries for absent vars are inert —
                      build() mirrors sharded params into @GRAD entries
                      so one plan serves train AND serve, and an
                      inference program declares neither — so they are
                      skipped, not flagged
  plan-int8-conflict  an entry targets a param the int8 rewrite has
                      demoted — the plan would shard a var the scope no
                      longer holds while X@QVAL/X@QSCALE ride unplanned
  plan-shape-mismatch entry's recorded shape differs from the var, the
                      spec outranks the var, or a sharded dim is not
                      divisible by its mesh-axis product
  plan-dtype-mismatch entry's recorded dtype differs from the var
  plan-grad-coverage  a sharded param whose @GRAD the program writes has
                      no GRADIENT entry — the reduce-scatter constraint
                      would silently degrade to all-reduce + slice
  plan-replicated     (WARNING) update sharding is on, the param is big
                      enough to shard, but its dim 0 doesn't divide the
                      shard axis — it silently replicates; the plan's
                      recorded reason is surfaced

Works on a real ShardingPlan or a deployment.PlanView (saved plan JSON
linted on a machine without the mesh).
"""
from ..core.framework import GRAD_SUFFIX
from .deployment import (DeploymentPass, plan_axis_sizes,
                         register_deployment_pass)
from .shape_infer import _canonical

_QVAL = "@QVAL"


def _spec_axes(spec):
    """Per-dim tuples of mesh-axis names ((),) for None dims."""
    out = []
    for ent in tuple(spec or ()):
        if isinstance(ent, (list, tuple)):
            out.append(tuple(ent))
        else:
            out.append(() if ent is None else (ent,))
    return out


@register_deployment_pass
class ShardingConsistencyPass(DeploymentPass):
    name = "sharding-consistency"

    @classmethod
    def applicable(cls, deploy):
        return deploy.plan is not None

    def run(self, ctx):
        plan = ctx.deploy.plan
        axis_sizes = plan_axis_sizes(plan)
        gb = ctx.program.global_block()
        written = set()
        for block in ctx.program.blocks:
            for op in block.ops:
                written.update(n for n in op.all_output_vars() if n)
        entries = plan.entries

        for name in sorted(entries):
            e = entries[name]
            var = ctx.lookup(gb, name)
            demoted = ctx.lookup(gb, name + _QVAL) is not None
            if var is None and e.kind != "param" and not demoted:
                # inert entry: build() mirrors sharded params into @GRAD
                # GRADIENT entries (and owners into accumulators) so ONE
                # plan serves train and serve — an inference program
                # declares none of them, and an entry for an absent var
                # is never consulted at lowering. Only a missing PARAM
                # means the plan no longer matches the program.
                continue
            if var is None or (demoted and not var.persistable):
                if demoted:
                    ctx.error(
                        "plan-int8-conflict",
                        "plan entry %r (%s) targets a param the int8 "
                        "rewrite demoted: the scope holds %r/%r now, and "
                        "sharding the dequantized intermediate is not "
                        "what this entry means" % (
                            name, e.kind, name + _QVAL, name + "@QSCALE"),
                        var_names=(name, name + _QVAL),
                        hint="rebuild the plan from the REWRITTEN "
                             "program, or serve this model with "
                             "weights_dtype != int8 under this plan")
                else:
                    ctx.error(
                        "plan-var-missing",
                        "plan entry %r (%s) names a variable the program "
                        "does not declare — the plan is stale or built "
                        "for a different program" % (name, e.kind),
                        var_names=(name,),
                        hint="rebuild the plan (ShardingPlan.build) "
                             "against this program")
                continue
            self._check_entry(ctx, e, var, axis_sizes)

        self._check_grad_coverage(ctx, plan, entries, written)
        self._warn_silent_replication(ctx, plan, entries, gb, axis_sizes)

    def _check_entry(self, ctx, e, var, axis_sizes):
        shape = tuple(getattr(var, "shape", ()) or ())
        if e.shape is not None and tuple(e.shape) != shape:
            ctx.error(
                "plan-shape-mismatch",
                "plan entry %r was built for shape %r but the program "
                "declares %r" % (e.name, tuple(e.shape), shape),
                var_names=(e.name,),
                hint="rebuild the plan against this program")
            return
        if e.dtype is not None and shape is not None:
            try:
                planned, actual = _canonical(e.dtype), _canonical(var.dtype)
            except Exception:  # noqa: BLE001 — unknown dtype string
                planned = actual = None
            if planned is not None and planned != actual:
                ctx.error(
                    "plan-dtype-mismatch",
                    "plan entry %r was built for dtype %s but the "
                    "program declares %s" % (e.name, e.dtype, var.dtype),
                    var_names=(e.name,),
                    hint="rebuild the plan against this program")
        per_dim = _spec_axes(e.spec)
        if len(per_dim) > len(shape):
            ctx.error(
                "plan-shape-mismatch",
                "plan entry %r has a rank-%d spec %r for a rank-%d "
                "variable" % (e.name, len(per_dim), tuple(e.spec),
                              len(shape)),
                var_names=(e.name,),
                hint="trim the spec or rebuild the plan")
            return
        for d, axes in enumerate(per_dim):
            factor = 1
            for a in axes:
                factor *= int(axis_sizes.get(a, 1))
            if factor > 1 and shape[d] >= 0 and shape[d] % factor:
                ctx.error(
                    "plan-shape-mismatch",
                    "plan entry %r shards dim %d (size %d) %d-ways over "
                    "%r — not divisible, GSPMD would reject or pad this "
                    "at lowering" % (e.name, d, shape[d], factor,
                                     tuple(axes)),
                    var_names=(e.name,),
                    hint="pad the dim, shard a different dim, or drop "
                         "the constraint")

    def _tp_gather_exempt(self, plan, e):
        """Gather-placed TP params keep their grads un-constrained by
        contract (ShardingPlan.grad_constraints docstring)."""
        tp_axis = getattr(plan, "tp_axis", None)
        if not tp_axis or getattr(plan, "tp_placement", None) != "gather":
            return False
        return any(tp_axis in axes for axes in _spec_axes(e.spec))

    def _check_grad_coverage(self, ctx, plan, entries, written):
        for name in sorted(entries):
            e = entries[name]
            if e.kind != "param" or not e.sharded:
                continue
            grad = name + GRAD_SUFFIX
            if grad not in written or grad in entries:
                continue
            if self._tp_gather_exempt(plan, e):
                continue
            ctx.error(
                "plan-grad-coverage",
                "param %r is sharded %r but its gradient %r (which this "
                "program writes) has no plan entry: without the "
                "reduce-scatter constraint the gradient sum lowers as a "
                "full all-reduce plus slice, and the update reads an "
                "unconstrained layout" % (name, tuple(e.spec), grad),
                var_names=(name, grad),
                hint="rebuild the plan (build() mirrors every sharded "
                     "param into a GRADIENT entry) or add the entry")

    def _warn_silent_replication(self, ctx, plan, entries, gb, axis_sizes):
        shard_axis = getattr(plan, "shard_axis", None)
        n_shard = int(axis_sizes.get(shard_axis, 1)) if shard_axis else 1
        if n_shard <= 1:
            return
        for name in sorted(entries):
            e = entries[name]
            if e.kind != "param" or e.sharded or e.override:
                continue
            var = ctx.lookup(gb, name)
            shape = tuple(getattr(var, "shape", ()) or ()) if var else ()
            if not shape or shape[0] < 0:
                continue
            numel = 1
            for d in shape:
                numel *= max(int(d), 1)
            if numel < n_shard or shape[0] % n_shard == 0:
                continue  # too small to matter / divisible, so by policy
            ctx.warning(
                "plan-replicated",
                "param %r (shape %r, %d elements) replicates on every "
                "chip under this plan%s — dim 0 does not divide the "
                "%d-way shard axis %r" % (
                    name, shape, numel,
                    ": %s" % e.reason if e.reason else "",
                    n_shard, shard_axis),
                var_names=(name,),
                hint="pad dim 0 to a multiple of %d, or pin a spec via "
                     "ParamAttr(mesh_axes=...) / param_shardings if the "
                     "replication is intended" % n_shard)
