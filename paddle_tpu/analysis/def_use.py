"""Def-use analysis over the Program in EXECUTION order.

Walks the global block with sub-blocks inlined at their owning op's
position — the order `lower_block` traces them — tracking which names
hold a value:

  * use-before-def (error): a read the trace would fail with
    `Env.read` KeyError, including reads of names declared in no
    reachable block (invalid cross-block captures into while/cond
    sub-blocks) and uninitialized While loop carries;
  * carrier hazard (error): a persistable var read before its first
    write that `lowering.analyze_state` classifies WRITE-ONLY — the
    multi-step scan would seed its loop carry with zeros instead of the
    scope value (the donation/aliasing trap), and a single-step run
    fails with read-before-write;
  * dead write (warning): write-after-write on the same name with no
    intervening read — the first write can never be observed;
  * dead ops / unused vars (warning): ops whose outputs nothing consumes
    and declared vars no op touches.

Env semantics being FLAT (name -> value across all blocks) is what makes
this a plain set-tracking walk; sub-blocks of while/conditional_block
read a snapshot of the enclosing env, so the strict ordering rules apply
inside them too. Sub-blocks of other graph-level ops (rnn_scan,
beam_search, listen_and_serv) bind step placeholders internally, so only
existence — not ordering — is checked there.
"""
from ..core.framework import GRAD_SUFFIX
from ..core.readers import is_host_io_op
from .pass_base import (AnalysisPass, register_pass, attr_referenced_names)

# sub-block owners whose bodies read a straight copy of the enclosing env
# (strict ordering holds); every other owner gets the lenient walk
_STRICT_SUB_OWNERS = frozenset({"while", "conditional_block"})

# ops kept even when nothing consumes their outputs
_EFFECTFUL_OPS = frozenset({"send", "recv", "listen_and_serv"})


@register_pass
class DefUsePass(AnalysisPass):
    name = "def-use"

    def run(self, ctx):
        self.ctx = ctx
        program = ctx.program
        defined = set(ctx.feed_names)
        # in-graph reader outputs are injected as feeds by the io
        # pre-pass BEFORE the program body runs, regardless of where the
        # read op sits in op order
        for op in program.global_block().ops:
            if op.type == "read":
                defined.update(n for ns in op.outputs.values()
                               for n in ns if n)
        self._pending_stack = []  # per-frame {name: (op_idx, op)} writes
        self._walk(program.global_block(), defined, strict=True)
        self._dead_and_unused()

    # ---- execution-order walk ---------------------------------------
    def _walk(self, block, defined, strict):
        ctx = self.ctx
        pending = {}
        self._pending_stack.append(pending)
        try:
            for op_idx, op in enumerate(block.ops):
                if is_host_io_op(op.type):
                    # host-side: reads host ReaderState (checked by the
                    # reader-placement pass), outputs become feeds
                    for ns in op.outputs.values():
                        defined.update(n for n in ns if n)
                    continue
                if op.type == "while":
                    self._check_while_carries(block, op_idx, op, defined)
                for slot, names in op.inputs.items():
                    if op.type == "conditional_block" and slot == "OutPrev":
                        continue  # read_opt: zeros when undefined
                    for name in names:
                        self._check_read(block, op_idx, op, name, defined,
                                         strict)
                for sub in ctx.sub_blocks(op):
                    self._walk(sub, set(defined),
                               strict=strict and
                               op.type in _STRICT_SUB_OWNERS)
                for names in op.outputs.values():
                    for name in names:
                        if name:
                            self._note_write(block, op_idx, op, name,
                                             pending)
                            defined.add(name)
                # values the sub-block lowering writes back at top level
                for key in ("carry_names", "out_names"):
                    val = op.attrs.get(key)
                    if isinstance(val, (list, tuple)):
                        defined.update(n for n in val
                                       if isinstance(n, str) and n)
        finally:
            self._pending_stack.pop()

    def _note_read(self, name):
        for frame in self._pending_stack:
            frame.pop(name, None)

    def _note_write(self, block, op_idx, op, name, pending):
        ctx = self.ctx
        prev = pending.get(name)
        accumulates = (op.type == "grad_of"
                       or op.attrs.get("__accumulate_outputs__", False))
        if prev is not None and not accumulates \
                and not name.endswith(GRAD_SUFFIX) \
                and not ctx.sub_blocks(op):
            prev_idx, prev_op = prev
            ctx.warning(
                "dead-write",
                "op %d (%s) overwrites %r which op %d (%s) wrote and "
                "nothing read in between — the first write is dead"
                % (op_idx, op.type, name, prev_idx, prev_op.type),
                block=block, op_idx=op_idx, op=op, var_names=(name,),
                hint="drop the earlier op or read its result before "
                     "overwriting")
        sub_or_acc = accumulates or ctx.sub_blocks(op) \
            or name.endswith(GRAD_SUFFIX)
        pending[name] = None if sub_or_acc else (op_idx, op)
        if pending[name] is None:
            pending.pop(name)

    def _check_while_carries(self, block, op_idx, op, defined):
        carries = op.attrs.get("carry_names") or ()
        missing = [n for n in carries
                   if n not in defined and not self._scope_backed(n, block)]
        if missing:
            self.ctx.error(
                "use-before-def",
                "While loop carries %r, but they have no value before "
                "the loop (XLA loop carries need an initial value)"
                % (missing,),
                block=block, op_idx=op_idx, op=op, var_names=missing,
                hint="assign / array_write / fill_constant each carried "
                     "var before `with while_op.block():`")
            # While ops also list carries in their X input slot — mark
            # them defined so the generic read check doesn't report the
            # same defect twice with a worse hint
            defined.update(missing)

    def _scope_backed(self, name, block):
        v = self.ctx.lookup(block, name)
        return (v is not None and v.persistable
                and name in self.ctx.state_in())

    def _check_read(self, block, op_idx, op, name, defined, strict):
        ctx = self.ctx
        if not name:
            return
        self._note_read(name)
        if name in defined:
            return
        var = ctx.lookup(block, name)
        if var is not None and var.persistable:
            if name in ctx.state_in():
                defined.add(name)  # provided by the Scope at run start
                return
            ctx.error(
                "carrier-hazard",
                "persistable variable %r is read before its first write, "
                "but the executor's state analysis classifies it "
                "write-only: a multi-step (steps=K) scan carry would "
                "start from ZEROS instead of the scope value, and a "
                "single-step run fails with read-before-write" % name,
                block=block, op_idx=op_idx, op=op, var_names=(name,),
                hint="initialize the var with an op before this read, or "
                     "reorder so the writing op comes first")
            defined.add(name)  # suppress cascades
            return
        if op.type == "grad_of" and name.endswith(GRAD_SUFFIX):
            return  # out-grad cotangents resolve via read_opt (zeros)
        if not strict:
            if var is None:
                ctx.warning(
                    "undefined-var",
                    "op reads %r which is declared in no reachable block "
                    "and never written" % name,
                    block=block, op_idx=op_idx, op=op, var_names=(name,))
            return
        if var is None:
            inside = " (invalid cross-block capture)" if block.idx != 0 \
                else ""
            ctx.error(
                "use-before-def",
                "op reads %r, which is declared in no block reachable "
                "from block %d and is never written%s"
                % (name, block.idx, inside),
                block=block, op_idx=op_idx, op=op, var_names=(name,),
                hint="declare the variable in this block or an ancestor, "
                     "or fix the name")
        else:
            ctx.error(
                "use-before-def",
                "variable %r is read before any op writes it (and it is "
                "neither fed, produced by a reader, nor persistable "
                "state)" % name,
                block=block, op_idx=op_idx, op=op, var_names=(name,),
                hint="feed it, or move/add the producing op before this "
                     "one")
        defined.add(name)  # suppress cascades

    # ---- whole-program liveness (dead ops, unused vars) --------------
    def _dead_and_unused(self):
        ctx = self.ctx
        program = ctx.program
        used = set()
        written = set()
        for block in program.blocks:
            for op in block.ops:
                used.update(n for n in op.all_input_vars() if n)
                used.update(attr_referenced_names(op))
                written.update(n for n in op.all_output_vars() if n)
        used.update(ctx.fetch_names)
        # a used sequence var pulls its lengths companion along at runtime
        for v in program.list_vars():
            comp = getattr(v, "seq_len_var", None)
            if comp and (v.name in used or v.name in ctx.fetch_names):
                used.add(comp)

        for op_idx, op in enumerate(program.global_block().ops):
            if (op.type in _EFFECTFUL_OPS or is_host_io_op(op.type)
                    or ctx.sub_blocks(op)):
                continue
            outs = [n for ns in op.outputs.values() for n in ns if n]
            if not outs:
                continue  # output-less ops are markers; assume effectful
            live = False
            for n in outs:
                v = ctx.lookup(program.global_block(), n)
                if n in used or (v is not None and v.persistable):
                    live = True
                    break
            if not live:
                ctx.warning(
                    "dead-op",
                    "nothing consumes any output of this op (%s)"
                    % ", ".join(sorted(outs)[:4]),
                    block=program.global_block(), op_idx=op_idx, op=op,
                    var_names=outs,
                    hint="drop it, fetch its result, or prune the program")

        companions = {getattr(v, "seq_len_var", None)
                      for v in program.list_vars()}
        for block in program.blocks:
            for name, v in block.vars.items():
                if (name in used or name in written
                        or name in ctx.feed_names
                        or getattr(v, "is_data", False) or v.persistable
                        or name in companions):
                    continue
                ctx.warning(
                    "unused-var",
                    "variable %r is declared but no op reads or writes it"
                    % name, block=block, var_names=(name,),
                    hint="remove the declaration")
