"""Evaluators: accumulate metric states across mini-batches in-graph.

Parity: python/paddle/fluid/evaluator.py — Evaluator base with
create_state/reset/eval, Accuracy, ChunkEvaluator, EditDistance,
DetectionMAP. States are persistable vars updated by `sums` ops appended
to the main program (so accumulation runs inside the jitted step);
`eval` fetches the states with a tiny side program.

DetectionMAP deviates mechanically: the reference's detection_map op is a
CPU-only accumulator kernel; here the evaluator accumulates fetched
detections host-side and computes 11point/integral AP in numpy (same API:
reset/eval). See metrics.DetectionMAP for the computation.
"""
import numpy as np

from .core.framework import Program, Variable, program_guard
from .core.layer_helper import LayerHelper
from .core import unique_name
from . import layers
from .layers import tensor

__all__ = ["Accuracy", "ChunkEvaluator", "EditDistance", "DetectionMAP"]


def _clone_var_(block, var):
    assert isinstance(var, Variable)
    return block.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                            lod_level=var.lod_level, persistable=True)


class Evaluator(object):
    """Base class: states reset to zero on reset(); metrics computed
    per-batch."""

    def __init__(self, name, **kwargs):
        self.states = []
        self.metrics = []
        self.helper = LayerHelper(name, **kwargs)

    def reset(self, executor, reset_program=None):
        if reset_program is None:
            reset_program = Program()
        with program_guard(main_program=reset_program):
            for var in self.states:
                g_var = _clone_var_(reset_program.current_block(), var)
                layers.fill_constant(shape=g_var.shape, value=0.0,
                                     dtype=g_var.dtype, out=g_var)
        executor.run(reset_program)

    def eval(self, executor, eval_program=None):
        raise NotImplementedError()

    def create_state(self, suffix, dtype, shape):
        from .core.initializer import ConstantInitializer
        state = self.helper.create_or_get_global_variable(
            name=unique_name.generate(".".join([self.helper.name, suffix])),
            persistable=True, dtype=dtype, shape=shape)
        # zero-init in startup too (the reference leaves states undefined
        # until the first reset(); here startup covers the no-reset case)
        self.helper.set_variable_initializer(state, ConstantInitializer(0.0))
        self.states.append(state)
        return state

    def _fetch_states(self, executor, eval_program=None):
        if eval_program is None:
            eval_program = Program()
        block = eval_program.current_block()
        return executor.run(
            eval_program,
            fetch_list=[_clone_var_(block, s) for s in self.states])


class Accuracy(Evaluator):
    """Accumulated top-k accuracy (evaluator.py Accuracy)."""

    def __init__(self, input, label, k=1, **kwargs):
        super(Accuracy, self).__init__("accuracy", **kwargs)
        self.total = self.create_state(dtype="int64", shape=[1],
                                       suffix="total")
        self.correct = self.create_state(dtype="int64", shape=[1],
                                         suffix="correct")
        total = tensor.create_tensor(dtype="int64")
        correct = tensor.create_tensor(dtype="int64")
        acc = layers.accuracy(input=input, label=label, k=k, total=total,
                              correct=correct)
        layers.sums(input=[self.total, total], out=self.total)
        layers.sums(input=[self.correct, correct], out=self.correct)
        self.metrics.append(acc)

    def eval(self, executor, eval_program=None):
        total, correct = self._fetch_states(executor, eval_program)
        total = float(np.ravel(total)[0])
        correct = float(np.ravel(correct)[0])
        return np.array([correct / total if total else 0.0], "float32")


class ChunkEvaluator(Evaluator):
    """Accumulated chunk precision/recall/F1 (evaluator.py ChunkEvaluator)."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None):
        super(ChunkEvaluator, self).__init__("chunk_eval")
        self.num_infer_chunks = self.create_state(
            dtype="int64", shape=[1], suffix="num_infer_chunks")
        self.num_label_chunks = self.create_state(
            dtype="int64", shape=[1], suffix="num_label_chunks")
        self.num_correct_chunks = self.create_state(
            dtype="int64", shape=[1], suffix="num_correct_chunks")
        (precision, recall, f1_score, num_infer_chunks, num_label_chunks,
         num_correct_chunks) = layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types)
        layers.sums(input=[self.num_infer_chunks, num_infer_chunks],
                    out=self.num_infer_chunks)
        layers.sums(input=[self.num_label_chunks, num_label_chunks],
                    out=self.num_label_chunks)
        layers.sums(input=[self.num_correct_chunks, num_correct_chunks],
                    out=self.num_correct_chunks)
        self.metrics.extend([precision, recall, f1_score])

    def eval(self, executor, eval_program=None):
        ni, nl, nc = [float(np.ravel(v)[0]) for v in
                      self._fetch_states(executor, eval_program)]
        precision = nc / ni if ni else 0.0
        recall = nc / nl if nl else 0.0
        f1 = 2 * precision * recall / (precision + recall) if nc else 0.0
        return (np.array([precision], "float32"),
                np.array([recall], "float32"), np.array([f1], "float32"))


class EditDistance(Evaluator):
    """Accumulated average edit distance + instance error rate."""

    def __init__(self, input, label, ignored_tokens=None, **kwargs):
        super(EditDistance, self).__init__("edit_distance", **kwargs)
        self.total_distance = self.create_state(
            dtype="float32", shape=[1], suffix="total_distance")
        self.seq_num = self.create_state(dtype="int64", shape=[1],
                                         suffix="seq_num")
        self.instance_error = self.create_state(
            dtype="int64", shape=[1], suffix="instance_error")
        distances, seq_num = layers.edit_distance(
            input=input, label=label, ignored_tokens=ignored_tokens)
        zero = layers.fill_constant(shape=[1], value=0.0, dtype="float32")
        compare_result = layers.equal(distances, zero)
        compare_result_int = tensor.cast(x=compare_result, dtype="int64")
        seq_right_count = layers.reduce_sum(compare_result_int)
        instance_error_count = layers.elementwise_sub(x=seq_num,
                                                      y=seq_right_count)
        total_distance = layers.reduce_sum(distances)
        layers.sums(input=[self.total_distance, total_distance],
                    out=self.total_distance)
        layers.sums(input=[self.seq_num, seq_num], out=self.seq_num)
        layers.sums(input=[self.instance_error, instance_error_count],
                    out=self.instance_error)
        self.metrics.append(total_distance)
        self.metrics.append(instance_error_count)

    def eval(self, executor, eval_program=None):
        total, seq_num, inst_err = [
            float(np.ravel(v)[0]) for v in
            self._fetch_states(executor, eval_program)]
        avg_distance = total / seq_num if seq_num else 0.0
        inst_err_rate = inst_err / seq_num if seq_num else 0.0
        return (np.array([avg_distance], "float32"),
                np.array([inst_err_rate], "float32"))


class DetectionMAP(object):
    """Mean average precision for detection (evaluator.py DetectionMAP).

    Host-side accumulator: call `update(nmsed_out, nmsed_lens, gt_boxes,
    gt_labels)` with fetched numpy results per batch; `eval()` returns the
    mAP. Computation in metrics.DetectionMAP."""

    def __init__(self, overlap_threshold=0.5, evaluate_difficult=True,
                 ap_version="integral", background_label=None):
        from .metrics import DetectionMAP as _Metric
        self._metric = _Metric(overlap_threshold=overlap_threshold,
                               ap_version=ap_version,
                               evaluate_difficult=evaluate_difficult,
                               background_label=background_label)

    def reset(self, executor=None, reset_program=None):
        self._metric.reset()

    def update(self, nmsed_out, nmsed_lens, gt_boxes, gt_labels,
               gt_difficult=None):
        self._metric.update(nmsed_out, nmsed_lens, gt_boxes, gt_labels,
                            gt_difficult=gt_difficult)

    def eval(self, executor=None, eval_program=None):
        return np.array([self._metric.eval()], "float32")
