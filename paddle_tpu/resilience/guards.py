"""Numerical guards: device-side all-finite gating + host-side divergence.

Device side (`install_numeric_guards`): rewrites a training program so
that every step checks loss / parameter gradients (optionally the updated
params) for NaN/Inf IN-GRAPH and, when anything is non-finite, SKIPS all
of its persistable state updates on device. Mechanics (ops/guard_ops.py):

    [guard_backup p -> p@GUARD_BK ...]   # prepended: pre-step aliases
    ... original forward/backward/update ops ...
    check_finite_guard(loss, grads...) -> __step_all_finite__
    guard_select_all(flag, [p...], [p@GUARD_BK...])   # the gate: ONE
                                                      # lax.cond

The check rides PR-1's sticky in-graph assertion-flag machinery
(`ctx.add_error`): it composes with `steps=K` multi-step scans (flags OR
across steps, each step gates independently — a NaN batch inside a
K-block skips exactly that step's update while the rest proceed) and
costs ONE host fetch (the combined `__any__` scalar the executor already
syncs), not a per-tensor D2H. On a trip the executor raises the typed
`NumericalGuardError` naming every non-finite var; because the update
was gated on device, the scope still holds the last-good state — "skip
batch" recovery is exact, not hopeful. The backups are trace-time
aliases (no copy op): XLA fuses each select into the update expression,
so donation/in-place param updates survive and the measured overhead on
a dispatch-bound model stays well under 10% (bench.py BENCH_RESIL=1).

Host side (`DivergenceDetector`): a running EMA of the loss with a
configurable window; a loss that spikes past `threshold` x EMA (or goes
non-finite at the host) flags divergence — the slow-motion failure the
all-finite check cannot see. The Supervisor feeds it every fetched loss.
"""
import numpy as np

from ..core.executor import NumericalGuardError  # noqa: F401  (re-export)
from ..core.framework import GRAD_SUFFIX
from ..core.readers import is_host_io_op

__all__ = ["install_numeric_guards", "DivergenceDetector",
           "NumericalGuardError", "GUARD_FLAG_VAR", "BACKUP_SUFFIX"]

GUARD_FLAG_VAR = "__step_all_finite__"
BACKUP_SUFFIX = "@GUARD_BK"


def install_numeric_guards(program, loss=None, check_params=False,
                           extra_vars=(), gate_updates=True,
                           granular=True, grad_norm=False):
    """Install device-side numerical guards into `program` (in place).

    Watched vars: `loss` (Variable or name, optional), every parameter
    gradient (`<param>@GRAD`) the block declares, `extra_vars`, and with
    check_params=True the post-update parameters themselves (catches an
    LR spike overflowing the update even when grads were finite).

    gate_updates=True (default) additionally gates EVERY persistable the
    program writes — params, optimizer accumulators, BN statistics, LR
    decay counters — behind the all-finite flag: a tripped step leaves
    the whole scope bit-identical to not having run (reader consumption
    and the seed cursor aside). gate_updates=False is detect-only.

    granular=True (default) checks each var with its own reduction —
    the raise names the exact offender, and the per-var reductions fuse
    into the gradient computations (measured cheaper than the
    alternative). granular=False instead concatenates the watched set
    into ONE reduction with one combined message; it forces the grads
    to materialize for the concat, so use it only when the watched set
    is so large that per-var flag plumbing dominates.

    grad_norm=True additionally emits ONE f32 global L2 norm over the
    watched parameter gradients on the guard stat channel
    (ops/guard_ops.py GRAD_NORM_STAT): the executor peels it into
    `last_stats["grad_norm"]` after every dispatch, so the training
    sentinel (resilience/sentinel.py) watches gradient health with zero
    additional host syncs. Across a steps=K block the channel folds
    with max — the block's worst norm, exactly what a blowup detector
    wants.

    Idempotent per program. Returns {"checked": [...], "gated": [...]}.
    """
    if getattr(program, "_numeric_guards", None):
        return program._numeric_guards
    block = program.global_block()

    checked = []

    def _watch(name):
        if name and name not in checked and name in block.vars:
            checked.append(name)

    if loss is not None:
        _watch(loss if isinstance(loss, str) else loss.name)
    params = [p.name for p in block.all_parameters()]
    for p in params:
        _watch(p + GRAD_SUFFIX)
    for n in extra_vars:
        _watch(n if isinstance(n, str) else n.name)
    if check_params:
        for p in params:
            _watch(p)
    if not checked:
        raise ValueError(
            "install_numeric_guards: nothing to watch — the program has "
            "no loss/extra_vars and no parameter gradients (run "
            "optimizer.minimize first, or pass loss=)")

    def _persistable_outs(op):
        outs = []
        if not is_host_io_op(op.type):
            for n in op.all_output_vars():
                v = block.vars.get(n)
                if v is not None and v.persistable:
                    outs.append(n)
        return outs

    flag = block.create_var(name=GUARD_FLAG_VAR, shape=(1,), dtype="bool",
                            persistable=False)

    # persistables any op writes: the state set to gate (same walk
    # lowering.analyze_state does for state_out)
    gated = []
    if gate_updates:
        for op in block.ops:
            for n in _persistable_outs(op):
                if n not in gated:
                    gated.append(n)
        # pre-step aliases first (prepend order among them is
        # irrelevant: all read scope state before anything writes). The
        # aliases are trace-time only — no copy op is emitted; they
        # just keep the pre-step value reachable for the select.
        for n in gated:
            v = block.vars[n]
            block.create_var(name=n + BACKUP_SUFFIX, shape=v.shape,
                             dtype=v.dtype, persistable=False)
            block.prepend_op(
                "guard_backup", inputs={"X": [n]},
                outputs={"Out": [n + BACKUP_SUFFIX]}, infer_shape=False)
    attrs = {"var_names": list(checked), "granular": bool(granular)}
    if grad_norm:
        attrs["grad_norm_vars"] = [n for n in checked
                                   if n.endswith(GRAD_SUFFIX)]
    block.append_op(
        "check_finite_guard", inputs={"X": list(checked)},
        outputs={"Out": [flag]},
        attrs=attrs,
        infer_shape=False)
    if gated:
        # ONE fused select (a lax.cond with identity branches) over the
        # whole state set: per-var wheres would shatter the XLA:CPU
        # update mega-fusion into N tiny select kernels (measured 2x
        # step time), and running the update tail INSIDE the cond is
        # worse still — the branch boundary forces every gradient to
        # materialize instead of fusing into its update.
        block.append_op(
            "guard_select_all",
            inputs={"Cond": [flag], "X": list(gated),
                    "Y": [n + BACKUP_SUFFIX for n in gated]},
            outputs={"Out": list(gated)}, infer_shape=False)
    info = {"checked": list(checked), "gated": list(gated)}
    program._numeric_guards = info
    return info


class DivergenceFault(RuntimeError):
    """Host-side divergence (loss spike vs running EMA, or a non-finite
    fetched loss). Raised/classified as a numeric-class fault; unlike a
    device guard trip, the offending step's updates DID apply — the
    sane policies are rollback (with lr_scale) or abort."""


class DivergenceDetector(object):
    """Running-EMA loss-spike detector.

    update(loss) returns None while healthy, or a detail string when the
    loss exceeds `threshold` x the EMA (after `window` warmup steps) or
    goes non-finite at the host. State is tiny and picklable;
    `state_dict`/`load_state_dict` let a supervisor snapshot it alongside
    a checkpoint so a resumed run keeps its baseline."""

    def __init__(self, window=20, threshold=10.0, eps=1e-8):
        self.window = max(1, int(window))
        self.threshold = float(threshold)
        self.eps = float(eps)
        self._alpha = 2.0 / (self.window + 1.0)
        self._ema = None
        self._count = 0

    def update(self, loss):
        v = float(np.asarray(loss).reshape(-1)[0])
        if not np.isfinite(v):
            return "non-finite loss %r reached the host" % v
        detail = None
        if self._count >= self.window and \
                abs(v) > self.threshold * (abs(self._ema) + self.eps):
            detail = ("loss %.6g spiked past %.3gx the running EMA %.6g "
                      "(window %d)" % (v, self.threshold, self._ema,
                                       self.window))
        if detail is None:
            # diverged samples are NOT folded into the baseline: one huge
            # loss would drag the EMA up and mask the steps after it
            self._ema = v if self._ema is None else (
                (1.0 - self._alpha) * self._ema + self._alpha * v)
            self._count += 1
        return detail

    def state_dict(self):
        return {"ema": self._ema, "count": self._count}

    def load_state_dict(self, state):
        self._ema = state.get("ema")
        self._count = int(state.get("count", 0))

    def reset(self):
        self._ema, self._count = None, 0
