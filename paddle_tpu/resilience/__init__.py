"""paddle_tpu.resilience — supervised training that survives bad batches,
hangs, and dying input pipelines (ARCHITECTURE.md §17).

Detection + policy + recovery as one subsystem over the PR-1 executor,
PR-4 checkpoints, and the reader stack:

  * guards    — device-side fused all-finite checks appended to the
                lowered step (sticky assertion flags, ONE extra fetch,
                composes with steps=K) that GATE every persistable
                update in-graph, plus a host-side loss-EMA divergence
                detector. `FLAGS_check_nan_inf`'s job, done without a
                per-tensor D2H sweep and without poisoned params.
  * watchdog  — per-dispatch deadlines (`Executor.run(timeout=)` →
                typed DispatchTimeoutError) and self-contained
                diagnostic bundles `tools/ptpu_doctor.py` can replay.
  * Supervisor — the policy engine: per fault class (numeric / hang /
                reader / dispatch) an escalation chain of skip_batch →
                retry(backoff) → rollback(lr_scale) → abort(bundle),
                every action in a structured event log + profiler tags.
  * sentinel  — the training-health layer (ARCHITECTURE.md §29):
                streaming robust statistics (median/MAD z-scores) over
                the loss and the guard-stat grad norm catching
                finite-but-WRONG steps — loss spikes (→ the PaLM-style
                rollback_skip_data: restore + route the reader streams
                past the bad window) and slow divergence.
  * sdc       — silent-data-corruption detection: a deterministic
                canary dispatch on a rotating device, digest-compared
                against a recorded reference; in the elastic cluster a
                mismatch quarantines the device (fence/rollback/
                reshard, per-device).
  * faults    — a deterministic fault plan (`PTPU_FAULT_PLAN` env or
                programmatic) injecting NaN feeds, reader stalls/EOFs/
                errors, dispatch exceptions, slow steps, checkpoint
                kills, finite bad batches (`loss_spike`/`grad_blowup`),
                canary bit flips (`bitflip`) — and cluster faults:
                whole-worker SIGKILLs (`host_death`) and heartbeat
                stalls — at chosen indices, so every recovery path
                above is provable in CI.
  * cluster   — the elastic multi-host layer (ARCHITECTURE.md §19): a
                ClusterCoordinator that heartbeat-monitors a cohort of
                ElasticWorkers, fences it on host death, rolls every
                survivor back to the newest valid snapshot and
                RESHARDS it onto the new mesh shape
                (CheckpointManager.restore(layout=)); replacement
                workers grow the mesh back at a step barrier with no
                aborted step. `tools/ptpu_elastic.py` launches it.

Quickstart:

    from paddle_tpu import resilience as rz
    mgr = fluid.CheckpointManager("ckpt/")
    sup = rz.Supervisor(exe, main_prog, checkpoint_manager=mgr,
                        watchdog_timeout=120,
                        policies={"numeric": [rz.skip_batch(2),
                                              rz.rollback(2, lr_scale=0.5),
                                              rz.abort("bundles/")]})
    rz.install_numeric_guards(main_prog, loss=avg_cost)
    sup.train(10000, fetch_list=[avg_cost], checkpoint_every=100)
"""
from ..core.executor import DispatchTimeoutError, NumericalGuardError
from .faults import (FaultPlan, InjectedDispatchError, InjectedFault,
                     InjectedReaderError, active_plan)
from .guards import (DivergenceDetector, DivergenceFault,
                     install_numeric_guards)
from .sentinel import (DivergenceError, LossSpikeError, RobustWindow,
                       TrainingSentinel)
from .sdc import CanaryChecker, SilentCorruptionError
from .supervisor import (DEFAULT_POLICIES, FAULT_CLASSES, Action,
                         Supervisor, TrainingAborted, abort, retry,
                         rollback, rollback_skip_data, skip_batch)
from .watchdog import read_bundle, write_bundle
from .heartbeat import HeartbeatMonitor, HeartbeatWriter, read_heartbeats
from .cluster import (ClusterAborted, ClusterCoordinator, ClusterFenced,
                      ElasticWorker)

__all__ = [
    "Supervisor", "TrainingAborted", "Action", "skip_batch", "retry",
    "rollback", "rollback_skip_data", "abort", "DEFAULT_POLICIES",
    "FAULT_CLASSES",
    "TrainingSentinel", "RobustWindow", "LossSpikeError",
    "DivergenceError", "CanaryChecker", "SilentCorruptionError",
    "install_numeric_guards", "DivergenceDetector", "DivergenceFault",
    "NumericalGuardError", "DispatchTimeoutError",
    "FaultPlan", "InjectedFault", "InjectedDispatchError",
    "InjectedReaderError", "active_plan",
    "write_bundle", "read_bundle",
    "HeartbeatWriter", "HeartbeatMonitor", "read_heartbeats",
    "ClusterCoordinator", "ElasticWorker", "ClusterFenced",
    "ClusterAborted",
]
