"""Training-health sentinel: streaming statistics over step metrics.

The guards (PR 5) catch values that are *broken* — NaN/Inf in-graph,
gated updates. This module catches values that are *wrong*: finite
losses that spike off the recent distribution (one bad batch), gradient
norms that blow up, and the slow upward drift of divergence. All three
are host-side statistics over values the executor ALREADY fetched —
the loss scalar every training loop pulls, plus the global grad-norm
scalar riding the guard stat channel (`ops/guard_ops.py GRAD_NORM_STAT`
via `install_numeric_guards(grad_norm=True)` → `Executor.last_stats`)
— so the sentinel costs zero additional host syncs per step.

Statistics: a robust z-score over a sliding median/MAD window,

    z = (x - median) / (1.4826 * MAD + eps)

(1.4826 scales the median absolute deviation to the stddev of a normal
distribution). Median/MAD instead of mean/stddev because the statistic
must survive exactly the events it detects: one huge loss drags a mean
and inflates a stddev enough to mask the next ten spikes, but moves a
median by at most one rank. Spiked samples are additionally NEVER
folded into the window, so the baseline stays clean even while a chain
of bad batches is being skipped.

Detections map to typed errors the Supervisor classifies into its new
fault classes (the escalation matrix, ARCHITECTURE.md §29):

    LossSpikeError    loss z-score past `z_threshold` (two-sided), a
                      non-finite loss at the host, or the grad norm
                      past `grad_z_threshold` (one-sided — only blowups
                      are faults). class "loss_spike" → default chain
                      rollback_skip_data: restore the newest snapshot
                      AND advance every reader stream past the
                      offending batch window (the PaLM remedy).
    DivergenceError   the window median exceeding `divergence_factor` x
                      the best median seen, for `divergence_patience`
                      consecutive steps — drift, not a one-off. class
                      "divergence" → rollback (damp LR), then abort.

`observe()` RETURNS the error instance instead of raising so the
Supervisor stays the one place that decides; a bare training loop can
use the sentinel standalone and raise (or log) as it pleases.
"""
import bisect
import collections
import math

import numpy as np

__all__ = ["LossSpikeError", "DivergenceError", "RobustWindow",
           "TrainingSentinel"]


class LossSpikeError(RuntimeError):
    """A step metric (loss, or the global grad norm) spiked off its
    robust window — finite but statistically impossible under the
    recent distribution, the signature of a bad batch. The offending
    step's updates DID apply (the spike is only visible after the
    fetch), so the sane remedy is rollback_skip_data."""

    def __init__(self, message, step=None, metric="loss", value=None,
                 zscore=None):
        super(LossSpikeError, self).__init__(message)
        self.step = step
        self.metric = metric
        self.value = value
        self.zscore = zscore


class DivergenceError(RuntimeError):
    """Sustained upward drift of the loss window median past the best
    median seen — training is walking away from convergence (bad LR,
    poisoned state), not hitting one bad batch."""

    def __init__(self, message, step=None, value=None, best=None):
        super(DivergenceError, self).__init__(message)
        self.step = step
        self.value = value
        self.best = best


class RobustWindow(object):
    """Sliding median/MAD window with robust z-scores.

    `zscore(x)` is None during warmup (fewer than `warmup` samples —
    a median over three points is noise, not a baseline); `push(x)`
    folds a sample in. Callers score BEFORE pushing and skip the push
    for detected outliers, keeping the baseline uncontaminated.

    The window runs once per training step on the dispatch path, so it
    keeps a SORTED copy of the values alongside the eviction deque:
    push is one bisect insort (+ one delete on eviction), median is an
    index, and MAD is a two-pointer merge outward from the median over
    the sorted array — the absolute deviations of the left half
    (descending indices) and right half (ascending) are each already in
    increasing order, so the k-th smallest deviation falls out of an
    O(window) pure-Python walk with no sort and no numpy round-trips.
    The np.median formulation this replaces cost ~90us per observe
    (five median kernels over tiny arrays is all dispatch overhead),
    which at CPU smoke-model step rates was alone a measurable slice
    of the <=3% overhead budget BENCH_SENTINEL=1 gates."""

    def __init__(self, window=64, warmup=16, eps=1e-9):
        self.window = max(2, int(window))
        self.warmup = max(2, int(warmup))
        self.eps = float(eps)
        self.values = collections.deque(maxlen=self.window)
        self._sorted = []

    def __len__(self):
        return len(self.values)

    @property
    def ready(self):
        return len(self.values) >= self.warmup

    def median(self):
        s = self._sorted
        n = len(s)
        if not n:
            return None
        mid = n >> 1
        return s[mid] if n & 1 else 0.5 * (s[mid - 1] + s[mid])

    def _mad(self, med):
        """Median absolute deviation from `med`, selected by merging
        the two deviation streams the sorted array already provides."""
        s = self._sorted
        n = len(s)
        i = bisect.bisect_right(s, med) - 1  # rightmost value <= med
        j = i + 1
        k2 = n >> 1  # 0-based ranks of the deviation median
        k1 = (n - 1) >> 1
        prev = cur = 0.0
        taken = 0
        while taken <= k2:
            left = med - s[i] if i >= 0 else math.inf
            right = s[j] - med if j < n else math.inf
            if left <= right:
                cur, i = left, i - 1
            else:
                cur, j = right, j + 1
            if taken == k1:
                prev = cur
            taken += 1
        return cur if k1 == k2 else 0.5 * (prev + cur)

    def zscore(self, x):
        if not self.ready:
            return None
        med = self.median()
        mad = self._mad(med)
        return (float(x) - med) / (1.4826 * mad + self.eps)

    def push(self, x):
        x = float(x)
        if len(self.values) == self.window:
            old = self.values[0]
            del self._sorted[bisect.bisect_left(self._sorted, old)]
        self.values.append(x)
        bisect.insort(self._sorted, x)

    def state_dict(self):
        return {"values": list(self.values)}

    def load_state_dict(self, state):
        self.values.clear()
        self.values.extend(float(v) for v in state.get("values", ()))
        self._sorted = sorted(self.values)

    def reset(self):
        self.values.clear()
        del self._sorted[:]


class TrainingSentinel(object):
    """The streaming monitor a Supervisor feeds once per healthy step.

    observe(loss, grad_norm=None, step=None) -> None | LossSpikeError |
    DivergenceError. State is tiny and JSON-able
    (state_dict/load_state_dict) so a supervisor can snapshot it beside
    a checkpoint; `status()` is the heartbeat payload (last z-scores,
    spike count) that lets `ptpu_elastic status` show WHY a worker
    fenced."""

    def __init__(self, window=64, warmup=16, z_threshold=8.0,
                 grad_z_threshold=None, divergence_factor=3.0,
                 divergence_patience=32, eps=1e-9):
        self.z_threshold = float(z_threshold)
        self.grad_z_threshold = float(
            z_threshold if grad_z_threshold is None else grad_z_threshold)
        self.divergence_factor = float(divergence_factor)
        self.divergence_patience = max(1, int(divergence_patience))
        self.eps = float(eps)
        self.loss_win = RobustWindow(window=window, warmup=warmup, eps=eps)
        self.grad_win = RobustWindow(window=window, warmup=warmup, eps=eps)
        self.last_z = None
        self.last_grad_z = None
        self.spikes = 0
        self.samples = 0
        self._best_median = None
        self._trend = 0

    # ------------------------------------------------------- detection --
    def observe(self, loss, grad_norm=None, step=None):
        v = float(loss)
        if not math.isfinite(v):
            # guards normally gate this on device; a host-visible
            # non-finite loss (guards off, or loss outside the watched
            # set) is a spike with infinite z
            self.spikes += 1
            self.last_z = float("inf")
            return LossSpikeError(
                "training sentinel: non-finite loss %r reached the host "
                "at step %s" % (v, step), step=step, value=v,
                zscore=self.last_z)
        z = self.loss_win.zscore(v)
        self.last_z = z
        if z is not None and abs(z) > self.z_threshold:
            self.spikes += 1
            return LossSpikeError(
                "training sentinel: loss %.6g at step %s has robust "
                "z-score %.1f (|z| > %.1f over a %d-sample median/MAD "
                "window) — bad batch suspected" % (
                    v, step, z, self.z_threshold, len(self.loss_win)),
                step=step, value=v, zscore=z)
        if grad_norm is not None:
            g = float(grad_norm)
            if not math.isfinite(g):
                self.spikes += 1
                self.last_grad_z = float("inf")
                return LossSpikeError(
                    "training sentinel: non-finite global grad norm %r "
                    "at step %s" % (g, step), step=step,
                    metric="grad_norm", value=g, zscore=self.last_grad_z)
            gz = self.grad_win.zscore(g)
            self.last_grad_z = gz
            # one-sided: a COLLAPSING grad norm is convergence, not a
            # fault; only blowups spike
            if gz is not None and gz > self.grad_z_threshold:
                self.spikes += 1
                return LossSpikeError(
                    "training sentinel: global grad norm %.6g at step "
                    "%s has robust z-score %.1f (> %.1f) — gradient "
                    "blowup suspected" % (g, step, gz,
                                          self.grad_z_threshold),
                    step=step, metric="grad_norm", value=g, zscore=gz)
            self.grad_win.push(g)
        self.loss_win.push(v)
        self.samples += 1
        # divergence: the window median walking up and STAYING up. The
        # sample already passed the spike check, so this triggers only
        # on drift the z-score is blind to (each step near its
        # neighbors, the whole window far from the best).
        med = self.loss_win.median()
        if med is not None and self.loss_win.ready:
            if self._best_median is None or med < self._best_median:
                self._best_median = med
                self._trend = 0
            elif med > self.divergence_factor * (
                    abs(self._best_median) + self.eps):
                self._trend += 1
                if self._trend >= self.divergence_patience:
                    return DivergenceError(
                        "training sentinel: loss window median %.6g has "
                        "exceeded %.3gx the best median %.6g for %d "
                        "consecutive steps — divergence" % (
                            med, self.divergence_factor,
                            self._best_median, self._trend),
                        step=step, value=med, best=self._best_median)
            else:
                self._trend = 0
        return None

    # ----------------------------------------------------------- state --
    def status(self):
        """Heartbeat/metrics payload: plain JSON-able floats."""
        def _f(x):
            return None if x is None or not np.isfinite(x) else float(x)
        return {"z": _f(self.last_z), "grad_z": _f(self.last_grad_z),
                "spikes": int(self.spikes), "samples": int(self.samples)}

    def state_dict(self):
        return {"loss_win": self.loss_win.state_dict(),
                "grad_win": self.grad_win.state_dict(),
                "spikes": self.spikes, "samples": self.samples,
                "best_median": self._best_median, "trend": self._trend}

    def load_state_dict(self, state):
        self.loss_win.load_state_dict(state.get("loss_win", {}))
        self.grad_win.load_state_dict(state.get("grad_win", {}))
        self.spikes = int(state.get("spikes", 0))
        self.samples = int(state.get("samples", 0))
        self._best_median = state.get("best_median")
        self._trend = int(state.get("trend", 0))

    def reset(self):
        """Full reset — the Supervisor calls this after a rollback: the
        restored state replays an earlier stream, so the window's
        samples (drawn from steps past the restore point) are from a
        future that will now unfold differently."""
        self.loss_win.reset()
        self.grad_win.reset()
        self.last_z = self.last_grad_z = None
        self._best_median = None
        self._trend = 0
