"""Hang watchdog + diagnostic bundles.

The per-dispatch deadline itself lives in the executors —
`Executor.run(timeout=)` / `ParallelExecutor.run(timeout=)` run the whole
dispatch (io pre-pass, compile, device execution, fetch readiness) on a
monitored worker thread (`core.executor.run_with_deadline`) and raise the
typed `DispatchTimeoutError`, carrying the compile-cache key of the
wedged program, instead of hanging forever. This module adds what a trip
needs NEXT: `write_bundle` captures a self-contained diagnostic bundle —
the program, the step, feed shapes (and arrays when available), the
recent-metrics ring buffer, the structured event log, every thread's
stack, and the persistable scope state — that `tools/ptpu_doctor.py` can
inspect and REPLAY offline (exit 1 when the recorded failing step
reproduces its fault against the bundled program + state).

Bundle layout (one directory per capture):

    bundle.json    reason, fault_class, step, error, feed shapes,
                   metrics ring, events, thread stacks, wall time
    program.bin    core/program_desc bytes (when a program was given)
    feeds.npz      the failing step's feed arrays (when available)
    state.npz      persistable scope values (readers and unmaterializable
                   donated buffers recorded by name in bundle.json)
"""
import json
import os
import sys
import time
import traceback

import numpy as np

from ..core.executor import (DispatchTimeoutError,  # noqa: F401 (re-export)
                             run_with_deadline)     # noqa: F401

__all__ = ["DispatchTimeoutError", "run_with_deadline", "write_bundle",
           "read_bundle", "BUNDLE_FILE"]

BUNDLE_FILE = "bundle.json"


def _thread_stacks():
    """Every live thread's current Python stack — the watchdog's answer
    to "what was the process doing when the deadline expired"."""
    frames = sys._current_frames()
    stacks = {}
    import threading
    names = {t.ident: t.name for t in threading.enumerate()}
    for ident, frame in frames.items():
        stacks["%s (%d)" % (names.get(ident, "?"), ident)] = \
            traceback.format_stack(frame)
    return stacks


def write_bundle(bundle_dir, reason, fault_class=None, step=None,
                 program=None, feed=None, scope=None, metrics=None,
                 events=None, error=None):
    """Capture a diagnostic bundle under `bundle_dir` and return its
    path. Never raises for partially-capturable state: a post-timeout
    scope can hold donated (deleted) device buffers — those land in
    bundle.json's `state_unavailable` list instead of killing the
    capture that exists to explain the failure."""
    os.makedirs(bundle_dir, exist_ok=True)
    base = "bundle_step%s" % ("NA" if step is None else int(step))
    path = os.path.join(bundle_dir, base)
    n = 0
    while os.path.exists(path):
        n += 1
        path = os.path.join(bundle_dir, "%s.%d" % (base, n))
    os.makedirs(path)

    meta = {
        "reason": str(reason),
        "fault_class": fault_class,
        "step": None if step is None else int(step),
        "error": None if error is None else repr(error),
        "wall_time": time.time(),
        "pid": os.getpid(),
        "metrics": list(metrics) if metrics else [],
        "events": list(events) if events else [],
        "thread_stacks": _thread_stacks(),
        "feed_shapes": {},
        "state_unavailable": [],
        "has_program": program is not None,
    }
    try:
        # flight-recorder dump (ARCHITECTURE.md §24): the bounded span
        # ring plus every span still OPEN at capture — for a hang this
        # is "what the pipeline was doing when it wedged", rendered by
        # `ptpu_doctor trace <bundle>`. Best-effort like everything
        # else here: a capture must never fail the capture.
        from ..observability import trace as _otrace
        meta["trace"] = _otrace.dump_jsonable()
    except Exception:  # noqa: BLE001
        pass

    if program is not None:
        from ..core import program_desc as _pd
        with open(os.path.join(path, "program.bin"), "wb") as f:
            f.write(_pd.program_to_bytes(program))
        meta["program_version"] = int(getattr(program, "_version", 0))

    feed_arrays = {}
    for name, v in (feed or {}).items():
        try:
            a = np.asarray(v)
        except Exception:
            meta["feed_shapes"][name] = ["<unavailable>"]
            continue
        meta["feed_shapes"][name] = [list(a.shape), str(a.dtype)]
        feed_arrays[name] = a
    if feed_arrays:
        np.savez(os.path.join(path, "feeds.npz"), **feed_arrays)

    if scope is not None:
        from ..core.readers import ReaderBase
        state = {}
        for name in scope.names():
            v = scope.get(name)
            if v is None or isinstance(v, ReaderBase):
                continue
            try:
                state[name] = np.asarray(v)
            except Exception:
                # donated buffer consumed by an abandoned dispatch: the
                # name is the diagnosis, the value is gone
                meta["state_unavailable"].append(name)
        if state:
            np.savez(os.path.join(path, "state.npz"), **state)

    with open(os.path.join(path, BUNDLE_FILE), "w") as f:
        json.dump(meta, f, indent=1, sort_keys=True)
    return path


def read_bundle(path):
    """Parse a bundle directory -> (meta, program|None, feeds|None,
    state|None). The doctor tool's loader; arrays come back as plain
    numpy dicts."""
    with open(os.path.join(path, BUNDLE_FILE)) as f:
        meta = json.load(f)
    program = None
    pb = os.path.join(path, "program.bin")
    if os.path.exists(pb):
        from ..core import program_desc as _pd
        with open(pb, "rb") as f:
            program = _pd.program_from_bytes(f.read())
    feeds = state = None
    fz = os.path.join(path, "feeds.npz")
    if os.path.exists(fz):
        with np.load(fz) as z:
            feeds = {k: z[k] for k in z.files}
    sz = os.path.join(path, "state.npz")
    if os.path.exists(sz):
        with np.load(sz) as z:
            state = {k: z[k] for k in z.files}
    return meta, program, feeds, state
