"""Deterministic fault injection: one registry for every failure mode.

A `FaultPlan` is an ordered set of (kind, index[, arg]) entries — parsed
from the `PTPU_FAULT_PLAN` env var (`"nan_feed@5;reader_stall@8:0.5"`) or
built programmatically — that injects failures at chosen indices so every
recovery path (resilience.Supervisor policies, checkpoint rollback, the
hang watchdog) is provable in CI instead of waited for in production.
Arming a plan installs hooks at three seams:

  * `core.executor._fault_hook` — fires per DISPATCH, keyed on the step
    counter (`plan.set_step`, which the Supervisor advances): `nan_feed`
    poisons a float feed array, `dispatch_exc` raises
    InjectedDispatchError, `slow_step` sleeps `arg` seconds (trips the
    watchdog). All fire BEFORE the io pre-pass and seed draw, so a
    failed attempt consumes nothing and retries replay bit-exactly.
    Cluster faults ride the same seam, keyed on the same
    coordinator-visible step cursor: `host_death@N` SIGKILLs the whole
    worker process at step N (the deterministic "a host just died"
    for the elastic multi-process CI leg — nothing of step N is
    consumed, so the newest snapshot is at most N-1), and
    `heartbeat_stall@N[:secs]` stops the heartbeat thread's writes
    from step N for `secs` seconds (default: forever) WITHOUT touching
    the training loop — the "wedged but not dead" host the coordinator
    must fence out on missed heartbeats alone. The sentinel faults
    (ARCHITECTURE.md §29) ride here for FEED-FED programs:
    `loss_spike@N[:mag]` / `grad_blowup@N[:mag]` scale every float feed
    of step N by a large-but-FINITE magnitude (defaults 1e3 / 1e6) —
    no guard trips, only the statistical monitors can see it.
  * `core.readers._fault_hook` — fires per RECORD, keyed on each
    reader's own delivered-record counter (deterministic even when a
    DoubleBufferReader worker pre-stages ahead of the training loop):
    `reader_nan` poisons the record's float fields, `reader_exc` raises
    InjectedReaderError (from the worker thread for buffered readers —
    exercising the immediate fault channel), `reader_stall` sleeps,
    `reader_eof` ends the stream early. For READER-FED programs the
    sentinel faults key here instead: `loss_spike@N[:mag]` /
    `grad_blowup@N[:mag]` scale record N's float fields — the bad
    batch lands at a known stream position, which is exactly what
    rollback_skip_data's bit-exactness proof needs.
  * `resilience.sdc._fault_hook` — `bitflip@N[:device]` flips ONE bit
    of canary check >= N's result (waiting, with `device`, until the
    rotation lands on that local device index): the minimal silent
    corruption, invisible to every guard, that must trip the digest
    compare and get the device quarantined.
  * `checkpoint.snapshot._fault_hook` — `ckpt_kill@N` SIGKILLs at the
    Nth durability crossing of the write protocol, subsuming PR-4's
    `PTPU_CKPT_FAULT_AT` (which keeps working unchanged) under this
    registry.
  * `serving_fault` — the SERVING seam: `serving.pool.ReplicaPool`'s
    pre-dispatch tap consults the armed plan before every replica
    dispatch, keyed on that REPLICA's own dispatch count (deterministic
    per replica regardless of routing): `replica_exc@N` raises
    InjectedReplicaError inside the Nth dispatch (the batcher's group
    isolation fails only that batch; the pool must fail the requests
    over), `replica_wedge@N[:secs]` sleeps the replica's batcher worker
    `secs` seconds (default: effectively forever) — the wedged-engine
    case only per-attempt timeouts can detect — and `replica_poison@N`
    NaNs every float value in the replica's private Scope, the
    crashed-trainer-pushed-garbage-weights case the pool's finite-output
    check must catch. The fleet chaos kinds ride the same tap:
    `replica_slow@N[:secs]` sleeps a SHORT, repeatable latency (default
    0.2s; arm with `*`) — the slow-but-alive replica the pool's latency
    breaker must brown out, as opposed to the wedge only timeouts see;
    `replica_crash@N` kills the engine abruptly MID-WINDOW (the batcher
    closes drain=False from a side thread while this dispatch fails) —
    queued and in-flight requests on it must all resolve via failover,
    nothing may hang; `canary_poison@N` corrupts weights like
    replica_poison but fires ONLY on a canary engine's tap
    (replica_id == "canary") — the bad-canary case promotion gating
    must catch and auto-roll-back with zero client errors. One-shot
    entries fire on the FIRST replica to reach count N; the recovery
    invariant (zero client-visible errors) must hold whichever replica
    that is.

Entries are ONE-SHOT by default (`kind@idx`); `kind@idx*` repeats every
time the index matches. One plan may be armed per process at a time.
"""
import os
import threading

import numpy as np

__all__ = ["FaultPlan", "InjectedFault", "InjectedDispatchError",
           "InjectedReaderError", "InjectedReplicaError",
           "InjectedReplicaCrash", "active_plan"]

_KINDS = frozenset({
    "nan_feed", "dispatch_exc", "slow_step",
    "reader_nan", "reader_exc", "reader_stall", "reader_eof",
    "ckpt_kill", "host_death", "heartbeat_stall",
    "replica_exc", "replica_wedge", "replica_poison",
    "replica_slow", "replica_crash", "canary_poison",
    "loss_spike", "grad_blowup", "bitflip",
})
_READER_KINDS = frozenset({"reader_nan", "reader_exc", "reader_stall",
                           "reader_eof"})


class InjectedFault(RuntimeError):
    """Base of all plan-injected failures (so tests/supervisors can tell
    injected faults from organic ones when they need to)."""


class InjectedDispatchError(InjectedFault):
    """Injected executor-dispatch failure (fault kind `dispatch_exc`)."""


class InjectedReaderError(InjectedFault):
    """Injected reader failure (fault kind `reader_exc`); tagged
    reader-class for the supervisor's fault classifier."""
    _reader_fault = True


class InjectedReplicaError(InjectedFault):
    """Injected serving-replica dispatch failure (fault kind
    `replica_exc`); tagged replica-class so the pool's failover logic
    and tests can tell an injected replica fault from an organic one."""
    _replica_fault = True


class InjectedReplicaCrash(InjectedFault):
    """Injected abrupt replica death (fault kind `replica_crash`): the
    replica's engine is force-closed (no drain) mid-window while this
    dispatch fails — the pool must fail everything queued on it over
    with zero client-visible errors and no hangs."""
    _replica_fault = True


class _Entry(object):
    __slots__ = ("kind", "at", "arg", "repeat", "fired")

    def __init__(self, kind, at, arg=None, repeat=False):
        if kind not in _KINDS:
            raise ValueError(
                "unknown fault kind %r; known kinds: %s"
                % (kind, ", ".join(sorted(_KINDS))))
        self.kind = kind
        self.at = int(at)
        self.arg = arg
        self.repeat = bool(repeat)
        self.fired = False

    def __repr__(self):
        return "%s@%d%s%s" % (self.kind, self.at,
                              ":%g" % self.arg if self.arg is not None
                              else "", "*" if self.repeat else "")


def _parse_entry(spec):
    """'kind@idx[:arg][*]' -> _Entry. Raises LOUDLY on malformed specs
    (the FLAGS_conv_layout rule: a typo'd plan silently injecting nothing
    would green-light an untested recovery path)."""
    s = spec.strip()
    repeat = s.endswith("*")
    if repeat:
        s = s[:-1]
    if "@" not in s:
        raise ValueError("fault spec %r: expected 'kind@index[:arg]'" % spec)
    kind, _, rest = s.partition("@")
    arg = None
    if ":" in rest:
        at_s, _, arg_s = rest.partition(":")
        arg = float(arg_s)
    else:
        at_s = rest
    return _Entry(kind.strip(), int(at_s), arg=arg, repeat=repeat)


_active = None
_lock = threading.Lock()


def active_plan():
    """The currently armed FaultPlan, or None."""
    return _active


class FaultPlan(object):
    def __init__(self, entries=()):
        self.entries = []
        for e in entries:
            if isinstance(e, _Entry):
                self.entries.append(e)
            elif isinstance(e, str):
                self.entries.append(_parse_entry(e))
            else:
                kind, at = e[0], e[1]
                arg = e[2] if len(e) > 2 else None
                self.entries.append(_Entry(kind, at, arg=arg))
        self._step = 0
        self._ckpt_crossings = 0
        self._hb_stall_until = 0.0  # monotonic deadline (inf = forever)
        # one-shot bookkeeping is check-then-act; reader hooks fire from
        # worker threads (DoubleBuffer pre-staging), so _take must be
        # atomic or a "one-shot" could fire twice in a tight race
        self._take_lock = threading.Lock()

    @classmethod
    def from_env(cls, spec=None):
        """Parse PTPU_FAULT_PLAN (or an explicit spec string). Returns
        None when the var is unset/empty — callers can arm
        unconditionally via `plan = FaultPlan.from_env();
        if plan: plan.arm()`."""
        spec = os.environ.get("PTPU_FAULT_PLAN", "") if spec is None \
            else spec
        spec = spec.strip()
        if not spec:
            return None
        return cls([s for s in spec.split(";") if s.strip()])

    # ------------------------------------------------------------ state --
    def set_step(self, step):
        """Advance the step cursor the dispatch-level faults key on (the
        Supervisor calls this before every attempt)."""
        self._step = int(step)

    def pending(self):
        """Entries that have not fired yet (one-shot bookkeeping)."""
        return [e for e in self.entries if e.repeat or not e.fired]

    def _take(self, kinds, at):
        with self._take_lock:
            for e in self.entries:
                if e.kind in kinds and e.at == at \
                        and (e.repeat or not e.fired):
                    e.fired = True
                    return e
        return None

    # ------------------------------------------------------------- arm --
    def arm(self):
        """Install this plan's hooks (executor, readers, checkpoint).
        Raises if another plan is armed — overlapping plans would make
        the injection schedule nondeterministic."""
        global _active
        from ..core import executor as _exe
        from ..core import readers as _rdr
        from ..checkpoint import snapshot as _snap
        from . import sdc as _sdc
        with _lock:
            if _active is not None and _active is not self:
                raise RuntimeError("another FaultPlan is already armed")
            _active = self
            _exe._fault_hook = self._executor_hook
            _rdr._fault_hook = self._reader_hook
            _snap._fault_hook = self._ckpt_hook
            _sdc._fault_hook = self._sdc_hook
        return self

    def disarm(self):
        global _active
        from ..core import executor as _exe
        from ..core import readers as _rdr
        from ..checkpoint import snapshot as _snap
        from . import sdc as _sdc
        with _lock:
            if _active is self:
                _active = None
                _exe._fault_hook = None
                _rdr._fault_hook = None
                _snap._fault_hook = None
                _sdc._fault_hook = None

    def __enter__(self):
        return self.arm()

    def __exit__(self, *exc):
        self.disarm()

    # ----------------------------------------------------------- hooks --
    def heartbeat_stalled(self):
        """True while an injected heartbeat stall is in effect
        (HeartbeatWriter.beat consults this before every write)."""
        import time
        return time.monotonic() < self._hb_stall_until

    def _executor_hook(self, point, program=None, steps=1,
                       feed_arrays=None):
        del point, program
        e = self._take(("host_death",), self._step)
        if e is not None:
            # the whole WORKER dies, exactly like a preempted host: no
            # atexit, no cleanup, before anything of this step is
            # consumed (the same SIGKILL discipline as ckpt_kill)
            import signal
            os.kill(os.getpid(), signal.SIGKILL)
        e = self._take(("heartbeat_stall",), self._step)
        if e is not None:
            import time
            self._hb_stall_until = time.monotonic() + (
                e.arg if e.arg is not None else float("inf"))
        e = self._take(("slow_step",), self._step)
        if e is not None:
            import time
            time.sleep(e.arg if e.arg is not None else 1.0)
        e = self._take(("dispatch_exc",), self._step)
        if e is not None:
            raise InjectedDispatchError(
                "injected dispatch failure at step %d (fault plan)"
                % self._step)
        e = self._take(("nan_feed",), self._step)
        if e is not None and feed_arrays is not None:
            _poison_first_float(feed_arrays)
        # sentinel faults, feed-fed seam: scale the float feeds by a
        # large-but-FINITE magnitude — no guard trips, only statistics
        # can see it. Taken only when explicit feeds exist; a reader-fed
        # program's records are injected at the reader seam instead
        # (same kinds, keyed on the source reader's record counter), so
        # a one-shot entry is never burned against an empty feed dict.
        if feed_arrays:
            e = self._take(("loss_spike", "grad_blowup"), self._step)
            if e is not None:
                _scale_float_feeds(feed_arrays, _spike_mag(e))

    def _reader_hook(self, phase, reader, record=None):
        # fire only at SOURCE readers (no `_under` wrapper): in a
        # decorator chain both the inner reader (worker thread,
        # pre-staging ahead) and the outer one pass every index, and
        # whichever hit a one-shot entry first would win by thread
        # timing — source-level injection is deterministic in stream
        # order regardless of buffering
        if getattr(reader, "_under", None) is not None:
            return None
        at = reader._consumed
        if phase == "read":
            e = self._take(("reader_stall",), at)
            if e is not None:
                import time
                time.sleep(e.arg if e.arg is not None else 1.0)
            e = self._take(("reader_eof",), at)
            if e is not None:
                from ..core.readers import EOFException
                raise EOFException()
            e = self._take(("reader_exc",), at)
            if e is not None:
                raise InjectedReaderError(
                    "injected reader failure at record %d (fault plan)"
                    % at)
            return None
        # phase == "record": poison the popped record's float fields
        e = self._take(("loss_spike", "grad_blowup"), at)
        if e is not None:
            # sentinel faults, reader seam: the "bad batch" — every
            # float field scaled by a finite magnitude at a KNOWN
            # record index, so rollback_skip_data's bit-exactness leg
            # can reconstruct exactly which records to never see
            mag = _spike_mag(e)
            return tuple(
                np.array(f, copy=True) * mag
                if np.issubdtype(np.asarray(f).dtype, np.floating)
                else f for f in record)
        e = self._take(("reader_nan",), at)
        if e is None:
            return None
        poisoned = []
        hit = False
        for f in record:
            a = np.array(f, copy=True)
            if not hit and np.issubdtype(a.dtype, np.floating):
                a.reshape(-1)[0] = np.nan
                hit = True
            poisoned.append(a)
        return tuple(poisoned)

    def serving_fault(self, replica_id, dispatch_count, engine=None):
        """Serving seam: called by ReplicaPool's pre-dispatch tap with
        the dispatching replica's id and ITS OWN dispatch count (the
        key). Unlike the executor/reader seams this one is pulled
        (`active_plan()` at the tap) rather than pushed at arm() — the
        pool may not exist when a training-only plan arms, and arming
        must not import the serving stack."""
        e = self._take(("replica_wedge",), dispatch_count)
        if e is not None:
            import time
            # sleeps ON the replica's batcher worker: every request
            # queued behind this dispatch stalls — only the pool's
            # per-attempt timeout can see it, exactly like a real wedge
            time.sleep(e.arg if e.arg is not None else 3600.0)
        e = self._take(("replica_slow",), dispatch_count)
        if e is not None:
            import time
            # SHORT, usually repeated (`replica_slow@0:0.2*`): the
            # slow-but-answering replica — requests complete, latency
            # collapses; the pool's latency breaker (and the fleet's
            # brownout) must act on measurements, not timeouts
            time.sleep(e.arg if e.arg is not None else 0.2)
        if replica_id == "canary":
            # canary-targeted corruption: fires only on the canary
            # engine's tap, never a serving replica's — the bad-canary
            # rollback leg must not depend on routing luck
            e = self._take(("canary_poison",), dispatch_count)
            if e is not None and engine is not None:
                _poison_scope_floats(engine._scope)
        e = self._take(("replica_poison",), dispatch_count)
        if e is not None and engine is not None:
            _poison_scope_floats(engine._scope)
        e = self._take(("replica_crash",), dispatch_count)
        if e is not None and engine is not None:
            import threading
            # abrupt death mid-window: close(drain=False) fails every
            # queued/formed request with ServingClosedError — from a
            # SIDE thread, because close() joins the very batcher
            # worker this tap runs on — while the current dispatch
            # fails with the typed crash error
            threading.Thread(
                target=lambda: engine.close(drain=False, timeout=5.0),
                daemon=True, name="ptpu-fault-crash").start()
            raise InjectedReplicaCrash(
                "injected replica crash on replica %s at dispatch %d "
                "(fault plan)" % (replica_id, dispatch_count))
        e = self._take(("replica_exc",), dispatch_count)
        if e is not None:
            raise InjectedReplicaError(
                "injected replica failure on replica %s at dispatch %d "
                "(fault plan)" % (replica_id, dispatch_count))

    def _ckpt_hook(self):
        n = self._ckpt_crossings
        self._ckpt_crossings = n + 1
        e = self._take(("ckpt_kill",), n)
        if e is not None:
            import signal
            os.kill(os.getpid(), signal.SIGKILL)

    def _sdc_hook(self, check_index, device_index, result):
        """SDC seam (resilience/sdc.py CanaryChecker): `bitflip@N[:dev]`
        corrupts the result of canary check >= N — waiting, when `dev`
        is given, until the round-robin rotation lands on that local
        device index, so the quarantine leg deterministically blames
        the device the plan names. One bit of one element flips: the
        minimal silent corruption, far below any statistical monitor's
        floor and invisible to every finiteness guard."""
        taken = None
        with self._take_lock:
            for en in self.entries:
                if en.kind == "bitflip" and (en.repeat or not en.fired) \
                        and check_index >= en.at \
                        and (en.arg is None
                             or int(en.arg) == device_index):
                    en.fired = True
                    taken = en
                    break
        if taken is None:
            return result
        a = np.array(result, copy=True)
        flat = a.reshape(-1)
        bits = flat[:1].view(np.uint32 if flat.dtype == np.float32
                             else np.uint64)
        bits[0] ^= np.asarray(1 << 20, bits.dtype)
        return a


def _poison_scope_floats(scope):
    """NaN the first element of EVERY float array in a Scope — the
    `replica_poison` payload. Poisoning every float persistable (not
    just the first) makes the corruption reach the outputs of any model
    topology: one NaN weight element propagates through its matmul
    column, and softmax/normalizing heads spread it across the row."""
    for name in sorted(scope.names()):
        v = scope.get(name)
        if v is None:
            continue
        a = np.asarray(v)
        if not np.issubdtype(a.dtype, np.floating) or a.size == 0:
            continue
        a = np.array(a, copy=True)
        a.reshape(-1)[0] = np.nan
        scope.set(name, a)


def _spike_mag(entry):
    """Magnitude for the sentinel fault kinds: the entry's arg, or a
    kind-specific default — loss_spike 1e3 (a clear statistical outlier
    that stays well inside float range through the loss), grad_blowup
    1e6 (big enough that the grad-norm monitor, watching a noisier
    stream, trips before the loss z-score does)."""
    if entry.arg is not None:
        return float(entry.arg)
    return 1e6 if entry.kind == "grad_blowup" else 1e3


def _scale_float_feeds(feed_arrays, mag):
    """Scale every float feed by `mag` in place in the feed dict — the
    finite 'bad batch' payload (contrast _poison_first_float: NaN)."""
    import jax.numpy as jnp
    for name in sorted(feed_arrays):
        v = feed_arrays[name]
        dt = np.dtype(getattr(v, "dtype", np.asarray(v).dtype))
        if not np.issubdtype(dt, np.floating):
            continue
        a = np.array(np.asarray(v), copy=True) * dt.type(mag)
        feed_arrays[name] = jnp.asarray(a) if not isinstance(
            v, np.ndarray) else a


def _poison_first_float(feed_arrays):
    """Overwrite the first element of the first float feed with NaN —
    in place in the feed dict, deterministically (sorted name order)."""
    import jax.numpy as jnp
    for name in sorted(feed_arrays):
        v = feed_arrays[name]
        dt = np.dtype(getattr(v, "dtype", np.asarray(v).dtype))
        if not np.issubdtype(dt, np.floating):
            continue
        a = np.array(np.asarray(v), copy=True)
        a.reshape(-1)[0] = np.nan
        feed_arrays[name] = jnp.asarray(a) if not isinstance(
            v, np.ndarray) else a
        return name
    return None
