"""Elastic multi-host training: the cluster Supervisor.

The PR-5 Supervisor recovers ONE process; this module extends the same
detection -> policy -> recovery shape across a cohort of worker
processes, the runtime-level cluster fault tolerance the TensorFlow
system paper argues for (arXiv:1605.08695) and the thing the
reference's pserver transpiler never had (one dead pserver = dead job).

Roles and protocol (everything rides the shared cluster directory — the
same shared-filesystem trust the checkpoint root already carries):

  ClusterCoordinator   one process (the launcher) that owns the PLAN —
                       an atomically-published, generation-numbered
                       JSON document naming the cohort: who is a member,
                       each member's rank and local device count, what
                       snapshot to restore, and the phase
                       (run / fence / abort / done).
  ElasticWorker        each worker runs the PR-5 guarded loop (inner
                       Supervisor: guards, watchdog, skip/retry) plus a
                       heartbeat thread (step cursor, status, acked
                       generation, reader positions). Local faults stay
                       local; a hang (DispatchTimeoutError) escalates as
                       a CLUSTER fault via the heartbeat.

Failure flow (shrink): the coordinator detects a dead host — missed
heartbeats, a vanished pid, or a worker-reported cluster fault — and
(1) FENCES the cohort: publishes a fence-phase plan; every survivor
stops at its next step boundary (the `core.executor` barrier hook fires
before the io pre-pass and seed draw, so the fenced attempt consumes
nothing) and acks; a survivor that dies DURING the fence re-starts the
fence with the remaining cohort (death-during-recovery is just another
generation). (2) ROLLS BACK: picks the newest valid snapshot and
publishes a run-phase plan pinning it. (3) RESHARDS: every survivor
tears down its old mesh (`shutdown_distributed` drops all cached
layout state), builds the new cohort's `DeviceLayout`, and restores the
pinned snapshot with `CheckpointManager.restore(layout=)` — arrays
recorded under N devices re-split onto the new M-device mesh. Training
resumes bit-exact with a from-scratch run on the small mesh resumed
from the same snapshot.

Silent data corruption (ARCHITECTURE.md §29) follows the same flow at
DEVICE granularity: a worker whose SDC canary (resilience/sdc.py)
convicts a chip escalates with `sdc_device` in its heartbeat; the
coordinator QUARANTINES that device — records it, publishes the
quarantine list in every plan, subtracts it from the member's device
budget — and runs the ordinary fence/rollback/reshard. The member's
next DeviceLayout builds its mesh around the bad chip
(skip_local_devices); a member with no devices left drops out of the
world entirely. `ptpu_elastic status` surfaces the list.

Growth (replacement-worker join) is the same fence, minus the rollback:
the coordinator fences AT a step barrier with `save_step` set, rank 0
snapshots its current step, and the new run-phase plan pins exactly
that step — survivors restore the state they already hold (resharded
onto their possibly-changed local mesh) and the joiner starts from it,
so no completed step is ever aborted.

Exhausted budgets (max_rescales) or a memberless cohort end in a
coordinator-side abort: one MERGED diagnostic bundle (coordinator
events, every worker's last heartbeat, the plan history, each worker's
own PR-5 bundles) and a typed ClusterAborted.

Data plane: each worker trains the same SPMD program over its local
mesh. Under a real multi-host runtime (`init_distributed` with a
rendezvous configured) the mesh spans the pod; without one (the CI leg
— this container has no multi-host rendezvous) the cohort trains
replicated, which the coordination layer cannot tell apart — the
fence/rollback/reshard protocol is data-plane agnostic, and the CI leg
proves every path of it with real processes and real SIGKILLs
(`host_death@N` / `heartbeat_stall@N` in the FaultPlan registry).
"""
import json
import os
import shutil
import time

import numpy as np

from ..core import executor as _exe_mod
from ..core.executor import DispatchTimeoutError, Scope, scope_guard
from ..core.readers import EOFException
from ..checkpoint import CheckpointManager, find_valid_snapshot
from ..observability import registry as _obsreg
from ..observability import trace as _otrace
from ..parallel import distributed as _dist
from ..parallel.distributed import DeviceLayout
from . import faults as _faults
from . import heartbeat as _hb
from .sdc import CanaryChecker, SilentCorruptionError
from .sentinel import TrainingSentinel
from .supervisor import Supervisor, TrainingAborted, abort as _abort_action

__all__ = ["ClusterCoordinator", "ElasticWorker", "ClusterFenced",
           "ClusterAborted", "read_plan", "write_plan", "PLAN_FILE",
           "default_checkpoint_dir"]

PLAN_FILE = "plan.json"


class ClusterFenced(RuntimeError):
    """The coordinator published a newer plan generation: this process
    must stop training and reconfigure. Raised by the step-barrier hook
    BEFORE anything of the attempt is consumed; the Supervisor passes it
    through untouched (it is coordination, not a fault)."""

    _cluster_fence = True

    def __init__(self, message, gen=None):
        super(ClusterFenced, self).__init__(message)
        self.gen = gen


class ClusterAborted(RuntimeError):
    """Terminal cluster-level escalation. `bundle` is the merged
    diagnostic bundle directory when one was written."""

    def __init__(self, message, bundle=None, cause=None):
        super(ClusterAborted, self).__init__(message)
        self.bundle = bundle
        self.cause = cause


def default_checkpoint_dir(cluster_dir):
    """Coordinator and workers must agree on the snapshot root; this is
    the shared default under the cluster directory."""
    return os.path.join(str(cluster_dir), "ckpt")


# ------------------------------------------------------------- the plan --
def write_plan(cluster_dir, plan):
    """Atomically publish `plan` (tmp + fsync + os.replace — readers
    never see a torn document, and the control plane survives power
    loss). Returns the plan with wall_time stamped."""
    from ..core.utils import atomic_write_json
    plan = dict(plan, wall_time=time.time())
    os.makedirs(str(cluster_dir), exist_ok=True)
    atomic_write_json(os.path.join(str(cluster_dir), PLAN_FILE), plan,
                      fsync=True, indent=1, sort_keys=True)
    return plan


def read_plan(cluster_dir):
    """The current plan, or None before the coordinator publishes one.
    A transiently unreadable file reads as None (atomic replace makes
    that a race, not a corruption)."""
    try:
        with open(os.path.join(str(cluster_dir), PLAN_FILE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------- coordinator --
class ClusterCoordinator(object):
    def __init__(self, cluster_dir, num_workers, checkpoint_dir=None,
                 heartbeat_timeout=3.0, poll_interval=0.05,
                 fence_timeout=60.0, join_timeout=180.0, max_rescales=8,
                 total_device_count=None, local_device_count=None,
                 mesh_axes=None, batch_axis="dp", shard_axis=None,
                 bundle_dir=None, allow_grow=True, on_event=None):
        """`num_workers` is the INITIAL cohort size (formation waits for
        that many registrations); later joiners grow the cohort when
        `allow_grow`. Device assignment per member: with
        `total_device_count` set, the cluster's chip budget is fixed
        and each member gets total // world_size (a shrinking cohort
        GROWS each survivor's local mesh — the in-process reshard);
        otherwise `local_device_count` (or each worker's own default)
        applies uniformly. `max_rescales` budgets reconfigurations
        (shrink + grow combined); past it the coordinator aborts with a
        merged bundle. `on_event(event_dict)` observes the event log
        live (the launcher's replace-a-dead-worker trigger)."""
        self.cluster_dir = str(cluster_dir)
        self.num_workers = int(num_workers)
        self.checkpoint_dir = checkpoint_dir or default_checkpoint_dir(
            cluster_dir)
        self.monitor = _hb.HeartbeatMonitor(cluster_dir,
                                            timeout=heartbeat_timeout)
        self.poll_interval = float(poll_interval)
        self.fence_timeout = float(fence_timeout)
        self.join_timeout = float(join_timeout)
        self.max_rescales = int(max_rescales)
        self.total_device_count = total_device_count
        self.local_device_count = local_device_count
        self.mesh_axes = dict(mesh_axes) if mesh_axes else None
        self.batch_axis = batch_axis
        # the update-state shard axis (parallel/plan.py): published in
        # every generation's plan so resharded cohorts keep the
        # sharded-weight-update layout across rescales. Validated HERE
        # (same rule as DeviceLayout) — deferring it would make every
        # worker's layout constructor raise instead, read as a cohort
        # of worker deaths burning fence/rollback cycles to abort.
        if shard_axis is not None and shard_axis not in (
                self.mesh_axes or {batch_axis: -1}):
            raise ValueError(
                "shard_axis %r is not one of the cluster's mesh axes %r"
                % (shard_axis, sorted(self.mesh_axes or {batch_axis: -1})))
        self.shard_axis = shard_axis
        self.bundle_dir = bundle_dir
        self.allow_grow = bool(allow_grow)
        self.on_event = on_event
        self.events = []
        self.gen = 0
        self.world = {}       # worker_id -> {"rank", "local_device_count"}
        # worker_id -> sorted list of local device indices the SDC
        # canary convicted (resilience/sdc.py). Published in every
        # plan; _assign_world subtracts them from the member's device
        # budget and the member's DeviceLayout builds its mesh around
        # them. A member with no devices left is dropped entirely.
        self.quarantine = {}
        self.rescales = 0
        self._plans = []      # published plan history (merged bundle)
        # a restarted cluster reuses its directory (that is how it finds
        # its checkpoints) — but a PREVIOUS run's plan must not leak
        # into the new one: fresh workers reading a stale done/abort
        # plan would exit before formation, and a stale high generation
        # would outrun the new coordinator's numbering. Construct the
        # coordinator before spawning workers (the launcher does).
        try:
            os.remove(os.path.join(self.cluster_dir, PLAN_FILE))
        except OSError:
            pass

    # ---------------------------------------------------------- events --
    def _log(self, event, **detail):
        ev = dict(detail, event=event, gen=self.gen,
                  wall_time=time.time())
        self.events.append(ev)
        # flight-recorder instants (ARCHITECTURE.md §24): fence/rescale/
        # grow/abort land in the same timeline as the dispatch spans
        _otrace.instant("cluster/%s" % event, cat="cluster",
                        gen=int(self.gen))
        if self.on_event is not None:
            try:
                self.on_event(ev)
            except Exception:  # noqa: BLE001 — observers must not kill
                pass           # the control loop
        return ev

    def _publish(self, phase, world, **extra):
        self.gen += 1
        plan = dict(extra, gen=self.gen, phase=phase, world=world,
                    num_workers=len(world),
                    checkpoint_dir=self.checkpoint_dir,
                    batch_axis=self.batch_axis,
                    quarantine={w: sorted(d)
                                for w, d in self.quarantine.items() if d})
        if self.mesh_axes:
            plan["mesh_axes"] = self.mesh_axes
        if self.shard_axis is not None:
            plan["shard_axis"] = self.shard_axis
        plan = write_plan(self.cluster_dir, plan)
        self._plans.append(plan)
        return plan

    # ----------------------------------------------------- world shapes --
    def _assign_world(self, worker_ids):
        """Deterministic rank + device assignment for a cohort: ranks in
        sorted worker_id order; local device counts per the configured
        policy (fixed total budget re-split, or uniform), MINUS each
        member's quarantined devices. A member whose quarantine covers
        its whole device budget is dropped from the world (and the
        budget re-split over the rest — which can re-trip the check, so
        iterate to a fixed point); with an unconfigured device count the
        member's own DeviceLayout subtracts, worker-side."""
        ids = sorted(set(worker_ids))
        while True:
            n = max(1, len(ids))
            dropped = []
            world = {}
            for rank, wid in enumerate(ids):
                if self.total_device_count is not None:
                    local = max(1, int(self.total_device_count) // n)
                else:
                    local = self.local_device_count
                lost = len(self.quarantine.get(wid, ()))
                if local is not None and lost:
                    local -= lost
                    if local < 1:
                        dropped.append(wid)
                        continue
                world[wid] = {"rank": rank, "local_device_count": local}
            if not dropped:
                return world
            self._log("member_out_of_devices", dropped=sorted(dropped),
                      quarantine={w: sorted(self.quarantine.get(w, ()))
                                  for w in dropped})
            ids = [w for w in ids if w not in dropped]
            if not ids:
                return {}

    def _newest_snapshot_step(self):
        found = find_valid_snapshot(self.checkpoint_dir)
        return None if found is None else int(found[0])

    def _note_quarantine(self, faulted, beats):
        """A faulted member whose heartbeat names an `sdc_device` (the
        canary convicted a chip, resilience/sdc.py) gets that device
        QUARANTINED: recorded here, subtracted from the member's budget
        by _assign_world, published in every later plan so the member's
        DeviceLayout builds its mesh around it. The rescale that follows
        is the ordinary fence/rollback/reshard — a bad chip is handled
        exactly like a dead host, but at device granularity."""
        for w in faulted:
            dev = beats.get(w, {}).get("sdc_device")
            if dev is None:
                continue
            devs = self.quarantine.setdefault(w, [])
            if int(dev) not in devs:
                devs.append(int(dev))
                self._log("quarantine", worker=w, device=int(dev),
                          fault=beats[w].get("fault"))

    # -------------------------------------------------------- main loop --
    def run(self, deadline=None):
        """Form the cohort, supervise it to completion. Returns a
        summary dict; raises ClusterAborted on terminal escalation (the
        merged bundle path rides the exception). `deadline` (seconds)
        bounds the whole run — expiry is an abort, not a hang."""
        t_end = None if deadline is None else time.monotonic() + deadline
        members = self._wait_for_formation(t_end)
        self.world = self._assign_world(members)
        restore = self._newest_snapshot_step()
        self._publish("run", self.world, restore_step=restore,
                      reason="initial formation")
        self._log("formed", members=sorted(members),
                  restore_step=restore)
        while True:
            if t_end is not None and time.monotonic() > t_end:
                self._abort("coordinator deadline exceeded")
            time.sleep(self.poll_interval)
            beats = self.monitor.poll()
            # a member whose last word was "left" departed WITHOUT
            # finishing (worker-side failure, orderly exit): it is not
            # coming back — rescale it out like a death, or the cohort
            # would wait on its "done" forever
            dead = [w for w in self.world
                    if w not in beats or not beats[w]["alive"]
                    or beats[w].get("status") == "left"]
            faulted = [w for w in self.world if w not in dead
                       and beats[w].get("status") == "fault"
                       and beats[w].get("gen") == self.gen]
            if dead or faulted:
                self._note_quarantine(faulted, beats)
                self._rescale(dead, faulted, beats)
                continue
            joiners = [w for w, hb in beats.items()
                       if w not in self.world
                       and hb.get("status") == "joining"]
            if joiners and self.allow_grow:
                self._grow(joiners, beats)
                continue
            if self.world and all(
                    beats.get(w, {}).get("status") == "done"
                    for w in self.world):
                self._publish("done", self.world,
                              reason="all members reported done")
                self._log("done", members=sorted(self.world))
                return {"events": self.events, "world": self.world,
                        "gen": self.gen,
                        "steps": {w: beats[w].get("step")
                                  for w in self.world}}

    def _wait_for_formation(self, t_end):
        t0 = time.monotonic()
        while True:
            beats = self.monitor.poll()
            members = [w for w, hb in beats.items()
                       if hb.get("status") == "joining" and hb["alive"]]
            if len(members) >= self.num_workers:
                return members[:self.num_workers] \
                    if len(members) > self.num_workers else members
            if time.monotonic() - t0 > self.join_timeout or (
                    t_end is not None and time.monotonic() > t_end):
                self._abort("formation timeout: %d/%d workers joined"
                            % (len(members), self.num_workers))
            time.sleep(self.poll_interval)

    # ---------------------------------------------------------- shrink --
    def _budget_or_abort(self, reason, cause=None):
        self.rescales += 1
        if self.rescales > self.max_rescales:
            self._abort("rescale budget exhausted (%d) at: %s"
                        % (self.max_rescales, reason), cause=cause)

    def _rescale(self, dead, faulted, beats):
        """Shrink (dead workers dropped) and/or cohort-wide rollback
        (faulted workers kept): fence, pick the newest valid snapshot,
        publish the new world. A member death DURING the fence restarts
        the fence with the remaining cohort."""
        reason = "dead=%s faulted=%s" % (sorted(dead), sorted(faulted))
        self._budget_or_abort(reason)
        survivors = [w for w in self.world if w not in dead]
        self._log("detected", dead=sorted(dead), faulted=sorted(faulted),
                  detail={w: beats.get(w, {}).get("status")
                          for w in self.world})
        survivors = self._fence(survivors, reason=reason)
        if not survivors:
            self._abort("no survivors after: %s" % reason)
        restore = self._newest_snapshot_step()
        self.world = self._assign_world(survivors)
        if not self.world:
            self._abort("quarantine left no usable devices after: %s"
                        % reason)
        self._publish("run", self.world, restore_step=restore,
                      reason="rescale: " + reason)
        self._log("rescale", survivors=sorted(survivors),
                  restore_step=restore, reason=reason,
                  quarantine={w: sorted(d)
                              for w, d in self.quarantine.items() if d})

    def _fence(self, members, reason, save_step=False):
        """Publish a fence-phase plan and wait for every member's ack
        (gen_acked == fence gen). Members that die while fencing are
        dropped and the fence RESTARTS for the rest — the
        death-during-recovery path. Returns the members that acked."""
        members = list(members)
        while members:
            plan = self._publish("fence", {w: self.world.get(w, {})
                                           for w in members},
                                 save_step=bool(save_step), reason=reason)
            self._log("fence", members=sorted(members),
                      save_step=bool(save_step))
            t0 = time.monotonic()
            while True:
                beats = self.monitor.poll()
                acked = [w for w in members
                         if beats.get(w, {}).get("gen_acked")
                         == plan["gen"]]
                if len(acked) == len(members):
                    self._log("fenced", members=sorted(members))
                    return members
                newly_dead = [w for w in members
                              if w not in beats
                              or not beats[w]["alive"]
                              or beats[w].get("status") == "left"]
                if newly_dead or time.monotonic() - t0 \
                        > self.fence_timeout:
                    stragglers = newly_dead or [
                        w for w in members if w not in acked]
                    self._budget_or_abort(
                        "death during recovery: %s" % sorted(stragglers))
                    self._log("refence", dropped=sorted(stragglers))
                    members = [w for w in members
                               if w not in stragglers]
                    break  # restart the fence for the remainder
                time.sleep(self.poll_interval)
        return members

    # ------------------------------------------------------------ grow --
    def _grow(self, joiners, beats):
        """Replacement-worker join: fence the running members AT a step
        barrier with save_step (rank 0 snapshots its current step), then
        publish the grown world pinning exactly that snapshot — nobody
        rolls back, no completed step is aborted."""
        del beats
        self._budget_or_abort("grow: %s" % sorted(joiners))
        members = list(self.world)
        self._log("join_detected", joiners=sorted(joiners))
        survivors = self._fence(members, save_step=True,
                                reason="grow: %s" % sorted(joiners))
        if not survivors:
            self._abort("cohort died while growing")
        # rank 0's ack carries the step it snapshotted; a member that
        # had already finished (no live state) acks without one, and the
        # newest valid snapshot (its final save) stands in
        rank0 = min(survivors,
                    key=lambda w: self.world.get(w, {}).get("rank", 1 << 30))
        saved = self.monitor.poll().get(rank0, {}).get("saved_step")
        restore = int(saved) if saved is not None \
            else self._newest_snapshot_step()
        self.world = self._assign_world(survivors + list(joiners))
        self._publish("run", self.world, restore_step=restore,
                      reason="grow: %s" % sorted(joiners))
        self._log("grow", joiners=sorted(joiners),
                  world=sorted(self.world), restore_step=restore)

    # ----------------------------------------------------------- abort --
    def _abort(self, reason, cause=None):
        bundle = self._write_merged_bundle(reason)
        self._publish("abort", self.world, reason=reason)
        self._log("abort", reason=reason, bundle=bundle)
        raise ClusterAborted(
            "cluster aborted: %s%s" % (
                reason, " (bundle: %s)" % bundle if bundle else ""),
            bundle=bundle, cause=cause)

    def _write_merged_bundle(self, reason):
        """One self-contained post-mortem: coordinator events + plan
        history + every worker's last heartbeat, plus each worker's own
        PR-5 bundles (written under <cluster_dir>/bundles/<worker_id>)
        copied alongside. Never raises — the bundle exists to explain a
        failure, not to cause another."""
        try:
            base = self.bundle_dir or os.path.join(self.cluster_dir,
                                                   "bundle")
            os.makedirs(base, exist_ok=True)
            path = os.path.join(base, "cluster_bundle")
            n = 0
            while os.path.exists(path):
                n += 1
                path = os.path.join(base, "cluster_bundle.%d" % n)
            os.makedirs(path)
            meta = {"reason": str(reason),
                    "wall_time": time.time(),
                    "gen": self.gen,
                    "rescales": self.rescales,
                    "world": self.world,
                    "events": self.events,
                    "plans": self._plans,
                    "heartbeats": _hb.read_heartbeats(self.cluster_dir)}
            try:
                # the coordinator's own flight-recorder ring (fence/
                # rescale/abort instants); each worker's span timeline
                # rides along inside its copied PR-5 bundles below
                meta["trace"] = _otrace.dump_jsonable()
            except Exception:  # noqa: BLE001
                pass
            with open(os.path.join(path, "bundle.json"), "w") as f:
                json.dump(meta, f, indent=1, sort_keys=True)
            wroot = os.path.join(self.cluster_dir, "bundles")
            if os.path.isdir(wroot):
                shutil.copytree(wroot, os.path.join(path, "workers"))
            return path
        except Exception:  # noqa: BLE001 — best-effort post-mortem
            return None


# --------------------------------------------------------------- worker --
# local policy of an elastic worker: hangs are CLUSTER faults (the
# cohort must fence and roll back together — a lone local rollback would
# desync the replicas), so the local chain aborts immediately and the
# worker escalates the TrainingAborted through its heartbeat. Everything
# else keeps the PR-5 local defaults.
def _elastic_policies(overrides=None):
    pol = {"hang": (_abort_action(),)}
    pol.update(overrides or {})
    return pol


class ElasticWorker(object):
    def __init__(self, cluster_dir, worker_id, build_fn,
                 checkpoint_dir=None, checkpoint_every=None,
                 policies=None, watchdog_timeout=None,
                 heartbeat_interval=0.2, poll_interval=0.02,
                 plan_timeout=180.0, record_results=True,
                 async_save=False, sharded_weight_update=False,
                 step_delay=0.0, metrics_port=None,
                 metrics_host="127.0.0.1", sentinel=None, sdc=None,
                 sdc_every=64):
        """One cohort member. `build_fn(layout)` -> dict with keys
        `main`, `startup`, `loss` (Variable or name) and optionally
        `feed_fn(step_index)` (deterministic feeds; omit for reader-fed
        programs) and `fetch_list`. It is called once per GENERATION —
        after every rescale — under a fresh Scope, so programs are
        rebuilt against the new mesh shape deterministically (set
        Program.random_seed inside it).

        Per generation the worker: drops all distributed state
        (`shutdown_distributed`), installs the new `DeviceLayout`,
        builds a ParallelExecutor over `layout.local_mesh()`, restores
        the plan's pinned snapshot WITH resharding
        (`restore(layout=)`), and trains under an inner Supervisor
        whose rollbacks also reshard (`restore_layout`). Rank 0 is the
        cohort's checkpoint writer (`checkpoint_every`); results
        (per-step first-fetch scalars) append to
        results_<worker_id>.jsonl for the bit-exactness legs."""
        self.cluster_dir = str(cluster_dir)
        self.worker_id = str(worker_id)
        self.build_fn = build_fn
        self.checkpoint_dir = checkpoint_dir or default_checkpoint_dir(
            cluster_dir)
        self.checkpoint_every = checkpoint_every
        self.policies = _elastic_policies(policies)
        self.watchdog_timeout = watchdog_timeout
        self.poll_interval = float(poll_interval)
        self.plan_timeout = float(plan_timeout)
        self.record_results = bool(record_results)
        self.async_save = bool(async_save)
        self.sharded_weight_update = bool(sharded_weight_update)
        # test/demo pacing: sleep this long after every completed step
        # (a CI cohort of tiny models otherwise finishes before a
        # replacement worker can even import jax and join)
        self.step_delay = float(step_delay)
        # trainer-side scrape endpoint (ARCHITECTURE.md §24): serve the
        # observability registry's Prometheus rendering — including the
        # heartbeat-derived fleet gauges for this cluster dir — on this
        # port (0 = pick a free one, published in the heartbeat so
        # `ptpu_elastic status` can point scrapers at it; None = off).
        # metrics_host defaults loopback; a multi-host fleet whose
        # scraper lives elsewhere passes "0.0.0.0" (the heartbeat's
        # `host` field names the machine)
        self.metrics_port = metrics_port
        self.metrics_host = metrics_host
        self._metrics_server = None
        # training-health layer (ARCHITECTURE.md §29). `sentinel` /
        # `sdc`: True for defaults, or a kwargs dict for the
        # TrainingSentinel / CanaryChecker constructors. Both are
        # rebuilt per generation (the sentinel's window restarts with
        # the restored stream; the canary's device rotation follows the
        # resharded, quarantine-filtered mesh) but the canary's
        # REFERENCE digest persists across generations — it must, or a
        # degraded chip joining a new generation would record its own
        # wrong answer as truth.
        self.sentinel_opts = sentinel
        self.sdc_opts = sdc
        self.sdc_every = sdc_every
        self._sdc_state = None
        self._sdc_device_map = None
        self._hb_writer = _hb.HeartbeatWriter(
            cluster_dir, worker_id, interval=heartbeat_interval)
        self._plan_path = os.path.join(self.cluster_dir, PLAN_FILE)
        self._plan_mtime = None
        self._plan_cache = None
        self._processed_gen = 0
        self._acked_gen = 0
        self._armed_gen = None
        self._done = False

    # ------------------------------------------------------------ plans --
    def _current_plan(self):
        """The published plan, re-read only when the file changed."""
        try:
            mtime = os.stat(self._plan_path).st_mtime_ns
        except OSError:
            return self._plan_cache
        if mtime != self._plan_mtime:
            plan = read_plan(self.cluster_dir)
            if plan is not None:
                self._plan_cache = plan
                self._plan_mtime = mtime
        return self._plan_cache

    def _wait_for_plan(self, past_gen):
        """Block until a plan with gen > past_gen exists."""
        t0 = time.monotonic()
        while True:
            plan = self._current_plan()
            if plan is not None and plan.get("gen", 0) > past_gen:
                return plan
            if time.monotonic() - t0 > self.plan_timeout:
                raise ClusterAborted(
                    "worker %s: no plan past gen %d within %.0fs — "
                    "coordinator lost?" % (self.worker_id, past_gen,
                                           self.plan_timeout))
            time.sleep(self.poll_interval)

    def _barrier_check(self, point, program=None, steps=1):
        """The core.executor step-barrier hook: one os.stat per
        dispatch; raises ClusterFenced the moment the plan moves past
        the generation this process is training under."""
        del point, program, steps
        plan = self._current_plan()
        if plan is not None and self._armed_gen is not None \
                and plan.get("gen", 0) != self._armed_gen:
            raise ClusterFenced(
                "cluster plan moved to gen %s (phase %r) past this "
                "worker's gen %d" % (plan.get("gen"), plan.get("phase"),
                                     self._armed_gen),
                gen=plan.get("gen"))

    # ------------------------------------------------------------- run --
    def run(self, num_steps):
        """Train `num_steps` total cluster steps, surviving rescales.
        Returns {"steps": final step, "generations": n} on success;
        raises ClusterAborted when the coordinator aborts the job."""
        num_steps = int(num_steps)
        if self.metrics_port is not None and self._metrics_server is None:
            # best-effort like the teardown: a metrics bind failure
            # (port taken) is an observability problem — it must not
            # kill the worker and read to the coordinator as a host
            # death burning a fence/rollback cycle
            try:
                # liveness window scaled to THIS fleet's beat cadence:
                # the 3s default reads a healthy slow-beating worker
                # (heartbeat_interval > 1s) as dead between beats
                _obsreg.watch_cluster(
                    self.cluster_dir,
                    heartbeat_timeout=max(
                        3.0, 3.0 * self._hb_writer.interval))
                self._metrics_server = _obsreg.serve_metrics(
                    port=int(self.metrics_port), host=self.metrics_host)
                self._hb_writer.update(
                    metrics_port=self._metrics_server.port)
            except Exception as e:  # noqa: BLE001 — train anyway
                _obsreg.unwatch_cluster(self.cluster_dir)
                if self._metrics_server is not None:
                    try:  # a bound server must not leak its port when
                        # a later setup step (heartbeat publish) raises
                        self._metrics_server.close()
                    except Exception:  # noqa: BLE001
                        pass
                self._metrics_server = None
                import logging
                logging.getLogger(__name__).warning(
                    "worker %s: metrics endpoint unavailable (%s); "
                    "training continues without /metrics",
                    self.worker_id, e)
        self._hb_writer.start()
        fault_plan = _faults.FaultPlan.from_env()
        if fault_plan is not None and _faults.active_plan() is None:
            fault_plan.arm()
        else:
            fault_plan = None
        generations = 0
        try:
            while True:
                plan = self._wait_for_plan(self._processed_gen)
                self._processed_gen = plan["gen"]
                phase = plan.get("phase")
                if phase == "done":
                    break
                if phase == "abort":
                    raise ClusterAborted(
                        "coordinator aborted the job: %s"
                        % plan.get("reason"))
                if phase == "fence":
                    # between generations there is no live state to
                    # snapshot; ack so the cohort can move on. A fence
                    # already acked from inside the generation (where a
                    # barrier save may have stamped saved_step) is NOT
                    # re-acked — re-writing here could clear or
                    # resurrect a stale saved_step under the
                    # coordinator's read.
                    if self.worker_id in plan.get("world", {}) \
                            and plan["gen"] != self._acked_gen:
                        self._acked_gen = plan["gen"]
                        self._hb_writer.update(status="fenced",
                                               gen_acked=plan["gen"],
                                               saved_step=None)
                    continue
                if self.worker_id not in plan.get("world", {}):
                    if generations > 0:
                        # fenced OUT of the cohort (a stalled-heartbeat
                        # worker the coordinator declared dead): leave
                        # in an orderly way instead of training as a
                        # zombie against a world that moved on
                        break
                    continue  # not yet a member: wait for inclusion
                generations += 1
                self._run_generation(plan, num_steps)
        finally:
            if fault_plan is not None:
                fault_plan.disarm()
            self._hb_writer.close("done" if self._done else "left")
            if self._metrics_server is not None:
                try:
                    self._metrics_server.close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
                self._metrics_server = None
                # drop the heartbeat collector with the endpoint: a
                # process cycling through cluster dirs must not keep
                # reading dead directories on every later render
                _obsreg.unwatch_cluster(self.cluster_dir)
        return {"steps": num_steps if self._done else None,
                "generations": generations}

    # -------------------------------------------------- one generation --
    def _layout_for(self, plan):
        me = plan["world"][self.worker_id]
        return DeviceLayout(
            num_processes=len(plan["world"]),
            process_index=int(me["rank"]),
            local_device_count=me.get("local_device_count"),
            mesh_axes=plan.get("mesh_axes"),
            batch_axis=plan.get("batch_axis", "dp"),
            # the cohort's update-state shard axis (parallel/plan.py)
            # rides the cluster plan so a resharded generation keeps
            # the sharded-update layout the snapshot recorded
            shard_axis=plan.get("shard_axis"),
            # devices the coordinator quarantined on THIS worker (SDC
            # canary convictions): the local mesh is built around them
            skip_local_devices=plan.get("quarantine", {}).get(
                self.worker_id))

    def _run_generation(self, plan, num_steps):
        from ..parallel.parallel_executor import ParallelExecutor
        from ..core.executor import Executor
        gen = plan["gen"]
        layout = self._layout_for(plan)
        rank = layout.process_index
        # tear down the previous world's cached state, install this one
        _dist.shutdown_distributed()
        _dist.init_distributed()  # real rendezvous when env-configured
        _dist.set_active_layout(layout)
        self._hb_writer.update(status="init", gen=gen, rank=rank,
                               layout=layout.to_json())
        scope = Scope()
        mgr = CheckpointManager(self.checkpoint_dir,
                                async_save=self.async_save)
        sup = None
        prev_hook = _exe_mod._barrier_hook
        self._armed_gen = gen
        try:
            with scope_guard(scope):
                built = self.build_fn(layout)
                main, startup = built["main"], built["startup"]
                loss = built["loss"]
                feed_fn = built.get("feed_fn")
                fetch_list = built.get("fetch_list") or [loss]
                exe = Executor()
                exe.run(startup)
                pexe = ParallelExecutor(
                    main_program=main, mesh=layout.local_mesh(),
                    batch_axis=layout.batch_axis,
                    shard_axis=layout.shard_axis,
                    sharded_weight_update=self.sharded_weight_update)
                step = self._restore_or_init(plan, mgr, main, scope,
                                             layout, rank, exe)
                sup = Supervisor(
                    pexe, main, scope=scope, checkpoint_manager=mgr,
                    policies=self.policies,
                    watchdog_timeout=self.watchdog_timeout,
                    bundle_dir=os.path.join(self.cluster_dir, "bundles",
                                            self.worker_id),
                    restore_layout=layout,
                    sentinel=self._make_sentinel(),
                    sdc=self._make_sdc(layout),
                    sdc_every=self.sdc_every)
                sup.step = step
                self._hb_writer.update(status="ok", step=step)
                _exe_mod._barrier_hook = self._barrier_check
                self._train_loop(sup, mgr, plan, main, scope, layout,
                                 rank, feed_fn, fetch_list, num_steps)
        finally:
            _exe_mod._barrier_hook = prev_hook
            self._armed_gen = None
            if sup is not None:
                if sup.sdc is not None:
                    # the reference digest survives the generation; the
                    # next generation's canary compares against it
                    self._sdc_state = sup.sdc.state_dict()
                sup.close()
            try:
                mgr.close()
            except Exception:  # noqa: BLE001 — a failed final save must
                pass           # not mask the loop's own outcome

    def _make_sentinel(self):
        if not self.sentinel_opts:
            return None
        opts = (dict(self.sentinel_opts)
                if isinstance(self.sentinel_opts, dict) else {})
        return TrainingSentinel(**opts)

    def _make_sdc(self, layout):
        """This generation's canary checker: rotation over exactly the
        devices the generation's mesh uses (quarantined chips excluded
        — a convicted device is neither trained on nor re-canaried),
        reference digest carried over from the previous generation."""
        if not self.sdc_opts:
            self._sdc_device_map = None
            return None
        opts = dict(self.sdc_opts) if isinstance(self.sdc_opts, dict) \
            else {}
        import jax
        skip = set(layout.skip_local_devices)
        usable = [i for i in range(len(jax.devices())) if i not in skip]
        mesh_n = layout.resolved_local_device_count()
        # rotation index -> GLOBAL local-device index: the quarantine
        # list the coordinator keeps is in global indices, so an SDC
        # escalation must translate before stamping sdc_device
        self._sdc_device_map = usable[:mesh_n]
        canary = CanaryChecker(
            devices=layout.local_devices()[:mesh_n], **opts)
        if self._sdc_state:
            canary.load_state_dict(self._sdc_state)
        return canary

    def _restore_or_init(self, plan, mgr, main, scope, layout, rank, exe):
        """Land the generation's starting state: the plan's pinned
        snapshot resharded onto this layout — or, on a fresh cluster
        (no snapshot yet), rank 0 publishes the post-startup state as
        step 0 and everyone else restores it, so every member starts
        from IDENTICAL bits no matter how its local init behaved."""
        del exe
        restore_step = plan.get("restore_step")
        if restore_step is not None:
            mgr.restore(program=main, scope=scope,
                        step=int(restore_step), layout=layout)
            return int(restore_step)
        if rank == 0:
            mgr.save(0, program=main, scope=scope, layout=layout,
                     wait=True)
            mgr.restore(program=main, scope=scope, step=0, layout=layout)
            return 0
        t0 = time.monotonic()
        while find_valid_snapshot(self.checkpoint_dir, step=0) is None:
            if time.monotonic() - t0 > self.plan_timeout:
                raise ClusterAborted(
                    "worker %s: rank 0 never published the step-0 "
                    "snapshot" % self.worker_id)
            time.sleep(self.poll_interval)
        mgr.restore(program=main, scope=scope, step=0, layout=layout)
        return 0

    def _train_loop(self, sup, mgr, plan, main, scope, layout, rank,
                    feed_fn, fetch_list, num_steps):
        gen = plan["gen"]
        while sup.step < num_steps:
            newp = self._current_plan()
            if newp is not None and newp["gen"] != gen:
                self._on_generation_change(newp, sup, mgr, main, scope,
                                           layout, rank)
                return
            idx = sup.step
            feed = feed_fn(idx) if feed_fn is not None else None
            try:
                out = sup.run_step(feed=feed, fetch_list=fetch_list)
            except ClusterFenced:
                continue  # loop top re-reads the plan and handles it
            except EOFException:
                break
            except (TrainingAborted, DispatchTimeoutError) as e:
                self._escalate_cluster_fault(e, gen)
                return
            if out is not None and sup.step > idx \
                    and self.record_results:
                self._record(gen, idx, out)
            hb_extra = {}
            if sup.sentinel is not None:
                # last z-scores / spike count ride the heartbeat so
                # `ptpu_elastic status` shows WHY a worker fenced
                hb_extra["sentinel"] = sup.sentinel.status()
            if sup.sdc is not None:
                hb_extra["sdc"] = sup.sdc.status()
            self._hb_writer.update(
                status="ok", step=sup.step, gen=gen,
                watchdog=self.watchdog_timeout,
                reader_positions=self._reader_positions(main, scope),
                **hb_extra)
            if rank == 0 and self.checkpoint_every \
                    and sup.step % int(self.checkpoint_every) == 0:
                # re-check the fence right before writing: a fenced-out
                # zombie (stalled heartbeat, still training) must not
                # keep publishing snapshots over the new cohort's
                cur = self._current_plan()
                if cur is not None and cur["gen"] == gen:
                    mgr.save(sup.step, program=main, scope=scope,
                             layout=layout)
            if self.step_delay > 0:
                time.sleep(self.step_delay)
        # reached num_steps (or clean EOF): publish the final state so
        # a later joiner (or a restarted cluster) resumes from it
        if rank == 0:
            mgr.save(sup.step, program=main, scope=scope, layout=layout,
                     wait=True)
        self._done = True
        self._hb_writer.update(status="done", step=sup.step, gen=gen)

    def _on_generation_change(self, newp, sup, mgr, main, scope, layout,
                              rank):
        """A newer plan landed mid-generation. For a fence: snapshot if
        asked (rank 0, save_step — the grow barrier) and ack; the outer
        loop then waits for the run-phase plan. Any other phase is
        simply left for the outer loop to process."""
        if newp.get("phase") == "fence" \
                and self.worker_id in newp.get("world", {}):
            fields = {"status": "fenced", "gen_acked": newp["gen"],
                      "step": sup.step, "saved_step": None}
            # the barrier save falls to the ACTING rank 0 — the lowest
            # rank in the FENCE's world, not literal rank==0: when the
            # old rank 0 died mid-fence, the restarted fence's world no
            # longer contains it, and without this the grow would find
            # no saved_step and silently degrade into a rollback to the
            # newest (possibly ancient) snapshot
            del rank
            me = newp["world"].get(self.worker_id) or {}
            ranks = [int(v.get("rank", 1 << 30))
                     for v in newp["world"].values()]
            if newp.get("save_step") and ranks \
                    and me.get("rank") == min(ranks):
                mgr.save(sup.step, program=main, scope=scope,
                         layout=layout, wait=True)
                fields["saved_step"] = sup.step
            self._acked_gen = newp["gen"]
            self._hb_writer.update(**fields)

    def _escalate_cluster_fault(self, exc, gen):
        """A fault the local chain could not (or must not) absorb — the
        wedged-dispatch case, and SDC convictions (a bad chip cannot be
        fixed in-process). Report it cluster-level and wait for the
        coordinator's fence; the cohort rolls back together. An SDC
        fault additionally stamps `sdc_device` (the GLOBAL local-device
        index of the convicted chip) so the coordinator quarantines the
        device rather than treating this as a whole-host problem."""
        fields = {"status": "fault", "gen": gen, "fault": repr(exc)}
        for e in (exc, getattr(exc, "cause", None),
                  getattr(exc, "__cause__", None)):
            if isinstance(e, SilentCorruptionError) \
                    and e.device_index is not None:
                dev = int(e.device_index)
                if self._sdc_device_map \
                        and dev < len(self._sdc_device_map):
                    dev = int(self._sdc_device_map[dev])
                fields["sdc_device"] = dev
                break
        self._hb_writer.update(**fields)
        t0 = time.monotonic()
        while True:
            plan = self._current_plan()
            if plan is not None and plan["gen"] != gen:
                return  # outer loop processes the new plan
            if time.monotonic() - t0 > self.plan_timeout:
                raise exc
            time.sleep(self.poll_interval)

    # --------------------------------------------------------- helpers --
    def _reader_positions(self, program, scope):
        out = {}
        for op in program.global_block().ops:
            if op.type != "read":
                continue
            name = op.inputs["Reader"][0]
            state = scope.get(name)
            consumed = getattr(state, "_consumed", None)
            if consumed is not None:
                out[name] = int(consumed)
        return out

    def _record(self, gen, step, fetches):
        val = float(np.asarray(fetches[0]).reshape(-1)[0])
        path = os.path.join(self.cluster_dir,
                            "results_%s.jsonl" % self.worker_id)
        with open(path, "a") as f:
            f.write(json.dumps({"gen": gen, "step": int(step),
                                "value": val}) + "\n")
