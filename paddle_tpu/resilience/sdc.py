"""Silent-data-corruption detection: the deterministic canary step.

A flaky chip that flips bits produces *finite, plausible* wrong numbers
— no guard trips, no heartbeat stops, and the fleet trains garbage to
convergence. The industrial remedy is the one this module implements:
periodically re-dispatch a KNOWN computation (fixed inputs, no RNG, no
dropout) on a rotating device and compare the result digest against the
recorded reference. Any mismatch is, by construction, hardware (or
compiler nondeterminism, which on this stack's fixed-program canary is
the same actionable event): the input bytes, program and device
assignment are identical on every check.

`CanaryChecker.check()` raises `SilentCorruptionError` carrying the
suspect device index; the Supervisor classifies it as fault class
"sdc" (default chain: abort — a bad chip is not recoverable
in-process), and in the elastic cluster the worker escalates it
through its heartbeat so the coordinator QUARANTINES the device:
fence, rollback, reshard onto the surviving mesh exactly like host
death, but keyed per-device with the quarantine list published in
`plan.json` (resilience/cluster.py, `ptpu_elastic status`).

Fault injection: `bitflip@N[:device]` (resilience/faults.py) corrupts
the Nth canary result — optionally waiting until the rotation lands on
a specific device index — through the module hook `_fault_hook`, the
same pulled-seam pattern as the executor/reader hooks.
"""
import collections
import hashlib

import numpy as np

__all__ = ["SilentCorruptionError", "CanaryChecker"]

# armed by resilience.faults.FaultPlan: fn(check_index, device_index,
# result_array) -> result_array (possibly corrupted). None in production.
_fault_hook = None


class SilentCorruptionError(RuntimeError):
    """A canary check's result digest diverged from the recorded
    reference: the device computed the wrong answer for a fixed input.
    `device_index` is the local index of the suspect device."""

    def __init__(self, message, device_index=None, expected=None,
                 got=None):
        super(SilentCorruptionError, self).__init__(message)
        self.device_index = device_index
        self.expected = expected
        self.got = got


class CanaryChecker(object):
    """Deterministic canary dispatch over a rotating device set.

    The canary is a few rounds of matmul + tanh over a fixed seeded
    input — enough FLOPs to exercise the matrix units where bit errors
    live, zero randomness (no dropout, no rng keys), and independent of
    the training program so its digest is stable across every training
    configuration. The reference digest is recorded on the FIRST check
    (device 0 of the rotation) — `record_reference()` forces that
    eagerly at startup, before any chip has had hours to degrade.

    The cadence cost is one small dispatch per `Supervisor(sdc_every=)`
    steps; BENCH_SENTINEL=1 measures it (<3%% gated)."""

    def __init__(self, shape=(128, 128), seed=0, iters=4, devices=None,
                 history=32):
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("canary shape must be square (y @ y.T "
                             "feeds back into y), got %r" % (shape,))
        rng = np.random.RandomState(int(seed))
        self._x = np.asarray(rng.uniform(-1.0, 1.0, size=shape),
                             np.float32)
        self._iters = max(1, int(iters))
        self._devices = list(devices) if devices is not None else None
        self._fn = None
        self.reference = None
        self.checks = 0
        self.mismatches = 0
        self.last_device = None
        self.verdicts = collections.deque(maxlen=max(1, int(history)))

    # ---------------------------------------------------------- devices --
    def devices(self):
        if self._devices is None:
            import jax
            self._devices = list(jax.local_devices())
        return self._devices

    def _compute(self, x):
        import jax.numpy as jnp
        y = x
        for _ in range(self._iters):
            y = jnp.tanh(y @ y.T) + 0.5 * y
        return y

    def _run_on(self, device):
        import jax
        if self._fn is None:
            self._fn = jax.jit(self._compute)
        # a committed input pins the jitted computation to `device`
        x = jax.device_put(self._x, device)
        return np.asarray(self._fn(x))

    @staticmethod
    def digest(array):
        return hashlib.sha256(
            np.ascontiguousarray(array, np.float32).tobytes()
        ).hexdigest()[:16]

    # ------------------------------------------------------------ check --
    def record_reference(self):
        """Eagerly record the reference digest (one check on device 0)."""
        if self.reference is None:
            self.check()
        return self.reference

    def check(self):
        """One canary dispatch on the next device in rotation. Records
        the reference on the first call; afterwards raises
        SilentCorruptionError on any digest mismatch. Returns the
        digest when it matches."""
        devs = self.devices()
        idx = self.checks
        dev_i = idx % len(devs)
        self.checks += 1
        self.last_device = dev_i
        out = self._run_on(devs[dev_i])
        hook = _fault_hook
        if hook is not None:
            out = hook(idx, dev_i, out)
        d = self.digest(out)
        if self.reference is None:
            self.reference = d
            self.verdicts.append({"check": idx, "device": dev_i,
                                  "ok": True, "digest": d,
                                  "reference": True})
            return d
        ok = d == self.reference
        self.verdicts.append({"check": idx, "device": dev_i, "ok": ok,
                              "digest": d})
        if not ok:
            self.mismatches += 1
            raise SilentCorruptionError(
                "silent data corruption: canary digest %s != reference "
                "%s on local device %d (%s) at check %d — fixed input, "
                "fixed program: the device computed a different answer"
                % (d, self.reference, dev_i, devs[dev_i], idx),
                device_index=dev_i, expected=self.reference, got=d)
        return d

    # ----------------------------------------------------------- state --
    def status(self):
        return {"checks": int(self.checks),
                "mismatches": int(self.mismatches),
                "last_device": self.last_device,
                "reference": self.reference}

    def state_dict(self):
        """The reference digest travels with a checkpoint so a resumed
        run compares against the ORIGINAL healthy reading, not a fresh
        one taken on possibly-already-degraded hardware."""
        return {"reference": self.reference, "checks": int(self.checks),
                "mismatches": int(self.mismatches)}

    def load_state_dict(self, state):
        self.reference = state.get("reference")
        self.checks = int(state.get("checks", 0))
        self.mismatches = int(state.get("mismatches", 0))
